"""Fused BN-apply Pallas kernel (normalize + scale + activation, one HBM
pass) — the experiment VERDICT r3 item 2 names.

Measured verdict (PERF_NOTES.md has the full ablation table): on v5e the
XLA FMA formulation in nn_ops._batch_norm already emits exactly this
fusion, so the kernel is at parity, not ahead — the ceiling on ResNet BN
cost is the forced second HBM read (stats must complete before any
normalize; the activation exceeds VMEM, so no kernel can revisit tiles
without re-reading HBM). Kept opt-in (PTPU_PALLAS_BN=1) as the measured
evidence and as a template for genuinely fusible patterns.

Layout: x viewed as [N, C, H*W]; grid over (N, C/8, HW/512); per-channel
k,b scalars ride VMEM blocks. Backward is plain XLA (dx = dy*mask*k — an
elementwise chain XLA fuses; the fwd read path was the only candidate)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _kernel(x_ref, k_ref, b_ref, o_ref, *, act):
    x = x_ref[...]                       # [1, Ct, T]
    k = k_ref[...].astype(x.dtype)[None]  # [Ct, 1] -> [1, Ct, 1]
    b = b_ref[...].astype(x.dtype)[None]
    y = x * k + b
    if act == 'relu':
        y = jnp.maximum(y, jnp.zeros_like(y))
    o_ref[...] = y


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_bn_apply(x, k, b, act='relu'):
    """y = act(x * k[c] + b[c]) over NCHW x, one fused HBM pass."""
    return _fwd_impl(x, k, b, act)


def _fwd_impl(x, k, b, act):
    from jax.experimental import pallas as pl

    n, c, h, w = x.shape
    hw = h * w
    ct = 8 if c % 8 == 0 else 1
    tile = 512 if hw % 512 == 0 else (128 if hw % 128 == 0 else hw)
    xv = x.reshape(n, c, hw)
    y = pl.pallas_call(
        functools.partial(_kernel, act=act),
        grid=(n, c // ct, hw // tile),
        in_specs=[
            pl.BlockSpec((1, ct, tile), lambda i, j, t: (i, j, t)),
            pl.BlockSpec((ct, 1), lambda i, j, t: (j, 0)),
            pl.BlockSpec((ct, 1), lambda i, j, t: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, ct, tile), lambda i, j, t: (i, j, t)),
        out_shape=jax.ShapeDtypeStruct((n, c, hw), x.dtype),
    )(xv, k.astype(jnp.float32).reshape(c, 1),
      b.astype(jnp.float32).reshape(c, 1))
    return y.reshape(n, c, h, w)


def _fwd(x, k, b, act):
    y = _fwd_impl(x, k, b, act)
    return y, (x, k, y)


def _bwd(act, res, dy):
    x, k, y = res
    if act == 'relu':
        dy = dy * (y > 0).astype(dy.dtype)
    kb = k.astype(dy.dtype).reshape(1, -1, 1, 1)
    dx = dy * kb
    red = (0, 2, 3)
    dk = jnp.sum((dy * x).astype(jnp.float32), axis=red).astype(k.dtype)
    db = jnp.sum(dy.astype(jnp.float32), axis=red).astype(k.dtype)
    return dx, dk, db


fused_bn_apply.defvjp(_fwd, _bwd)


def supported(x, layout):
    return (layout == 'NCHW' and x.ndim == 4
            and any(d.platform in ('tpu', 'axon') for d in jax.devices()))
