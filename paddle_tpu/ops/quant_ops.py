"""Int8 quantized-inference op lowerings (ISSUE 11 tentpole).

The reference lineage grew INT8 calibration in its inference transpiler
after Fluid 1.2 (PAPER.md §6: fake-quant calibration + a frozen int8
program); the TPU-native counterpart is this op family, emitted by
`passes/quantize.py` over calibrated inference programs:

  quantize_int8     f32 activation -> int8 at a CALIBRATED per-tensor
                    scale (round-to-nearest-even, symmetric [-127, 127])
  dequantize_int8   int8 -> f32 at a fixed scale (fetched quantized vars,
                    tests; the pass itself fuses dequant into consumers)
  mul_int8          int8 activation x int8 per-channel weight matmul,
                    dequant fused into the output epilogue
  conv2d_int8       int8 NCHW conv over per-output-channel int8 filters,
  (+ depthwise)     dequant fused into the output epilogue

Platform split (lax.platform_dependent, kept inside ONE multi-platform
exported module): on TPU the MXU executes the s8 x s8 -> s32 form
directly — int8 operands halve HBM traffic vs bf16 and double MXU
throughput on the memory-bound serving buckets. XLA:CPU has no fast s8
GEMM (the naive int8 dot measures ~10-100x slower than Eigen f32), so
the cpu/default branch computes the SAME quantized integer values in
f32 — int8 weight constants are folded to f32 by XLA at compile time,
making the CPU proxy a numerics-faithful reference for the TPU path
rather than a throughput simulation. Accumulation differs (exact int32
on TPU vs f32 on CPU); products can exceed f32's 2^24 exact-int range
for K > ~1500, a ~1e-7 relative effect dwarfed by the ~1e-2 quantization
step itself — the parity tolerance the quantize reports state.

All ops are serving-only (no_grad): quantization-aware TRAINING stays in
contrib/quantize.py (fake-quant with STE); this family is the post-
training inference form.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register

# symmetric signed-int8 grid: +-127 levels, -128 unused (the standard
# symmetric convention — keeps w and -w representable at equal error)
QMAX = 127.0

_platform_dependent = getattr(lax, 'platform_dependent', None)


def _per_platform(args, tpu_fn, ref_fn):
    """tpu_fn on TPU, ref_fn elsewhere — one traced module carries both
    branches (multi-platform jax.export keeps platform_dependent)."""
    if _platform_dependent is None:  # very old jax: reference path only
        return ref_fn(*args)
    return _platform_dependent(*args, tpu=tpu_fn, default=ref_fn)


def quantize_array(x, scale):
    """round(x / scale) clipped to the symmetric int8 grid (`scale` may
    be a scalar or any broadcastable per-channel array). Shared by the
    runtime lowerings below AND passes/quantize.quantize_weight's
    host-side per-channel weight quantization — one rounding rule
    everywhere, or activation/weight parity would drift."""
    q = jnp.clip(jnp.round(x / scale), -QMAX, QMAX)
    return q.astype(jnp.int8)


@register('quantize_int8', no_grad=True, lod='none')
def _quantize_int8(ctx, ins):
    """Per-tensor symmetric activation quant at the calibrated scale
    (attr 'scale' > 0, fixed at pass time — no runtime statistics, so
    the op is a pure elementwise XLA fuses into its producer)."""
    x = ins['X'][0]
    scale = float(ctx.attr('scale'))
    return {'Out': [quantize_array(x, scale)]}


@register('dequantize_int8', no_grad=True, lod='none')
def _dequantize_int8(ctx, ins):
    x = ins['X'][0]
    scale = float(ctx.attr('scale'))
    return {'Out': [x.astype(jnp.float32) * scale]}


@register('mul_int8', no_grad=True, lod='none')
def _mul_int8(ctx, ins):
    """Quantized `mul`: X int8 (activation), Y int8 [K, N] (per-OUTPUT-
    channel quantized weight), Scale f32 [N] (per-channel weight scales).
    Dequant is fused into the epilogue: out = (x_q . w_q) * in_scale *
    w_scale[None, :] — one f32 multiply per output element, which XLA
    folds into the surrounding elementwise chain."""
    x, y = ins['X'][0], ins['Y'][0]
    w_scale = ins['Scale'][0]
    in_scale = float(ctx.attr('in_scale'))
    xn = ctx.attr('x_num_col_dims', 1)
    yn = ctx.attr('y_num_col_dims', 1)
    lead = int(np.prod(x.shape[:xn])) if xn else 1
    x2 = x.reshape(lead, -1)
    y2 = y.reshape(int(np.prod(y.shape[:yn])), -1)
    dims = (((1,), (0,)), ((), ()))

    def tpu_path(x2, y2):
        acc = lax.dot_general(x2, y2, dims,
                              preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32)

    def ref_path(x2, y2):
        return lax.dot_general(x2.astype(jnp.float32),
                               y2.astype(jnp.float32), dims)

    acc = _per_platform((x2, y2), tpu_path, ref_path)
    out = acc * (in_scale * w_scale.reshape(1, -1))
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {'Out': [out.reshape(out_shape)]}


def _conv2d_int8_impl(ctx, ins):
    x, w = ins['Input'][0], ins['Filter'][0]
    w_scale = ins['Scale'][0]                      # [O]
    in_scale = float(ctx.attr('in_scale'))

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    strides = _pair(ctx.attr('strides', [1, 1]))
    pads = _pair(ctx.attr('paddings', [0, 0]))
    dils = _pair(ctx.attr('dilations', [1, 1]))
    groups = ctx.attr('groups', 1) or 1
    kw = dict(window_strides=strides,
              padding=[(pads[0], pads[0]), (pads[1], pads[1])],
              rhs_dilation=dils, feature_group_count=groups,
              dimension_numbers=('NCHW', 'OIHW', 'NCHW'))

    def tpu_path(x, w):
        acc = lax.conv_general_dilated(
            x, w, preferred_element_type=jnp.int32, **kw)
        return acc.astype(jnp.float32)

    def ref_path(x, w):
        return lax.conv_general_dilated(
            x.astype(jnp.float32), w.astype(jnp.float32), **kw)

    acc = _per_platform((x, w), tpu_path, ref_path)
    out = acc * (in_scale * w_scale.reshape(1, -1, 1, 1))
    return {'Output': [out]}


@register('conv2d_int8', no_grad=True, lod='none')
def _conv2d_int8(ctx, ins):
    """Quantized conv2d: Input int8 NCHW, Filter int8 OIHW quantized per
    OUTPUT channel, Scale f32 [O]; dequant fused into the epilogue as
    with mul_int8."""
    return _conv2d_int8_impl(ctx, ins)


@register('depthwise_conv2d_int8', no_grad=True, lod='none')
def _depthwise_conv2d_int8(ctx, ins):
    return _conv2d_int8_impl(ctx, ins)
