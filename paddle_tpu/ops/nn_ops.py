"""NN op lowerings: conv / pool / normalization / embedding / resize.

Replaces the reference's cuDNN-backed kernels (operators/conv_op.cc,
conv_cudnn_op.cu.cc, pool_op.cc, batch_norm_op.cc/cu, layer_norm_op.cc,
lookup_table_op.cc, interpolate_op.cc ...). Convs lower to
lax.conv_general_dilated in NCHW — XLA picks MXU-friendly internal layouts;
grads come from the generic vjp path (no conv_grad kernels needed).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core import amp
from .math_ops import X


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


# ---------------------------------------------------------------------------
# convolutions
# ---------------------------------------------------------------------------
@register('conv2d')
def _conv2d(ctx, ins):
    x, w = ins['Input'][0], ins['Filter'][0]
    strides = _pair(ctx.attr('strides', [1, 1]))
    pads = _pair(ctx.attr('paddings', [0, 0]))
    dils = _pair(ctx.attr('dilations', [1, 1]))
    groups = ctx.attr('groups', 1) or 1
    out = amp.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dils, feature_group_count=groups,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    return {'Output': [out]}


@register('depthwise_conv2d')
def _depthwise_conv2d(ctx, ins):
    return _conv2d(ctx, ins)


@register('conv3d')
def _conv3d(ctx, ins):
    x, w = ins['Input'][0], ins['Filter'][0]
    strides = _pair(ctx.attr('strides', [1, 1, 1]), 3)
    pads = _pair(ctx.attr('paddings', [0, 0, 0]), 3)
    dils = _pair(ctx.attr('dilations', [1, 1, 1]), 3)
    groups = ctx.attr('groups', 1) or 1
    out = amp.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads], rhs_dilation=dils,
        feature_group_count=groups,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'))
    return {'Output': [out]}


def _conv_transpose(x, w, strides, pads, dils, groups, nd):
    # w: [C_in, C_out/groups, *k]; emulate grad-of-conv via lhs dilation
    k = w.shape[2:]
    if groups > 1:
        xs = jnp.split(x, groups, axis=1)
        ws = jnp.split(w, groups, axis=0)
        outs = [_conv_transpose(xi, wi, strides, pads, dils, 1, nd)
                for xi, wi in zip(xs, ws)]
        return jnp.concatenate(outs, axis=1)
    wt = jnp.swapaxes(w, 0, 1)  # [C_out, C_in, *k]
    wt = jnp.flip(wt, axis=tuple(range(2, 2 + nd)))
    dk = [(ki - 1) * di + 1 for ki, di in zip(k, dils)]  # dilated kernel size
    padding = [(dki - 1 - p, dki - 1 - p) for dki, p in zip(dk, pads)]
    dims = (('NCHW', 'OIHW', 'NCHW') if nd == 2
            else ('NCDHW', 'OIDHW', 'NCDHW'))
    return amp.conv_general_dilated(
        x, wt, window_strides=[1] * nd, padding=padding,
        lhs_dilation=strides, rhs_dilation=dils, dimension_numbers=dims)


@register('conv2d_transpose')
def _conv2d_transpose(ctx, ins):
    x, w = ins['Input'][0], ins['Filter'][0]
    out = _conv_transpose(x, w, _pair(ctx.attr('strides', [1, 1])),
                          _pair(ctx.attr('paddings', [0, 0])),
                          _pair(ctx.attr('dilations', [1, 1])),
                          ctx.attr('groups', 1) or 1, 2)
    return {'Output': [out]}


@register('conv3d_transpose')
def _conv3d_transpose(ctx, ins):
    x, w = ins['Input'][0], ins['Filter'][0]
    out = _conv_transpose(x, w, _pair(ctx.attr('strides', [1, 1, 1]), 3),
                          _pair(ctx.attr('paddings', [0, 0, 0]), 3),
                          _pair(ctx.attr('dilations', [1, 1, 1]), 3),
                          ctx.attr('groups', 1) or 1, 3)
    return {'Output': [out]}


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------
def ceil_mode_pads(spatial, ksize, strides, pads):
    """Per-spatial-dim (lo, hi) padding implementing pool ceil_mode: the
    high side grows so the last (partial) window is kept instead of
    dropped — output dims become ceil((in + 2p - k) / s) + 1. Shared by
    the graph lowering below and imperative.Pool2D."""
    out = []
    for i in range(len(ksize)):
        in_sz = spatial[i] + 2 * pads[i]
        rem = (in_sz - ksize[i]) % strides[i]
        out.append((pads[i],
                    pads[i] + (strides[i] - rem if rem else 0)))
    return out


def _pool(ctx, ins, nd):
    x = X(ins)
    ptype = ctx.attr('pooling_type', 'max')
    ksize = _pair(ctx.attr('ksize'), nd)
    strides = _pair(ctx.attr('strides', [1] * nd), nd)
    pads = _pair(ctx.attr('paddings', [0] * nd), nd)
    if ctx.attr('global_pooling', False):
        ksize = list(x.shape[2:])
        pads = [0] * nd
    if ctx.attr('adaptive', False):
        return {'Out': [_adaptive_pool(x, ksize, ptype, nd)]}
    window = (1, 1) + tuple(ksize)
    strides_full = (1, 1) + tuple(strides)
    pad_full = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    if ctx.attr('ceil_mode', False):
        pad_full[2:] = ceil_mode_pads(x.shape[2:], ksize, strides, pads)
    if ptype == 'max':
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window,
                                    strides_full, pad_full)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_full,
                                  pad_full)
        # count windows' REAL elements when any padding exists — including
        # ceil_mode's high-side extension (pads alone misses it)
        if ctx.attr('exclusive', True) and any(lo or hi
                                               for lo, hi in pad_full[2:]):
            ones = jnp.ones(x.shape, x.dtype)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides_full, pad_full)
            # clamp: a window entirely inside padding (ceil_mode with
            # stride > kernel) counts 0 real elements — 0/0 would NaN;
            # clamped it yields the finite 0 the pre-ceil path produced
            out = s / jnp.maximum(cnt, 1.0)
        else:
            out = s / float(np.prod(ksize))
    return {'Out': [out]}


def _adaptive_pool(x, out_size, ptype, nd):
    # general adaptive pooling: per-dim bucket boundaries (static)
    spatial = x.shape[2:]
    red = jnp.max if ptype == 'max' else jnp.mean
    # reshape trick when evenly divisible, else explicit window slices
    if all(s % o == 0 for s, o in zip(spatial, out_size)):
        shape = [x.shape[0], x.shape[1]]
        axes = []
        for i, (s, o) in enumerate(zip(spatial, out_size)):
            shape += [o, s // o]
            axes.append(2 + 2 * i + 1)
        return red(x.reshape(shape), axis=tuple(axes))
    slices = []
    import itertools
    for idx in itertools.product(*[range(o) for o in out_size]):
        window = [slice(None), slice(None)]
        for i, o in enumerate(idx):
            s = spatial[i]
            start = (o * s) // out_size[i]
            end = -(-((o + 1) * s) // out_size[i])
            window.append(slice(start, end))
        slices.append(red(x[tuple(window)], axis=tuple(range(2, 2 + nd))))
    out = jnp.stack(slices, axis=-1)
    return out.reshape(x.shape[:2] + tuple(out_size))


@register('pool2d')
def _pool2d(ctx, ins):
    return _pool(ctx, ins, 2)


@register('pool3d')
def _pool3d(ctx, ins):
    return _pool(ctx, ins, 3)


@register('max_pool2d_with_index')
def _max_pool2d_with_index(ctx, ins):
    """Max pool returning values + argmax flat index within each input
    [H, W] plane (ref: operators/pool_with_index_op.cc, math/pooling.cc:625
    index = h * input_width + w; first max wins, matching jnp.argmax).

    TPU design: the kernel window is unrolled statically (kh*kw strided
    slices stacked on a trailing axis) so value-max and index-gather are
    one fused argmax — no data-dependent shapes."""
    x = X(ins)
    kh, kw = _pair(ctx.attr('ksize'))
    sh, sw = _pair(ctx.attr('strides', [1, 1]))
    ph, pw = _pair(ctx.attr('paddings', [0, 0]))
    if ctx.attr('global_pooling', False):
        # one argmax over the flattened plane — the windowed unroll below
        # would trace H*W slices for the same result
        n, c, h, w = x.shape
        flat = x.reshape(n, c, h * w)
        arg = jnp.argmax(flat, axis=-1)
        return {'Out': [jnp.max(flat, axis=-1).reshape(n, c, 1, 1)],
                'Mask': [arg.astype(jnp.int32).reshape(n, c, 1, 1)]}
    if ph >= kh or pw >= kw:
        raise ValueError(
            "max_pool2d_with_index: paddings must be smaller than ksize "
            "(got ksize=%r paddings=%r) — the reference constraint "
            "(pool_with_index_op.cc); a window lying entirely in padding "
            "has no valid argmax index" % ((kh, kw), (ph, pw)))
    n, c, h, w = x.shape
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=neg)
    vals, idxs, valid = [], [], []
    for i in range(kh):
        for j in range(kw):
            sl = jax.lax.slice(
                xp, (0, 0, i, j),
                (n, c, i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1),
                (1, 1, sh, sw))
            vals.append(sl)
            row = jnp.arange(oh) * sh + i - ph      # input-plane coords
            col = jnp.arange(ow) * sw + j - pw
            idxs.append(row[:, None] * w + col[None, :])
            valid.append((row[:, None] >= 0) & (row[:, None] < h)
                         & (col[None, :] >= 0) & (col[None, :] < w))
    stack_v = jnp.stack(vals, axis=-1)              # [N, C, OH, OW, K]
    stack_i = jnp.stack(idxs, axis=-1)              # [OH, OW, K]
    stack_m = jnp.broadcast_to(jnp.stack(valid, axis=-1), stack_v.shape)
    # padded slots must never win the argmax: a real value equal to
    # dtype-min would TIE the padding fill and an earlier padded slot
    # would emit its out-of-plane index — pick the first max that is
    # also a valid in-plane slot (every window has one: paddings < ksize)
    eff = jnp.where(stack_m, stack_v, neg)
    mx = jnp.max(eff, axis=-1, keepdims=True)
    score = (eff == mx) & stack_m
    # NaN window: eff == mx is all-False (NaN != NaN) — fall back to the
    # first VALID slot so the Mask stays in-plane while the NaN value
    # propagates through Out
    pick = jnp.where(score.any(axis=-1, keepdims=True), score, stack_m)
    arg = jnp.argmax(pick, axis=-1)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(stack_i, stack_v.shape), arg[..., None],
        axis=-1)[..., 0]
    return {'Out': [mx[..., 0]], 'Mask': [mask.astype(jnp.int32)]}


@register('unpool')
def _unpool(ctx, ins):
    """Max unpooling: scatter X values to the Indices positions of each
    output plane, zeros elsewhere (ref: operators/unpool_op.cc:68
    OutputSize = (in - 1) * stride - 2 * padding + ksize,
    math/unpooling.cc scatter). One batched scatter — XLA lowers it to a
    single dynamic-update pass."""
    x, idx = ins['X'][0], ins['Indices'][0]
    kh, kw = _pair(ctx.attr('ksize'))
    sh, sw = _pair(ctx.attr('strides', [1, 1]))
    ph, pw = _pair(ctx.attr('paddings', [0, 0]))
    n, c, h, w = x.shape
    oh = (h - 1) * sh - 2 * ph + kh
    ow = (w - 1) * sw - 2 * pw + kw
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    b_ix = jnp.arange(n)[:, None, None]
    c_ix = jnp.arange(c)[None, :, None]
    out = flat.at[b_ix, c_ix, idx.reshape(n, c, -1).astype(jnp.int32)].set(
        x.reshape(n, c, -1), mode='drop')
    return {'Out': [out.reshape(n, c, oh, ow)]}


@register('spp')
def _spp(ctx, ins):
    """Spatial pyramid pooling: levels 2^0..2^(h-1) bins per side, each an
    exact-cover pool (kernel = ceil(dim/bins), asymmetric pad to
    kernel*bins), flattened [N, C*bins*bins] and concatenated
    (ref: operators/spp_op.h). Each level is one reduce_window — no
    per-bin loops."""
    x = X(ins)
    levels = int(ctx.attr('pyramid_height', 1))
    ptype = ctx.attr('pooling_type', 'max')
    n, c, h, w = x.shape
    outs = []
    for p in range(levels):
        bins = 2 ** p
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        pad = [(0, 0), (0, 0),
               (ph, max(0, kh * bins - h - ph)),
               (pw, max(0, kw * bins - w - pw))]
        window, strides = (1, 1, kh, kw), (1, 1, kh, kw)
        if ptype == 'max':
            lvl = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                        strides, pad)
        else:
            s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                      pad)
            cnt = jax.lax.reduce_window(jnp.ones(x.shape, x.dtype), 0.0,
                                        jax.lax.add, window, strides, pad)
            lvl = s / cnt  # exclusive counting, as the reference pools
        outs.append(lvl.reshape(n, c * bins * bins))
    return {'Out': [jnp.concatenate(outs, axis=1)]}


@register('conv_shift')
def _conv_shift(ctx, ins):
    """Circular convolution (NTM shift): Out[b,i] = sum_j X[b,(i+j-half)%M]
    * Y[b,j], N odd (ref: operators/conv_shift_op.cc). The N rotations are
    a static gather -> one batched contraction on the MXU."""
    x, y = ins['X'][0], ins['Y'][0]
    m, nk = x.shape[1], y.shape[1]
    offs = jnp.arange(nk) - (nk - 1) // 2
    idx = (jnp.arange(m)[None, :] + offs[:, None]) % m   # [N, M]
    return {'Out': [jnp.einsum('bnm,bn->bm', x[:, idx], y)]}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
@register('batch_norm')
def _batch_norm(ctx, ins):
    """Bandwidth-lean BN: stats accumulate in f32 THROUGH the reduction
    (the dtype convert fuses into the reduce — no f32 copy of a bf16 x is
    ever materialized), and the normalize runs as one FMA in the compute
    dtype (y = x*k + b with per-channel f32-derived k,b), so the big
    tensor is read once at storage width. Measured +2% e2e on ResNet-50
    v5e vs the promote-everything formulation (PERF_NOTES.md)."""
    x = X(ins)
    scale, bias = ins['Scale'][0], ins['Bias'][0]
    mean, var = ins['Mean'][0], ins['Variance'][0]
    eps = ctx.attr('epsilon', 1e-5)
    momentum = ctx.attr('momentum', 0.9)
    layout = ctx.attr('data_layout', 'NCHW')
    use_global = ctx.attr('use_global_stats', False) or ctx.is_test

    c_axis = 1 if layout == 'NCHW' else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if use_global:
        m, v = mean, var
        mean_out, var_out = mean, var
    else:
        xf = x.astype(jnp.float32)
        m = jnp.mean(xf, axis=red_axes)
        v = jnp.mean(jnp.square(xf), axis=red_axes) - jnp.square(m)
        mean_out = momentum * mean + (1.0 - momentum) * m
        var_out = momentum * var + (1.0 - momentum) * v
    inv = jax.lax.rsqrt(v + eps)
    kvec = inv * scale
    import os
    if os.environ.get('PTPU_PALLAS_BN', '0') not in ('', '0'):
        from . import pallas_bn
        if pallas_bn.supported(x, layout):
            y = pallas_bn.fused_bn_apply(x, kvec, bias - m * kvec, None)
            return {'Y': [y], 'MeanOut': [mean_out],
                    'VarianceOut': [var_out],
                    'SavedMean': [m], 'SavedVariance': [inv]}
    # pre-folded FMA y = x*k + (bias - m*k). In bf16 this rounds x*k
    # before the mean cancels, adding ~|m|*2^-8 absolute error — but a
    # bf16 x ALREADY carries (|m|+sigma)*2^-8 quantization from the
    # producing conv, so the floor is unchanged in order; the centered
    # (x-m)*k form measured 2.5% slower e2e for no floor improvement
    # (PERF_NOTES.md)
    k = kvec.astype(x.dtype).reshape(bshape)
    b = (bias - m * kvec).astype(x.dtype).reshape(bshape)
    y = x * k + b
    return {'Y': [y], 'MeanOut': [mean_out],
            'VarianceOut': [var_out],
            'SavedMean': [m], 'SavedVariance': [inv]}


@register('layer_norm')
def _layer_norm(ctx, ins):
    x_in = X(ins)
    x = amp.promote_f32(x_in)
    eps = ctx.attr('epsilon', 1e-5)
    axis = ctx.attr('begin_norm_axis', 1)
    red = tuple(range(axis, x.ndim))
    m = jnp.mean(x, axis=red, keepdims=True)
    v = jnp.mean(jnp.square(x - m), axis=red, keepdims=True)
    y = (x - m) * jax.lax.rsqrt(v + eps)
    norm_shape = x.shape[axis:]
    if ins.get('Scale') and ins['Scale'][0] is not None:
        y = y * ins['Scale'][0].reshape(norm_shape)
    if ins.get('Bias') and ins['Bias'][0] is not None:
        y = y + ins['Bias'][0].reshape(norm_shape)
    lead = int(np.prod(x.shape[:axis]))
    return {'Y': [amp.restore(y, x_in)], 'Mean': [m.reshape(lead)],
            'Variance': [v.reshape(lead)]}


@register('group_norm')
def _group_norm(ctx, ins):
    x_in = X(ins)  # NCHW
    x = amp.promote_f32(x_in)
    g = ctx.attr('groups')
    eps = ctx.attr('epsilon', 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    red = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=red, keepdims=True)
    v = jnp.mean(jnp.square(xg - m), axis=red, keepdims=True)
    y = ((xg - m) * jax.lax.rsqrt(v + eps)).reshape(x.shape)
    bshape = [1, c] + [1] * (x.ndim - 2)
    if ins.get('Scale') and ins['Scale'][0] is not None:
        y = y * ins['Scale'][0].reshape(bshape)
    if ins.get('Bias') and ins['Bias'][0] is not None:
        y = y + ins['Bias'][0].reshape(bshape)
    return {'Y': [amp.restore(y, x_in)], 'Mean': [m.reshape(n, g)],
            'Variance': [v.reshape(n, g)]}


@register('data_norm')
def _data_norm(ctx, ins):
    x = X(ins)
    bsum = ins['BatchSum'][0]
    bsize = ins['BatchSize'][0]
    bsquare = ins['BatchSquareSum'][0]
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsquare)
    y = (x - means) * scales
    return {'Y': [y], 'Means': [means], 'Scales': [scales]}


@register('lrn')
def _lrn(ctx, ins):
    x = X(ins)  # NCHW
    n_ = ctx.attr('n', 5)
    k = ctx.attr('k', 2.0)
    alpha = ctx.attr('alpha', 1e-4)
    beta = ctx.attr('beta', 0.75)
    sq = jnp.square(x)
    half = n_ // 2
    pad = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n_))
    mid = k + alpha * acc
    return {'Out': [x / jnp.power(mid, beta)], 'MidOut': [mid]}


@register('l2_normalize')
def _l2_normalize(ctx, ins):
    x = X(ins)
    axis = ctx.attr('axis', -1)
    eps = ctx.attr('epsilon', 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    return {'Out': [x / jnp.maximum(norm, eps)], 'Norm': [norm]}


@register('affine_channel')
def _affine_channel(ctx, ins):
    x = X(ins)
    layout = ctx.attr('data_layout', 'NCHW')
    c_axis = 1 if layout == 'NCHW' else x.ndim - 1
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    return {'Out': [x * ins['Scale'][0].reshape(shape)
                    + ins['Bias'][0].reshape(shape)]}


# ---------------------------------------------------------------------------
# embedding (ref: operators/lookup_table_op.cc). is_sparse/remote prefetch
# collapse into dense gather; sharded tables ride the mesh (see parallel/).
# ---------------------------------------------------------------------------
@register('lookup_table')
def _lookup_table(ctx, ins):
    w = ins['W'][0]
    ids = ins['Ids'][0]
    flat = ids.reshape(-1).astype(jnp.int32)
    out = jnp.take(w, flat, axis=0)
    pad = ctx.attr('padding_idx', -1)
    if pad is not None and pad != -1:
        if pad < 0:
            pad += w.shape[0]
        out = jnp.where((flat == pad)[:, None], 0.0, out)
    shape = ids.shape
    if shape[-1] == 1:
        shape = shape[:-1]
    return {'Out': [out.reshape(shape + (w.shape[1],))]}


@register('embedding')
def _embedding(ctx, ins):
    return _lookup_table(ctx, ins)


@register('lookup_table_grad', no_grad=True, lod='aware')
def _lookup_table_grad(ctx, ins):
    """Explicit grad: with is_sparse the table gradient is a SelectedRows
    (rows = the batch's ids, values = output cotangent rows) instead of a
    dense [V, D] scatter — the SelectedRows path of the reference
    (lookup_table_op.cc W@GRAD as SelectedRows, selected_rows_functor.h).
    Dense fallback matches the generic vjp."""
    from ..core.selected_rows import SelectedRowsVal
    from ..core.lod import unwrap as _unw
    a = ctx.attrs
    w_name = a['_fwd_inputs']['W'][0]
    ids_name = a['_fwd_inputs']['Ids'][0]
    out_name = a['_fwd_outputs']['Out'][0]
    gname = a['_in_grad_map'].get(w_name, '')
    if not gname:
        return
    g_out_name = a['_out_grad_map'].get(out_name, '')
    w = _unw(ctx.env(w_name))
    ids = _unw(ctx.env(ids_name))
    flat = ids.reshape(-1).astype(jnp.int32)
    if not g_out_name or g_out_name not in ctx.tracer.env:
        gv = jnp.zeros((flat.shape[0], w.shape[1]), w.dtype)
    else:
        gv = _unw(ctx.env(g_out_name)).reshape(flat.shape[0], w.shape[1])
    pad = ctx.attr('padding_idx', -1)
    if pad is not None and pad != -1:
        if pad < 0:
            pad += w.shape[0]
        gv = jnp.where((flat == pad)[:, None], 0.0, gv)
    if ctx.attr('is_sparse', False):
        return {'IN@GRAD': [SelectedRowsVal(flat, gv, w.shape[0])]}
    dense = jnp.zeros_like(w).at[flat].add(gv, mode='drop')
    return {'IN@GRAD': [dense]}


@register('embedding_grad', no_grad=True, lod='aware')
def _embedding_grad(ctx, ins):
    return _lookup_table_grad(ctx, ins)


@register('merge_selected_rows', no_grad=True, lod='none')
def _merge_selected_rows(ctx, ins):
    from ..core.selected_rows import SelectedRowsVal
    x = X(ins)
    if not isinstance(x, SelectedRowsVal):
        raise TypeError("merge_selected_rows expects SelectedRows input "
                        "(a sparse embedding gradient), got %r" % (x,))
    return {'Out': [x.merged()]}


@register('get_tensor_from_selected_rows', no_grad=True, lod='none')
def _get_tensor_from_selected_rows(ctx, ins):
    from ..core.selected_rows import SelectedRowsVal
    x = X(ins)
    if not isinstance(x, SelectedRowsVal):
        raise TypeError("get_tensor_from_selected_rows expects SelectedRows "
                        "input, got %r" % (x,))
    return {'Out': [x.values]}


# ---------------------------------------------------------------------------
# image resize (ref: operators/interpolate_op.cc)
# ---------------------------------------------------------------------------
def _out_hw(ctx, ins, x):
    if ins.get('OutSize') and ins['OutSize'][0] is not None:
        sz = np.asarray(ins['OutSize'][0])
        return int(sz[0]), int(sz[1])
    oh, ow = ctx.attr('out_h', -1), ctx.attr('out_w', -1)
    scale = ctx.attr('scale', 0.0)
    if (oh <= 0 or ow <= 0) and scale > 0:
        oh = int(x.shape[2] * scale)
        ow = int(x.shape[3] * scale)
    return oh, ow


def _src_index(out_len, in_len, align_corners, align_mode):
    i = jnp.arange(out_len, dtype=jnp.float32)
    if align_corners and out_len > 1:
        return i * (in_len - 1) / (out_len - 1)
    ratio = in_len / out_len
    if align_mode == 0:
        return jnp.clip((i + 0.5) * ratio - 0.5, 0.0)
    return i * ratio


@register('bilinear_interp')
def _bilinear_interp(ctx, ins):
    x = X(ins)
    oh, ow = _out_hw(ctx, ins, x)
    ac = ctx.attr('align_corners', True)
    am = ctx.attr('align_mode', 1)
    h, w = x.shape[2], x.shape[3]
    fy = _src_index(oh, h, ac, am)
    fx = _src_index(ow, w, ac, am)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x0 = jnp.floor(fx).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (fy - y0).reshape(1, 1, -1, 1)
    wx = (fx - x0).reshape(1, 1, 1, -1)
    g = lambda yi, xi: x[:, :, yi, :][:, :, :, xi]
    out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
           + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
    return {'Out': [out.astype(x.dtype)]}


@register('nearest_interp')
def _nearest_interp(ctx, ins):
    x = X(ins)
    oh, ow = _out_hw(ctx, ins, x)
    ac = ctx.attr('align_corners', True)
    h, w = x.shape[2], x.shape[3]
    fy = _src_index(oh, h, ac, 1)
    fx = _src_index(ow, w, ac, 1)
    yi = (jnp.round(fy) if ac else jnp.floor(fy)).astype(jnp.int32)
    xi = (jnp.round(fx) if ac else jnp.floor(fx)).astype(jnp.int32)
    yi = jnp.clip(yi, 0, h - 1)
    xi = jnp.clip(xi, 0, w - 1)
    return {'Out': [x[:, :, yi, :][:, :, :, xi]]}


@register('grid_sampler')
def _grid_sampler(ctx, ins):
    x = X(ins)           # [N, C, H, W]
    grid = ins['Grid'][0]  # [N, H', W', 2] in [-1, 1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx, wy = gx - x0, gy - y0

    def gather(yi, xi):
        yi = jnp.clip(yi, 0, h - 1)
        xi = jnp.clip(xi, 0, w - 1)
        bidx = jnp.arange(n).reshape(n, 1, 1)
        return x[bidx, :, yi, xi]  # [N, H', W', C]

    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
           + gather(y0, x1) * (wx * (1 - wy))[..., None]
           + gather(y1, x0) * ((1 - wx) * wy)[..., None]
           + gather(y1, x1) * (wx * wy)[..., None])
    return {'Output': [jnp.moveaxis(out, -1, 1)]}


@register('affine_grid')
def _affine_grid(ctx, ins):
    theta = ins['Theta'][0]  # [N, 2, 3]
    if ins.get('OutputShape') and ins['OutputShape'][0] is not None:
        shape = [int(s) for s in np.asarray(ins['OutputShape'][0])]
    else:
        shape = ctx.attr('output_shape')
    n, c, h, w = shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    out = jnp.einsum('nij,hwj->nhwi', theta, base)
    return {'Output': [out]}


def _flash_policy(seq, causal):
    """Measured v5e auto-selection (fwd+bwd timings, /tmp-sweep recorded in
    PERF_NOTES.md): the Pallas kernel WINS for non-causal 512<=S<=1024
    (q256/k512 blocks, 13-27% faster than the XLA composition) and is
    mandatory above S>=4096 where [B,H,S,S] materialization hits the HBM
    wall; the causal path loses at every measured S on this chip, so only
    memory forces it. Returns (use_flash, block_q, block_kv)."""
    if seq % 128 != 0:
        return False, 0, 0

    def fit(pref):  # largest preferred block that DIVIDES seq — the kernel
        return next(b for b in (pref, 256, 128) if seq % b == 0)  # rejects
    if causal:                                  # non-divisors outright
        return seq >= 4096, fit(512), fit(256)
    if 512 <= seq <= 1024 or seq >= 4096:
        return True, fit(256), fit(512)
    return False, 0, 0


@register('fused_multihead_attention', diff_inputs=('Q', 'K', 'V'))
def _fused_multihead_attention(ctx, ins):
    """TPU-native fused attention (beyond reference parity: the reference
    composes scaled_dot_product_attention from matmul/softmax ops,
    nets.py). On TPU, auto-selects the Pallas flash kernel where measured
    to win or memory-necessary (_flash_policy); elsewhere the
    composition. PTPU_FLASH_ATTN=0/1 forces. Q/K/V: [B, H, S, D]."""
    import os
    q, k, v = ins['Q'][0], ins['K'][0], ins['V'][0]
    causal = bool(ctx.attr('causal', False))
    scale = float(ctx.attr('scale', 1.0))
    if ctx.attr('sequence_parallel', False):
        from ..parallel.mesh import current_trace_mesh, SEQ_AXIS
        mesh = current_trace_mesh()
        if mesh is not None and mesh.shape.get(SEQ_AXIS, 1) > 1:
            from ..parallel.ring_attention import ring_attention
            return {'Out': [ring_attention(q, k, v, mesh, causal=causal,
                                           scale=scale)]}
        # no sp axis in the compile mesh: single-device semantics below
    on_tpu = any(d.platform in ('tpu', 'axon') for d in jax.devices())
    want, bq, bkv = _flash_policy(q.shape[2], causal)
    force = os.environ.get('PTPU_FLASH_ATTN', '')
    if force == '1':
        seq = q.shape[2]
        want = seq % 128 == 0
        bq = next(b for b in (256, 128) if seq % b == 0) if want else 0
        bkv = next(b for b in (512, 256, 128) if seq % b == 0) if want else 0
    elif force == '0':
        want = False
    if on_tpu and want:
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention, BlockSizes)
            bs = BlockSizes(
                block_q=bq, block_k_major=bkv, block_k=bkv, block_b=1,
                block_q_major_dkv=bq, block_k_major_dkv=bkv,
                block_k_dkv=bkv, block_q_dkv=bq,
                block_k_major_dq=bkv, block_k_dq=bkv, block_q_dq=bq)
            out = flash_attention(q * scale, k, v, causal=causal,
                                  block_sizes=bs)
            return {'Out': [out]}
        except (ImportError, NotImplementedError, ValueError) as e:
            # fall through to the O(S^2) composition — but say so: on long
            # sequences the fallback may be the OOM flash was avoiding
            import warnings
            warnings.warn("flash attention unavailable (%s); using the "
                          "naive O(S^2) attention composition" % (e,))
    s = jnp.einsum('bhqd,bhkd->bhqk', q * scale, k)
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        s = jnp.where(mask, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(amp.promote_f32(s), axis=-1)
    p = amp.restore(p, s)
    return {'Out': [jnp.einsum('bhqk,bhkd->bhqd', p, v)]}
