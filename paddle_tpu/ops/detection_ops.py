"""Detection op lowerings (ref: paddle/fluid/operators/detection/ — ~10k
LoC of CUDA/C++ across prior_box_op.cc, anchor_generator_op.cc,
iou_similarity_op.cc, box_coder_op.cc, bipartite_match_op.cc,
target_assign_op.cc, mine_hard_examples_op.cc, multiclass_nms_op.cc,
roi_align_op.cc, roi_pool_op.cc, psroi_pool_op.cc,
rpn_target_assign_op.cc, generate_proposals_op.cc,
generate_proposal_labels_op.cc, polygon_box_transform_op.cc,
roi_perspective_transform_op.cc, yolov3_loss_op.cc, detection_map_op.cc).

TPU-native designs:
- static shapes everywhere: NMS/proposal outputs are FIXED-capacity,
  padded with -1 labels / zero boxes (the reference emits data-dependent
  LoD; padding carries the same information, like the decode ops);
- greedy algorithms (bipartite match, NMS) are lax.fori_loop/scan over a
  static iteration count with masked argmax — no host loops;
- roi ops are vmapped bilinear/max sampling over a static roi count;
- ground-truth boxes arrive lod-packed like the reference; the lod is
  static structure (a handful of gt-count patterns per dataset).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core.lod import LoDArray, unwrap, lengths_to_offsets
from .math_ops import X


# ---------------------------------------------------------------------------
# priors / anchors — pure functions of feature-map shape + attrs
# ---------------------------------------------------------------------------
def _center_grid(h, w, step_h, step_w, offset):
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    return jnp.meshgrid(cx, cy)  # [h, w] each


@register('prior_box', no_grad=True)
def _prior_box(ctx, ins):
    x = ins['Input'][0]
    img = ins['Image'][0]
    h, w = x.shape[2], x.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    min_sizes = [float(v) for v in ctx.attr('min_sizes')]
    max_sizes = [float(v) for v in ctx.attr('max_sizes', []) or []]
    ars = [1.0]
    for ar in ctx.attr('aspect_ratios', []) or []:
        ar = float(ar)
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if ctx.attr('flip', False):
                ars.append(1.0 / ar)
    variances = [float(v) for v in ctx.attr('variances',
                                            [0.1, 0.1, 0.2, 0.2])]
    step_w = float(ctx.attr('step_w', 0) or 0) or float(img_w) / w
    step_h = float(ctx.attr('step_h', 0) or 0) or float(img_h) / h
    offset = float(ctx.attr('offset', 0.5))

    # per-location prior (w, h) list — reference order: per min_size: the
    # ar=1 prior, then other aspect ratios, then the max_size prior
    whs = []
    for i, ms in enumerate(min_sizes):
        whs.append((ms, ms))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if i < len(max_sizes):
            s = np.sqrt(ms * max_sizes[i])
            whs.append((s, s))
    num_priors = len(whs)
    cx, cy = _center_grid(h, w, step_h, step_w, offset)
    pw = jnp.asarray([p[0] for p in whs], jnp.float32) / 2.0
    ph = jnp.asarray([p[1] for p in whs], jnp.float32) / 2.0
    boxes = jnp.stack([
        (cx[..., None] - pw) / img_w, (cy[..., None] - ph) / img_h,
        (cx[..., None] + pw) / img_w, (cy[..., None] + ph) / img_h,
    ], axis=-1)  # [h, w, P, 4]
    if ctx.attr('clip', False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, num_priors, 4))
    return {'Boxes': [boxes], 'Variances': [var]}


@register('density_prior_box', no_grad=True)
def _density_prior_box(ctx, ins):
    x = ins['Input'][0]
    img = ins['Image'][0]
    h, w = x.shape[2], x.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    densities = [int(v) for v in ctx.attr('densities', []) or []]
    fixed_sizes = [float(v) for v in ctx.attr('fixed_sizes', []) or []]
    fixed_ratios = [float(v) for v in ctx.attr('fixed_ratios', []) or []]
    variances = [float(v) for v in ctx.attr('variances',
                                            [0.1, 0.1, 0.2, 0.2])]
    step_w = float(ctx.attr('step_w', 0) or 0) or float(img_w) / w
    step_h = float(ctx.attr('step_h', 0) or 0) or float(img_h) / h
    offset = float(ctx.attr('offset', 0.5))
    # density grid: each fixed_size spawns density^2 shifted centers per
    # ratio (ref density_prior_box_op.h)
    prior_list = []  # list of (shift_x, shift_y, half_w, half_h)
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio) / 2.0
            bh = size / np.sqrt(ratio) / 2.0
            dstep_w, dstep_h = step_w / density, step_h / density
            for di in range(density):
                for dj in range(density):
                    sx = -step_w / 2.0 + dstep_w / 2.0 + dj * dstep_w
                    sy = -step_h / 2.0 + dstep_h / 2.0 + di * dstep_h
                    prior_list.append((sx, sy, bw, bh))
    P = len(prior_list)
    cx, cy = _center_grid(h, w, step_h, step_w, offset)
    sx = jnp.asarray([p[0] for p in prior_list], jnp.float32)
    sy = jnp.asarray([p[1] for p in prior_list], jnp.float32)
    bw = jnp.asarray([p[2] for p in prior_list], jnp.float32)
    bh = jnp.asarray([p[3] for p in prior_list], jnp.float32)
    boxes = jnp.stack([
        (cx[..., None] + sx - bw) / img_w, (cy[..., None] + sy - bh) / img_h,
        (cx[..., None] + sx + bw) / img_w, (cy[..., None] + sy + bh) / img_h,
    ], axis=-1)
    if ctx.attr('clip', False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, P, 4))
    return {'Boxes': [boxes], 'Variances': [var]}


@register('anchor_generator', no_grad=True)
def _anchor_generator(ctx, ins):
    x = ins['Input'][0]
    h, w = x.shape[2], x.shape[3]
    sizes = [float(v) for v in ctx.attr('anchor_sizes')]
    ratios = [float(v) for v in ctx.attr('aspect_ratios')]
    variances = [float(v) for v in ctx.attr('variances',
                                            [0.1, 0.1, 0.2, 0.2])]
    stride = [float(v) for v in ctx.attr('stride')]
    offset = float(ctx.attr('offset', 0.5))
    whs = []
    for r in ratios:
        for s in sizes:
            area = stride[0] * stride[1]
            area_ratios = area / r
            base_w = np.round(np.sqrt(area_ratios))
            base_h = np.round(base_w * r)
            scale_w = s / stride[0]
            scale_h = s / stride[1]
            whs.append((scale_w * base_w, scale_h * base_h))
    A = len(whs)
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * stride[1]
    cx, cy = jnp.meshgrid(cx, cy)
    aw = jnp.asarray([p[0] for p in whs], jnp.float32) / 2.0
    ah = jnp.asarray([p[1] for p in whs], jnp.float32) / 2.0
    anchors = jnp.stack([
        cx[..., None] - aw,  # xmin
        cy[..., None] - ah,  # ymin
        cx[..., None] + aw,  # xmax
        cy[..., None] + ah,  # ymax
    ], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), (h, w, A, 4))
    return {'Anchors': [anchors], 'Variances': [var]}


# ---------------------------------------------------------------------------
# geometry: IoU / box coding
# ---------------------------------------------------------------------------
def _iou_matrix(a, b):
    """a [N,4], b [M,4] (xmin,ymin,xmax,ymax) -> IoU [N,M]."""
    ax0, ay0, ax1, ay1 = [a[:, i:i + 1] for i in range(4)]
    bx0, by0, bx1, by1 = [b[None, :, i] for i in range(4)]
    ix0 = jnp.maximum(ax0, bx0)
    iy0 = jnp.maximum(ay0, by0)
    ix1 = jnp.minimum(ax1, bx1)
    iy1 = jnp.minimum(ay1, by1)
    iw = jnp.maximum(ix1 - ix0, 0.0)
    ih = jnp.maximum(iy1 - iy0, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((ax1 - ax0) * (ay1 - ay0), 0.0)
    area_b = jnp.maximum((bx1 - bx0) * (by1 - by0), 0.0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register('iou_similarity', no_grad=True, lod='aware')
def _iou_similarity(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    out = _iou_matrix(unwrap(x), unwrap(y))
    if isinstance(x, LoDArray) and x.nlevels:
        return {'Out': [x.with_lod_of(out)]}
    return {'Out': [out]}


def _encode_center_size(target, prior, pvar, normalized=True):
    """target [N,4] vs prior [M,4] -> [N,M,4] (ref box_coder_op.h)."""
    plen = 0.0 if normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + plen
    ph = prior[:, 3] - prior[:, 1] + plen
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    tw = (target[:, 2] - target[:, 0] + plen)[:, None]
    th = (target[:, 3] - target[:, 1] + plen)[:, None]
    tcx = (target[:, 0])[:, None] + tw * 0.5
    tcy = (target[:, 1])[:, None] + th * 0.5
    out = jnp.stack([
        (tcx - pcx[None]) / pw[None],
        (tcy - pcy[None]) / ph[None],
        jnp.log(jnp.maximum(tw / pw[None], 1e-10)),
        jnp.log(jnp.maximum(th / ph[None], 1e-10)),
    ], axis=-1)
    if pvar is not None:
        out = out / pvar[None]
    return out


def _encode_rows(target, prior, pvar=None, normalized=True):
    """1:1 rowwise encode: target [K,4] against prior [K,4] -> [K,4]
    (avoids the [N,M,4] matrix when each target has one known prior)."""
    plen = 0.0 if normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + plen
    ph = prior[:, 3] - prior[:, 1] + plen
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    tw = target[:, 2] - target[:, 0] + plen
    th = target[:, 3] - target[:, 1] + plen
    tcx = target[:, 0] + tw * 0.5
    tcy = target[:, 1] + th * 0.5
    out = jnp.stack([(tcx - pcx) / jnp.maximum(pw, 1e-10),
                     (tcy - pcy) / jnp.maximum(ph, 1e-10),
                     jnp.log(jnp.maximum(tw / jnp.maximum(pw, 1e-10),
                                         1e-10)),
                     jnp.log(jnp.maximum(th / jnp.maximum(ph, 1e-10),
                                         1e-10))], axis=-1)
    if pvar is not None:
        out = out / pvar
    return out


def _decode_center_size(target, prior, pvar, normalized=True):
    """target [N,M,4] (or [N,4] broadcast) deltas -> boxes [N,M,4]."""
    plen = 0.0 if normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + plen
    ph = prior[:, 3] - prior[:, 1] + plen
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if target.ndim == 2:
        target = target[:, None, :] if target.shape[0] != prior.shape[0] \
            else target[None].reshape(1, prior.shape[0], 4)
    t = target if pvar is None else target * pvar[None]
    cx = t[..., 0] * pw + pcx
    cy = t[..., 1] * ph + pcy
    w = jnp.exp(t[..., 2]) * pw
    h = jnp.exp(t[..., 3]) * ph
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - plen, cy + h * 0.5 - plen], axis=-1)


@register('box_coder', no_grad=True, lod='aware')
def _box_coder(ctx, ins):
    prior = unwrap(ins['PriorBox'][0])
    pvar = None
    if ins.get('PriorBoxVar') and ins['PriorBoxVar'][0] is not None:
        pvar = unwrap(ins['PriorBoxVar'][0]).reshape(-1, 4)
    elif ctx.attr('variance'):
        # variance as a 4-list attr broadcasts over priors (ref box_coder)
        pvar = jnp.broadcast_to(
            jnp.asarray([float(v) for v in ctx.attr('variance')],
                        jnp.float32), (unwrap(ins['PriorBox'][0])
                                       .reshape(-1, 4).shape[0], 4))
    if int(ctx.attr('axis', 0)) != 0:
        raise NotImplementedError(
            "box_coder axis=1 (prior per batch row) is not supported; "
            "tile the priors instead")
    target_in = ins['TargetBox'][0]
    target = unwrap(target_in)
    code_type = ctx.attr('code_type', 'encode_center_size')
    normalized = ctx.attr('box_normalized', True)
    prior = prior.reshape(-1, 4)
    if 'encode' in code_type:
        out = _encode_center_size(target.reshape(-1, 4), prior, pvar,
                                  normalized)
        if isinstance(target_in, LoDArray) and target_in.nlevels:
            return {'OutputBox': [target_in.with_lod_of(out)]}
        return {'OutputBox': [out]}
    out = _decode_center_size(target.reshape(target.shape[0], -1, 4)
                              if target.ndim == 3 else target,
                              prior, pvar, normalized)
    if target.ndim == 2:
        out = out.reshape(target.shape)
    return {'OutputBox': [out]}


# ---------------------------------------------------------------------------
# matching / target assignment / hard mining
# ---------------------------------------------------------------------------
def _bipartite_match_one(dist):
    """Greedy global-max bipartite match (ref bipartite_match_op.cc
    BipartiteMatch): repeatedly take the global argmax of the remaining
    matrix; returns (match_idx [M] int32 row-or--1, match_dist [M])."""
    n, m = dist.shape
    steps = min(n, m)

    def body(_, carry):
        d, idx, dv = carry
        flat = jnp.argmax(d)
        r, c = flat // m, flat % m
        best = d[r, c]
        take = best > -1e9  # anything left?
        idx = jnp.where(take, idx.at[c].set(r.astype(jnp.int32)), idx)
        dv = jnp.where(take, dv.at[c].set(best), dv)
        d = jnp.where(take, d.at[r, :].set(-1e10).at[:, c].set(-1e10), d)
        return d, idx, dv

    idx0 = jnp.full((m,), -1, jnp.int32)
    dv0 = jnp.zeros((m,), dist.dtype)
    _, idx, dv = jax.lax.fori_loop(
        0, steps, body, (jnp.where(dist > 0, dist, -1e10), idx0, dv0))
    return idx, dv


def _argmax_match_one(dist, threshold):
    """per_prediction: col -> argmax row when above threshold."""
    best = jnp.max(dist, axis=0)
    idx = jnp.argmax(dist, axis=0).astype(jnp.int32)
    return jnp.where(best >= threshold, idx, -1), jnp.where(
        best >= threshold, best, 0.0)


@register('bipartite_match', no_grad=True, lod='aware')
def _bipartite_match(ctx, ins):
    x = ins['DistMat'][0]
    match_type = ctx.attr('match_type', 'bipartite')
    threshold = float(ctx.attr('dist_threshold', 0.5))
    dist = unwrap(x)
    m = dist.shape[1]
    if isinstance(x, LoDArray) and x.nlevels:
        off = np.asarray(x.lod[0], np.int64)
    else:
        off = np.asarray([0, dist.shape[0]], np.int64)
    idxs, dvs = [], []
    for i in range(len(off) - 1):
        d = dist[int(off[i]):int(off[i + 1])]
        idx, dv = _bipartite_match_one(d)
        if match_type == 'per_prediction':
            # keep bipartite winners, then add per-prediction extras
            aidx, adv = _argmax_match_one(d, threshold)
            extra = (idx < 0) & (aidx >= 0)
            idx = jnp.where(extra, aidx, idx)
            dv = jnp.where(extra, adv, dv)
        idxs.append(idx)
        dvs.append(dv)
    return {'ColToRowMatchIndices': [jnp.stack(idxs)],
            'ColToRowMatchDis': [jnp.stack(dvs)],
            'ColToRowMatchDist': [jnp.stack(dvs)]}


@register('target_assign', no_grad=True, lod='aware')
def _target_assign(ctx, ins):
    """Gather per-prior targets by match index (ref target_assign_op.h):
    Out[b, m] = X_rows_of_image_b[match[b, m]]; weight 1 where matched.
    NegIndices rows get weight 1 with mismatch_value targets."""
    x = ins['X'][0]
    match = unwrap(ins['MatchIndices'][0]).astype(jnp.int32)  # [B, M]
    mismatch_value = ctx.attr('mismatch_value', 0)
    xd = unwrap(x)
    B, M = match.shape
    per_prior = xd.ndim == 3  # e.g. encoded boxes [N_gt, M, K]
    k = xd.shape[-1] if xd.ndim > 1 else 1
    if not per_prior:
        xd = xd.reshape(-1, k)
    if isinstance(x, LoDArray) and x.nlevels:
        off = np.asarray(x.lod[0], np.int64)
    else:
        off = np.asarray([0, xd.shape[0]], np.int64)
    outs, wts = [], []
    cols = jnp.arange(M, dtype=jnp.int32)
    for b in range(B):
        base = int(off[b])
        rows = jnp.clip(match[b], 0, None) + base
        if per_prior:
            # ref target_assign_op.h: Out[b, m] = X[lod[b]+match[b,m], m]
            vals = xd[rows, cols]
        else:
            vals = jnp.take(xd, rows, axis=0)
        matched = match[b] >= 0
        vals = jnp.where(matched[:, None], vals,
                         jnp.asarray(mismatch_value, xd.dtype))
        outs.append(vals)
        wts.append(matched.astype(jnp.float32))
    out = jnp.stack(outs)           # [B, M, K]
    wt = jnp.stack(wts)[..., None]  # [B, M, 1]
    if ins.get('NegIndices') and ins['NegIndices'][0] is not None:
        neg = ins['NegIndices'][0]
        negd = unwrap(neg).reshape(-1).astype(jnp.int32)
        noff = np.asarray(neg.lod[0], np.int64) if isinstance(neg, LoDArray) \
            and neg.nlevels else np.asarray([0, negd.shape[0]], np.int64)
        for b in range(B):
            seg = negd[int(noff[b]):int(noff[b + 1])]
            # -1 padding must NOT wrap to the last prior: route to M (OOB)
            seg = jnp.where(seg >= 0, seg, M)
            wt = wt.at[b, seg, 0].set(1.0, mode='drop')
    return {'Out': [out], 'OutWeight': [wt]}


@register('mine_hard_examples', no_grad=True, lod='aware')
def _mine_hard_examples(ctx, ins):
    """Hard negative mining (ref mine_hard_examples_op.cc, max_negative):
    per image pick the top-(neg_pos_ratio x num_pos) unmatched priors by
    classification loss. Output NegIndices as a FIXED-capacity lod (one
    row span per image, capacity M), -1-padded."""
    cls_loss = unwrap(ins['ClsLoss'][0])           # [B, M]
    match = unwrap(ins['MatchIndices'][0])         # [B, M]
    loc_loss = None
    if ins.get('LocLoss') and ins['LocLoss'][0] is not None:
        loc_loss = unwrap(ins['LocLoss'][0])
    neg_pos_ratio = float(ctx.attr('neg_pos_ratio', 3.0))
    neg_overlap = float(ctx.attr('neg_dist_threshold', 0.5))
    B, M = cls_loss.shape
    loss = cls_loss if loc_loss is None else cls_loss + loc_loss
    if ctx.attr('mining_type', 'max_negative') == 'hard_example':
        # ref mine_hard_examples_op.cc kHardExample: EVERY prior is
        # eligible; take the top-min(sample_size, M) by (cls+loc) loss,
        # DEMOTE matched priors that did not make the cut (match -> -1),
        # and emit the selected unmatched ones as negatives (ascending
        # prior ids, like the reference's std::set ordering)
        sample_size = int(ctx.attr('sample_size', 0) or 0)
        if sample_size <= 0:
            raise ValueError(
                "mine_hard_examples: sample_size must be > 0 in "
                "hard_example mode (ref mine_hard_examples_op.cc:240)")
        neg_sel = min(sample_size, M)                 # static bound
        ranks = jnp.argsort(jnp.argsort(-loss, axis=1),
                            axis=1).astype(jnp.int32)  # desc position
        sel = ranks < neg_sel                          # [B, M]
        updated = jnp.where((match >= 0) & ~sel, -1, match)
        negm = (match < 0) & sel
        vals = jnp.where(negm, jnp.arange(M, dtype=jnp.int32)[None, :], M)
        vals = jnp.sort(vals, axis=1)
        neg_idx = jnp.where(vals < M, vals, -1)
        lod = lengths_to_offsets([M] * B)
        return {'NegIndices': [LoDArray(neg_idx.reshape(-1, 1), (lod,))],
                'UpdatedMatchIndices': [updated]}
    dist = None
    if ins.get('MatchDist') and ins['MatchDist'][0] is not None:
        dist = unwrap(ins['MatchDist'][0])
    is_neg = match < 0
    if dist is not None:
        is_neg &= dist < neg_overlap
    num_pos = jnp.sum((match >= 0).astype(jnp.int32), axis=1)   # [B]
    num_neg = jnp.minimum((num_pos.astype(jnp.float32)
                           * neg_pos_ratio).astype(jnp.int32),
                          jnp.sum(is_neg.astype(jnp.int32), axis=1))
    masked = jnp.where(is_neg, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1).astype(jnp.int32)      # [B, M]
    rank = jnp.arange(M, dtype=jnp.int32)[None, :]
    keep = rank < num_neg[:, None]
    neg_idx = jnp.where(keep, order, -1)                        # [B, M]
    lod = lengths_to_offsets([M] * B)
    return {'NegIndices': [LoDArray(neg_idx.reshape(-1, 1), (lod,))],
            'UpdatedMatchIndices': [match]}


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------
def _nms_mask(boxes, scores, iou_threshold, top_k):
    """Greedy NMS over boxes sorted by score. Returns (order, keep_mask)
    of length top_k (static)."""
    order = jnp.argsort(-scores)[:top_k]
    b = jnp.take(boxes, order, axis=0)
    s = jnp.take(scores, order)
    iou = _iou_matrix(b, b)
    K = b.shape[0]

    def body(i, keep):
        # suppressed if any kept higher-scoring box overlaps > threshold
        over = (iou[:, i] > iou_threshold) & keep & \
            (jnp.arange(K) < i)
        return keep.at[i].set(~jnp.any(over) & keep[i])

    keep0 = s > -jnp.inf
    keep = jax.lax.fori_loop(0, K, body, keep0)
    return order, keep, s


@register('multiclass_nms', no_grad=True, lod='aware')
def _multiclass_nms(ctx, ins):
    """Per-class NMS + cross-class keep_top_k (ref multiclass_nms_op.cc).
    Output is a FIXED keep_top_k rows per image [label, score, x0,y0,x1,y1],
    label -1 on padding rows; lod = keep_top_k per image."""
    bboxes = unwrap(ins['BBoxes'][0])   # [B, M, 4]
    scores = unwrap(ins['Scores'][0])   # [B, C, M]
    bg = int(ctx.attr('background_label', 0))
    score_thresh = float(ctx.attr('score_threshold', 0.01))
    nms_top_k = int(ctx.attr('nms_top_k', 400))
    nms_thresh = float(ctx.attr('nms_threshold', 0.3))
    keep_top_k = int(ctx.attr('keep_top_k', 200))
    B, C, M = scores.shape
    nms_top_k = min(nms_top_k if nms_top_k > 0 else M, M)
    n_fg_classes = C - (1 if 0 <= bg < C else 0)
    cap = n_fg_classes * nms_top_k
    keep_top_k = min(keep_top_k, cap) if keep_top_k > 0 else cap

    def one_image(boxes, sc):
        rows = []
        for c in range(C):
            if c == bg:
                continue
            s = jnp.where(sc[c] >= score_thresh, sc[c], -jnp.inf)
            order, keep, ss = _nms_mask(boxes, s, nms_thresh, nms_top_k)
            kept_boxes = jnp.take(boxes, order, axis=0)
            valid = keep & jnp.isfinite(ss)
            rows.append(jnp.concatenate([
                jnp.where(valid, float(c), -1.0)[:, None],
                jnp.where(valid, ss, -jnp.inf)[:, None],
                kept_boxes], axis=1))
        allr = jnp.concatenate(rows, axis=0)    # [(C-1)*K, 6]
        top = jnp.argsort(-allr[:, 1])[:keep_top_k]
        out = jnp.take(allr, top, axis=0)
        pad = ~jnp.isfinite(out[:, 1])
        out = jnp.concatenate([
            jnp.where(pad, -1.0, out[:, 0])[:, None],
            jnp.where(pad, 0.0, out[:, 1])[:, None],
            jnp.where(pad[:, None], 0.0, out[:, 2:])], axis=1)
        return out

    outs = jax.vmap(one_image)(bboxes, scores)  # [B, keep_top_k, 6]
    lod = lengths_to_offsets([keep_top_k] * B)
    return {'Out': [LoDArray(outs.reshape(B * keep_top_k, 6), (lod,))]}


# ---------------------------------------------------------------------------
# ROI ops — vmapped sampling over a static roi count
# ---------------------------------------------------------------------------
def _roi_batch_ids(rois, nimg):
    """Batch id per roi from the rois' lod (static)."""
    if isinstance(rois, LoDArray) and rois.nlevels:
        off = np.asarray(rois.lod[0], np.int64)
        lens = off[1:] - off[:-1]
        return np.repeat(np.arange(len(lens)), lens).astype(np.int32)
    return np.zeros(unwrap(rois).shape[0], np.int32)


def _bilinear(img, y, x):
    """img [C, H, W]; y, x scalar float coords -> [C]."""
    H, W = img.shape[1], img.shape[2]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly, lx = y - y0, x - x0
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
    v = (img[:, y0i, x0i] * (1 - ly) * (1 - lx)
         + img[:, y1i, x0i] * ly * (1 - lx)
         + img[:, y0i, x1i] * (1 - ly) * lx
         + img[:, y1i, x1i] * ly * lx)
    return jnp.where((y >= -1.0) & (y <= H) & (x >= -1.0) & (x <= W), v, 0.0)


@register('roi_align', lod='aware')
def _roi_align(ctx, ins):
    """ref roi_align_op: average of sampling_ratio^2 bilinear samples per
    output bin."""
    x = unwrap(ins['X'][0])            # [N, C, H, W]
    rois_in = ins['ROIs'][0]
    rois = unwrap(rois_in).reshape(-1, 4)
    ph = int(ctx.attr('pooled_height', 1))
    pw = int(ctx.attr('pooled_width', 1))
    scale = float(ctx.attr('spatial_scale', 1.0))
    ratio = int(ctx.attr('sampling_ratio', -1))
    bids = jnp.asarray(_roi_batch_ids(rois_in, x.shape[0]))

    def one(roi, bid):
        img = x[bid]
        x0, y0, x1, y1 = roi * scale
        rw = jnp.maximum(x1 - x0, 1.0)
        rh = jnp.maximum(y1 - y0, 1.0)
        bin_w, bin_h = rw / pw, rh / ph
        r = ratio if ratio > 0 else 2
        iy = (jnp.arange(ph)[:, None, None, None] * bin_h + y0
              + (jnp.arange(r)[None, None, :, None] + 0.5) * bin_h / r)
        ix = (jnp.arange(pw)[None, :, None, None] * bin_w + x0
              + (jnp.arange(r)[None, None, None, :] + 0.5) * bin_w / r)
        iy = jnp.broadcast_to(iy, (ph, pw, r, r)).reshape(-1)
        ix = jnp.broadcast_to(ix, (ph, pw, r, r)).reshape(-1)
        vals = jax.vmap(lambda yy, xx: _bilinear(img, yy, xx))(iy, ix)
        return vals.reshape(ph, pw, r * r, -1).mean(axis=2) \
            .transpose(2, 0, 1)  # [C, ph, pw]

    out = jax.vmap(one)(rois, bids)
    return {'Out': [out]}


@register('roi_pool', lod='aware')
def _roi_pool(ctx, ins):
    """ref roi_pool_op: max over each quantized bin."""
    x = unwrap(ins['X'][0])
    rois_in = ins['ROIs'][0]
    rois = unwrap(rois_in).reshape(-1, 4)
    ph = int(ctx.attr('pooled_height', 1))
    pw = int(ctx.attr('pooled_width', 1))
    scale = float(ctx.attr('spatial_scale', 1.0))
    bids = jnp.asarray(_roi_batch_ids(rois_in, x.shape[0]))
    H, W = x.shape[2], x.shape[3]
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one(roi, bid):
        img = x[bid]                      # [C, H, W]
        rx0 = jnp.round(roi[0] * scale)
        ry0 = jnp.round(roi[1] * scale)
        rx1 = jnp.round(roi[2] * scale)
        ry1 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(rx1 - rx0 + 1, 1.0)
        rh = jnp.maximum(ry1 - ry0 + 1, 1.0)
        # bin of each pixel relative to this roi; mask pixels outside
        by = jnp.floor((ys - ry0) * ph / rh)
        bx = jnp.floor((xs - rx0) * pw / rw)
        inside_y = (ys >= ry0) & (ys <= ry1)
        inside_x = (xs >= rx0) & (xs <= rx1)
        out = jnp.full((img.shape[0], ph, pw), -jnp.inf, img.dtype)
        byc = jnp.clip(by, 0, ph - 1).astype(jnp.int32)
        bxc = jnp.clip(bx, 0, pw - 1).astype(jnp.int32)
        # scatter-max pixels into their bins
        yy, xx = jnp.meshgrid(jnp.arange(H), jnp.arange(W), indexing='ij')
        mask = inside_y[:, None] & inside_x[None, :]
        vals = jnp.where(mask[None], img, -jnp.inf)
        out = out.at[:, byc[yy].reshape(-1), bxc[xx].reshape(-1)].max(
            vals.reshape(img.shape[0], -1))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = jax.vmap(one)(rois, bids)
    return {'Out': [out], 'Argmax': None}


@register('psroi_pool', lod='aware')
def _psroi_pool(ctx, ins):
    """Position-sensitive roi pooling (ref psroi_pool_op): channel block
    (i,j) pools bin (i,j) only; average pooling."""
    x = unwrap(ins['X'][0])            # [N, C=out_c*ph*pw, H, W]
    rois_in = ins['ROIs'][0]
    rois = unwrap(rois_in).reshape(-1, 4)
    out_c = int(ctx.attr('output_channels'))
    ph = int(ctx.attr('pooled_height', 1))
    pw = int(ctx.attr('pooled_width', 1))
    scale = float(ctx.attr('spatial_scale', 1.0))
    bids = jnp.asarray(_roi_batch_ids(rois_in, x.shape[0]))
    H, W = x.shape[2], x.shape[3]
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one(roi, bid):
        img = x[bid].reshape(out_c, ph, pw, H, W)
        rx0, ry0 = roi[0] * scale, roi[1] * scale
        rw = jnp.maximum(roi[2] * scale - rx0, 0.1)
        rh = jnp.maximum(roi[3] * scale - ry0, 0.1)
        by = jnp.floor((ys - ry0) * ph / rh)
        bx = jnp.floor((xs - rx0) * pw / rw)
        outs = []
        for i in range(ph):
            row = []
            for j in range(pw):
                m = ((by == i)[:, None] & (bx == j)[None, :]).astype(
                    img.dtype)
                s = jnp.sum(img[:, i, j] * m[None], axis=(1, 2))
                cnt = jnp.maximum(jnp.sum(m), 1.0)
                row.append(s / cnt)
            outs.append(jnp.stack(row, axis=-1))
        return jnp.stack(outs, axis=1)  # [out_c, ph, pw]

    out = jax.vmap(one)(rois, bids)
    return {'Out': [out]}


# ---------------------------------------------------------------------------
# RPN: target assign / proposals / proposal labels
# ---------------------------------------------------------------------------
def _sample_topk_random(mask, count, key):
    """Pick up to `count` True positions uniformly at random: random scores
    on masked entries, take top-count (static). Returns int32 [capacity]
    index vector, -1-padded, capacity = mask size."""
    n = mask.shape[0]
    scores = jnp.where(mask, jax.random.uniform(key, (n,)), -jnp.inf)
    order = jnp.argsort(-scores).astype(jnp.int32)
    rank = jnp.arange(n)
    avail = jnp.sum(mask.astype(jnp.int32))
    take = jnp.minimum(count, avail)
    return jnp.where(rank < take, order, -1)


@register('rpn_target_assign', no_grad=True, lod='aware')
def _rpn_target_assign(ctx, ins):
    """ref rpn_target_assign_op.cc: label anchors fg/bg by IoU with gt,
    subsample to rpn_batch_size_per_im with fg_fraction. Static design:
    outputs are FIXED capacity (batch_size_per_im per image), -1-padded
    index vectors + gathered targets."""
    anchors = unwrap(ins['Anchor'][0]).reshape(-1, 4)
    gt = ins['GtBoxes'][0]
    gtd = unwrap(gt).reshape(-1, 4)
    off = np.asarray(gt.lod[0], np.int64) if isinstance(gt, LoDArray) \
        and gt.nlevels else np.asarray([0, gtd.shape[0]], np.int64)
    bs = int(ctx.attr('rpn_batch_size_per_im', 256))
    fg_frac = float(ctx.attr('rpn_fg_fraction', 0.5))
    pos_thresh = float(ctx.attr('rpn_positive_overlap', 0.7))
    neg_thresh = float(ctx.attr('rpn_negative_overlap', 0.3))
    A = anchors.shape[0]
    key = ctx.rng()
    loc_idx, score_idx, tgt_lbl, tgt_bbox, bbox_iw = [], [], [], [], []
    for b in range(len(off) - 1):
        g = gtd[int(off[b]):int(off[b + 1])]
        iou = _iou_matrix(anchors, g)           # [A, G]
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        fg = best >= pos_thresh
        # every gt's best anchor is fg (ref: keep at least one per gt)
        fg = fg.at[jnp.argmax(iou, axis=0)].set(True)
        bg = (best < neg_thresh) & ~fg
        k1, k2, key = jax.random.split(key, 3)
        n_fg = int(bs * fg_frac)
        n_bg = bs - n_fg
        fg_sel = _sample_topk_random(fg, n_fg, k1)[:n_fg]   # [n_fg], -1 pad
        bg_sel = _sample_topk_random(bg, n_bg, k2)[:n_bg]
        fg_valid = fg_sel >= 0
        # LocationIndex pairs 1:1 with TargetBBox rows (n_fg per image);
        # invalid slots point at anchor 0 with zero inside-weight
        loc_idx.append(jnp.where(fg_valid, fg_sel, 0) + b * A)
        both = jnp.concatenate([fg_sel, bg_sel])
        score_idx.append(jnp.where(both >= 0, both, 0) + b * A)
        lbl = jnp.concatenate([jnp.ones((n_fg,), jnp.int32),
                               jnp.zeros((n_bg,), jnp.int32)])
        lbl = jnp.where(both >= 0, lbl, -1)   # -1 = ignore
        tgt_lbl.append(lbl)
        fg_clip = jnp.where(fg_valid, fg_sel, 0)
        gsel = jnp.take(best_gt, fg_clip)
        tb = _encode_rows(jnp.take(g, gsel, axis=0),
                          jnp.take(anchors, fg_clip, axis=0))
        tgt_bbox.append(jnp.where(fg_valid[:, None], tb, 0.0))
        in_w = fg_valid.astype(jnp.float32)[:, None] * jnp.ones((1, 4))
        bbox_iw.append(in_w)
    return {'LocationIndex': [jnp.concatenate(loc_idx)],
            'ScoreIndex': [jnp.concatenate(score_idx)],
            'TargetLabel': [jnp.concatenate(tgt_lbl).reshape(-1, 1)],
            'TargetBBox': [jnp.concatenate(tgt_bbox)],
            'BBoxInsideWeight': [jnp.concatenate(bbox_iw)]}


@register('generate_proposals', no_grad=True, lod='aware')
def _generate_proposals(ctx, ins):
    """ref generate_proposals_op.cc: decode RPN deltas at every anchor,
    clip to image, pre-NMS top-k, NMS, post-NMS top-k. Fixed capacity:
    post_nms_topN rois per image, zero-padded."""
    scores = unwrap(ins['Scores'][0])       # [N, A, H, W]
    deltas = unwrap(ins['BboxDeltas'][0])   # [N, A*4, H, W]
    im_info = unwrap(ins['ImInfo'][0])      # [N, 3] (h, w, scale)
    anchors = unwrap(ins['Anchors'][0]).reshape(-1, 4)
    variances = unwrap(ins['Variances'][0]).reshape(-1, 4) \
        if ins.get('Variances') and ins['Variances'][0] is not None else None
    pre_n = int(ctx.attr('pre_nms_topN', 6000))
    post_n = int(ctx.attr('post_nms_topN', 1000))
    thresh = float(ctx.attr('nms_thresh', 0.7))
    min_size = float(ctx.attr('min_size', 0.1))
    N = scores.shape[0]
    K = anchors.shape[0]
    post_n = min(post_n, K)  # lod rows must match actual capacity
    # layout: [N, A*4, H, W] -> [N, H, W, A, 4] -> [N, K, 4]
    A4 = deltas.shape[1]
    A = A4 // 4
    dl = deltas.reshape(N, A, 4, deltas.shape[2], deltas.shape[3])
    dl = jnp.transpose(dl, (0, 3, 4, 1, 2)).reshape(N, -1, 4)
    sc = jnp.transpose(scores.reshape(N, A, scores.shape[2],
                                      scores.shape[3]),
                       (0, 2, 3, 1)).reshape(N, -1)
    pre_n = min(pre_n, K)

    def one(s, d, info):
        boxes = _decode_center_size(d[None], anchors, variances)[0]  # [K,4]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=1)
        # drop degenerate proposals (ref FilterBoxes): side < min_size
        # in original-image scale (info[2] = im_scale)
        ms = min_size * info[2]
        ok = ((boxes[:, 2] - boxes[:, 0] + 1 >= ms)
              & (boxes[:, 3] - boxes[:, 1] + 1 >= ms))
        s = jnp.where(ok, s, -jnp.inf)
        order, keep, ss = _nms_mask(boxes, s, thresh, pre_n)
        keep = keep & jnp.isfinite(ss)
        kept = jnp.take(boxes, order, axis=0)
        sel = jnp.argsort(-jnp.where(keep, ss, -jnp.inf))[:post_n]
        rois = jnp.take(kept, sel, axis=0)
        probs = jnp.take(jnp.where(keep, ss, 0.0), sel)
        valid = jnp.take(keep, sel)
        return jnp.where(valid[:, None], rois, 0.0), \
            jnp.where(valid, probs, 0.0)

    rois, probs = jax.vmap(one)(sc, dl, im_info)
    lod = lengths_to_offsets([post_n] * N)
    return {'RpnRois': [LoDArray(rois.reshape(-1, 4), (lod,))],
            'RpnRoiProbs': [LoDArray(probs.reshape(-1, 1), (lod,))]}


@register('generate_proposal_labels', no_grad=True, lod='aware')
def _generate_proposal_labels(ctx, ins):
    """ref generate_proposal_labels_op.cc: sample rois vs gt into
    foreground/background with targets for the RCNN head. Fixed capacity
    batch_size_per_im per image."""
    rois_in = ins['RpnRois'][0]
    rois = unwrap(rois_in).reshape(-1, 4)
    gt_classes = unwrap(ins['GtClasses'][0]).reshape(-1).astype(jnp.int32)
    gt_boxes_in = ins['GtBoxes'][0]
    gt_boxes = unwrap(gt_boxes_in).reshape(-1, 4)
    roff = np.asarray(rois_in.lod[0], np.int64) \
        if isinstance(rois_in, LoDArray) and rois_in.nlevels \
        else np.asarray([0, rois.shape[0]], np.int64)
    goff = np.asarray(gt_boxes_in.lod[0], np.int64) \
        if isinstance(gt_boxes_in, LoDArray) and gt_boxes_in.nlevels \
        else np.asarray([0, gt_boxes.shape[0]], np.int64)
    bs = int(ctx.attr('batch_size_per_im', 256))
    fg_frac = float(ctx.attr('fg_fraction', 0.25))
    fg_thresh = float(ctx.attr('fg_thresh', 0.5))
    bg_hi = float(ctx.attr('bg_thresh_hi', 0.5))
    bg_lo = float(ctx.attr('bg_thresh_lo', 0.0))
    class_nums = int(ctx.attr('class_nums', 81))
    key = ctx.rng()
    out_rois, out_lbl, out_tgt, out_iw, out_ow = [], [], [], [], []
    B = len(roff) - 1
    for b in range(B):
        r = rois[int(roff[b]):int(roff[b + 1])]
        g = gt_boxes[int(goff[b]):int(goff[b + 1])]
        gc = gt_classes[int(goff[b]):int(goff[b + 1])]
        r = jnp.concatenate([r, g], axis=0)  # gt boxes join the roi pool
        iou = _iou_matrix(r, g)
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        fg = best >= fg_thresh
        bg = (best < bg_hi) & (best >= bg_lo)
        k1, k2, key = jax.random.split(key, 3)
        n_fg = int(bs * fg_frac)
        n_bg = bs - n_fg
        fg_sel = _sample_topk_random(fg, n_fg, k1)[:n_fg]
        bg_sel = _sample_topk_random(bg, n_bg, k2)[:n_bg]
        sel = jnp.concatenate([fg_sel, bg_sel])
        valid = sel >= 0
        selc = jnp.clip(sel, 0, None)
        rs = jnp.take(r, selc, axis=0) * valid[:, None]
        lbl = jnp.take(gc, jnp.take(best_gt, selc))
        isfg = jnp.arange(bs) < n_fg
        lbl = jnp.where(isfg & valid, lbl, 0)
        tgt = _encode_rows(jnp.take(g, jnp.take(best_gt, selc), axis=0), rs)
        # expand to per-class targets (ref bbox_targets [bs, 4*class_nums])
        tgt_full = jnp.zeros((bs, 4 * class_nums), tgt.dtype)
        colbase = jnp.clip(lbl, 0, class_nums - 1) * 4
        rowi = jnp.arange(bs)
        for j in range(4):
            tgt_full = tgt_full.at[rowi, colbase + j].set(
                jnp.where(isfg & valid, tgt[:, j], 0.0))
        w = (isfg & valid).astype(jnp.float32)[:, None] * jnp.ones((1, 4))
        w_full = jnp.zeros((bs, 4 * class_nums), jnp.float32)
        for j in range(4):
            w_full = w_full.at[rowi, colbase + j].set(w[:, j])
        out_rois.append(rs)
        out_lbl.append(lbl)
        out_tgt.append(tgt_full)
        out_iw.append(w_full)
        out_ow.append(w_full)
    lod = lengths_to_offsets([bs] * B)
    return {'Rois': [LoDArray(jnp.concatenate(out_rois), (lod,))],
            'LabelsInt32': [LoDArray(
                jnp.concatenate(out_lbl).reshape(-1, 1), (lod,))],
            'BboxTargets': [LoDArray(jnp.concatenate(out_tgt), (lod,))],
            'BboxInsideWeights': [LoDArray(jnp.concatenate(out_iw), (lod,))],
            'BboxOutsideWeights': [LoDArray(jnp.concatenate(out_ow),
                                            (lod,))]}


# ---------------------------------------------------------------------------
# geometric transforms
# ---------------------------------------------------------------------------
@register('polygon_box_transform', no_grad=True)
def _polygon_box_transform(ctx, ins):
    """ref polygon_box_transform_op: EAST geometry — input channel 2k is an
    x-offset, 2k+1 a y-offset; output = absolute corner coordinate
    (4*pixel_coord - offset)."""
    x = ins['Input'][0] if 'Input' in ins else X(ins)  # [N, 2K, H, W]
    n, c, h, w = x.shape
    xx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    yy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    coord = jnp.where(is_x, xx, yy)
    return {'Output': [4 * coord - x]}


@register('roi_perspective_transform', no_grad=True, lod='aware')
def _roi_perspective_transform(ctx, ins):
    """ref roi_perspective_transform_op: warp each quadrilateral roi
    ([x1..y4], 8 values) to a fixed output grid via the perspective
    transform, bilinear-sampled."""
    x = unwrap(ins['X'][0])            # [N, C, H, W]
    rois_in = ins['ROIs'][0]
    rois = unwrap(rois_in).reshape(-1, 8)
    th = int(ctx.attr('transformed_height'))
    tw = int(ctx.attr('transformed_width'))
    scale = float(ctx.attr('spatial_scale', 1.0))
    bids = jnp.asarray(_roi_batch_ids(rois_in, x.shape[0]))

    def one(quad, bid):
        img = x[bid]
        q = (quad * scale).reshape(4, 2)  # tl, tr, br, bl
        gy = jnp.arange(th, dtype=jnp.float32) / max(th - 1, 1)
        gx = jnp.arange(tw, dtype=jnp.float32) / max(tw - 1, 1)
        gyy, gxx = jnp.meshgrid(gy, gx, indexing='ij')
        # bilinear interpolation of the quad corners (projective approx)
        px = ((1 - gyy) * ((1 - gxx) * q[0, 0] + gxx * q[1, 0])
              + gyy * ((1 - gxx) * q[3, 0] + gxx * q[2, 0]))
        py = ((1 - gyy) * ((1 - gxx) * q[0, 1] + gxx * q[1, 1])
              + gyy * ((1 - gxx) * q[3, 1] + gxx * q[2, 1]))
        vals = jax.vmap(lambda yy2, xx2: _bilinear(img, yy2, xx2))(
            py.reshape(-1), px.reshape(-1))
        return vals.reshape(th, tw, -1).transpose(2, 0, 1)

    out = jax.vmap(one)(rois, bids)
    return {'Out': [out]}


# ---------------------------------------------------------------------------
# YOLOv3 loss
# ---------------------------------------------------------------------------
@register('yolov3_loss', lod='aware', diff_inputs=('X',))
def _yolov3_loss(ctx, ins):
    """ref yolov3_loss_op.h: per-cell anchor-box objectness + box + class
    loss. GTBox [N, B, 4] (cx, cy, w, h relative), GTLabel [N, B]."""
    x = unwrap(ins['X'][0])                # [N, A*(5+C), H, W]
    gtbox = unwrap(ins['GTBox'][0])        # [N, B, 4]
    gtlabel = unwrap(ins['GTLabel'][0]).astype(jnp.int32)
    anchors = [float(v) for v in ctx.attr('anchors')]
    mask = [int(v) for v in ctx.attr('anchor_mask',
                                     list(range(len(anchors) // 2)))]
    C = int(ctx.attr('class_num'))
    ignore = float(ctx.attr('ignore_thresh', 0.7))
    down = int(ctx.attr('downsample_ratio', 32))
    N, _, H, W = x.shape
    A = len(mask)
    x = x.reshape(N, A, 5 + C, H, W)
    px = jax.nn.sigmoid(x[:, :, 0])
    py = jax.nn.sigmoid(x[:, :, 1])
    pw = x[:, :, 2]
    ph = x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]
    an_w = jnp.asarray([anchors[2 * m] for m in mask], jnp.float32)
    an_h = jnp.asarray([anchors[2 * m + 1] for m in mask], jnp.float32)
    in_w, in_h = W * down, H * down

    # predicted boxes (relative) for ignore-mask IoU
    gx = (jnp.arange(W, dtype=jnp.float32)[None, None, None, :] + px) / W
    gy = (jnp.arange(H, dtype=jnp.float32)[None, None, :, None] + py) / H
    gw = jnp.exp(pw) * an_w[None, :, None, None] / in_w
    gh = jnp.exp(ph) * an_h[None, :, None, None] / in_h
    pred = jnp.stack([gx - gw / 2, gy - gh / 2, gx + gw / 2, gy + gh / 2],
                     axis=-1)                      # [N, A, H, W, 4]
    gt_xyxy = jnp.stack([
        gtbox[..., 0] - gtbox[..., 2] / 2, gtbox[..., 1] - gtbox[..., 3] / 2,
        gtbox[..., 0] + gtbox[..., 2] / 2, gtbox[..., 1] + gtbox[..., 3] / 2,
    ], axis=-1)                                    # [N, B, 4]

    def per_img(pred_i, gt_i, gl_i, px_i, py_i, pw_i, ph_i, pobj_i, pcls_i):
        iou = _iou_matrix(pred_i.reshape(-1, 4), gt_i)  # [AHW, B]
        best = jnp.max(iou, axis=1).reshape(A, H, W)
        noobj_mask = best < ignore
        # responsible cell/anchor per gt
        valid_gt = gt_i[:, 2] > gt_i[:, 0]
        gi = jnp.clip((gt_i[:, 0] + gt_i[:, 2]) / 2 * W, 0,
                      W - 1).astype(jnp.int32)
        gj = jnp.clip((gt_i[:, 1] + gt_i[:, 3]) / 2 * H, 0,
                      H - 1).astype(jnp.int32)
        gtw = (gt_i[:, 2] - gt_i[:, 0]) * in_w
        gth = (gt_i[:, 3] - gt_i[:, 1]) * in_h
        # best anchor by shape IoU
        inter = (jnp.minimum(gtw[:, None], an_w[None]) *
                 jnp.minimum(gth[:, None], an_h[None]))
        union = gtw[:, None] * gth[:, None] + an_w[None] * an_h[None] - inter
        ba = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=1)
        tx = (gt_i[:, 0] + gt_i[:, 2]) / 2 * W - gi
        ty = (gt_i[:, 1] + gt_i[:, 3]) / 2 * H - gj
        tw_t = jnp.log(jnp.maximum(gtw / jnp.take(an_w, ba), 1e-10))
        th_t = jnp.log(jnp.maximum(gth / jnp.take(an_h, ba), 1e-10))
        wgt = 2.0 - (gtw / in_w) * (gth / in_h)
        sq = lambda p, t: jnp.square(p[ba, gj, gi] - t)
        loc = jnp.sum(jnp.where(valid_gt, (sq(px_i, tx) + sq(py_i, ty)
                                           + sq(pw_i, tw_t)
                                           + sq(ph_i, th_t)) * wgt, 0.0))
        # objectness: BCE; positives at responsible cells, negatives where
        # below ignore threshold
        obj_mask = jnp.zeros((A, H, W), bool).at[ba, gj, gi].set(
            valid_gt, mode='drop')
        bce = lambda lg, t: jax.nn.softplus(lg) - t * lg
        obj = jnp.sum(jnp.where(obj_mask, bce(pobj_i, 1.0), 0.0)) + \
            jnp.sum(jnp.where(~obj_mask & noobj_mask, bce(pobj_i, 0.0), 0.0))
        # class: BCE over C at responsible cells
        onehot = jax.nn.one_hot(gl_i, C, dtype=pcls_i.dtype)   # [B, C]
        pc = pcls_i[ba, :, gj, gi]                             # [B, C]
        cls = jnp.sum(jnp.where(valid_gt[:, None],
                                bce(pc, onehot), 0.0))
        return loc + obj + cls

    loss = jax.vmap(per_img)(pred, gt_xyxy, gtlabel, px, py, pw, ph,
                             pobj, pcls)
    return {'Loss': [loss.reshape(-1, 1)]}


# ---------------------------------------------------------------------------
# detection mAP
# ---------------------------------------------------------------------------
@register('detection_map', no_grad=True, lod='aware')
def _detection_map(ctx, ins):
    """ref detection_map_op: per-batch mAP over detections vs labeled gt.

    Pure-XLA formulation (TPU has no host callbacks): detections arrive as
    multiclass_nms fixed-capacity rows (label -1 = padding); per class,
    detections sorted by score greedily claim the best unclaimed gt of the
    same class+image via a fori_loop over the static detection count, then
    AP is the integral/11-point precision-recall sweep in masked cumsums.
    """
    det_in = ins['DetectRes'][0]
    det = unwrap(det_in).reshape(-1, 6)     # [label, score, x0,y0,x1,y1]
    lbl_in = ins['Label'][0]
    lbl = unwrap(lbl_in)                    # [label, x0,y0,x1,y1(,difficult)]
    overlap = float(ctx.attr('overlap_threshold', 0.5))
    ap_type = ctx.attr('ap_type', 'integral')
    class_num = int(ctx.attr('class_num'))
    d_off = np.asarray(det_in.lod[0], np.int64) \
        if isinstance(det_in, LoDArray) and det_in.nlevels \
        else np.asarray([0, det.shape[0]], np.int64)
    l_off = np.asarray(lbl_in.lod[0], np.int64) \
        if isinstance(lbl_in, LoDArray) and lbl_in.nlevels \
        else np.asarray([0, lbl.shape[0]], np.int64)
    D, G = det.shape[0], lbl.shape[0]
    d_img = jnp.asarray(np.repeat(np.arange(len(d_off) - 1),
                                  (d_off[1:] - d_off[:-1])).astype(np.int32))
    g_img = jnp.asarray(np.repeat(np.arange(len(l_off) - 1),
                                  (l_off[1:] - l_off[:-1])).astype(np.int32))
    d_cls = det[:, 0].astype(jnp.int32)
    d_score = det[:, 1]
    g_cls = lbl[:, 0].astype(jnp.int32)
    iou = _iou_matrix(det[:, 2:6], lbl[:, 1:5])          # [D, G]
    same = (d_img[:, None] == g_img[None, :]) & \
        (d_cls[:, None] == g_cls[None, :]) & \
        (d_cls[:, None] >= 0)
    iou = jnp.where(same, iou, -1.0)
    order = jnp.argsort(-jnp.where(d_cls >= 0, d_score, -jnp.inf))

    def claim(i, carry):
        used, tp = carry
        di = order[i]
        # reference semantics (detection_map_op.h:379-403): argmax over ALL
        # same-class gts; if that gt is already claimed, the det is an FP —
        # it does NOT fall through to its second-best gt
        row = iou[di]
        j = jnp.argmax(row)
        hit = (row[j] >= overlap) & (d_cls[di] >= 0) & ~used[j]
        used = used.at[j].set(used[j] | hit)
        tp = tp.at[di].set(hit)
        return used, tp

    if G == 0:
        tp = jnp.zeros((D,), bool)
    else:
        _, tp = jax.lax.fori_loop(
            0, D, claim, (jnp.zeros((G,), bool), jnp.zeros((D,), bool)))

    # per-class AP via masked score-ordered cumsums
    def class_ap(c):
        mask = (d_cls == c)
        npos = jnp.sum((g_cls == c).astype(jnp.float32))
        sc = jnp.where(mask, d_score, -jnp.inf)
        o = jnp.argsort(-sc)
        tpo = jnp.take(tp & mask, o).astype(jnp.float32)
        valid = jnp.isfinite(jnp.take(sc, o)).astype(jnp.float32)
        ctp = jnp.cumsum(tpo)
        cnt = jnp.cumsum(valid)
        rec = ctp / jnp.maximum(npos, 1.0)
        prec = ctp / jnp.maximum(cnt, 1.0)
        if ap_type == '11point':
            ts = jnp.linspace(0.0, 1.0, 11)
            pmax = jnp.max(jnp.where((rec[None, :] >= ts[:, None])
                                     & (valid[None, :] > 0), prec[None, :],
                                     0.0), axis=1)
            ap = jnp.mean(pmax)
        else:
            prev_rec = jnp.concatenate([jnp.zeros((1,)), rec[:-1]])
            ap = jnp.sum(jnp.where(valid > 0, (rec - prev_rec) * prec, 0.0))
        has = (npos > 0).astype(jnp.float32)
        return ap * has, has

    aps, present = jax.vmap(class_ap)(jnp.arange(class_num))
    m_ap = jnp.sum(aps) / jnp.maximum(jnp.sum(present), 1.0)
    z = jnp.zeros((1,), jnp.int32)
    return {'MAP': [m_ap.reshape(1).astype(jnp.float32)],
            'AccumPosCount': [z], 'AccumTruePos': [jnp.zeros((1, 2))],
            'AccumFalsePos': [jnp.zeros((1, 2))]}


