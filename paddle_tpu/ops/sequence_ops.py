"""Sequence (LoD) op lowerings (ref: paddle/fluid/operators/sequence_ops/ —
~20 ops — plus lod_reset_op.cc, im2sequence_op.cc, row_conv_op.cc).

Design (core/lod.py): LoD offsets are STATIC host metadata; every lowering
here turns them into constant index/segment arrays, so the compiled program
is pure static-shape XLA — gathers, segment reductions, matmuls. The jit
cache keys on the lod pattern; host-side bucketing (reader decorators)
bounds recompiles. This trades the reference's per-batch dynamic kernels
(e.g. math/sequence2batch.h re-batching) for XLA-optimal static programs.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..framework import int_t as INT_T
from ..core.lod import LoDArray, unwrap, segment_ids_from_offsets


def _off(x, level=-1):
    assert isinstance(x, LoDArray) and x.lod, (
        "sequence op input must carry LoD (got %r)" % (x,))
    return np.asarray(x.lod[level], dtype=np.int64)


def _seg_ids(x):
    off = _off(x)
    return segment_ids_from_offsets(off, x.data.shape[0]), len(off) - 1


# ---------------------------------------------------------------------------
# pooling / softmax — reductions within sequences
# ---------------------------------------------------------------------------
@register('sequence_pool', lod='aware')
def _sequence_pool(ctx, ins):
    x = ins['X'][0]
    ptype = ctx.attr('pooltype', 'AVERAGE').upper()
    data = x.data
    off = _off(x)
    n = len(off) - 1
    seg, _ = _seg_ids(x)
    lens = jnp.asarray((off[1:] - off[:-1]).astype(np.float32))
    lens_col = lens.reshape((n,) + (1,) * (data.ndim - 1))
    if ptype == 'SUM':
        out = jax.ops.segment_sum(data, seg, num_segments=n)
    elif ptype == 'AVERAGE':
        out = jax.ops.segment_sum(data, seg, num_segments=n) / jnp.maximum(
            lens_col, 1.0)
    elif ptype == 'SQRT':
        out = jax.ops.segment_sum(data, seg, num_segments=n) / jnp.sqrt(
            jnp.maximum(lens_col, 1.0))
    elif ptype == 'MAX':
        out = jax.ops.segment_max(data, seg, num_segments=n)
        idx = jnp.argmax(
            jnp.where((seg[:, None] == jnp.arange(n)[None, :]).T[..., None]
                      if data.ndim > 1 else
                      (seg[None, :] == jnp.arange(n)[:, None]),
                      data[None], -jnp.inf).reshape(n, data.shape[0], -1),
            axis=1)
        return {'Out': [out], 'MaxIndex': [idx.astype(jnp.int32)]}
    elif ptype == 'LAST':
        out = jnp.take(data, jnp.asarray(off[1:] - 1), axis=0)
    elif ptype == 'FIRST':
        out = jnp.take(data, jnp.asarray(off[:-1]), axis=0)
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    return {'Out': [out]}


@register('sequence_softmax', lod='aware')
def _sequence_softmax(ctx, ins):
    x = ins['X'][0]
    data = x.data
    flat = data.reshape(-1)
    seg, n = _seg_ids(x)
    mx = jax.ops.segment_max(flat, seg, num_segments=n)
    e = jnp.exp(flat - mx[seg])
    s = jax.ops.segment_sum(e, seg, num_segments=n)
    out = (e / s[seg]).reshape(data.shape)
    return {'Out': [LoDArray(out, x.lod)]}


# ---------------------------------------------------------------------------
# expand / concat / reshape / reverse — row-index gathers from static lod
# ---------------------------------------------------------------------------
def _expand_index(x_off, y_off):
    """Row gather index replicating x regions to match y lengths."""
    idx = []
    for i in range(len(y_off) - 1):
        xs, xe = x_off[i], x_off[i + 1]
        reps = y_off[i + 1] - y_off[i]
        if xe - xs == 0:
            continue
        # reference semantics: repeat x's region `reps` times
        region = list(range(xs, xe))
        idx.extend(region * int(reps))
    return np.asarray(idx, dtype=np.int32)


@register('sequence_expand', lod='aware')
def _sequence_expand(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    ref_level = ctx.attr('ref_level', -1)
    y_lod = y.lod
    y_off = np.asarray(y_lod[ref_level], dtype=np.int64)
    xd = unwrap(x)
    if isinstance(x, LoDArray) and x.lod:
        x_off = _off(x, 0)
    else:
        x_off = np.arange(xd.shape[0] + 1, dtype=np.int64)
    # out region i = x region i tiled (y_len_i) times
    idx = []
    out_lens = []
    for i in range(len(y_off) - 1):
        xs, xe = int(x_off[i]), int(x_off[i + 1])
        reps = int(y_off[i + 1] - y_off[i])
        region = list(range(xs, xe))
        idx.extend(region * reps)
        out_lens.append(len(region) * reps)
    out = jnp.take(xd, jnp.asarray(idx, dtype=jnp.int32), axis=0)
    off = np.concatenate([[0], np.cumsum(out_lens)])
    return {'Out': [LoDArray(out, (off,))]}


@register('sequence_expand_as', lod='aware')
def _sequence_expand_as(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    y_off = _off(y, 0)
    xd = unwrap(x)
    reps = (y_off[1:] - y_off[:-1]).astype(np.int64)
    idx = np.repeat(np.arange(xd.shape[0]), reps).astype(np.int32)
    out = jnp.take(xd, jnp.asarray(idx), axis=0)
    return {'Out': [LoDArray(out, (y_off,))]}


@register('sequence_concat', lod='aware')
def _sequence_concat(ctx, ins):
    xs = [x for x in ins['X'] if x is not None]
    offs = [_off(x, 0) for x in xs]
    n = len(offs[0]) - 1
    idx = []
    out_lens = []
    bases = np.cumsum([0] + [unwrap(x).shape[0] for x in xs])
    for i in range(n):
        total = 0
        for k, off in enumerate(offs):
            s, e = int(off[i]), int(off[i + 1])
            idx.extend(range(bases[k] + s, bases[k] + e))
            total += e - s
        out_lens.append(total)
    big = jnp.concatenate([unwrap(x) for x in xs], axis=0)
    out = jnp.take(big, jnp.asarray(idx, dtype=jnp.int32), axis=0)
    off = np.concatenate([[0], np.cumsum(out_lens)])
    return {'Out': [LoDArray(out, (off,))]}


@register('sequence_reshape', lod='aware')
def _sequence_reshape(ctx, ins):
    x = ins['X'][0]
    new_dim = ctx.attr('new_dim')
    off = _off(x, 0)
    d = x.data.shape[1]
    out = x.data.reshape(-1, new_dim)
    new_off = (off * d) // new_dim
    return {'Out': [LoDArray(out, (new_off,))]}


@register('sequence_reverse', lod='aware')
def _sequence_reverse(ctx, ins):
    x = ins['X'][0]
    off = _off(x)
    idx = np.arange(unwrap(x).shape[0], dtype=np.int32)
    for i in range(len(off) - 1):
        idx[off[i]:off[i + 1]] = idx[off[i]:off[i + 1]][::-1]
    out = jnp.take(unwrap(x), jnp.asarray(idx), axis=0)
    return {'Y': [LoDArray(out, x.lod)]}


@register('sequence_slice', lod='aware')
def _sequence_slice(ctx, ins):
    x = ins['X'][0]
    offset = np.asarray(unwrap(ins['Offset'][0]))
    length = np.asarray(unwrap(ins['Length'][0]))
    # Offset/Length must be trace-time constants (host numpy); the layers API
    # passes them as fed numpy or assign_value constants.
    off = _off(x, 0)
    idx = []
    lens = []
    for i in range(len(off) - 1):
        s = int(off[i] + offset.reshape(-1)[i])
        l = int(length.reshape(-1)[i])
        idx.extend(range(s, s + l))
        lens.append(l)
    out = jnp.take(unwrap(x), jnp.asarray(idx, dtype=jnp.int32), axis=0)
    return {'Out': [LoDArray(out, (np.concatenate([[0], np.cumsum(lens)]),))]}


@register('sequence_enumerate', lod='aware', no_grad=True)
def _sequence_enumerate(ctx, ins):
    x = ins['X'][0]
    win = ctx.attr('win_size')
    pad = ctx.attr('pad_value', 0)
    off = _off(x)
    t = unwrap(x).shape[0]
    flat = unwrap(x).reshape(t)
    gather = np.zeros((t, win), dtype=np.int32)
    mask = np.zeros((t, win), dtype=bool)
    for i in range(len(off) - 1):
        for r in range(off[i], off[i + 1]):
            for k in range(win):
                if r + k < off[i + 1]:
                    gather[r, k] = r + k
                    mask[r, k] = True
    out = jnp.where(jnp.asarray(mask), jnp.take(flat, jnp.asarray(gather)),
                    jnp.asarray(pad, dtype=flat.dtype))
    return {'Out': [LoDArray(out, x.lod)]}


@register('sequence_erase', lod='aware', no_grad=True)
def _sequence_erase(ctx, ins):
    x = ins['X'][0]
    tokens = set(ctx.attr('tokens', []))
    data = np.asarray(unwrap(x))  # trace-time constant path only
    off = _off(x)
    keep = ~np.isin(data.reshape(-1), list(tokens))
    lens = []
    for i in range(len(off) - 1):
        lens.append(int(keep[off[i]:off[i + 1]].sum()))
    out = jnp.asarray(data.reshape(-1)[keep].reshape(-1, 1))
    return {'Out': [LoDArray(out, (np.concatenate([[0], np.cumsum(lens)]),))]}


# ---------------------------------------------------------------------------
# pad / unpad / mask — ragged <-> dense bridges
# ---------------------------------------------------------------------------
@register('sequence_pad', lod='aware')
def _sequence_pad(ctx, ins):
    x = ins['X'][0]
    pad_value = unwrap(ins['PadValue'][0])
    padded_len = ctx.attr('padded_length', -1)
    off = _off(x, 0)
    lens = off[1:] - off[:-1]
    n = len(lens)
    maxlen = int(lens.max()) if padded_len in (-1, None) else int(padded_len)
    feat = unwrap(x).shape[1:]
    gather = np.zeros((n, maxlen), dtype=np.int32)
    mask = np.zeros((n, maxlen), dtype=bool)
    for i in range(n):
        l = min(int(lens[i]), maxlen)
        gather[i, :l] = np.arange(off[i], off[i] + l)
        mask[i, :l] = True
    rows = jnp.take(unwrap(x), jnp.asarray(gather.reshape(-1)), axis=0)
    rows = rows.reshape((n, maxlen) + feat)
    m = jnp.asarray(mask).reshape((n, maxlen) + (1,) * len(feat))
    out = jnp.where(m, rows, pad_value.astype(rows.dtype).reshape(
        (1, 1) + pad_value.shape if pad_value.ndim else (1, 1) + (1,) * len(feat)))
    ctx.tracer.static_lengths[ctx.op.outputs['Length'][0]] = tuple(
        int(v) for v in lens)
    return {'Out': [out], 'Length': [jnp.asarray(lens, dtype=INT_T())]}


@register('sequence_unpad', lod='aware')
def _sequence_unpad(ctx, ins):
    x = unwrap(ins['X'][0])  # [N, L, ...]
    len_name = ctx.op.inputs['Length'][0]
    lens = ctx.tracer.static_lengths.get(len_name)
    if lens is None:
        lv = ins['Length'][0]
        lens_np = np.asarray(unwrap(lv))  # works only for constants
        lens = tuple(int(v) for v in lens_np.reshape(-1))
    idx = []
    for i, l in enumerate(lens):
        idx.extend(range(i * x.shape[1], i * x.shape[1] + int(l)))
    flat = x.reshape((-1,) + x.shape[2:])
    out = jnp.take(flat, jnp.asarray(idx, dtype=jnp.int32), axis=0)
    off = np.concatenate([[0], np.cumsum(lens)])
    return {'Out': [LoDArray(out, (off,))]}


@register('sequence_mask', no_grad=True, lod='none')
def _sequence_mask(ctx, ins):
    x = ins['X'][0]  # lengths
    maxlen = ctx.attr('maxlen', -1)
    if ins.get('MaxLenTensor') and ins['MaxLenTensor'][0] is not None:
        maxlen = int(np.asarray(unwrap(ins['MaxLenTensor'][0])))
    if maxlen in (-1, None):
        raise ValueError(
            "sequence_mask needs a static maxlen on TPU (pass maxlen=...)")
    from ..framework import convert_dtype
    dt = convert_dtype(ctx.attr('out_dtype', 'int64'))
    rng = jnp.arange(maxlen, dtype=x.dtype if jnp.issubdtype(
        x.dtype, jnp.integer) else INT_T())
    out = (rng[None, :] < x.reshape(-1)[:, None]).astype(jnp.dtype(dt))
    return {'Y': [out.reshape(tuple(x.shape) + (maxlen,))]}


@register('lod_reset', lod='aware')
def _lod_reset(ctx, ins):
    x = ins['X'][0]
    data = unwrap(x)
    if ins.get('Y') and ins['Y'][0] is not None:
        y = ins['Y'][0]
        if isinstance(y, LoDArray) and y.lod:
            return {'Out': [LoDArray(data, y.lod)]}
        target = np.asarray(unwrap(y)).reshape(-1)
        return {'Out': [LoDArray(data, (target,))]}
    target = np.asarray(ctx.attr('target_lod'), dtype=np.int64)
    return {'Out': [LoDArray(data, (target,))]}


# ---------------------------------------------------------------------------
# sequence_conv / row_conv — context-window convolutions
# ---------------------------------------------------------------------------
@register('sequence_conv', lod='aware')
def _sequence_conv(ctx, ins):
    x = ins['X'][0]
    w = unwrap(ins['Filter'][0])  # [ctx_len * D, num_filters]
    ctx_len = ctx.attr('contextLength')
    ctx_start = ctx.attr('contextStart', -(ctx_len // 2) if ctx_len else 0)
    off = _off(x, 0)
    t, d = unwrap(x).shape
    gather = np.zeros((t, ctx_len), dtype=np.int32)
    mask = np.zeros((t, ctx_len), dtype=bool)
    for i in range(len(off) - 1):
        for r in range(off[i], off[i + 1]):
            for k in range(ctx_len):
                src = r + ctx_start + k
                if off[i] <= src < off[i + 1]:
                    gather[r, k] = src
                    mask[r, k] = True
    cols = jnp.take(unwrap(x), jnp.asarray(gather.reshape(-1)), axis=0)
    cols = cols.reshape(t, ctx_len, d)
    cols = jnp.where(jnp.asarray(mask)[:, :, None], cols, 0.0)
    out = cols.reshape(t, ctx_len * d) @ w
    return {'Out': [LoDArray(out, x.lod)]}


@register('row_conv', lod='aware')
def _row_conv(ctx, ins):
    x = ins['X'][0]
    w = unwrap(ins['Filter'][0])  # [future_ctx, D]
    fut = w.shape[0]
    off = _off(x, 0)
    t, d = unwrap(x).shape
    gather = np.zeros((t, fut), dtype=np.int32)
    mask = np.zeros((t, fut), dtype=bool)
    for i in range(len(off) - 1):
        for r in range(off[i], off[i + 1]):
            for k in range(fut):
                if r + k < off[i + 1]:
                    gather[r, k] = r + k
                    mask[r, k] = True
    cols = jnp.take(unwrap(x), jnp.asarray(gather.reshape(-1)), axis=0)
    cols = cols.reshape(t, fut, d)
    cols = jnp.where(jnp.asarray(mask)[:, :, None], cols, 0.0)
    out = jnp.einsum('tfd,fd->td', cols, w)
    return {'Out': [LoDArray(out, x.lod)]}


@register('im2sequence')
def _im2sequence(ctx, ins):
    x = X = ins['X'][0]  # [N, C, H, W]
    kernels = ctx.attr('kernels')
    strides = ctx.attr('strides', [1, 1])
    paddings = ctx.attr('paddings', [0, 0, 0, 0])
    n, c, h, w = x.shape
    kh, kw = kernels
    ph0, pw0, ph1, pw1 = (paddings + paddings)[:4] if len(paddings) == 2 \
        else paddings
    xp = jnp.pad(x, [(0, 0), (0, 0), (ph0, ph1), (pw0, pw1)])
    oh = (h + ph0 + ph1 - kh) // strides[0] + 1
    ow = (w + pw0 + pw1 - kw) // strides[1] + 1
    patches = []
    for i in range(oh):
        for j in range(ow):
            si, sj = i * strides[0], j * strides[1]
            patches.append(xp[:, :, si:si + kh, sj:sj + kw])
    stacked = jnp.stack(patches, axis=1)  # [N, oh*ow, C, kh, kw]
    out = stacked.reshape(n * oh * ow, c * kh * kw)
    off = np.arange(n + 1, dtype=np.int64) * (oh * ow)
    return {'Out': [LoDArray(out, (off,))]}


@register('sequence_scatter', lod='aware')
def _sequence_scatter(ctx, ins):
    x = unwrap(ins['X'][0])
    ids = ins['Ids'][0]
    updates = ins['Updates'][0]
    off = _off(ids, 0)
    idx_np = np.asarray(unwrap(ids)).reshape(-1)
    rows = []
    for i in range(len(off) - 1):
        rows.extend([i] * int(off[i + 1] - off[i]))
    out = x.at[(jnp.asarray(np.asarray(rows, np.int32)),
                jnp.asarray(idx_np.astype(np.int32)))].add(
        unwrap(updates).reshape(-1))
    return {'Out': [out]}


# ---------------------------------------------------------------------------
# compile-time shape inference for LoD-aware ops (eval_shape probing can't
# construct LoDArrays; mirror the reference's InferShape rules instead)
# ---------------------------------------------------------------------------
from ..core import registry as _registry


def _set_out(op, block, slot, shape, dtype=None):
    for n in op.outputs.get(slot, []):
        v = block._find_var_recursive(n)
        if v is not None:
            v.shape = tuple(shape)
            if dtype is not None:
                v.dtype = dtype


def _in_var(op, block, slot='X'):
    return block._find_var_recursive(op.inputs[slot][0])


def _rows_like_infer(*slots_out):
    def infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        for slot in slots_out:
            _set_out(op, block, slot, (-1,) + tuple(x.shape[1:]))
    return infer


def _install():
    R = _registry.get
    R('sequence_softmax').infer_shape = _rows_like_infer('Out')
    R('sequence_reverse').infer_shape = _rows_like_infer('Y')
    R('sequence_expand').infer_shape = _rows_like_infer('Out')
    R('sequence_expand_as').infer_shape = _rows_like_infer('Out')
    R('sequence_slice').infer_shape = _rows_like_infer('Out')
    R('sequence_erase').infer_shape = _rows_like_infer('Out')
    R('sequence_scatter').infer_shape = _rows_like_infer('Out')

    def _pool_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        _set_out(op, block, 'Out', (-1,) + tuple(x.shape[1:]))
        _set_out(op, block, 'MaxIndex', (-1,) + tuple(x.shape[1:]), 'int32')
    R('sequence_pool').infer_shape = _pool_infer

    def _concat_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        _set_out(op, block, 'Out', (-1,) + tuple(x.shape[1:]))
    R('sequence_concat').infer_shape = _concat_infer

    def _reshape_infer(op, block):
        _set_out(op, block, 'Out', (-1, op.attrs['new_dim']))
    R('sequence_reshape').infer_shape = _reshape_infer

    def _conv_infer(op, block):
        f = block._find_var_recursive(op.inputs['Filter'][0])
        if f is None or f.shape is None:
            return
        _set_out(op, block, 'Out', (-1, f.shape[1]))
    R('sequence_conv').infer_shape = _conv_infer
    R('row_conv').infer_shape = _rows_like_infer('Out')

    def _pad_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        plen = op.attrs.get('padded_length', -1)
        _set_out(op, block, 'Out',
                 (-1, plen if plen and plen > 0 else -1) + tuple(x.shape[1:]))
        _set_out(op, block, 'Length', (-1,), 'int64')
    R('sequence_pad').infer_shape = _pad_infer

    def _unpad_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        _set_out(op, block, 'Out', (-1,) + tuple(x.shape[2:]))
    R('sequence_unpad').infer_shape = _unpad_infer

    def _enum_infer(op, block):
        _set_out(op, block, 'Out', (-1, op.attrs['win_size']), 'int64')
    R('sequence_enumerate').infer_shape = _enum_infer

    def _mask_infer(op, block):
        x = _in_var(op, block)
        maxlen = op.attrs.get('maxlen', -1)
        shape = tuple(x.shape) if x is not None and x.shape else (-1,)
        _set_out(op, block, 'Y', shape + (maxlen if maxlen > 0 else -1,),
                 op.attrs.get('out_dtype', 'int64'))
    R('sequence_mask').infer_shape = _mask_infer

    def _lod_reset_infer(op, block):
        x = _in_var(op, block)
        if x is not None and x.shape is not None:
            _set_out(op, block, 'Out', x.shape)
    R('lod_reset').infer_shape = _lod_reset_infer

    def _im2seq_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        kh, kw = op.attrs['kernels']
        _set_out(op, block, 'Out', (-1, x.shape[1] * kh * kw))
    R('im2sequence').infer_shape = _im2seq_infer


_install()
