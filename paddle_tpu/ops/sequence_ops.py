"""Sequence (LoD) op lowerings (ref: paddle/fluid/operators/sequence_ops/ —
~20 ops — plus lod_reset_op.cc, im2sequence_op.cc, row_conv_op.cc).

Design (core/lod.py): every lowering here is written in OFFSET MATH —
searchsorted segment ids, offset-gather indices, masked windows — over
`off_t()`, the device view of the lod. The SAME code therefore serves both
lod modes: with static lod the offsets are XLA constants (folded away,
yesterday's behavior); with traced lod the compiled program is lod-GENERIC
— any batch of the same bucket shape reuses the executable, the moral
equivalent of the reference's lod-generic kernels
(operators/math/sequence2batch.h). No lowering loops over rows or bakes
O(batch) Python into the trace.

Ops whose OUTPUT SHAPE depends on lod content (sequence_expand,
sequence_slice, sequence_erase) read `x.lod` (host values) and remain
static-mode only — dynamic output shapes cannot be compiled; they raise
TracedLoDError with guidance on traced inputs.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..framework import int_t as INT_T
from ..core.lod import (LoDArray, unwrap, seg_ids_t, valid_rows_t,
                        segment_ids_from_offsets)


def _la(x, what):
    assert isinstance(x, LoDArray) and x.nlevels, (
        "%s input must carry LoD (got %r)" % (what, x))
    return x


def _off(x, level=-1):
    """STATIC host offsets — only for ops with content-dependent shapes."""
    assert isinstance(x, LoDArray) and x.nlevels, (
        "sequence op input must carry LoD (got %r)" % (x,))
    return np.asarray(x.lod[level], dtype=np.int64)


# ---------------------------------------------------------------------------
# pooling / softmax — segment reductions
# ---------------------------------------------------------------------------
@register('sequence_pool', lod='aware')
def _sequence_pool(ctx, ins):
    x = _la(ins['X'][0], 'sequence_pool')
    ptype = ctx.attr('pooltype', 'AVERAGE').upper()
    data = x.data
    off = x.off_t()
    n = x.nseq_of()
    T = data.shape[0]
    seg = seg_ids_t(off, T)
    lens = (off[1:] - off[:-1]).astype(jnp.float32)
    lens_col = lens.reshape((n,) + (1,) * (data.ndim - 1))
    if ptype == 'SUM':
        out = jax.ops.segment_sum(data, seg, num_segments=n)
    elif ptype == 'AVERAGE':
        out = jax.ops.segment_sum(data, seg, num_segments=n) / jnp.maximum(
            lens_col, 1.0)
    elif ptype == 'SQRT':
        out = jax.ops.segment_sum(data, seg, num_segments=n) / jnp.sqrt(
            jnp.maximum(lens_col, 1.0))
    elif ptype == 'MAX':
        out = jax.ops.segment_max(data, seg, num_segments=n)
        member = seg[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
        masked = jnp.where(member[..., None] if data.ndim > 1 else member,
                           data[None], -jnp.inf)
        idx = jnp.argmax(masked.reshape(n, T, -1), axis=1)
        return {'Out': [out], 'MaxIndex': [idx.astype(jnp.int32)]}
    elif ptype == 'LAST':
        out = jnp.take(data, jnp.maximum(off[1:] - 1, 0), axis=0)
    elif ptype == 'FIRST':
        out = jnp.take(data, off[:-1], axis=0)
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    return {'Out': [out]}


@register('sequence_softmax', lod='aware')
def _sequence_softmax(ctx, ins):
    x = _la(ins['X'][0], 'sequence_softmax')
    data = x.data
    flat = data.reshape(-1)
    T = flat.shape[0]
    seg = seg_ids_t(x.off_t(), T)
    n = x.nseq_of()
    mx = jax.ops.segment_max(flat, seg, num_segments=n)
    safe = jnp.where(jnp.isfinite(mx), mx, 0.0)
    e = jnp.exp(flat - jnp.take(safe, jnp.minimum(seg, n - 1)))
    e = jnp.where(valid_rows_t(x.off_t(), T), e, 0.0)
    s = jax.ops.segment_sum(e, seg, num_segments=n)
    out = (e / jnp.maximum(jnp.take(s, jnp.minimum(seg, n - 1)), 1e-30)
           ).reshape(data.shape)
    return {'Out': [x.with_lod_of(out)]}


# ---------------------------------------------------------------------------
# expand / concat / reshape / reverse
# ---------------------------------------------------------------------------
@register('sequence_expand', lod='aware')
def _sequence_expand(ctx, ins):
    # output row count depends on lod VALUES -> static mode by design
    x, y = ins['X'][0], ins['Y'][0]
    ref_level = ctx.attr('ref_level', -1)
    y_off = np.asarray(y.lod[ref_level], dtype=np.int64)
    xd = unwrap(x)
    if isinstance(x, LoDArray) and x.nlevels:
        x_off = _off(x, 0)
    else:
        x_off = np.arange(xd.shape[0] + 1, dtype=np.int64)
    # out region i = x region i tiled (y_len_i) times — vectorized index
    # construction (no per-row python)
    reps = (y_off[1:] - y_off[:-1]).astype(np.int64)
    xlens = (x_off[1:] - x_off[:-1]).astype(np.int64)
    out_lens = xlens * reps
    starts = np.repeat(x_off[:-1], reps)            # region start per copy
    copy_lens = np.repeat(xlens, reps)              # region len per copy
    ends = np.cumsum(copy_lens)
    total = int(ends[-1]) if len(ends) else 0
    base = np.repeat(starts - (ends - copy_lens), copy_lens)
    idx = (np.arange(total, dtype=np.int64) + base).astype(np.int32)
    out = jnp.take(xd, jnp.asarray(idx), axis=0)
    off = np.concatenate([[0], np.cumsum(out_lens)])
    return {'Out': [LoDArray(out, (off,))]}


@register('sequence_expand_as', lod='aware')
def _sequence_expand_as(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    y = _la(y, 'sequence_expand_as Y')
    xd = unwrap(x)
    y_off = y.off_t(0)
    T = unwrap(y).shape[0]
    seg = seg_ids_t(y_off, T)  # out row j copies x row seg[j]
    out = jnp.take(xd, jnp.minimum(seg, xd.shape[0] - 1), axis=0)
    out = jnp.where(
        valid_rows_t(y_off, T).reshape((T,) + (1,) * (out.ndim - 1)),
        out, 0)
    return {'Out': [y.with_lod_of(out, slice(0, 1))]}


@register('sequence_concat', lod='aware')
def _sequence_concat(ctx, ins):
    """Interleave per-sequence regions of K inputs. Output rows = sum of
    input rows (STATIC); positions are offset math — scatter each input's
    rows to out_off[seg] + prior-inputs' length + within-seq index."""
    xs = [_la(x, 'sequence_concat') for x in ins['X'] if x is not None]
    offs = [x.off_t(0) for x in xs]
    n = xs[0].nseq_of(0)
    lens = [o[1:] - o[:-1] for o in offs]                 # [K][n]
    out_lens = sum(lens[1:], lens[0])
    out_off = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(out_lens).astype(jnp.int32)])
    total = sum(unwrap(x).shape[0] for x in xs)
    out = jnp.zeros((total,) + unwrap(xs[0]).shape[1:], unwrap(xs[0]).dtype)
    prior = jnp.zeros((n,), jnp.int32)
    for k, x in enumerate(xs):
        d = unwrap(x)
        Tk = d.shape[0]
        seg = seg_ids_t(offs[k], Tk)
        segc = jnp.minimum(seg, n - 1)
        within = jnp.arange(Tk, dtype=jnp.int32) - jnp.take(offs[k], segc)
        pos = jnp.take(out_off[:-1], segc) + jnp.take(prior, segc) + within
        pos = jnp.where(valid_rows_t(offs[k], Tk), pos, total)  # drop pads
        out = out.at[pos].set(d, mode='drop')
        prior = prior + lens[k].astype(jnp.int32)
    if xs[0].is_traced:
        return {'Out': [LoDArray.traced(out, [out_off])]}
    # static: host offsets (jnp values are tracers under jit)
    host_off = np.zeros(n + 1, np.int64)
    for x in xs:
        o = np.asarray(x.lod[0], np.int64)
        host_off[1:] += o[1:] - o[:-1]
    return {'Out': [LoDArray(out, (np.cumsum(host_off),))]}


@register('sequence_reshape', lod='aware')
def _sequence_reshape(ctx, ins):
    x = _la(ins['X'][0], 'sequence_reshape')
    new_dim = ctx.attr('new_dim')
    d = x.data.shape[1]
    out = x.data.reshape(-1, new_dim)
    if x.is_traced:
        return {'Out': [LoDArray.traced(out, [(x.off_t(0) * d)
                                              // new_dim])]}
    # static mode: offsets stay HOST numpy (under jit every jnp value is a
    # tracer, even "constants")
    new_off = (np.asarray(x.lod[0], np.int64) * d) // new_dim
    return {'Out': [LoDArray(out, (new_off,))]}


@register('sequence_reverse', lod='aware')
def _sequence_reverse(ctx, ins):
    x = _la(ins['X'][0], 'sequence_reverse')
    data = unwrap(x)
    T = data.shape[0]
    off = x.off_t()
    n = x.nseq_of()
    seg = seg_ids_t(off, T)
    segc = jnp.minimum(seg, n - 1)
    # reversed index within the row's sequence: start + end - 1 - i
    idx = (jnp.take(off, segc) + jnp.take(off, segc + 1) - 1
           - jnp.arange(T, dtype=jnp.int32))
    valid = valid_rows_t(off, T)
    idx = jnp.where(valid, idx, jnp.arange(T, dtype=jnp.int32))
    out = jnp.take(data, idx, axis=0)
    return {'Y': [x.with_lod_of(out)]}


@register('sequence_slice', lod='aware')
def _sequence_slice(ctx, ins):
    # output rows = sum(Length) -> content-dependent: Offset/Length must be
    # trace-time constants (assign_value host side-channel, or fed numpy
    # when running eagerly)
    x = ins['X'][0]

    def _const(slot):
        name = ctx.op.inputs[slot][0]
        if name in ctx.tracer.host_consts:
            return np.asarray(ctx.tracer.host_consts[name]).reshape(-1)
        try:
            return np.asarray(unwrap(ins[slot][0])).reshape(-1)
        except Exception:
            raise TypeError(
                "sequence_slice %s must be a trace-time constant (use "
                "layers.assign of a numpy array); a fed/computed tensor "
                "would make the output shape dynamic" % slot)

    offset = _const('Offset')
    length = _const('Length')
    off = _off(x, 0)
    starts = off[:-1] + offset.astype(np.int64)
    lens = length.astype(np.int64)
    ends_cum = np.cumsum(lens)
    total = int(ends_cum[-1]) if len(lens) else 0
    base = np.repeat(starts - (ends_cum - lens), lens)
    idx = (np.arange(total, dtype=np.int64) + base).astype(np.int32)
    out = jnp.take(unwrap(x), jnp.asarray(idx), axis=0)
    return {'Out': [LoDArray(out, (np.concatenate([[0], ends_cum]),))]}


# ---------------------------------------------------------------------------
# windowed ops: gather[r, k] = r + shift_k, valid iff same sequence
# ---------------------------------------------------------------------------
def _window(x, shifts):
    """Returns (cols [T, K, ...], mask [T, K]) of per-row windows clipped to
    the row's sequence — pure offset math, mode-generic."""
    data = unwrap(x)
    T = data.shape[0]
    off = x.off_t()
    seg = seg_ids_t(off, T)
    r = jnp.arange(T, dtype=jnp.int32)[:, None]
    src = r + jnp.asarray(shifts, jnp.int32)[None, :]      # [T, K]
    inb = (src >= 0) & (src < T)
    srcc = jnp.clip(src, 0, T - 1)
    same = jnp.take(seg, srcc) == seg[:, None]
    mask = inb & same & valid_rows_t(off, T)[:, None]
    cols = jnp.take(data, srcc.reshape(-1), axis=0)
    cols = cols.reshape((T, len(shifts)) + data.shape[1:])
    return cols, mask


@register('sequence_enumerate', lod='aware', no_grad=True)
def _sequence_enumerate(ctx, ins):
    x = _la(ins['X'][0], 'sequence_enumerate')
    win = ctx.attr('win_size')
    pad = ctx.attr('pad_value', 0)
    flat_in = unwrap(x).reshape(unwrap(x).shape[0])
    cols, mask = _window(x.with_lod_of(flat_in), list(range(win)))
    out = jnp.where(mask, cols, jnp.asarray(pad, flat_in.dtype))
    return {'Out': [x.with_lod_of(out)]}


@register('sequence_conv', lod='aware')
def _sequence_conv(ctx, ins):
    x = _la(ins['X'][0], 'sequence_conv')
    w = unwrap(ins['Filter'][0])  # [ctx_len * D, num_filters]
    ctx_len = ctx.attr('contextLength')
    ctx_start = ctx.attr('contextStart', -(ctx_len // 2) if ctx_len else 0)
    t, d = unwrap(x).shape
    cols, mask = _window(x, [ctx_start + k for k in range(ctx_len)])
    cols = jnp.where(mask[:, :, None], cols, 0.0)
    out = cols.reshape(t, ctx_len * d) @ w
    return {'Out': [x.with_lod_of(out)]}


@register('row_conv', lod='aware')
def _row_conv(ctx, ins):
    x = _la(ins['X'][0], 'row_conv')
    w = unwrap(ins['Filter'][0])  # [future_ctx, D]
    fut = w.shape[0]
    cols, mask = _window(x, list(range(fut)))
    cols = jnp.where(mask[:, :, None], cols, 0.0)
    out = jnp.einsum('tfd,fd->td', cols, w)
    return {'Out': [x.with_lod_of(out)]}


@register('sequence_erase', lod='aware', no_grad=True)
def _sequence_erase(ctx, ins):
    """Remove listed tokens from each sequence. The reference compacts rows
    (dynamic shape); the static-shape formulation keeps the lod and
    left-aligns survivors within each row span, -1 after — the same
    convention as ctc_greedy_decoder, which downstream edit_distance /
    chunk_eval understand."""
    x = _la(ins['X'][0], 'sequence_erase')
    tokens = list(ctx.attr('tokens', []))
    flat = unwrap(x).reshape(-1)
    T = flat.shape[0]
    off = x.off_t()
    seg = seg_ids_t(off, T)
    segc = jnp.minimum(seg, x.nseq_of() - 1)
    keep = valid_rows_t(off, T)
    for tok in tokens:
        keep &= flat != tok
    csum = jnp.cumsum(keep.astype(jnp.int32))
    off32 = off.astype(jnp.int32)
    seq_base = jnp.take(jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), csum]), jnp.take(off32, segc))
    rank = csum - 1 - seq_base
    tgt = jnp.where(keep, jnp.take(off32, segc) + rank, T)
    out = jnp.full((T,), -1, flat.dtype).at[tgt].set(flat, mode='drop')
    return {'Out': [x.with_lod_of(out.reshape(-1, 1))]}


# ---------------------------------------------------------------------------
# pad / unpad / mask — ragged <-> dense bridges
# ---------------------------------------------------------------------------
@register('sequence_pad', lod='aware')
def _sequence_pad(ctx, ins):
    x = _la(ins['X'][0], 'sequence_pad')
    pad_value = unwrap(ins['PadValue'][0])
    padded_len = ctx.attr('padded_length', -1)
    off = x.off_t(0)
    n = x.nseq_of(0)
    data = unwrap(x)
    feat = data.shape[1:]
    if padded_len not in (-1, None):
        maxlen = int(padded_len)
    elif not x.is_traced:
        lens_np = np.asarray(x.lod[0])
        maxlen = int((lens_np[1:] - lens_np[:-1]).max())
    else:
        raise TypeError(
            "sequence_pad on traced-lod input needs a static padded_length "
            "attr (the bucket's max length) — the default max-over-batch "
            "is a lod VALUE, which is device data here")
    lens = off[1:] - off[:-1]
    j = jnp.arange(maxlen, dtype=jnp.int32)
    gather = off[:-1, None] + j[None, :]                 # [n, maxlen]
    mask = j[None, :] < lens[:, None]
    rows = jnp.take(data, jnp.clip(gather, 0, data.shape[0] - 1).reshape(-1),
                    axis=0).reshape((n, maxlen) + feat)
    m = mask.reshape((n, maxlen) + (1,) * len(feat))
    pv = pad_value.astype(rows.dtype).reshape(
        (1, 1) + pad_value.shape if pad_value.ndim
        else (1, 1) + (1,) * len(feat))
    out = jnp.where(m, rows, pv)
    if not x.is_traced:
        lens_np = np.asarray(x.lod[0])
        ctx.tracer.static_lengths[ctx.op.outputs['Length'][0]] = tuple(
            int(v) for v in (lens_np[1:] - lens_np[:-1]))
    return {'Out': [out], 'Length': [lens.astype(INT_T())]}


@register('sequence_unpad', lod='aware')
def _sequence_unpad(ctx, ins):
    # output rows = sum(Length) -> content-dependent: static mode only
    x = unwrap(ins['X'][0])  # [N, L, ...]
    len_name = ctx.op.inputs['Length'][0]
    lens = ctx.tracer.static_lengths.get(len_name)
    if lens is None:
        lv = ins['Length'][0]
        lens_np = np.asarray(unwrap(lv))  # works only for constants
        lens = tuple(int(v) for v in lens_np.reshape(-1))
    from .rnn_ops import _unpad_to_lod
    off = np.concatenate([[0], np.cumsum(np.asarray(lens, np.int64))])
    out = _unpad_to_lod(x, off)
    return {'Out': [LoDArray(out, (off,))]}


@register('sequence_mask', no_grad=True, lod='none')
def _sequence_mask(ctx, ins):
    x = ins['X'][0]  # lengths
    maxlen = ctx.attr('maxlen', -1)
    if ins.get('MaxLenTensor') and ins['MaxLenTensor'][0] is not None:
        maxlen = int(np.asarray(unwrap(ins['MaxLenTensor'][0])))
    if maxlen in (-1, None):
        raise ValueError(
            "sequence_mask needs a static maxlen on TPU (pass maxlen=...)")
    from ..framework import convert_dtype, runtime_dtype
    dt = runtime_dtype(convert_dtype(ctx.attr('out_dtype', 'int64')))
    rng = jnp.arange(maxlen, dtype=x.dtype if jnp.issubdtype(
        x.dtype, jnp.integer) else INT_T())
    out = (rng[None, :] < x.reshape(-1)[:, None]).astype(dt)
    return {'Y': [out.reshape(tuple(x.shape) + (maxlen,))]}


@register('lod_reset', lod='aware')
def _lod_reset(ctx, ins):
    x = ins['X'][0]
    data = unwrap(x)
    if ins.get('Y') and ins['Y'][0] is not None:
        y = ins['Y'][0]
        if isinstance(y, LoDArray) and y.nlevels:
            return {'Out': [y.with_lod_of(data)]}
        target = np.asarray(unwrap(y)).reshape(-1)
        return {'Out': [LoDArray(data, (target,))]}
    target = np.asarray(ctx.attr('target_lod'), dtype=np.int64)
    return {'Out': [LoDArray(data, (target,))]}


@register('im2sequence')
def _im2sequence(ctx, ins):
    x = ins['X'][0]  # [N, C, H, W]
    kernels = ctx.attr('kernels')
    strides = ctx.attr('strides', [1, 1])
    paddings = ctx.attr('paddings', [0, 0, 0, 0])
    n, c, h, w = x.shape
    kh, kw = kernels
    ph0, pw0, ph1, pw1 = (paddings + paddings)[:4] if len(paddings) == 2 \
        else paddings
    xp = jnp.pad(x, [(0, 0), (0, 0), (ph0, ph1), (pw0, pw1)])
    oh = (h + ph0 + ph1 - kh) // strides[0] + 1
    ow = (w + pw0 + pw1 - kw) // strides[1] + 1
    # extract all patches in one strided-window op (no python loop over
    # output pixels): [N, C*kh*kw, oh, ow] -> rows
    patches = jax.lax.conv_general_dilated_patches(
        xp, (kh, kw), strides, 'VALID',
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    off = np.arange(n + 1, dtype=np.int64) * (oh * ow)
    return {'Out': [LoDArray(out, (off,))]}


@register('sequence_scatter', lod='aware')
def _sequence_scatter(ctx, ins):
    x = unwrap(ins['X'][0])
    ids = _la(ins['Ids'][0], 'sequence_scatter Ids')
    updates = ins['Updates'][0]
    T = unwrap(ids).shape[0]
    rows = seg_ids_t(ids.off_t(0), T)
    cols = unwrap(ids).reshape(-1).astype(jnp.int32)
    rows = jnp.where(valid_rows_t(ids.off_t(0), T), rows, x.shape[0])
    out = x.at[(rows, cols)].add(unwrap(updates).reshape(-1), mode='drop')
    return {'Out': [out]}


# ---------------------------------------------------------------------------
# compile-time shape inference for LoD-aware ops (eval_shape probing can't
# construct LoDArrays; mirror the reference's InferShape rules instead)
# ---------------------------------------------------------------------------
from ..core import registry as _registry


def _set_out(op, block, slot, shape, dtype=None):
    for n in op.outputs.get(slot, []):
        v = block._find_var_recursive(n)
        if v is not None:
            v.shape = tuple(shape)
            if dtype is not None:
                v.dtype = dtype


def _in_var(op, block, slot='X'):
    return block._find_var_recursive(op.inputs[slot][0])


def _rows_like_infer(*slots_out):
    def infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        for slot in slots_out:
            _set_out(op, block, slot, (-1,) + tuple(x.shape[1:]))
    return infer


def _install():
    R = _registry.get
    R('sequence_softmax').infer_shape = _rows_like_infer('Out')
    R('sequence_reverse').infer_shape = _rows_like_infer('Y')
    R('sequence_expand').infer_shape = _rows_like_infer('Out')
    R('sequence_expand_as').infer_shape = _rows_like_infer('Out')
    R('sequence_slice').infer_shape = _rows_like_infer('Out')
    R('sequence_erase').infer_shape = _rows_like_infer('Out')
    R('sequence_scatter').infer_shape = _rows_like_infer('Out')

    def _pool_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        _set_out(op, block, 'Out', (-1,) + tuple(x.shape[1:]))
        _set_out(op, block, 'MaxIndex', (-1,) + tuple(x.shape[1:]), 'int32')
    R('sequence_pool').infer_shape = _pool_infer

    def _concat_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        _set_out(op, block, 'Out', (-1,) + tuple(x.shape[1:]))
    R('sequence_concat').infer_shape = _concat_infer

    def _reshape_infer(op, block):
        _set_out(op, block, 'Out', (-1, op.attrs['new_dim']))
    R('sequence_reshape').infer_shape = _reshape_infer

    def _conv_infer(op, block):
        f = block._find_var_recursive(op.inputs['Filter'][0])
        if f is None or f.shape is None:
            return
        _set_out(op, block, 'Out', (-1, f.shape[1]))
    R('sequence_conv').infer_shape = _conv_infer
    R('row_conv').infer_shape = _rows_like_infer('Out')

    def _pad_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        plen = op.attrs.get('padded_length', -1)
        _set_out(op, block, 'Out',
                 (-1, plen if plen and plen > 0 else -1) + tuple(x.shape[1:]))
        _set_out(op, block, 'Length', (-1,), 'int64')
    R('sequence_pad').infer_shape = _pad_infer

    def _unpad_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        _set_out(op, block, 'Out', (-1,) + tuple(x.shape[2:]))
    R('sequence_unpad').infer_shape = _unpad_infer

    def _enum_infer(op, block):
        _set_out(op, block, 'Out', (-1, op.attrs['win_size']), 'int64')
    R('sequence_enumerate').infer_shape = _enum_infer

    def _mask_infer(op, block):
        x = _in_var(op, block)
        maxlen = op.attrs.get('maxlen', -1)
        shape = tuple(x.shape) if x is not None and x.shape else (-1,)
        _set_out(op, block, 'Y', shape + (maxlen if maxlen > 0 else -1,),
                 op.attrs.get('out_dtype', 'int64'))
    R('sequence_mask').infer_shape = _mask_infer

    def _lod_reset_infer(op, block):
        x = _in_var(op, block)
        if x is not None and x.shape is not None:
            _set_out(op, block, 'Out', x.shape)
    R('lod_reset').infer_shape = _lod_reset_infer

    def _im2seq_infer(op, block):
        x = _in_var(op, block)
        if x is None or x.shape is None:
            return
        kh, kw = op.attrs['kernels']
        _set_out(op, block, 'Out', (-1, x.shape[1] * kh * kw))
    R('im2sequence').infer_shape = _im2seq_infer


_install()
