"""Math / elementwise / activation / reduction op lowerings.

Each op here replaces a C++/CUDA kernel pair from the reference
(paddle/fluid/operators/*_op.{cc,cu}, elementwise/, reduce_ops/,
activation_op.cc) with a single JAX lowering; XLA supplies both the TPU and
CPU kernels, the fusion the reference got from fused_* ops, and — via the
generic vjp path — the grad kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core import amp


def X(ins, slot='X'):
    return ins[slot][0]


# ---------------------------------------------------------------------------
# elementwise binary ops with Paddle's axis-broadcast semantics
# (ref: operators/elementwise/elementwise_op_function.h)
# ---------------------------------------------------------------------------
def _bcast_y(x, y, axis):
    if x.ndim == y.ndim:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    shape = [1] * axis + list(y.shape)
    shape += [1] * (x.ndim - len(shape))
    return y.reshape(shape)


def _elementwise(name, fn):
    @register(name)
    def _lower(ctx, ins, _fn=fn):
        x, y = ins['X'][0], ins['Y'][0]
        y = _bcast_y(x, y, ctx.attr('axis', -1))
        x, y = amp.unify(x, y)
        out = _fn(x, y)
        scale = ctx.attr('scale', None)  # fused scale (rare attr)
        if scale not in (None, 1.0):
            out = out * scale
        return {'Out': [out]}


_elementwise('elementwise_add', jnp.add)
_elementwise('elementwise_sub', jnp.subtract)
_elementwise('elementwise_mul', jnp.multiply)
_elementwise('elementwise_div', jnp.divide)
_elementwise('elementwise_max', jnp.maximum)
_elementwise('elementwise_min', jnp.minimum)
_elementwise('elementwise_pow', jnp.power)
_elementwise('elementwise_mod', jnp.mod)
_elementwise('elementwise_floordiv', jnp.floor_divide)


# ---------------------------------------------------------------------------
# activations (ref: operators/activation_op.cc — ~25 kernels)
# ---------------------------------------------------------------------------
def _unary(name, fn):
    @register(name)
    def _lower(ctx, ins, _fn=fn):
        return {'Out': [_fn(X(ins))]}


_unary('relu', jax.nn.relu)
_unary('sigmoid', jax.nn.sigmoid)
_unary('logsigmoid', jax.nn.log_sigmoid)
_unary('tanh', jnp.tanh)
_unary('tanh_shrink', lambda x: x - jnp.tanh(x))
_unary('exp', jnp.exp)
_unary('sqrt', jnp.sqrt)
_unary('rsqrt', jax.lax.rsqrt)
_unary('abs', jnp.abs)
_unary('ceil', jnp.ceil)
_unary('floor', jnp.floor)
_unary('cos', jnp.cos)
_unary('sin', jnp.sin)
_unary('round', jnp.round)
_unary('reciprocal', jnp.reciprocal)
_unary('square', jnp.square)
_unary('softplus', jax.nn.softplus)
_unary('softsign', jax.nn.soft_sign)
_unary('log', jnp.log)
_unary('gelu', jax.nn.gelu)
_unary('erf', jax.scipy.special.erf)
_unary('sign', jnp.sign)


@register('leaky_relu')
def _leaky_relu(ctx, ins):
    a = ctx.attr('alpha', 0.02)
    x = X(ins)
    return {'Out': [jnp.where(x >= 0, x, a * x)]}


@register('elu')
def _elu(ctx, ins):
    return {'Out': [jax.nn.elu(X(ins), alpha=ctx.attr('alpha', 1.0))]}


@register('relu6')
def _relu6(ctx, ins):
    t = ctx.attr('threshold', 6.0)
    return {'Out': [jnp.clip(X(ins), 0.0, t)]}


@register('brelu')
def _brelu(ctx, ins):
    return {'Out': [jnp.clip(X(ins), ctx.attr('t_min', 0.0),
                             ctx.attr('t_max', 24.0))]}


@register('soft_relu')
def _soft_relu(ctx, ins):
    t = ctx.attr('threshold', 40.0)
    x = jnp.clip(X(ins), -t, t)
    return {'Out': [jnp.log1p(jnp.exp(x))]}


@register('stanh')
def _stanh(ctx, ins):
    a = ctx.attr('scale_a', 2.0 / 3.0)
    b = ctx.attr('scale_b', 1.7159)
    return {'Out': [b * jnp.tanh(a * X(ins))]}


@register('hard_sigmoid')
def _hard_sigmoid(ctx, ins):
    s = ctx.attr('slope', 0.2)
    o = ctx.attr('offset', 0.5)
    return {'Out': [jnp.clip(s * X(ins) + o, 0.0, 1.0)]}


@register('hard_shrink')
def _hard_shrink(ctx, ins):
    t = ctx.attr('threshold', 0.5)
    x = X(ins)
    return {'Out': [jnp.where(jnp.abs(x) > t, x, 0.0)]}


@register('softshrink')
def _softshrink(ctx, ins):
    lam = ctx.attr('lambda', 0.5)
    x = X(ins)
    return {'Out': [jnp.where(x > lam, x - lam,
                              jnp.where(x < -lam, x + lam, 0.0))]}


@register('thresholded_relu')
def _thresholded_relu(ctx, ins):
    t = ctx.attr('threshold', 1.0)
    x = X(ins)
    return {'Out': [jnp.where(x > t, x, 0.0)]}


@register('swish')
def _swish(ctx, ins):
    b = ctx.attr('beta', 1.0)
    x = X(ins)
    return {'Out': [x * jax.nn.sigmoid(b * x)]}


@register('selu')
def _selu(ctx, ins):
    scale = ctx.attr('scale', 1.0507009873554805)
    alpha = ctx.attr('alpha', 1.6732632423543772)
    x = X(ins)
    return {'Out': [scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))]}


@register('prelu')
def _prelu(ctx, ins):
    x = X(ins)
    alpha = ins['Alpha'][0]
    mode = ctx.attr('mode', 'all')
    if mode == 'all':
        a = alpha.reshape(())
    elif mode == 'channel':
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:  # element
        a = alpha.reshape((1,) + x.shape[1:])
    return {'Out': [jnp.where(x > 0, x, a * x)]}


@register('pow')
def _pow(ctx, ins):
    return {'Out': [jnp.power(X(ins), ctx.attr('factor', 1.0))]}


@register('clip')
def _clip(ctx, ins):
    return {'Out': [jnp.clip(X(ins), ctx.attr('min'), ctx.attr('max'))]}


@register('clip_by_norm')
def _clip_by_norm(ctx, ins):
    x = X(ins)
    m = ctx.attr('max_norm')
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {'Out': [jnp.where(norm > m, x * (m / norm), x)]}


# ---------------------------------------------------------------------------
# matmul family (ref: operators/mul_op.cc, matmul_op.cc) — the MXU path.
# ---------------------------------------------------------------------------
def _flatten2(x, num_col_dims):
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims else 1
    return x.reshape(lead, -1)


@register('mul')
def _mul(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    xn = ctx.attr('x_num_col_dims', 1)
    yn = ctx.attr('y_num_col_dims', 1)
    x2 = _flatten2(x, xn)
    y2 = y.reshape(int(np.prod(y.shape[:yn])), -1)
    out = amp.matmul(x2, y2, preferred_element_type=x2.dtype)
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {'Out': [out.reshape(out_shape)]}


@register('matmul')
def _matmul(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    tx, ty = ctx.attr('transpose_X', False), ctx.attr('transpose_Y', False)
    alpha = ctx.attr('alpha', 1.0)
    squeeze_out = []
    if x.ndim == 1:
        x = x[None, :]
        squeeze_out.append(-2)
    if y.ndim == 1:
        y = y[:, None]
        squeeze_out.append(-1)
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = amp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    if squeeze_out:
        out = jnp.squeeze(out, axis=tuple(squeeze_out))
    return {'Out': [out]}


@register('bilinear_tensor_product')
def _bilinear_tensor_product(ctx, ins):
    x, y, w = ins['X'][0], ins['Y'][0], ins['Weight'][0]
    # w: [out, dx, dy]
    out = jnp.einsum('bi,oij,bj->bo', x, w, y)
    if ins.get('Bias') and ins['Bias'][0] is not None:
        out = out + ins['Bias'][0]
    return {'Out': [out]}


# ---------------------------------------------------------------------------
# reductions (ref: operators/reduce_ops/)
# ---------------------------------------------------------------------------
def _reduce(name, fn):
    @register(name)
    def _lower(ctx, ins, _fn=fn):
        x = X(ins)
        if ctx.attr('reduce_all', False):
            axes = None
        else:
            dims = ctx.attr('dim', [0])
            if isinstance(dims, int):
                dims = [dims]
            axes = tuple(d % x.ndim for d in dims)
        out = _fn(x, axis=axes, keepdims=ctx.attr('keep_dim', False))
        return {'Out': [out]}


_reduce('reduce_sum', jnp.sum)
_reduce('reduce_mean', jnp.mean)
_reduce('reduce_max', jnp.max)
_reduce('reduce_min', jnp.min)
_reduce('reduce_prod', jnp.prod)


@register('mean')
def _mean(ctx, ins):
    # reference mean_op emits a {1}-shaped tensor (mean_op.cc InferShape);
    # loss reductions accumulate in f32 even when activations flow bf16
    return {'Out': [jnp.mean(amp.promote_f32(X(ins))).reshape(1)]}


@register('scale')
def _scale(ctx, ins):
    x = X(ins)
    s = ctx.attr('scale', 1.0)
    b = ctx.attr('bias', 0.0)
    if 'ScaleTensor' in ins and ins['ScaleTensor'] and ins['ScaleTensor'][0] is not None:
        s = ins['ScaleTensor'][0]
    if ctx.attr('bias_after_scale', True):
        return {'Out': [x * s + b]}
    return {'Out': [(x + b) * s]}


@register('sum')
def _sum(ctx, ins):
    from ..core.selected_rows import SelectedRowsVal, concat_rows
    xs = [x for x in ins['X'] if x is not None]
    sparse = [x for x in xs if isinstance(x, SelectedRowsVal)]
    if sparse:
        # sparse grad accumulation (ref selected_rows_functor Add): all
        # sparse -> concatenated rows (addition for scatter consumers);
        # mixed -> densify the sparse parts
        if len(sparse) == len(xs):
            return {'Out': [concat_rows(xs)]}
        xs = [x.to_dense() if isinstance(x, SelectedRowsVal) else x
              for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {'Out': [out]}


@register('cast')
def _cast(ctx, ins):
    from ..framework import runtime_dtype
    return {'Out': [X(ins).astype(runtime_dtype(ctx.attr('out_dtype')))]}


# ---------------------------------------------------------------------------
# softmax / losses (ref: operators/softmax_op.cc, cross_entropy_op.cc,
# softmax_with_cross_entropy_op.cc)
# ---------------------------------------------------------------------------
@register('softmax')
def _softmax(ctx, ins):
    x = X(ins)
    axis = ctx.attr('axis', -1)
    # exp/sum in f32 for bf16 activations, back to the compute dtype after
    return {'Out': [amp.restore(jax.nn.softmax(amp.promote_f32(x),
                                               axis=axis), x)]}


@register('log_softmax')
def _log_softmax(ctx, ins):
    x = X(ins)
    out = jax.nn.log_softmax(amp.promote_f32(x), axis=ctx.attr('axis', -1))
    return {'Out': [amp.restore(out, x)]}


@register('cross_entropy')
def _cross_entropy(ctx, ins):
    x = amp.promote_f32(X(ins))  # probabilities [N, C] (or [..., C])
    label = ins['Label'][0]
    logp = jnp.log(jnp.clip(x, 1e-20))
    if ctx.attr('soft_label', False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        ignore = ctx.attr('ignore_index', -100)
        lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        picked = jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32),
                                     axis=-1)
        loss = -picked
        loss = jnp.where((lab == ignore)[..., None], 0.0, loss)
    return {'Y': [loss]}


@register('softmax_with_cross_entropy')
def _softmax_with_cross_entropy(ctx, ins):
    logits = amp.promote_f32(ins['Logits'][0])  # loss math stays f32
    label = ins['Label'][0]
    logp = jax.nn.log_softmax(logits, axis=-1)
    if ctx.attr('soft_label', False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        ignore = ctx.attr('ignore_index', -100)
        lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        picked = jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32),
                                     axis=-1)
        loss = jnp.where((lab == ignore)[..., None], 0.0, -picked)
    return {'Softmax': [amp.restore(jnp.exp(logp), ins['Logits'][0])],
            'Loss': [loss]}


@register('square_error_cost')
def _square_error_cost(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    return {'Out': [jnp.square(x - y)]}


@register('huber_loss')
def _huber_loss(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    d = ctx.attr('delta', 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d))
    return {'Out': [loss], 'Residual': [r]}


@register('hinge_loss')
def _hinge_loss(ctx, ins):
    """max(0, 1 - logits * (2*label - 1)) with {0,1} labels
    (ref: operators/hinge_loss_op.cc)."""
    x, y = ins['Logits'][0], ins['Labels'][0]
    return {'Loss': [jnp.maximum(0.0, 1.0 - x * (2.0 * y - 1.0))]}


@register('modified_huber_loss')
def _modified_huber_loss(ctx, ins):
    """z = x*(2y-1); loss = -4z for z<-1, (1-z)^2 for z<1, else 0
    (ref: operators/modified_huber_loss_op.cc)."""
    x, y = ins['X'][0], ins['Y'][0]
    z = x * (2.0 * y - 1.0)
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.square(jnp.maximum(0.0, 1.0 - z)))
    return {'Out': [loss.reshape(-1, 1)], 'IntermediateVal': [z]}


@register('squared_l2_distance')
def _squared_l2_distance(ctx, ins):
    """Row-wise ||x - y||^2; y may have one row broadcast over the batch
    (ref: operators/squared_l2_distance_op.cc)."""
    x, y = ins['X'][0], ins['Y'][0]
    x2 = x.reshape(x.shape[0], -1)
    y2 = y.reshape(y.shape[0], -1)
    sub = x2 - y2  # broadcasts when y has a single row
    return {'sub_result': [sub],
            'Out': [jnp.sum(jnp.square(sub), axis=1, keepdims=True)]}


@register('l1_norm')
def _l1_norm(ctx, ins):
    """Scalar sum of absolute values (ref: operators/l1_norm_op.cc)."""
    return {'Out': [jnp.sum(jnp.abs(X(ins))).reshape(1)]}


@register('smooth_l1_loss')
def _smooth_l1_loss(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    sigma = ctx.attr('sigma', 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ins.get('InsideWeight') and ins['InsideWeight'][0] is not None:
        diff = diff * ins['InsideWeight'][0]
    a = jnp.abs(diff)
    val = jnp.where(a < 1.0 / s2, 0.5 * s2 * diff * diff, a - 0.5 / s2)
    if ins.get('OutsideWeight') and ins['OutsideWeight'][0] is not None:
        val = val * ins['OutsideWeight'][0]
    loss = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return {'Out': [loss], 'Diff': [diff]}


@register('log_loss')
def _log_loss(ctx, ins):
    p = ins['Predicted'][0]
    y = ins['Labels'][0]
    eps = ctx.attr('epsilon', 1e-4)
    loss = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    return {'Loss': [loss]}


@register('sigmoid_cross_entropy_with_logits')
def _sce_logits(ctx, ins):
    x = X(ins)
    label = ins['Label'][0]
    ignore = ctx.attr('ignore_index', -100)
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    loss = jnp.where(label == ignore, 0.0, loss)
    if ctx.attr('normalize', False):
        cnt = jnp.maximum(jnp.sum(label != ignore), 1)
        loss = loss / cnt
    return {'Out': [loss]}


@register('bpr_loss')
def _bpr_loss(ctx, ins):
    x = X(ins)  # [N, C] logits/probs
    label = ins['Label'][0]
    lab = label.reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lab[:, None], axis=1)
    # -log sigmoid(x_pos - x_neg) = log1p(exp(x_neg - x_pos)), averaged over
    # the negatives (ref bpr_loss_op.h:72 sums -log(1+exp(neg-pos)) and
    # negates/normalizes)
    diff = x - pos
    # exclude the positive column itself
    mask = jnp.ones_like(x, dtype=bool).at[jnp.arange(x.shape[0]), lab].set(False)
    loss = jnp.where(mask, jnp.log1p(jnp.exp(diff)), 0.0)
    loss = jnp.sum(loss, axis=1, keepdims=True) / (x.shape[1] - 1)
    return {'Y': [loss]}


@register('margin_rank_loss')
def _margin_rank_loss(ctx, ins):
    x1, x2, label = ins['X1'][0], ins['X2'][0], ins['Label'][0]
    m = ctx.attr('margin', 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + m)
    return {'Out': [act], 'Activated': [(act > 0).astype(x1.dtype)]}


@register('rank_loss')
def _rank_loss(ctx, ins):
    label = ins['Label'][0]
    left, right = ins['Left'][0], ins['Right'][0]
    d = left - right
    return {'Out': [jnp.log1p(jnp.exp(d)) - label * d]}


@register('cos_sim')
def _cos_sim(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True))
    out = jnp.sum(x * y, axis=1, keepdims=True) / (xn * yn + 1e-12)
    return {'Out': [out], 'XNorm': [xn], 'YNorm': [yn]}


# ---------------------------------------------------------------------------
# logical / compare (ref: operators/controlflow/compare_op.cc, logical_op.cc)
# ---------------------------------------------------------------------------
def _compare(name, fn):
    @register(name, no_grad=True)
    def _lower(ctx, ins, _fn=fn):
        x, y = ins['X'][0], ins['Y'][0]
        y = _bcast_y(x, y, ctx.attr('axis', -1))
        return {'Out': [_fn(x, y)]}


_compare('less_than', jnp.less)
_compare('less_equal', jnp.less_equal)
_compare('greater_than', jnp.greater)
_compare('greater_equal', jnp.greater_equal)
_compare('equal', jnp.equal)
_compare('not_equal', jnp.not_equal)
_compare('logical_and', jnp.logical_and)
_compare('logical_or', jnp.logical_or)
_compare('logical_xor', jnp.logical_xor)


@register('logical_not', no_grad=True)
def _logical_not(ctx, ins):
    return {'Out': [jnp.logical_not(X(ins))]}


@register('isfinite', no_grad=True)
def _isfinite(ctx, ins):
    return {'Out': [jnp.all(jnp.isfinite(X(ins)))[None]]}


@register('squared_l2_norm', lod='none')
def _squared_l2_norm(ctx, ins):
    x = X(ins)
    return {'Out': [jnp.sum(jnp.square(x))]}


@register('global_norm_scale', no_grad=True, lod='none')
def _global_norm_scale(ctx, ins):
    norm = ins['Norm'][0]
    clip = ctx.attr('clip_norm')
    return {'Out': [jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))]}


@register('norm')
def _norm(ctx, ins):
    x = X(ins)
    axis = ctx.attr('axis', -1)
    eps = ctx.attr('epsilon', 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {'Out': [x / norm], 'Norm': [norm]}


@register('teacher_student_sigmoid_loss', diff_inputs=('X',))
def _teacher_student_sigmoid_loss(ctx, ins):
    """ref teacher_student_sigmoid_loss_op.h: BCE on the click bit plus BCE
    on the teacher score, encoded in one label:
      label < -1: clk=0, no teacher;  label in [-1,0): clk=1, no teacher;
      label in [0,1): clk=0, teacher=label;  label >= 1: clk=1,
      teacher=label-1."""
    x = X(ins).reshape(-1)
    lab = ins['Label'][0].reshape(-1)
    bce = lambda z: jnp.maximum(x, 0.0) - x * z + jnp.log1p(
        jnp.exp(-jnp.abs(x)))
    clk = jnp.where(lab < -1.0, 0.0,
                    jnp.where(lab < 0.0, 1.0,
                              jnp.where(lab < 1.0, 0.0, 1.0)))
    teacher = jnp.where(lab < 0.0, 0.0,
                        jnp.where(lab < 1.0, lab, lab - 1.0))
    has_teacher = lab >= 0.0
    loss = bce(clk) + jnp.where(has_teacher, bce(teacher), 0.0)
    return {'Y': [loss.reshape(-1, 1)]}
