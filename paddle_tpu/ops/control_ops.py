"""Small control/scalar ops (ref: operators/controlflow/, increment_op.cc).

The heavyweight control flow (while / conditional_block) lowers to
lax.while_loop / lax.cond in sequence_ops/control_flow lowering."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register
from .math_ops import X


@register('increment', no_grad=True, lod='none')
def _increment(ctx, ins):
    x = X(ins)
    return {'Out': [x + jnp.asarray(ctx.attr('step', 1.0), dtype=x.dtype)]}


@register('select', lod='none')
def _select(ctx, ins):
    cond = ins['Cond'][0]
    x, y = ins['X'][0], ins['Y'][0]
    return {'Out': [jnp.where(cond.reshape([1] * x.ndim) if cond.ndim < x.ndim
                              else cond, x, y)]}


@register('is_empty', no_grad=True, lod='none')
def _is_empty(ctx, ins):
    x = X(ins)
    return {'Out': [jnp.asarray(x.size == 0).reshape(1)]}


@register('print', no_grad=True)
def _print(ctx, ins):
    # jax.debug.print would force host sync; keep as identity (debug hook
    # available via FLAGS in utils/flags.py)
    x = ins['In'][0] if 'In' in ins else X(ins)
    return {'Out': [x]}
