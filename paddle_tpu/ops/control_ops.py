"""Control-flow op lowerings (ref: operators/controlflow/while_op.cc:50,
conditional_block_op.cc, recurrent_op.cc, increment_op.cc).

TPU-native design: the reference interprets sub-blocks against nested scopes
per iteration; here each structured op lowers to ONE XLA control-flow op —
`while` → lax.while_loop with an explicit carry (the sub-block's writes that
are visible outside), `static_rnn`/`dynamic_rnn` → lax.scan (differentiable,
so the generic vjp grad path covers their backward with no per-op grad
code), `conditional_block` → dense compute-both + select (scalar-predicate
blocks stay fusible; no divergent branches on the MXU)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core.lod import LoDArray, unwrap
from ..core.tensor_array import TensorArrayVal
from .math_ops import X


def _written_names(program, block):
    """All var names written by a block, transitively through nested
    sub-blocks (control-flow ops store the child index in attrs)."""
    out = set()
    for op in block.ops:
        out.update(n for n in op.output_arg_names() if n)
        for key in ('sub_block', 'sub_block_false'):
            idx = op.attrs.get(key)
            if isinstance(idx, int):
                out.update(_written_names(program, program.block(idx)))
    return out


def _select_val(pred, new, old):
    """Scalar-predicate select over any runtime value kind."""
    if isinstance(new, LoDArray) or isinstance(old, LoDArray):
        nd, od = unwrap(new), unwrap(old)
        lod = new.lod if isinstance(new, LoDArray) else old.lod
        return LoDArray(jnp.where(pred, nd, od), lod)
    if isinstance(new, TensorArrayVal):
        old_data = old.data if isinstance(old, TensorArrayVal) else None
        old_len = old.length if isinstance(old, TensorArrayVal) \
            else jnp.asarray(0, jnp.int32)
        if old_data is None:
            # first array_write happened inside the conditional branch: the
            # not-taken side is the zero-filled buffer of the same shape
            old_data = jnp.zeros_like(new.data)
        return TensorArrayVal(jnp.where(pred, new.data, old_data),
                              jnp.where(pred, new.length, old_len),
                              new.capacity)
    return jnp.where(pred, new, jnp.asarray(old, new.dtype)
                     if hasattr(new, 'dtype') else old)


@register('while', no_grad=True, lod='aware')
def _while(ctx, ins):
    """lax.while_loop over the sub-block. Carry = sub-block writes that have
    a pre-loop value (everything else is a loop-local temporary recomputed
    each iteration). Decode-style loops (beam search) are the target; grads
    flow through scan-based RNN ops instead (reverse-mode while is
    unbounded-memory by construction)."""
    tracer = ctx.tracer
    program = tracer.program
    sub_idx = int(ctx.attr('sub_block'))
    sub = program.block(sub_idx)
    cond_name = ctx.op.inputs['Condition'][0]

    written = _written_names(program, sub)
    carry_names = sorted(n for n in written if n in tracer.env)
    if cond_name not in carry_names:
        raise RuntimeError(
            "While loop condition %r is never updated inside the loop body "
            "— the loop would not terminate" % cond_name)
    init = {n: tracer.env[n] for n in carry_names}
    for n, v in init.items():
        if isinstance(v, TensorArrayVal) and v.data is None:
            raise RuntimeError(
                "TensorArray %r enters a While loop unallocated; write an "
                "element before the loop or create it with capacity + an "
                "initial write so its buffer shape is static" % n)

    def cond_fn(carry):
        return jnp.reshape(unwrap(carry[cond_name]), ())

    def body_fn(carry):
        benv = dict(tracer.env)
        benv.update(carry)
        tracer.run_block(sub, benv)
        return {n: benv[n] for n in carry_names}

    out = jax.lax.while_loop(cond_fn, body_fn, init)
    for n, v in out.items():
        tracer.write(n, v)
    return {}


@register('conditional_block', no_grad=True, lod='aware')
def _conditional_block(ctx, ins):
    """Dense lowering: the sub-block runs unconditionally and each write is
    merged with its prior value under the scalar predicate. Identical math
    for the side-effect-free ops the IR allows, and XLA fuses the selects."""
    tracer = ctx.tracer
    program = tracer.program
    sub_idx = int(ctx.attr('sub_block'))
    sub = program.block(sub_idx)
    pred = jnp.reshape(unwrap(ins['Cond'][0]), ())

    benv = dict(tracer.env)
    tracer.run_block(sub, benv)
    for n in sorted(_written_names(program, sub)):
        if n not in benv:
            continue
        new = benv[n]
        old = tracer.env.get(n)
        if old is None:
            tracer.write(n, new)
        elif new is not old:
            tracer.write(n, _select_val(pred, new, old))
    return {}


# ---------------------------------------------------------------------------
# Activation rematerialization: remat_segment → jax.checkpoint over the
# sub-block (passes/recompute.py owns the rewrite; ISSUE 18 tentpole).
# ---------------------------------------------------------------------------

def _remat_infer_shape(op, block):
    # The recompute rewrite moves ops verbatim AFTER their outputs were
    # shape-inferred at build time; boundary var metadata is already
    # correct, and the abstract ShapeCtx cannot run sub-blocks anyway.
    return


@register('remat_segment', lod='aware', infer_shape=_remat_infer_shape)
def _remat_segment(ctx, ins):
    """Run the segment sub-block under jax.checkpoint: only the boundary
    values (X in, Out out) survive the forward; when append_backward
    differentiates this op through the generic vjp path, the interior
    recomputes inside the checkpoint's rematerialized trace — XLA's CSE
    cannot merge it back into the original forward (prevent_cse
    barriers), which is the whole point. Seeded interior ops (dropout)
    replay bit-identical draws: the rewrite preserved their ``_op_uid``
    attrs, so the (program seed, step, op seed) rng fold is unchanged.

    At grad-replay time ``ctx.op`` is the remat_segment_grad op, whose
    inputs/outputs are the grad maps — the forward boundary names ride
    its ``_fwd_inputs``/``_fwd_outputs`` attrs instead."""
    op = ctx.op
    if op.type == 'remat_segment':
        in_names = list(op.inputs.get('X', ()))
        out_names = list(op.outputs.get('Out', ()))
    else:
        in_names = list(op.attrs['_fwd_inputs']['X'])
        out_names = list(op.attrs['_fwd_outputs']['Out'])
    sub_idx = int(ctx.attr('sub_block'))

    def seg(*vals):
        env = dict(zip(in_names, vals))
        ctx.run_block(sub_idx, env)
        return tuple(env[n] for n in out_names)

    outs = jax.checkpoint(seg)(*ins['X'])
    return {'Out': list(outs)}


# ---------------------------------------------------------------------------
# Recurrent sub-block ops: StaticRNN / DynamicRNN → lax.scan
# (ref: operators/recurrent_op.cc, python/paddle/fluid/layers/
# control_flow.py StaticRNN:278, DynamicRNN:1395).
# ---------------------------------------------------------------------------

def _pad_time_major(x):
    """LoDArray [sum, D] -> (xs [L, B, D], mask [L, B]) via the static lod."""
    from .rnn_ops import _pad_from_lod
    off = np.asarray(x.lod[0], np.int64)
    padded, mask = _pad_from_lod(unwrap(x), off)   # [B, L, D], [B, L]
    return jnp.moveaxis(padded, 1, 0), jnp.moveaxis(mask, 1, 0)


def _unpad_time_major(ys, lod):
    """[L, B, D] -> packed LoD rows [sum, D]."""
    from .rnn_ops import _unpad_to_lod
    off = np.asarray(lod[0], np.int64)
    return LoDArray(_unpad_to_lod(jnp.moveaxis(ys, 0, 1), off), lod)


def _run_step_block(ctx, sub_idx, bindings):
    env = dict(ctx.tracer.env)
    env.update(bindings)
    ctx.run_block(sub_idx, env)
    return env


@register('static_rnn', lod='aware')
def _static_rnn(ctx, ins):
    """Time-major scan: step inputs are [T, ...] tensors sliced per step.
    Differentiable end-to-end (scan), so append_backward's generic grad op
    covers the reference's RecurrentGradOp."""
    a = ctx.attrs
    sub_idx = int(a['sub_block'])
    step_inputs = a['rnn_step_inputs']    # [(outer, inner)]
    memories = a['rnn_memories']          # [(init_outer, pre_inner, upd_inner)]
    step_outputs = a['rnn_step_outputs']  # [(inner, outer)]
    ex_names = list(a.get('rnn_externals', ()))

    xs = [unwrap(v) for v in ins.get('X', [])]
    init = [unwrap(v) for v in ins.get('Init', [])]
    exs = dict(zip(ex_names, ins.get('Ex', [])))

    def body(carry, xts):
        bind = dict(exs)
        for (_, inner), xt in zip(step_inputs, xts):
            bind[inner] = xt
        for (_, pre, _), c in zip(memories, carry):
            bind[pre] = c
        env = _run_step_block(ctx, sub_idx, bind)
        new_carry = [env[upd] for (_, _, upd) in memories]
        ys = [env[inner] for (inner, _) in step_outputs]
        return new_carry, ys

    final, ys = jax.lax.scan(body, init, xs)
    return {'Out': ys, 'Final': final}


@register('dynamic_rnn', lod='aware')
def _dynamic_rnn(ctx, ins):
    """LoD-aware scan: variable-length sequences padded (static lod → static
    max_len), memories masked frozen past each sequence's end, outputs packed
    back to LoD rows. The reference instead sorts by length and shrinks the
    batch per step (lod_tensor_to_array / shrink_memory) — dynamic shapes
    XLA can't tile; masking is the TPU-native equivalent with the same
    per-row math."""
    a = ctx.attrs
    sub_idx = int(a['sub_block'])
    step_inputs = a['rnn_step_inputs']
    static_inputs = a.get('rnn_static_inputs', ())  # [(outer, inner)]
    memories = a['rnn_memories']
    step_outputs = a['rnn_step_outputs']
    ex_names = list(a.get('rnn_externals', ()))

    x0 = ins['X'][0]
    if not (isinstance(x0, LoDArray) and x0.lod):
        raise TypeError("dynamic_rnn step_input must be a LoD tensor")
    lod = x0.lod
    xs_mask = [_pad_time_major(v) for v in ins['X']]
    xs = [p for p, _ in xs_mask]
    mask = xs_mask[0][1]                     # [L, B]
    nseq = xs[0].shape[1]

    init = []
    for spec, v in zip(memories, ins.get('Init', [])):
        if v is None:
            shape, value, dtype = spec[3], spec[4], spec[5]
            init.append(jnp.full((nseq,) + tuple(shape), value,
                                 jnp.dtype(dtype)))
        else:
            init.append(unwrap(v))
    exs = dict(zip(ex_names, ins.get('Ex', [])))
    statics = {inner: unwrap(v)
               for (_, inner), v in zip(static_inputs, ins.get('Static', []))}

    def body(carry, scan_in):
        xts, m_t = scan_in
        bind = dict(exs)
        bind.update(statics)
        for (_, inner), xt in zip(step_inputs, xts):
            bind[inner] = xt
        for spec, c in zip(memories, carry):
            bind[spec[1]] = c
        env = _run_step_block(ctx, sub_idx, bind)
        new_carry = []
        for spec, c in zip(memories, carry):
            new = env[spec[2]]
            keep = m_t.reshape((-1,) + (1,) * (new.ndim - 1))
            new_carry.append(jnp.where(keep, new, c))
        ys = [env[inner] for (inner, _) in step_outputs]
        return new_carry, ys

    _, ys = jax.lax.scan(body, init, (xs, mask))
    outs = []
    for y in ys:
        keep = mask.reshape(mask.shape + (1,) * (y.ndim - 2))
        outs.append(_unpad_time_major(y * keep.astype(y.dtype), lod))
    return {'Out': outs}


@register('increment', no_grad=True, lod='none')
def _increment(ctx, ins):
    x = X(ins)
    return {'Out': [x + jnp.asarray(ctx.attr('step', 1.0), dtype=x.dtype)]}


@register('select', lod='none')
def _select(ctx, ins):
    cond = ins['Cond'][0]
    x, y = ins['X'][0], ins['Y'][0]
    # per-row semantics: align cond rank to x by dropping trailing 1-dims
    # (e.g. [N,1] cond over [N] values) or adding broadcast dims
    while cond.ndim > x.ndim and cond.shape[-1] == 1:
        cond = cond.reshape(cond.shape[:-1])
    if cond.ndim < x.ndim:
        cond = cond.reshape(cond.shape + (1,) * (x.ndim - cond.ndim))
    return {'Out': [jnp.where(cond, x, y)]}


@register('is_empty', no_grad=True, lod='none')
def _is_empty(ctx, ins):
    x = X(ins)
    return {'Out': [jnp.asarray(x.size == 0).reshape(1)]}


@register('print', no_grad=True)
def _print(ctx, ins):
    # jax.debug.print would force host sync; keep as identity (debug hook
    # available via FLAGS in utils/flags.py)
    x = ins['In'][0] if 'In' in ins else X(ins)
    return {'Out': [x]}
