"""Optimizer op lowerings (ref: paddle/fluid/operators/optimizers/).

Each update is an op in the graph, exactly like the reference — the
"in-place" ParamOut/MomentOut outputs are env rebindings inside the traced
step function, so the whole update fuses into the compiled step. All are
no_grad (OpRole kOptimize).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core.selected_rows import SelectedRowsVal


def _lr(ins):
    lr = ins['LearningRate'][0]
    return lr.reshape(()) if hasattr(lr, 'reshape') else lr


def _is_sparse(g):
    return isinstance(g, SelectedRowsVal)


@register('sgd', no_grad=True, lod='none')
def _sgd(ctx, ins):
    p, g = ins['Param'][0], ins['Grad'][0]
    lr = _lr(ins)
    if _is_sparse(g):
        # sparse update touches only looked-up rows (ref sgd_op.h
        # SelectedRows path); duplicate ids accumulate via scatter-add
        return {'ParamOut': [p.at[g.rows].add(-lr * g.values, mode='drop')]}
    return {'ParamOut': [p - lr * g]}


@register('momentum', no_grad=True, lod='none')
def _momentum(ctx, ins):
    p, g, v = ins['Param'][0], ins['Grad'][0], ins['Velocity'][0]
    mu = ctx.attr('mu')
    lr = _lr(ins)
    if _is_sparse(g):
        # rowwise sparse momentum (ref momentum_op.h SparseMomentumFunctor):
        # only touched rows update velocity/param; merge duplicates first so
        # the read-modify-write per row sees the full row gradient
        m = g.merged()
        gv = m.values
        rows = m.rows
        v_rows = v.at[rows].get(mode='fill', fill_value=0.0)
        v_new = mu * v_rows + gv
        if ctx.attr('use_nesterov', False):
            p_delta = (gv + mu * v_new) * lr
        else:
            p_delta = lr * v_new
        return {'ParamOut': [p.at[rows].add(-p_delta, mode='drop')],
                'VelocityOut': [v.at[rows].set(v_new, mode='drop')]}
    v_out = mu * v + g
    if ctx.attr('use_nesterov', False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {'ParamOut': [p_out], 'VelocityOut': [v_out]}


@register('lars_momentum', no_grad=True, lod='none')
def _lars_momentum(ctx, ins):
    p, g, v = ins['Param'][0], ins['Grad'][0], ins['Velocity'][0]
    mu = ctx.attr('mu')
    coeff = ctx.attr('lars_coeff', 0.001)
    decay = ctx.attr('lars_weight_decay', 0.0005)
    lr = _lr(ins)
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * coeff * pn / (gn + decay * pn + 1e-20)
    v_out = mu * v + local_lr * (g + decay * p)
    return {'ParamOut': [p - v_out], 'VelocityOut': [v_out]}


@register('adam', no_grad=True, lod='none')
def _adam(ctx, ins):
    p, g = ins['Param'][0], ins['Grad'][0]
    m, v = ins['Moment1'][0], ins['Moment2'][0]
    b1p, b2p = ins['Beta1Pow'][0], ins['Beta2Pow'][0]
    b1 = ctx.attr('beta1', 0.9)
    b2 = ctx.attr('beta2', 0.999)
    eps = ctx.attr('epsilon', 1e-8)
    lr = _lr(ins)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    if _is_sparse(g) and not ctx.attr('lazy_mode', False):
        # reference default (lazy_mode=False): a sparse grad still updates
        # every row's moments/param (missing rows see grad 0) — densify and
        # fall through (ref adam_op.h SparseAdamFunctor non-lazy branch)
        g = g.merged().to_dense()
    if _is_sparse(g):
        # lazy sparse adam (ref adam_op.h SparseAdamFunctor, lazy_mode):
        # moments/param update only on looked-up rows
        mg = g.merged()
        rows, gv = mg.rows, mg.values
        m_rows = m.at[rows].get(mode='fill', fill_value=0.0)
        v_rows = v.at[rows].get(mode='fill', fill_value=0.0)
        m_new = b1 * m_rows + (1 - b1) * gv
        v_new = b2 * v_rows + (1 - b2) * jnp.square(gv)
        delta = lr_t * m_new / (jnp.sqrt(v_new) + eps)
        return {'ParamOut': [p.at[rows].add(-delta, mode='drop')],
                'Moment1Out': [m.at[rows].set(m_new, mode='drop')],
                'Moment2Out': [v.at[rows].set(v_new, mode='drop')],
                'Beta1PowOut': [b1p * b1], 'Beta2PowOut': [b2p * b2]}
    m_out = b1 * m + (1 - b1) * g
    v_out = b2 * v + (1 - b2) * jnp.square(g)
    p_out = p - lr_t * m_out / (jnp.sqrt(v_out) + eps)
    return {'ParamOut': [p_out], 'Moment1Out': [m_out], 'Moment2Out': [v_out],
            'Beta1PowOut': [b1p * b1], 'Beta2PowOut': [b2p * b2]}


@register('adamax', no_grad=True, lod='none')
def _adamax(ctx, ins):
    p, g = ins['Param'][0], ins['Grad'][0]
    m, inf = ins['Moment'][0], ins['InfNorm'][0]
    b1p = ins['Beta1Pow'][0]
    b1 = ctx.attr('beta1', 0.9)
    b2 = ctx.attr('beta2', 0.999)
    eps = ctx.attr('epsilon', 1e-8)
    lr = _lr(ins)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g))
    p_out = p - (lr / (1 - b1p.reshape(()))) * (m_out / (inf_out + eps))
    return {'ParamOut': [p_out], 'MomentOut': [m_out], 'InfNormOut': [inf_out]}


@register('adagrad', no_grad=True, lod='none')
def _adagrad(ctx, ins):
    p, g, m = ins['Param'][0], ins['Grad'][0], ins['Moment'][0]
    eps = ctx.attr('epsilon', 1e-6)
    lr = _lr(ins)
    if _is_sparse(g):
        # sparse adagrad (ref adagrad_op.h SparseAdagradFunctor)
        mg = g.merged()
        rows, gv = mg.rows, mg.values
        m_rows = m.at[rows].get(mode='fill', fill_value=0.0)
        m_new = m_rows + jnp.square(gv)
        delta = lr * gv / (jnp.sqrt(m_new) + eps)
        return {'ParamOut': [p.at[rows].add(-delta, mode='drop')],
                'MomentOut': [m.at[rows].set(m_new, mode='drop')]}
    m_out = m + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {'ParamOut': [p_out], 'MomentOut': [m_out]}


@register('decayed_adagrad', no_grad=True, lod='none')
def _decayed_adagrad(ctx, ins):
    p, g, m = ins['Param'][0], ins['Grad'][0], ins['Moment'][0]
    decay = ctx.attr('decay', 0.95)
    eps = ctx.attr('epsilon', 1e-6)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {'ParamOut': [p_out], 'MomentOut': [m_out]}


@register('adadelta', no_grad=True, lod='none')
def _adadelta(ctx, ins):
    p, g = ins['Param'][0], ins['Grad'][0]
    avg_sq_g, avg_sq_u = ins['AvgSquaredGrad'][0], ins['AvgSquaredUpdate'][0]
    rho = ctx.attr('rho', 0.95)
    eps = ctx.attr('epsilon', 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(upd)
    return {'ParamOut': [p + upd], 'AvgSquaredGradOut': [g2],
            'AvgSquaredUpdateOut': [u2]}


@register('rmsprop', no_grad=True, lod='none')
def _rmsprop(ctx, ins):
    p, g = ins['Param'][0], ins['Grad'][0]
    ms, mom = ins['MeanSquare'][0], ins['Moment'][0]
    rho = ctx.attr('decay', 0.95)
    eps = ctx.attr('epsilon', 1e-6)
    mu = ctx.attr('momentum', 0.0)
    lr = _lr(ins)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    if ctx.attr('centered', False):
        mg = ins['MeanGrad'][0]
        mg_out = rho * mg + (1 - rho) * g
        mom_out = mu * mom + lr * g / jnp.sqrt(ms_out - jnp.square(mg_out) + eps)
        return {'ParamOut': [p - mom_out], 'MeanSquareOut': [ms_out],
                'MomentOut': [mom_out], 'MeanGradOut': [mg_out]}
    mom_out = mu * mom + lr * g / jnp.sqrt(ms_out + eps)
    return {'ParamOut': [p - mom_out], 'MeanSquareOut': [ms_out],
            'MomentOut': [mom_out]}


@register('ftrl', no_grad=True, lod='none')
def _ftrl(ctx, ins):
    p, g = ins['Param'][0], ins['Grad'][0]
    sq, lin = ins['SquaredAccumulator'][0], ins['LinearAccumulator'][0]
    l1 = ctx.attr('l1', 0.0) + 1e-10
    l2 = ctx.attr('l2', 0.0) + 1e-10
    lr_power = ctx.attr('lr_power', -0.5)
    lr = _lr(ins)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    lin_out = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = pre / denom
    return {'ParamOut': [p_out], 'SquaredAccumOut': [new_sq],
            'LinearAccumOut': [lin_out]}


@register('proximal_gd', no_grad=True, lod='none')
def _proximal_gd(ctx, ins):
    p, g = ins['Param'][0], ins['Grad'][0]
    l1 = ctx.attr('l1', 0.0)
    l2 = ctx.attr('l2', 0.0)
    lr = _lr(ins)
    prox = p - lr * g
    if l1 > 0:
        prox = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0))
    return {'ParamOut': [prox / (1.0 + lr * l2)]}


@register('proximal_adagrad', no_grad=True, lod='none')
def _proximal_adagrad(ctx, ins):
    p, g, m = ins['Param'][0], ins['Grad'][0], ins['Moment'][0]
    l1 = ctx.attr('l1', 0.0)
    l2 = ctx.attr('l2', 0.0)
    lr = _lr(ins)
    m_out = m + jnp.square(g)
    eff_lr = lr / jnp.sqrt(m_out)
    prox = p - eff_lr * g
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff_lr * l1, 0.0)
    return {'ParamOut': [prox / (1.0 + eff_lr * l2)], 'MomentOut': [m_out]}


@register('average_accumulates', no_grad=True, lod='none')
def _average_accumulates(ctx, ins):
    """ModelAverage support (ref: operators/average_accumulates_op.cc)."""
    param = ins['param'][0]
    sum1, sum2, sum3 = ins['in_sum_1'][0], ins['in_sum_2'][0], ins['in_sum_3'][0]
    num_acc = ins['in_num_accumulates'][0]
    old_num = ins['in_old_num_accumulates'][0]
    num_upd = ins['in_num_updates'][0]
    avg_window = ctx.attr('average_window', 0.0)
    max_avg = ctx.attr('max_average_window', 10000)
    min_avg = ctx.attr('min_average_window', 10000)

    num_acc = num_acc + 1
    num_upd = num_upd + 1
    sum1 = sum1 + param
    window = jnp.maximum(min_avg, jnp.minimum(
        max_avg, num_upd.astype(jnp.float32) * avg_window)).astype(num_acc.dtype)
    do_shift = num_acc >= window
    new_sum1 = jnp.where(do_shift, jnp.zeros_like(sum1), sum1)
    new_sum2 = jnp.where(do_shift, sum2 + sum1, sum2)
    # shift sum2->sum3 when it, too, ages out (simplified single-window shift)
    new_sum3 = jnp.where(do_shift & (old_num > 0), sum3 + sum2, sum3)
    new_sum2 = jnp.where(do_shift & (old_num > 0), jnp.zeros_like(sum2), new_sum2)
    new_old = jnp.where(do_shift, num_acc, old_num)
    new_num = jnp.where(do_shift, jnp.zeros_like(num_acc), num_acc)
    return {'out_sum_1': [new_sum1], 'out_sum_2': [new_sum2],
            'out_sum_3': [new_sum3], 'out_num_accumulates': [new_num],
            'out_old_num_accumulates': [new_old], 'out_num_updates': [num_upd]}
