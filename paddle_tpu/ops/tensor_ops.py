"""Tensor creation / manipulation op lowerings
(ref: operators/reshape_op.cc, transpose_op.cc, concat_op.cc, split_op.cc,
slice_op.cc, gather_op.cc, fill_constant_op.cc, uniform_random_op.cc, ...).
Random ops draw from the per-op folded PRNG stream (ctx.rng()) — the
counter-based TPU-native replacement for the reference's per-device curand
generators.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..framework import runtime_dtype, int_t as INT_T
from ..framework import convert_dtype
from .math_ops import X


def _np_dtype(attr_dtype, default='float32'):
    # runtime_dtype canonicalizes declared 64-bit dtypes to the 32-bit
    # carrier (no jax x64) without a per-call truncation warning
    return runtime_dtype(convert_dtype(attr_dtype)
                         if attr_dtype is not None else default)


# -- creation ---------------------------------------------------------------
@register('fill_constant', no_grad=True)
def _fill_constant(ctx, ins):
    shape = [int(s) for s in ctx.attr('shape', [1])]
    dt = _np_dtype(ctx.attr('dtype'))
    return {'Out': [jnp.full(shape, ctx.attr('value', 0.0), dtype=dt)]}


@register('fill_constant_batch_size_like', no_grad=True)
def _fill_constant_bsl(ctx, ins):
    x = ins['Input'][0]
    shape = [int(s) for s in ctx.attr('shape')]
    in_idx = ctx.attr('input_dim_idx', 0)
    out_idx = ctx.attr('output_dim_idx', 0)
    shape[out_idx] = x.shape[in_idx]
    dt = _np_dtype(ctx.attr('dtype'))
    return {'Out': [jnp.full(shape, ctx.attr('value', 0.0), dtype=dt)]}


@register('range', no_grad=True)
def _range(ctx, ins):
    # static start/end/step (attrs) -> jnp.arange; tensor inputs would make
    # the output shape dynamic, which XLA cannot compile
    dt = _np_dtype(ctx.attr('dtype'), 'int64')
    return {'Out': [jnp.arange(ctx.attr('start', 0), ctx.attr('end'),
                               ctx.attr('step', 1), dtype=dt)]}


@register('fill_zeros_like', no_grad=True)
def _fill_zeros_like(ctx, ins):
    return {'Out': [jnp.zeros_like(X(ins))]}


@register('fill_any_like', no_grad=True)
def _fill_any_like(ctx, ins):
    dt = ctx.attr('dtype', None)
    x = X(ins)
    dtype = _np_dtype(dt, str(x.dtype)) if dt not in (None, -1) else x.dtype
    return {'Out': [jnp.full_like(x, ctx.attr('value', 0.0), dtype=dtype)]}


@register('assign')
def _assign(ctx, ins):
    return {'Out': [X(ins)]}


@register('assign_value', no_grad=True)
def _assign_value(ctx, ins):
    dt = _np_dtype(ctx.attr('dtype'))
    shape = ctx.attr('shape')
    if jnp.issubdtype(dt, jnp.integer) or dt == jnp.bool_:
        vals = ctx.attr('int32_values') or ctx.attr('int64_values')
    else:
        vals = ctx.attr('fp32_values')
    host = np.asarray(vals, dtype=dt).reshape(shape)
    # host side-channel: trace-time consumers (sequence_slice offsets etc.)
    # can read the constant even though the jnp value is a tracer under jit
    ctx.tracer.host_consts[ctx.op.outputs['Out'][0]] = host
    return {'Out': [jnp.asarray(host)]}


@register('shape', no_grad=True)
def _shape(ctx, ins):
    x = ins['Input'][0] if 'Input' in ins else X(ins)
    return {'Out': [jnp.asarray(x.shape, dtype=jnp.int32)]}


# -- random -----------------------------------------------------------------
@register('uniform_random', no_grad=True)
def _uniform_random(ctx, ins):
    shape = [int(s) for s in ctx.attr('shape')]
    dt = _np_dtype(ctx.attr('dtype'))
    lo, hi = ctx.attr('min', -1.0), ctx.attr('max', 1.0)
    out = jax.random.uniform(ctx.rng(), shape, dtype=dt, minval=lo, maxval=hi)
    return {'Out': [out]}


@register('uniform_random_batch_size_like', no_grad=True)
def _uniform_random_bsl(ctx, ins):
    x = X(ins, 'Input') if 'Input' in ins else X(ins)
    shape = [int(s) for s in ctx.attr('shape')]
    shape[ctx.attr('output_dim_idx', 0)] = x.shape[ctx.attr('input_dim_idx', 0)]
    dt = _np_dtype(ctx.attr('dtype'))
    out = jax.random.uniform(ctx.rng(), shape, dtype=dt,
                             minval=ctx.attr('min', -1.0),
                             maxval=ctx.attr('max', 1.0))
    return {'Out': [out]}


@register('gaussian_random', no_grad=True)
def _gaussian_random(ctx, ins):
    shape = [int(s) for s in ctx.attr('shape')]
    dt = _np_dtype(ctx.attr('dtype'))
    out = (ctx.attr('mean', 0.0)
           + ctx.attr('std', 1.0) * jax.random.normal(ctx.rng(), shape, dt))
    return {'Out': [out]}


@register('gaussian_random_batch_size_like', no_grad=True)
def _gaussian_random_bsl(ctx, ins):
    x = ins['Input'][0]
    shape = [int(s) for s in ctx.attr('shape')]
    shape[ctx.attr('output_dim_idx', 0)] = x.shape[ctx.attr('input_dim_idx', 0)]
    dt = _np_dtype(ctx.attr('dtype'))
    out = (ctx.attr('mean', 0.0)
           + ctx.attr('std', 1.0) * jax.random.normal(ctx.rng(), shape, dt))
    return {'Out': [out]}


@register('truncated_gaussian_random', no_grad=True)
def _truncated_gaussian_random(ctx, ins):
    shape = [int(s) for s in ctx.attr('shape')]
    dt = _np_dtype(ctx.attr('dtype'))
    out = jax.random.truncated_normal(ctx.rng(), -2.0, 2.0, shape, dt)
    return {'Out': [ctx.attr('mean', 0.0) + ctx.attr('std', 1.0) * out]}


@register('randperm', no_grad=True)
def _randperm(ctx, ins):
    n = ctx.attr('n')
    return {'Out': [jax.random.permutation(ctx.rng(), n).astype(
        _np_dtype(ctx.attr('dtype'), 'int64'))]}


@register('sampling_id', no_grad=True)
def _sampling_id(ctx, ins):
    x = X(ins)  # [batch, C] probabilities
    out = jax.random.categorical(ctx.rng(), jnp.log(jnp.clip(x, 1e-20)), axis=1)
    return {'Out': [out.astype(INT_T())]}


@register('random_crop', no_grad=True)
def _random_crop(ctx, ins):
    x = X(ins)
    shape = ctx.attr('shape')  # crop shape, trailing dims
    ndim = x.ndim
    crop = list(x.shape[:ndim - len(shape)]) + [int(s) for s in shape]
    starts = []
    key = ctx.rng()
    for i, (xs, cs) in enumerate(zip(x.shape, crop)):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, xs - cs + 1)
                      if xs > cs else jnp.zeros((), jnp.int32))
    out = jax.lax.dynamic_slice(x, [s.astype(jnp.int32) for s in starts], crop)
    return {'Out': [out]}


@register('dropout')
def _dropout(ctx, ins):
    x = X(ins)
    p = ctx.attr('dropout_prob', 0.5)
    impl = ctx.attr('dropout_implementation', 'downgrade_in_infer')
    if ctx.is_test:
        out = x if impl == 'upscale_in_train' else x * (1.0 - p)
        return {'Out': [out], 'Mask': [jnp.ones_like(x)]}
    from ..core import config as _config
    bits = int(_config.get_flag('dropout_bits') or 0)
    if bits in (8, 16):
        # low-bit keep decision (FLAGS_dropout_bits): threshold compare on
        # uint8/16 random bits — quantizes p to 1/2^bits (bernoulli itself
        # quantizes to f32's 2^-24), generating/holding 4x/2x less random
        # material per element than the 32-bit default. Measured ablation
        # in PERF_NOTES.md (transformer dropout-tax section).
        #
        # Train/eval contract under this flag + downgrade_in_infer: the
        # TRAIN keep-rate is (1-p) quantized to 1/2^bits while eval
        # scales by the EXACT (1-p) — a ~2^-bits expectation mismatch.
        # upscale_in_train does not share it (the train-time rescale uses
        # the same quantized keep decision it drew). ADVICE r5 item 4.
        if p >= 1.0:
            # p == 1 drops everything exactly (bernoulli semantics);
            # rounding it to 2^bits would wrap to 0 in the unsigned
            # compare below and silently keep EVERYTHING
            keep = jnp.zeros(x.shape, bool)
        else:
            dt = jnp.uint8 if bits == 8 else jnp.uint16
            # clamp: p ~ 1 rounds to 2^bits, which wraps to 0 in the
            # unsigned compare
            thresh = min(int(round(p * (1 << bits))), (1 << bits) - 1)
            keep = jax.random.bits(ctx.rng(), x.shape, dt) >= thresh
    else:
        keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    if impl == 'upscale_in_train':
        scale = 0.0 if p >= 1.0 else 1.0 / (1.0 - p)
        out = jnp.where(keep, x * scale, 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return {'Out': [out], 'Mask': [keep.astype(x.dtype)]}


# -- shape manipulation -----------------------------------------------------
def _resolve_reshape(x, shape):
    out = []
    for i, s in enumerate(shape):
        if s == 0:
            out.append(x.shape[i])
        else:
            out.append(int(s))
    return out

def _reshape_infer(op, block):
    """Direct shape inference: the generic probe-based path cannot
    evaluate a STATIC target reshape of a dynamic(-1)-dim input (probe
    sizes mismatch), which left output shapes None inside decode loops
    (array_read -> embedding -> reshape -> concat -> fc chains)."""
    shape = list(op.attrs.get('shape', ()))
    if not shape or (op.inputs.get('Shape') and op.inputs['Shape'][0]):
        return  # runtime shape tensor: leave to the generic path
    xv = block._find_var_recursive(op.inputs['X'][0])
    out = []
    for i, s in enumerate(shape):
        if s == 0:  # copy this dim from the input (reference semantics)
            if xv is None or xv.shape is None or i >= len(xv.shape):
                return
            out.append(xv.shape[i])
        else:
            out.append(int(s))
    if -1 in out and xv is not None and xv.shape is not None \
            and all(d not in (-1, None) for d in xv.shape):
        # fully-static input: resolve -1 to numel // prod(known dims)
        known = 1
        for d in out:
            if d != -1:
                known *= d
        numel = int(np.prod(xv.shape)) if len(xv.shape) else 1
        if known > 0 and numel % known == 0:
            out[out.index(-1)] = numel // known
    for n in op.outputs.get('Out', []):
        v = block._find_var_recursive(n)
        if v is not None:
            v.shape = tuple(out)
            if xv is not None and xv.dtype:
                v.dtype = xv.dtype
    if xv is not None and xv.shape is not None:
        # reshape2's XShape output declares (0,) + x.shape (reference
        # reshape_op.cc InferShape); the generic probe path populated it
        # and this direct path must too (ADVICE r5 item 2)
        for n in op.outputs.get('XShape', []):
            v = block._find_var_recursive(n)
            if v is not None:
                v.shape = (0,) + tuple(xv.shape)
                if xv.dtype:
                    v.dtype = xv.dtype


@register('reshape', infer_shape=_reshape_infer)
def _reshape(ctx, ins):
    x = X(ins)
    if ins.get('Shape') and ins['Shape'][0] is not None:
        shape = [int(s) for s in np.asarray(ins['Shape'][0])]
    else:
        shape = ctx.attr('shape')
    return {'Out': [x.reshape(_resolve_reshape(x, shape))]}


@register('reshape2', infer_shape=_reshape_infer)
def _reshape2(ctx, ins):
    x = X(ins)
    if ins.get('Shape') and ins['Shape'][0] is not None:
        shape = [int(s) for s in np.asarray(ins['Shape'][0])]
    else:
        shape = ctx.attr('shape')
    return {'Out': [x.reshape(_resolve_reshape(x, shape))],
            'XShape': [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register('transpose')
def _transpose(ctx, ins):
    return {'Out': [jnp.transpose(X(ins), ctx.attr('axis'))]}


@register('transpose2')
def _transpose2(ctx, ins):
    x = X(ins)
    return {'Out': [jnp.transpose(x, ctx.attr('axis'))],
            'XShape': [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register('flatten')
def _flatten(ctx, ins):
    x = X(ins)
    ax = ctx.attr('axis', 1)
    lead = int(np.prod(x.shape[:ax])) if ax else 1
    return {'Out': [x.reshape(lead, -1)]}


@register('flatten2')
def _flatten2_op(ctx, ins):
    x = X(ins)
    ax = ctx.attr('axis', 1)
    lead = int(np.prod(x.shape[:ax])) if ax else 1
    return {'Out': [x.reshape(lead, -1)],
            'XShape': [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register('squeeze')
def _squeeze(ctx, ins):
    x = X(ins)
    axes = ctx.attr('axes', [])
    if not axes:
        out = jnp.squeeze(x)
    else:
        out = jnp.squeeze(x, axis=tuple(a % x.ndim for a in axes))
    return {'Out': [out]}


@register('squeeze2')
def _squeeze2(ctx, ins):
    x = X(ins)
    axes = ctx.attr('axes', [])
    out = jnp.squeeze(x) if not axes else jnp.squeeze(
        x, axis=tuple(a % x.ndim for a in axes))
    return {'Out': [out], 'XShape': [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register('unsqueeze')
def _unsqueeze(ctx, ins):
    x = X(ins)
    for a in sorted(ctx.attr('axes')):
        x = jnp.expand_dims(x, a)
    return {'Out': [x]}


@register('unsqueeze2')
def _unsqueeze2(ctx, ins):
    x0 = X(ins)
    x = x0
    for a in sorted(ctx.attr('axes')):
        x = jnp.expand_dims(x, a)
    return {'Out': [x], 'XShape': [jnp.zeros((0,) + x0.shape, dtype=x0.dtype)]}


@register('concat')
def _concat(ctx, ins):
    xs = [x for x in ins['X'] if x is not None]
    return {'Out': [jnp.concatenate(xs, axis=ctx.attr('axis', 0))]}


@register('split')
def _split(ctx, ins):
    x = X(ins)
    axis = ctx.attr('axis', 0)
    num = ctx.attr('num', 0)
    sections = ctx.attr('sections', [])
    if num:
        outs = jnp.split(x, num, axis=axis)
    else:
        idx = np.cumsum(sections)[:-1]
        outs = jnp.split(x, idx, axis=axis)
    return {'Out': outs}


@register('slice')
def _slice(ctx, ins):
    x = ins['Input'][0]
    axes = ctx.attr('axes')
    starts = ctx.attr('starts')
    ends = ctx.attr('ends')
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {'Out': [x[tuple(idx)]]}


@register('strided_slice')
def _strided_slice(ctx, ins):
    x = ins['Input'][0]
    axes = ctx.attr('axes')
    starts, ends, strides = ctx.attr('starts'), ctx.attr('ends'), ctx.attr('strides')
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return {'Out': [x[tuple(idx)]]}


@register('crop')
def _crop(ctx, ins):
    x = X(ins)
    shape = ctx.attr('shape')
    if ins.get('Offsets') and ins['Offsets'][0] is not None:
        offsets = [int(o) for o in np.asarray(ins['Offsets'][0])]
    else:
        offsets = ctx.attr('offsets', [0] * x.ndim)
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {'Out': [x[idx]]}


@register('expand')
def _expand(ctx, ins):
    x = X(ins)
    times = ctx.attr('expand_times')
    return {'Out': [jnp.tile(x, times)]}


@register('tile')
def _tile(ctx, ins):
    return {'Out': [jnp.tile(X(ins), ctx.attr('repeat_times'))]}


@register('stack')
def _stack(ctx, ins):
    xs = [x for x in ins['X'] if x is not None]
    return {'Y': [jnp.stack(xs, axis=ctx.attr('axis', 0))]}


@register('unstack')
def _unstack(ctx, ins):
    x = X(ins)
    axis = ctx.attr('axis', 0)
    num = ctx.attr('num', x.shape[axis])
    outs = [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, num, axis=axis)]
    return {'Y': outs}


@register('gather')
def _gather(ctx, ins):
    x = X(ins)
    idx = ins['Index'][0].reshape(-1).astype(jnp.int32)
    return {'Out': [jnp.take(x, idx, axis=0)]}


@register('gather_nd')
def _gather_nd(ctx, ins):
    x = X(ins)
    idx = ins['Index'][0]
    return {'Out': [x[tuple(jnp.moveaxis(idx, -1, 0))]]}


@register('scatter')
def _scatter(ctx, ins):
    x, idx, upd = ins['X'][0], ins['Ids'][0], ins['Updates'][0]
    idx = idx.reshape(-1).astype(jnp.int32)
    if ctx.attr('overwrite', True):
        out = x.at[idx].set(upd)
    else:
        out = x.at[idx].set(0.0).at[idx].add(upd)
    return {'Out': [out]}


@register('pad')
def _pad(ctx, ins):
    x = X(ins)
    p = ctx.attr('paddings')
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {'Out': [jnp.pad(x, pads, constant_values=ctx.attr('pad_value', 0.0))]}


@register('pad2d')
def _pad2d(ctx, ins):
    x = X(ins)
    p = ctx.attr('paddings', [0, 0, 0, 0])
    mode = ctx.attr('mode', 'constant')
    fmt = ctx.attr('data_format', 'NCHW')
    if fmt == 'NCHW':
        pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pads = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    modes = {'constant': 'constant', 'reflect': 'reflect', 'edge': 'edge'}
    kw = {'constant_values': ctx.attr('pad_value', 0.0)} if mode == 'constant' else {}
    return {'Out': [jnp.pad(x, pads, mode=modes[mode], **kw)]}


@register('pad_constant_like')
def _pad_constant_like(ctx, ins):
    x, y = ins['X'][0], ins['Y'][0]
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {'Out': [jnp.pad(y, pads, constant_values=ctx.attr('pad_value', 0.0))]}


@register('reverse')
def _reverse(ctx, ins):
    axes = ctx.attr('axis')
    if isinstance(axes, int):
        axes = [axes]
    return {'Out': [jnp.flip(X(ins), axis=tuple(axes))]}


@register('one_hot', no_grad=True)
def _one_hot(ctx, ins):
    x = X(ins)
    depth = ctx.attr('depth')
    lab = x.reshape(x.shape[:-1]) if (x.ndim > 1 and x.shape[-1] == 1) else x
    return {'Out': [jax.nn.one_hot(lab.astype(jnp.int32), depth,
                                   dtype=jnp.float32)]}


@register('cum_sum')
def _cumsum(ctx, ins):
    x = X(ins)
    axis = ctx.attr('axis', -1)
    if ctx.attr('flatten', False):
        x = x.reshape(-1)
        axis = 0
    out = x
    if ctx.attr('reverse', False):
        out = jnp.flip(out, axis)
    if ctx.attr('exclusive', False):
        pad = [(0, 0)] * out.ndim
        pad[axis] = (1, 0)
        sliced = [slice(None)] * out.ndim
        sliced[axis] = slice(0, out.shape[axis])
        out = jnp.pad(out, pad)[tuple(sliced)]
    out = jnp.cumsum(out, axis=axis)
    if ctx.attr('reverse', False):
        out = jnp.flip(out, axis)
    return {'Out': [out]}


@register('top_k')
def _top_k(ctx, ins):
    x = X(ins)
    k = ctx.attr('k', 1)
    vals, idx = jax.lax.top_k(x, k)
    return {'Out': [vals], 'Indices': [idx.astype(INT_T())]}


@register('arg_max', no_grad=True)
def _arg_max(ctx, ins):
    return {'Out': [jnp.argmax(X(ins), axis=ctx.attr('axis', -1)).astype(INT_T())]}


@register('arg_min', no_grad=True)
def _arg_min(ctx, ins):
    return {'Out': [jnp.argmin(X(ins), axis=ctx.attr('axis', -1)).astype(INT_T())]}


@register('argsort')
def _argsort(ctx, ins):
    x = X(ins)
    axis = ctx.attr('axis', -1)
    idx = jnp.argsort(x, axis=axis)
    return {'Out': [jnp.sort(x, axis=axis)], 'Indices': [idx.astype(INT_T())]}


@register('multiplex')
def _multiplex(ctx, ins):
    ids = ins['Ids'][0].reshape(-1).astype(jnp.int32)
    xs = jnp.stack([x for x in ins['X'] if x is not None], axis=0)
    rows = jnp.arange(ids.shape[0])
    return {'Out': [xs[ids, rows]]}


@register('where', no_grad=True)
def _where(ctx, ins):
    cond = ins['Condition'][0]
    return {'Out': [jnp.stack(jnp.nonzero(cond), axis=-1).astype(INT_T())]}


@register('maxout')
def _maxout(ctx, ins):
    x = X(ins)  # NCHW
    groups = ctx.attr('groups')
    n, c, h, w = x.shape
    out = x.reshape(n, c // groups, groups, h, w).max(axis=2)
    return {'Out': [out]}


@register('space_to_depth')
def _space_to_depth(ctx, ins):
    x = X(ins)
    b = ctx.attr('blocksize')
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // b, b, w // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b, h // b, w // b)
    return {'Out': [out]}


@register('pixel_shuffle')
def _pixel_shuffle(ctx, ins):
    x = X(ins)
    r = ctx.attr('upscale_factor')
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r, w * r)
    return {'Out': [out]}


@register('shuffle_channel')
def _shuffle_channel(ctx, ins):
    x = X(ins)
    g = ctx.attr('group')
    n, c, h, w = x.shape
    out = x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4).reshape(x.shape)
    return {'Out': [out]}


@register('add_position_encoding')
def _add_position_encoding(ctx, ins):
    x = X(ins)  # [batch, seq, dim] (dense path)
    alpha = ctx.attr('alpha', 1.0)
    beta = ctx.attr('beta', 1.0)
    b, t, d = x.shape
    half = d // 2
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    enc = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    return {'Out': [alpha * x + beta * enc[None, :, :].astype(x.dtype)]}


@register('hash', no_grad=True)
def _hash_op(ctx, ins):
    """Deterministic integer hash bucketing (ref operators/hash_op.cc uses
    xxhash; behaviorally equivalent bucketing, different hash family)."""
    x = X(ins).astype(jnp.uint32)
    num_hash = ctx.attr('num_hash', 1)
    mod_by = ctx.attr('mod_by')
    outs = []
    flat = x.reshape(x.shape[0], -1)
    for i in range(num_hash):
        h = flat * jnp.uint32(2654435761) + jnp.uint32(
            (0x9E3779B9 * (i + 1)) & 0xFFFFFFFF)
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        # combine columns
        acc = h[:, 0]
        for c in range(1, h.shape[1]):
            acc = acc * jnp.uint32(31) + h[:, c]
        outs.append((acc % jnp.uint32(mod_by)).astype(INT_T()))
    return {'Out': [jnp.stack(outs, axis=1)[:, :, None]]}


@register('similarity_focus', no_grad=True)
def _similarity_focus(ctx, ins):
    x = X(ins)  # [N, C, A, B]
    axis = ctx.attr('axis')
    indexes = ctx.attr('indexes')
    n, c, a, b = x.shape
    mask = jnp.zeros_like(x)
    if axis == 1:
        for idx in indexes:
            ch = x[:, idx]  # [N, A, B]
            row_max = (ch == ch.max(axis=2, keepdims=True))
            col_max = (ch == ch.max(axis=1, keepdims=True))
            m = (row_max | col_max).astype(x.dtype)[:, None, :, :]
            mask = jnp.maximum(mask, jnp.broadcast_to(m, x.shape))
    return {'Out': [mask]}


@register('load', no_grad=True)
def _load_op(ctx, ins):
    """Load a tensor from disk at trace time (becomes an XLA constant);
    ref operators/load_op.cc."""
    from ..io import _deserialize_tensor
    with open(ctx.attr('file_path'), 'rb') as f:
        return {'Out': [_deserialize_tensor(f)]}


@register('label_smooth')
def _label_smooth(ctx, ins):
    x = X(ins)
    eps = ctx.attr('epsilon', 0.0)
    if ins.get('PriorDist') and ins['PriorDist'][0] is not None:
        prior = ins['PriorDist'][0]
        out = (1.0 - eps) * x + eps * prior
    else:
        out = (1.0 - eps) * x + eps / x.shape[-1]
    return {'Out': [out]}


@register('py_func', lod='none', diff_inputs=('X',))
def _py_func(ctx, ins):
    """Host callback op (ref operators/py_func_op.cc). Output shapes/dtypes
    come from the declared out vars; jax.pure_callback bridges the trace."""
    from ..layers.nn import _PY_FUNC_REGISTRY
    func, backward_func, skip_names = \
        _PY_FUNC_REGISTRY[int(ctx.attr('func_id'))]
    xs = [v for v in ins['X'] if v is not None]
    in_names = (ctx.op.inputs.get('X')
                or ctx.attr('_fwd_inputs', {}).get('X', []))
    # under the generic-vjp grad replay, ctx wraps the GRAD op: the forward
    # output names live in its _fwd_outputs attr
    out_names = (ctx.op.outputs.get('Out')
                 or ctx.attr('_fwd_outputs')['Out'])
    shapes = []
    for n in out_names:
        v = ctx.var(n)
        if v is None or v.shape is None or any(
                s is None or int(s) < 0 for s in (v.shape or [-1])):
            raise ValueError(
                "py_func output %r needs a fully static declared shape" % n)
        from ..framework import runtime_dtype
        shapes.append(jax.ShapeDtypeStruct(
            tuple(int(s) for s in v.shape), runtime_dtype(v.dtype)))

    def host(*arrs):
        res = func(*[np.asarray(a) for a in arrs])
        if not isinstance(res, (tuple, list)):
            res = [res]
        return tuple(np.asarray(r, dtype=s.dtype).reshape(s.shape)
                     for r, s in zip(res, shapes))

    if backward_func is None:
        outs = jax.pure_callback(host, tuple(shapes), *xs)
        return {'Out': list(outs)}

    @jax.custom_vjp
    def f(*args):
        return jax.pure_callback(host, tuple(shapes), *args)

    def f_fwd(*args):
        outs = f(*args)
        return outs, (args, outs)

    def f_bwd(res, cots):
        args, outs = res
        in_shapes = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                          for a in args)
        # reference backward contract: (inputs + outputs + out grads),
        # minus skip_vars_in_backward_input
        bwd_args = [a for a, n in zip(args, in_names)
                    if n not in skip_names]
        bwd_args += [o for o, n in zip(outs, out_names)
                     if n not in skip_names]
        bwd_args += list(cots)

        def host_bwd(*arrs):
            grads = backward_func(*[np.asarray(a) for a in arrs])
            if not isinstance(grads, (tuple, list)):
                grads = [grads]
            return tuple(np.asarray(g, dtype=s.dtype).reshape(s.shape)
                         for g, s in zip(grads, in_shapes))
        return jax.pure_callback(host_bwd, in_shapes, *bwd_args)

    f.defvjp(f_fwd, f_bwd)
    outs = f(*xs)
    return {'Out': list(outs)}


@register('fake_quantize_abs_max', diff_inputs=('X',))
def _fake_quantize_abs_max(ctx, ins):
    """ref fake_quantize_op.cc FakeQuantizeAbsMax: scale = max|x|, round x
    onto the (2^(bits-1) - 1)-step grid; straight-through estimator for the
    gradient (value-preserving stop_gradient trick)."""
    x = X(ins)
    bits = int(ctx.attr('bit_length', 8))
    levels = float((1 << (bits - 1)) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * levels) / levels * scale
    out = x + jax.lax.stop_gradient(q - x)   # STE
    return {'Out': [out], 'OutScale': [scale.reshape(1)]}


@register('fake_quantize_range_abs_max', diff_inputs=('X',))
def _fake_quantize_range_abs_max(ctx, ins):
    """ref fake_quantize_op.cc FakeQuantizeRangeAbsMax: the activation
    scale is the max of a sliding window of per-step abs-max statistics
    instead of this batch's alone. The window (`Scales`, [window_size])
    and the step counter (`Iter`, [1]) are persistable state threaded
    through the op UNDER THE SAME NAMES (OutScales/OutIter rebind them),
    so the scope commit persists them across steps. Train: window[iter %
    W] = max|x|, scale = max(window), iter += 1; is_test: the window is
    frozen and only read. Straight-through estimator for the gradient,
    same as abs_max."""
    x = X(ins)
    bits = int(ctx.attr('bit_length', 8))
    levels = float((1 << (bits - 1)) - 1)
    window = ins['Scales'][0]
    it = ins['Iter'][0].reshape(())
    if bool(ctx.attr('is_test', False)):
        scale = jnp.maximum(jnp.max(window), 1e-8)
        new_window, new_it = window, it
    else:
        cur = jnp.max(jnp.abs(x))
        slot = (it % window.shape[0]).astype(jnp.int32)
        new_window = window.at[slot].set(cur)
        scale = jnp.maximum(jnp.max(new_window), 1e-8)
        new_it = it + 1
    q = jnp.round(x / scale * levels) / levels * scale
    out = x + jax.lax.stop_gradient(q - x)   # STE
    return {'Out': [out], 'OutScale': [scale.reshape(1)],
            'OutScales': [new_window], 'OutIter': [new_it.reshape(1)]}


@register('fake_dequantize_max_abs', diff_inputs=('X',))
def _fake_dequantize_max_abs(ctx, ins):
    x = X(ins)
    scale = ins['Scale'][0].reshape(())
    max_range = float(ctx.attr('max_range', 127))
    return {'Out': [x * scale / max_range]}

