"""Sampled / hierarchical softmax ops: nce, hsigmoid
(ref: operators/nce_op.cc/.h, operators/hierarchical_sigmoid_op.cc/.h,
operators/math/matrix_bit_code.h, operators/math/sampler.cc).

These are the reference's large-vocabulary losses: instead of a full [B, C]
softmax, NCE scores num_true + S sampled classes per example and hsigmoid
scores the ~log2(C) nodes on the label's path through a complete binary
tree. Both keep the MXU busy with small dense gathers + batched dots —
exactly the shapes XLA handles well — and NCE's weight gradient is a
SelectedRows over the sampled rows when is_sparse is set.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core.selected_rows import SelectedRowsVal
from ..core.lod import unwrap


# ---------------------------------------------------------------------------
# samplers (ref operators/math/sampler.cc): probability of drawing class c
# ---------------------------------------------------------------------------
def _sample_ids(rng, sampler, shape, num_classes, probs=None):
    if sampler == 2:  # custom distribution (ref CustomSampler): the
        # reference builds a host-side alias table; TPU-native the static
        # probs become an XLA-constant CDF and sampling is one
        # searchsorted over it — same O(1)-per-draw on the VPU
        cdf = jnp.cumsum(jnp.asarray(probs, jnp.float32))
        u = jax.random.uniform(rng, shape) * cdf[-1]
        ids = jnp.searchsorted(cdf, u, side='right').astype(jnp.int32)
        return jnp.clip(ids, 0, num_classes - 1)
    if sampler == 1:  # log-uniform (Zipfian), ref LogUniformSampler
        u = jax.random.uniform(rng, shape)
        ids = jnp.exp(u * np.log(num_classes + 1.0)).astype(jnp.int32) - 1
        return jnp.clip(ids, 0, num_classes - 1)
    return jax.random.randint(rng, shape, 0, num_classes)  # uniform


def _sample_prob(sampler, ids, num_classes, probs=None):
    if sampler == 2:
        p = jnp.asarray(probs, jnp.float32)
        return p[ids] / jnp.sum(p)
    if sampler == 1:
        idf = ids.astype(jnp.float32)
        return (jnp.log((idf + 2.0) / (idf + 1.0))
                / np.log(num_classes + 1.0))
    return jnp.full(ids.shape, 1.0 / num_classes)


def _nce_logits(x, w, b, ids):
    """x [B,D], ids [B,K] -> logits [B,K] = w[ids]·x + b[ids]."""
    w_rows = w[ids]                              # [B, K, D]
    logits = jnp.einsum('bkd,bd->bk', w_rows, x)
    if b is not None:
        logits = logits + b.reshape(-1)[ids]
    return logits


def _nce_parts(ctx, ins):
    x = unwrap(ins['Input'][0])
    label = unwrap(ins['Label'][0]).astype(jnp.int32)
    w = ins['Weight'][0]
    b = ins['Bias'][0] if ins.get('Bias') and ins['Bias'][0] is not None \
        else None
    C = int(ctx.attr('num_total_classes'))
    S = int(ctx.attr('num_neg_samples', 10))
    sampler = int(ctx.attr('sampler', 0))
    probs = ctx.attr('custom_probs', None)
    if sampler == 2 and not probs:
        raise ValueError("nce sampler='custom_dist' needs custom_dist "
                         "probabilities (layers.nce custom_dist=...)")
    B = x.shape[0]
    num_true = label.shape[-1] if label.ndim > 1 else 1
    label = label.reshape(B, num_true)
    neg = _sample_ids(ctx.rng(), sampler, (B, S), C, probs)
    ids = jnp.concatenate([label, neg], axis=1)      # [B, T+S]
    logits = _nce_logits(x, w, b, ids)
    q = _sample_prob(sampler, ids, C, probs)
    # P(sampled|x) model: o/(o + k·q); in log space l = logit - log(k·q)
    k = float(S)
    l = logits - jnp.log(k * q)
    is_true = jnp.concatenate([jnp.ones((B, num_true), bool),
                               jnp.zeros((B, S), bool)], axis=1)
    sw = None
    if ins.get('SampleWeight') and ins['SampleWeight'][0] is not None:
        sw = unwrap(ins['SampleWeight'][0]).reshape(B, 1)
    return x, w, b, ids, l, is_true, logits, sw


@register('nce', lod='aware', diff_inputs=('Input', 'Weight', 'Bias'))
def _nce(ctx, ins):
    _, _, _, ids, l, is_true, logits, sw = _nce_parts(ctx, ins)
    # -log σ(l) for true classes, -log σ(-l) for noise (ref nce_op.h:
    # ComputeCost) — softplus keeps it stable without the reference's clip
    cost = jnp.where(is_true, jax.nn.softplus(-l), jax.nn.softplus(l))
    cost = jnp.sum(cost, axis=1, keepdims=True)
    if sw is not None:
        cost = cost * sw  # per-example weight (ref nce_op.h sample_weight)
    return {'Cost': [cost],
            'SampleLogits': [logits],
            'SampleLabels': [ids.astype(jnp.int32)]}


@register('nce_grad', no_grad=True, lod='aware')
def _nce_grad(ctx, ins):
    """Explicit grad so Weight@GRAD can be SelectedRows over the sampled
    rows (ref nce_op.h NCEGradKernel SelectedRows path). Dense fallback
    when is_sparse is off."""
    a = ctx.attrs
    igm, ogm = a['_in_grad_map'], a['_out_grad_map']
    cost_name = a['_fwd_outputs']['Cost'][0]
    cot_name = ogm.get(cost_name, '')
    x, w, b, ids, l, is_true, _, sw = _nce_parts(ctx, ins)
    B = x.shape[0]
    cot = (unwrap(ctx.env(cot_name)).reshape(B, 1)
           if cot_name and cot_name in ctx.tracer.env
           else jnp.zeros((B, 1), x.dtype))
    if sw is not None:
        cot = cot * sw
    # d cost / d logit: σ(l) - 1 on true slots, σ(l) on noise slots
    g_logit = (jax.nn.sigmoid(l) - is_true.astype(x.dtype)) * cot  # [B,K]
    outs = {}
    names = []
    x_name = a['_fwd_inputs']['Input'][0]
    w_name = a['_fwd_inputs']['Weight'][0]
    b_name = (a['_fwd_inputs'].get('Bias') or [''])[0]
    for n in (x_name, w_name, b_name):
        if n and igm.get(n):
            names.append(n)
    vals = {}
    if igm.get(x_name):
        vals[x_name] = jnp.einsum('bk,bkd->bd', g_logit, w[ids])
    if igm.get(w_name):
        rows = ids.reshape(-1)
        gw_vals = (g_logit[..., None] * x[:, None, :]).reshape(-1, x.shape[1])
        if ctx.attr('is_sparse', False):
            vals[w_name] = SelectedRowsVal(rows, gw_vals, w.shape[0])
        else:
            vals[w_name] = jnp.zeros_like(w).at[rows].add(gw_vals,
                                                          mode='drop')
    if b_name and igm.get(b_name):
        gb = jnp.zeros((b.size,), x.dtype).at[ids.reshape(-1)].add(
            g_logit.reshape(-1), mode='drop')
        vals[b_name] = gb.reshape(b.shape)
    # IN@GRAD output order follows in_grad_map insertion order
    ordered = [vals[n] for n in igm if n in vals]
    return {'IN@GRAD': ordered}


# ---------------------------------------------------------------------------
# hierarchical sigmoid over the default complete binary tree
# ---------------------------------------------------------------------------
def _hsigmoid_parts(ctx, ins):
    """Path encoding mirrors the reference SimpleCode
    (math/matrix_bit_code.h): for label c, node index at depth j is
    ((c + C) >> (j + 1)) - 1 and the target bit is ((c + C) >> j) & 1,
    with path length floor(log2(c + C)). Everything is a fixed [B, Lmax]
    program with a depth mask, so XLA sees static shapes for any labels.

    CUSTOM trees (ref CustomCode, hierarchical_sigmoid_op.h): the caller
    supplies PathTable [B, L] (rows into W, leaf->root, -1 padding) and
    PathCode [B, L] (target bits) — the same fixed-shape masked program,
    just with table-driven indices instead of the SimpleCode bit math."""
    x = unwrap(ins['X'][0])
    w = ins['W'][0]            # [C-1, D] (default) / [non-leaf, D] (custom)
    b = ins['Bias'][0] if ins.get('Bias') and ins['Bias'][0] is not None \
        else None
    pt = ins.get('PathTable')
    if pt and pt[0] is not None:
        idx_raw = unwrap(pt[0]).astype(jnp.int32)      # [B, L], -1 = pad
        bit = unwrap(ins['PathCode'][0]).astype(x.dtype)
        mask = (idx_raw >= 0).astype(x.dtype)
        idx = jnp.clip(idx_raw, 0, w.shape[0] - 1)
    else:
        label = unwrap(ins['Label'][0]).astype(jnp.int32).reshape(-1)
        C = int(ctx.attr('num_classes'))
        Lmax = int(np.floor(np.log2(2 * C - 1)))
        code = label + C                                   # [B]
        j = jnp.arange(Lmax, dtype=jnp.int32)              # [Lmax]
        idx = (code[:, None] >> (j[None, :] + 1)) - 1      # [B, Lmax]
        bit = ((code[:, None] >> j[None, :]) & 1).astype(x.dtype)
        length = 31 - jax.lax.clz(code)                # floor(log2(code))
        mask = (j[None, :] < length[:, None]).astype(x.dtype)
        idx = jnp.clip(idx, 0, w.shape[0] - 1)
    pre = jnp.einsum('bld,bd->bl', w[idx], x)          # [B, Lmax]
    if b is not None:
        pre = pre + b.reshape(-1)[idx]
    pre = jnp.clip(pre, -40.0, 40.0)
    return x, w, b, idx, bit, mask, pre


@register('hierarchical_sigmoid', lod='aware',
          diff_inputs=('X', 'W', 'Bias'))
def _hsigmoid(ctx, ins):
    x, w, b, idx, bit, mask, pre = _hsigmoid_parts(ctx, ins)
    # BCE with logits per path node: softplus(pre) - bit * pre
    loss = (jax.nn.softplus(pre) - bit * pre) * mask
    return {'Out': [jnp.sum(loss, axis=1, keepdims=True)],
            'PreOut': [pre * mask]}


@register('hierarchical_sigmoid_grad', no_grad=True, lod='aware')
def _hsigmoid_grad(ctx, ins):
    """Explicit grad: with is_sparse the W gradient is SelectedRows over the
    ~log2(C) path nodes per example (ref hierarchical_sigmoid_op.cc
    W@GRAD SelectedRows path); dense scatter fallback otherwise."""
    a = ctx.attrs
    igm, ogm = a['_in_grad_map'], a['_out_grad_map']
    out_name = a['_fwd_outputs']['Out'][0]
    cot_name = ogm.get(out_name, '')
    x, w, b, idx, bit, mask, pre = _hsigmoid_parts(ctx, ins)
    B = x.shape[0]
    cot = (unwrap(ctx.env(cot_name)).reshape(B, 1)
           if cot_name and cot_name in ctx.tracer.env
           else jnp.zeros((B, 1), x.dtype))
    # dL/dpre = (σ(pre) - bit) * mask * cot  (clip is inactive in (-40,40))
    g_pre = (jax.nn.sigmoid(pre) - bit) * mask * cot       # [B, Lmax]
    x_name = a['_fwd_inputs']['X'][0]
    w_name = a['_fwd_inputs']['W'][0]
    b_name = (a['_fwd_inputs'].get('Bias') or [''])[0]
    vals = {}
    if igm.get(x_name):
        vals[x_name] = jnp.einsum('bl,bld->bd', g_pre, w[idx])
    if igm.get(w_name):
        rows = idx.reshape(-1)
        gw = (g_pre[..., None] * x[:, None, :]).reshape(-1, x.shape[1])
        if ctx.attr('is_sparse', False):
            vals[w_name] = SelectedRowsVal(rows, gw, w.shape[0])
        else:
            vals[w_name] = jnp.zeros_like(w).at[rows].add(gw, mode='drop')
    if b_name and igm.get(b_name):
        gb = jnp.zeros((b.size,), x.dtype).at[idx.reshape(-1)].add(
            g_pre.reshape(-1), mode='drop')
        vals[b_name] = gb.reshape(b.shape)
    return {'IN@GRAD': [vals[n] for n in igm if n in vals]}
