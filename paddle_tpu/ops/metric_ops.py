"""Metric op lowerings (ref: operators/metrics/ — accuracy_op.cc, auc_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register


@register('accuracy', no_grad=True, lod='none')
def _accuracy(ctx, ins):
    pred = ins['Out'][0]          # [N, k] top-k values (unused)
    indices = ins['Indices'][0]   # [N, k]
    label = ins['Label'][0]       # [N, 1]
    lab = label.reshape(-1, 1).astype(indices.dtype)
    correct = jnp.any(indices == lab, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = jnp.asarray(indices.shape[0], jnp.int32)
    acc = num_correct.astype(jnp.float32) / indices.shape[0]
    return {'Accuracy': [acc.reshape(1)], 'Correct': [num_correct.reshape(1)],
            'Total': [total.reshape(1)]}


@register('auc', no_grad=True, lod='none')
def _auc(ctx, ins):
    """Streaming AUC: stat buffers are persistable state threaded through the
    step function (the reference mutates them in place)."""
    predict = ins['Predict'][0]   # [N, 2]
    label = ins['Label'][0]       # [N, 1]
    stat_pos = ins['StatPos'][0]  # [num_thresholds + 1]
    stat_neg = ins['StatNeg'][0]
    num_t = ctx.attr('num_thresholds', 4095)
    pos_prob = predict[:, 1]
    bucket = jnp.floor(pos_prob * num_t).astype(jnp.int32)
    bucket = jnp.clip(bucket, 0, num_t)
    lab = label.reshape(-1).astype(jnp.int32)
    pos_new = stat_pos.at[bucket].add((lab == 1).astype(stat_pos.dtype))
    neg_new = stat_neg.at[bucket].add((lab == 0).astype(stat_neg.dtype))
    # compute AUC by trapezoid over thresholds (descending)
    pos_rev = jnp.cumsum(pos_new[::-1])
    neg_rev = jnp.cumsum(neg_new[::-1])
    tot_pos = pos_rev[-1]
    tot_neg = neg_rev[-1]
    tp = jnp.concatenate([jnp.zeros(1, pos_rev.dtype), pos_rev])
    fp = jnp.concatenate([jnp.zeros(1, neg_rev.dtype), neg_rev])
    area = jnp.sum((fp[1:] - fp[:-1]) * (tp[1:] + tp[:-1]) / 2.0)
    auc = jnp.where(tot_pos * tot_neg > 0,
                    area / jnp.maximum(tot_pos * tot_neg, 1.0), 0.0)
    return {'AUC': [auc.astype(jnp.float64).reshape(1)],
            'StatPosOut': [pos_new], 'StatNegOut': [neg_new]}


@register('precision_recall', no_grad=True, lod='none')
def _precision_recall(ctx, ins):
    max_probs = ins['MaxProbs'][0]
    indices = ins['Indices'][0]
    labels = ins['Labels'][0]
    states = ins['StatesInfo'][0]  # [C, 4] TP/FP/TN/FN
    cls = ctx.attr('class_number')
    idx = indices.reshape(-1).astype(jnp.int32)
    lab = labels.reshape(-1).astype(jnp.int32)
    onehot_pred = jnp.zeros((idx.shape[0], cls)).at[jnp.arange(idx.shape[0]), idx].set(1.0)
    onehot_lab = jnp.zeros((lab.shape[0], cls)).at[jnp.arange(lab.shape[0]), lab].set(1.0)
    tp = jnp.sum(onehot_pred * onehot_lab, axis=0)
    fp = jnp.sum(onehot_pred * (1 - onehot_lab), axis=0)
    fn = jnp.sum((1 - onehot_pred) * onehot_lab, axis=0)
    tn = idx.shape[0] - tp - fp - fn
    batch = jnp.stack([tp, fp, tn, fn], axis=1)
    acc = states + batch

    def prf(mat):
        tp_, fp_, _tn, fn_ = mat[:, 0], mat[:, 1], mat[:, 2], mat[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1.0), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1.0), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0)
        return jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])

    bm = prf(batch)
    am = prf(acc)
    return {'BatchMetrics': [jnp.concatenate([bm, bm])],
            'AccumMetrics': [jnp.concatenate([am, am])],
            'AccumStatesInfo': [acc]}


@register('mean_iou', no_grad=True, lod='none')
def _mean_iou(ctx, ins):
    pred = ins['Predictions'][0].reshape(-1).astype(jnp.int32)
    lab = ins['Labels'][0].reshape(-1).astype(jnp.int32)
    c = ctx.attr('num_classes')
    inter = jnp.zeros((c,), jnp.float32).at[pred].add(
        (pred == lab).astype(jnp.float32))
    pred_cnt = jnp.zeros((c,), jnp.float32).at[pred].add(1.0)
    lab_cnt = jnp.zeros((c,), jnp.float32).at[lab].add(1.0)
    union = pred_cnt + lab_cnt - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return {'OutMeanIou': [miou.reshape(1)], 'OutWrong': [(union - inter)],
            'OutCorrect': [inter]}
