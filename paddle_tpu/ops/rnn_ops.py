"""Recurrent op lowerings: dynamic LSTM/GRU over LoD sequences
(ref: operators/lstm_op.cc, gru_op.cc, gru_unit_op.cc, lstm_unit_op.cc,
math/detail/lstm_kernel.h:30-42, gru_kernel.h).

The reference re-batches variable-length sequences by time step on the host
(math/sequence2batch.h) and runs a per-step GEMM. TPU-native: pad to
[batch, maxlen, ...] from the static lod, run ONE lax.scan over time (the
whole unrolled loop compiles to a single XLA while-op with MXU GEMMs), mask
carries at sequence ends, and unpad back to LoD rows. Gate layouts follow the
reference exactly: LSTM {c, i, f, o} with optional peepholes
(Bias = {b_c,b_i,b_f,b_o,W_ic,W_fc,W_oc}); GRU {u, r, c} with
h_t = (1-u)⊙h_{t-1} + u⊙ĉ.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register
from ..core.lod import LoDArray, unwrap


def _require_lod(x, op_name):
    if not (isinstance(x, LoDArray) and x.lod):
        raise TypeError(
            "%s requires a LoD (variable-length) input — feed it as a "
            "LoDTensor (fluid.create_lod_tensor) or via DataFeeder with "
            "lod_level=1; got a dense tensor" % op_name)
    return x

_ACT = {
    'sigmoid': jax.nn.sigmoid,
    'tanh': jnp.tanh,
    'relu': jax.nn.relu,
    'identity': lambda x: x,
}


def _pad_from_lod(x, off):
    """[T, D] + static offsets -> ([N, L, D], mask [N, L]); vectorized
    numpy index construction (no per-row python)."""
    off = np.asarray(off, np.int64)
    lens = off[1:] - off[:-1]
    n, maxlen = len(lens), int(lens.max()) if len(lens) else 0
    d = x.shape[1:]
    j = np.arange(maxlen, dtype=np.int64)
    gather = np.minimum(off[:-1, None] + j[None, :],
                        max(x.shape[0] - 1, 0)).astype(np.int32)
    mask = j[None, :] < lens[:, None]
    rows = jnp.take(x, jnp.asarray(gather.reshape(-1)), axis=0)
    return rows.reshape((n, maxlen) + d), jnp.asarray(mask)


def _unpad_idx(off, maxlen):
    """Flat [N*L] -> packed-row gather index for the valid positions."""
    off = np.asarray(off, np.int64)
    lens = off[1:] - off[:-1]
    ends = np.cumsum(lens)
    total = int(ends[-1]) if len(lens) else 0
    base = np.repeat(np.arange(len(lens), dtype=np.int64) * maxlen
                     - (ends - lens), lens)
    return (np.arange(total, dtype=np.int64) + base).astype(np.int32)


def _unpad_to_lod(y, off):
    flat = y.reshape((-1,) + y.shape[2:])
    return jnp.take(flat, jnp.asarray(_unpad_idx(off, y.shape[1])), axis=0)


def _reverse_lod_rows(x, off):
    off = np.asarray(off, np.int64)
    lens = off[1:] - off[:-1]
    seg = np.repeat(np.arange(len(lens)), lens)
    valid = int(off[-1])
    pos = np.arange(valid, dtype=np.int64)
    idx = np.arange(x.shape[0], dtype=np.int64)  # bucket-pad rows: identity
    idx[:valid] = off[seg] + off[seg + 1] - 1 - pos
    return jnp.take(x, jnp.asarray(idx.astype(np.int32)), axis=0)


@register('lstm', lod='aware')
def _lstm(ctx, ins):
    x = _require_lod(ins['Input'][0], 'dynamic_lstm')
    w = unwrap(ins['Weight'][0])      # [D, 4D] hidden-to-hidden {c,i,f,o}
    bias = unwrap(ins['Bias'][0]).reshape(-1)
    use_peepholes = ctx.attr('use_peepholes', True)
    is_reverse = ctx.attr('is_reverse', False)
    act_gate = _ACT[ctx.attr('gate_activation', 'sigmoid')]
    act_cell = _ACT[ctx.attr('cell_activation', 'tanh')]
    act_cand = _ACT[ctx.attr('candidate_activation', 'tanh')]

    off = np.asarray(x.lod[0], dtype=np.int64)
    xd = x.data
    d = w.shape[0]
    if is_reverse:
        xd = _reverse_lod_rows(xd, off)
    xp, mask = _pad_from_lod(xd, off)          # [N, L, 4D], [N, L]
    n, maxlen = mask.shape

    b = bias[:4 * d]
    if use_peepholes:
        w_ic = bias[4 * d:5 * d]
        w_fc = bias[5 * d:6 * d]
        w_oc = bias[6 * d:7 * d]

    h0 = (unwrap(ins['H0'][0]) if ins.get('H0') and ins['H0'][0] is not None
          else jnp.zeros((n, d), xd.dtype))
    c0 = (unwrap(ins['C0'][0]) if ins.get('C0') and ins['C0'][0] is not None
          else jnp.zeros((n, d), xd.dtype))

    xs = jnp.swapaxes(xp, 0, 1)      # [L, N, 4D]
    ms = jnp.swapaxes(mask, 0, 1)    # [L, N]

    def step(carry, inp):
        h_prev, c_prev = carry
        x_t, m_t = inp
        gates = x_t + h_prev @ w + b
        g_c, g_i, g_f, g_o = jnp.split(gates, 4, axis=1)
        cand = act_cand(g_c)
        if use_peepholes:
            i = act_gate(g_i + c_prev * w_ic)
            f = act_gate(g_f + c_prev * w_fc)
        else:
            i = act_gate(g_i)
            f = act_gate(g_f)
        c = cand * i + c_prev * f
        if use_peepholes:
            o = act_gate(g_o + c * w_oc)
        else:
            o = act_gate(g_o)
        h = o * act_cell(c)
        m = m_t[:, None]
        # carry dtype stays fixed: under bf16 AMP the recurrent matmul
        # promotes (bf16 @ f32 -> f32) and the scan carry would drift
        h = jnp.where(m, h, h_prev).astype(h_prev.dtype)
        c = jnp.where(m, c, c_prev).astype(c_prev.dtype)
        return (h, c), (h, c, jnp.concatenate([cand, i, f, o], axis=1))

    (_, _), (hs, cs, gs) = jax.lax.scan(step, (h0, c0), (xs, ms))
    hidden = _unpad_to_lod(jnp.swapaxes(hs, 0, 1), off)
    cell = _unpad_to_lod(jnp.swapaxes(cs, 0, 1), off)
    gates_out = _unpad_to_lod(jnp.swapaxes(gs, 0, 1), off)
    if is_reverse:
        hidden = _reverse_lod_rows(hidden, off)
        cell = _reverse_lod_rows(cell, off)
        gates_out = _reverse_lod_rows(gates_out, off)
    lod = x.lod
    return {'Hidden': [LoDArray(hidden, lod)],
            'Cell': [LoDArray(cell, lod)],
            'BatchGate': [LoDArray(gates_out, lod)],
            'BatchCellPreAct': [LoDArray(cell, lod)]}


@register('gru', lod='aware')
def _gru(ctx, ins):
    x = _require_lod(ins['Input'][0], 'dynamic_gru')
    w = unwrap(ins['Weight'][0])  # [D, 3D]: [:, :2D] = u,r ; [:, 2D:] = c
    d = w.shape[0]
    bias = (unwrap(ins['Bias'][0]).reshape(-1)
            if ins.get('Bias') and ins['Bias'][0] is not None
            else jnp.zeros((3 * d,), w.dtype))
    is_reverse = ctx.attr('is_reverse', False)
    act_gate = _ACT[ctx.attr('gate_activation', 'sigmoid')]
    act_node = _ACT[ctx.attr('activation', 'tanh')]
    origin_mode = ctx.attr('origin_mode', False)

    off = np.asarray(x.lod[0], dtype=np.int64)
    xd = x.data
    if is_reverse:
        xd = _reverse_lod_rows(xd, off)
    xp, mask = _pad_from_lod(xd, off)
    n, maxlen = mask.shape
    w_g = w[:, :2 * d]
    w_c = w[:, 2 * d:]

    h0 = (unwrap(ins['H0'][0]) if ins.get('H0') and ins['H0'][0] is not None
          else jnp.zeros((n, d), xd.dtype))
    xs = jnp.swapaxes(xp, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)

    def step(h_prev, inp):
        x_t, m_t = inp
        xg = x_t[:, :2 * d] + h_prev @ w_g + bias[:2 * d]
        u = act_gate(xg[:, :d])
        r = act_gate(xg[:, d:])
        c = act_node(x_t[:, 2 * d:] + (r * h_prev) @ w_c + bias[2 * d:])
        if origin_mode:
            h = u * h_prev + (1.0 - u) * c
        else:
            h = (1.0 - u) * h_prev + u * c
        # carry dtype stays fixed under bf16 AMP (see lstm step above)
        h = jnp.where(m_t[:, None], h, h_prev).astype(h_prev.dtype)
        return h, (h, jnp.concatenate([u, r, c], axis=1), r * h_prev)

    _, (hs, gs, rs) = jax.lax.scan(step, h0, (xs, ms))
    hidden = _unpad_to_lod(jnp.swapaxes(hs, 0, 1), off)
    gates_out = _unpad_to_lod(jnp.swapaxes(gs, 0, 1), off)
    resets = _unpad_to_lod(jnp.swapaxes(rs, 0, 1), off)
    if is_reverse:
        hidden = _reverse_lod_rows(hidden, off)
        gates_out = _reverse_lod_rows(gates_out, off)
        resets = _reverse_lod_rows(resets, off)
    lod = x.lod
    return {'Hidden': [LoDArray(hidden, lod)],
            'BatchGate': [LoDArray(gates_out, lod)],
            'BatchResetHiddenPrev': [LoDArray(resets, lod)],
            'BatchHidden': [LoDArray(hidden, lod)]}


@register('cudnn_lstm', lod='none')
def _cudnn_lstm(ctx, ins):
    """Stacked dense LSTM (ref operators/cudnn_lstm_op.cc:1): the
    reference calls into cudnn's packed-weight RNN; TPU-native we run one
    lax.scan per (layer, direction) — each compiles to a single XLA
    while-op whose per-step GEMMs ride the MXU — with per-layer separate
    weight params (cudnn's single packed blob was an API artifact, not
    semantics). Four gates, no peepholes, packed {i, f, c, o}:
        i,f,o = sigmoid(x W + h W_h + b);  c~ = tanh(...)
        c_t = f*c_{t-1} + i*c~;  h_t = o * tanh(c_t)
    Dropout applies between stacked layers only (never across time steps,
    never after the last layer), cudnn-style upscale-at-train.
    """
    x = unwrap(ins['Input'][0])          # [S, B, Din] (seq-major, dense)
    h0 = unwrap(ins['InitH'][0])         # [L*ndir, B, H]
    c0 = unwrap(ins['InitC'][0])
    wx = [unwrap(w) for w in ins['WeightX']]   # per (layer,dir): [in, 4H]
    wh = [unwrap(w) for w in ins['WeightH']]   # [H, 4H]
    bias = [unwrap(b) for b in ins['Bias']]    # [4H]
    nlayers = int(ctx.attr('num_layers', 1))
    ndir = 2 if ctx.attr('is_bidirec', False) else 1
    p = float(ctx.attr('dropout_prob', 0.0))
    dropout_on = p > 0.0 and not ctx.is_test

    def run_dir(xseq, w_x, w_h, b, h_init, c_init, reverse):
        xp = xseq @ w_x + b              # hoisted input GEMM: one big
                                         # [S*B, in]x[in, 4H] MXU matmul

        def step(carry, x_t):
            h_prev, c_prev = carry
            gates = x_t + h_prev @ w_h
            g_i, g_f, g_c, g_o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(g_f) * c_prev \
                + jax.nn.sigmoid(g_i) * jnp.tanh(g_c)
            h = jax.nn.sigmoid(g_o) * jnp.tanh(c)
            # carry dtype stays fixed under bf16 AMP (see _lstm above)
            return (h.astype(h_prev.dtype), c.astype(c_prev.dtype)), h

        # reverse=True scans back-to-front and stacks outputs at their
        # original time positions — exactly the backward direction
        (h_t, c_t), hs = jax.lax.scan(step, (h_init, c_init), xp,
                                      reverse=reverse)
        return hs, h_t, c_t

    key = ctx.rng() if dropout_on else None
    # fused multi-layer mode (attr 'fuse_layers', layers.lstm): ONE scan
    # over time carrying every layer's (h, c), so the single XLA while-op
    # body runs all L packed-gate GEMMs back-to-back instead of L
    # sequential scans each re-crossing the dispatch/loop boundary per
    # layer. Unidirectional only — a backward direction needs the whole
    # forward-layer sequence before its first step, which no single
    # forward scan can carry (those programs keep the per-layer path).
    if ctx.attr('fuse_layers', False) and ndir == 1 and nlayers > 1:
        return _fused_layer_stack(x, h0, c0, wx, wh, bias, nlayers, p,
                                  dropout_on, key)

    cur = x
    last_h, last_c = [], []
    for layer in range(nlayers):
        outs = []
        for d in range(ndir):
            i = layer * ndir + d
            hs, h_t, c_t = run_dir(cur, wx[i], wh[i], bias[i],
                                   h0[i], c0[i], reverse=(d == 1))
            outs.append(hs)
            last_h.append(h_t)
            last_c.append(c_t)
        cur = jnp.concatenate(outs, axis=-1) if ndir > 1 else outs[0]
        if dropout_on and layer < nlayers - 1:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - p, cur.shape)
            cur = jnp.where(keep, cur / (1.0 - p), 0.0).astype(cur.dtype)
    return {'Out': [cur], 'LastH': [jnp.stack(last_h)],
            'LastC': [jnp.stack(last_c)]}


def _fused_layer_stack(x, h0, c0, wx, wh, bias, nlayers, p, dropout_on,
                       key):
    """cudnn_lstm fuse_layers=True body: one lax.scan over time whose
    carry is every layer's (h, c). Layer 0 keeps the hoisted input GEMM
    (one [S*B, Din] x [Din, 4H] matmul outside the loop); layers above
    compute their input projection inside the step off the layer below's
    fresh h_t — back-to-back [B, H] x [H, 4H] MXU GEMMs in a single
    while-op body, where the per-layer path pays L scan loops.

    Dropout masks are pre-sampled OUTSIDE the scan with the exact
    key-split order and [S, B, H] shapes of the per-layer path, so the
    two modes draw bit-identical masks from the same op rng stream."""
    s, b = x.shape[0], x.shape[1]
    h = wh[0].shape[0]
    xp0 = x @ wx[0] + bias[0]            # [S, B, 4H]

    xs = (xp0,)
    if dropout_on:
        masks = []
        for _ in range(nlayers - 1):
            key, sub = jax.random.split(key)
            masks.append(jax.random.bernoulli(sub, 1.0 - p, (s, b, h)))
        xs = (xp0, jnp.stack(masks, axis=1))   # [S, L-1, B, H]

    def step(carry, inp):
        hs, cs = carry
        x_t = inp[0]
        new_h, new_c = [], []
        cur = None
        for layer in range(nlayers):
            if layer == 0:
                gates = x_t + hs[0] @ wh[0]
            else:
                gates = cur @ wx[layer] + bias[layer] + hs[layer] @ wh[layer]
            g_i, g_f, g_c, g_o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(g_f) * cs[layer] \
                + jax.nn.sigmoid(g_i) * jnp.tanh(g_c)
            ht = jax.nn.sigmoid(g_o) * jnp.tanh(c)
            # carry dtype stays fixed under bf16 AMP (see _lstm above)
            ht = ht.astype(hs[layer].dtype)
            new_h.append(ht)
            new_c.append(c.astype(cs[layer].dtype))
            cur = ht
            if layer < nlayers - 1 and dropout_on:
                m_t = inp[1][layer]
                cur = jnp.where(m_t, cur / (1.0 - p), 0.0).astype(cur.dtype)
        return (tuple(new_h), tuple(new_c)), new_h[-1]

    carry0 = (tuple(h0[i] for i in range(nlayers)),
              tuple(c0[i] for i in range(nlayers)))
    (h_t, c_t), out = jax.lax.scan(step, carry0, xs)
    return {'Out': [out], 'LastH': [jnp.stack(h_t)],
            'LastC': [jnp.stack(c_t)]}


@register('gru_unit', lod='none')
def _gru_unit(ctx, ins):
    x = ins['Input'][0]           # [N, 3D]
    h_prev = ins['HiddenPrev'][0]
    w = ins['Weight'][0]          # [D, 3D]
    d = w.shape[0]
    bias = (ins['Bias'][0].reshape(-1)
            if ins.get('Bias') and ins['Bias'][0] is not None else 0.0)
    act_gate = _ACT[{1: 'sigmoid', 2: 'tanh', 0: 'identity',
                     3: 'relu'}.get(ctx.attr('gate_activation', 1),
                                    'sigmoid')] \
        if isinstance(ctx.attr('gate_activation', 1), int) \
        else _ACT[ctx.attr('gate_activation')]
    act_node = _ACT[{1: 'sigmoid', 2: 'tanh', 0: 'identity',
                     3: 'relu'}.get(ctx.attr('activation', 2), 'tanh')] \
        if isinstance(ctx.attr('activation', 2), int) \
        else _ACT[ctx.attr('activation')]
    # per reference: u, r from first 2D columns; candidate uses r⊙h_prev
    xu = x[:, :d]
    xr = x[:, d:2 * d]
    xc = x[:, 2 * d:]
    b_u = bias[:d] if not np.isscalar(bias) else 0.0
    b_r = bias[d:2 * d] if not np.isscalar(bias) else 0.0
    b_c = bias[2 * d:] if not np.isscalar(bias) else 0.0
    u = act_gate(xu + h_prev @ w[:, :d] + b_u)
    r = act_gate(xr + h_prev @ w[:, d:2 * d] + b_r)
    c = act_node(xc + (r * h_prev) @ w[:, 2 * d:] + b_c)
    h = (1.0 - u) * h_prev + u * c
    gate = jnp.concatenate([u, r, c], axis=1)
    return {'Gate': [gate], 'ResetHiddenPrev': [r * h_prev], 'Hidden': [h]}


@register('lstm_unit', lod='none')
def _lstm_unit(ctx, ins):
    x = ins['X'][0]       # [N, 4D] projections
    c_prev = ins['C_prev'][0]
    forget_bias = ctx.attr('forget_bias', 0.0)
    d = c_prev.shape[1]
    g_i = x[:, :d]
    g_f = x[:, d:2 * d]
    g_c = x[:, 2 * d:3 * d]
    g_o = x[:, 3 * d:]
    i = jax.nn.sigmoid(g_i)
    f = jax.nn.sigmoid(g_f + forget_bias)
    c = f * c_prev + i * jnp.tanh(g_c)
    h = jax.nn.sigmoid(g_o) * jnp.tanh(c)
    return {'C': [c], 'H': [h]}


# compile-time shape inference (LoD-aware; see sequence_ops._install)
from ..core import registry as _registry
from .sequence_ops import _set_out


def _lstm_infer(op, block):
    w = block._find_var_recursive(op.inputs['Weight'][0])
    if w is None or w.shape is None:
        return
    d = w.shape[0]
    _set_out(op, block, 'Hidden', (-1, d))
    _set_out(op, block, 'Cell', (-1, d))
    _set_out(op, block, 'BatchGate', (-1, 4 * d))
    _set_out(op, block, 'BatchCellPreAct', (-1, d))


def _gru_infer(op, block):
    w = block._find_var_recursive(op.inputs['Weight'][0])
    if w is None or w.shape is None:
        return
    d = w.shape[0]
    _set_out(op, block, 'Hidden', (-1, d))
    _set_out(op, block, 'BatchGate', (-1, 3 * d))
    _set_out(op, block, 'BatchResetHiddenPrev', (-1, d))
    _set_out(op, block, 'BatchHidden', (-1, d))


_registry.get('lstm').infer_shape = _lstm_infer
_registry.get('gru').infer_shape = _gru_infer
