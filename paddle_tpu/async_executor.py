"""AsyncExecutor: multithreaded host ingest feeding the compiled TPU step
(ref: framework/async_executor.cc:236 RunFromFile,
executor_thread_worker.cc, framework/data_feed.cc MultiSlotDataFeed,
python/paddle/fluid/async_executor.py).

Architectural inversion: the reference runs one CPU interpreter per thread
over a shared param scope (Hogwild); on TPU there is ONE compiled step and
the host's job is to keep it fed. So thread_num here parallelizes the
INGEST — file reading + MultiSlot text parsing (native C++ parser when
built) — into a bounded batch queue drained by the device train loop.
Throughput-equivalent for the CTR workload, deterministic by
construction (single optimizer stream, no lock-free races).
"""
from __future__ import annotations

import glob as _glob
import os
import queue as _queue
import threading
import time as _time

import numpy as np

from .framework import Program, default_main_program
from .executor import Executor
from .core.scope import global_scope
from .lod_tensor import create_lod_tensor


class DataFeedDesc(object):
    """Minimal reader of the reference's data_feed.proto prototxt
    (fluid.DataFeedDesc): batch_size + multi_slot_desc.slots with
    name/type/is_dense/is_used."""

    def __init__(self, proto_file_or_text):
        import os as _os
        looks_inline = ('\n' in proto_file_or_text
                        or '{' in proto_file_or_text)
        if not looks_inline:
            # a path: fail loudly when it doesn't exist instead of parsing
            # the path string as (empty) prototxt
            if not _os.path.exists(proto_file_or_text):
                raise IOError("DataFeedDesc: proto file %r does not exist"
                              % proto_file_or_text)
            with open(proto_file_or_text) as f:
                text = f.read()
        else:
            text = proto_file_or_text
        self.batch_size = 32
        self.slots = []   # dicts: name, type, is_dense, is_used
        # tokenize so both one-line and multi-line prototxt parse
        import re
        toks = re.findall(r'[A-Za-z_][A-Za-z_0-9]*|"[^"]*"|[{}:]|[-0-9.]+',
                          text)
        cur = None
        i = 0
        while i < len(toks):
            t = toks[i]
            if t == 'batch_size' and i + 2 < len(toks):
                self.batch_size = int(toks[i + 2])
                i += 3
            elif t == 'slots':
                cur = {'name': '', 'type': 'uint64', 'is_dense': False,
                       'is_used': True}
                self.slots.append(cur)
                i += 1
            elif cur is not None and t in ('name', 'type', 'is_dense',
                                           'is_used') \
                    and i + 2 < len(toks) and toks[i + 1] == ':':
                v = toks[i + 2].strip('"')
                if t in ('is_dense', 'is_used'):
                    cur[t] = v.lower() == 'true'
                else:
                    cur[t] = v
                i += 3
            else:
                i += 1

    def set_batch_size(self, bs):
        self.batch_size = int(bs)

    def set_use_slots(self, names):
        for s in self.slots:
            s['is_used'] = s['name'] in names

    def set_dense_slots(self, names):
        for s in self.slots:
            s['is_dense'] = s['name'] in names

    def desc(self):
        return self.__dict__


def parse_multislot_lines(text, slots):
    """Parse MultiSlot lines -> per-slot (values list, lengths list).
    Uses the native C++ parser when built; numpy-python fallback."""
    from . import recordio as _rio
    lib = _rio._native()
    n = len(slots)
    if lib is not None and not hasattr(lib, '_ms_ready'):
        import ctypes
        lib.multislot_parse.restype = ctypes.c_int64
        lib.multislot_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.multislot_free.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.c_uint32]
        lib._ms_ready = True
    if lib is not None:
        import ctypes
        buf = text.encode() if isinstance(text, str) else text
        types = (ctypes.c_uint8 * n)(*[0 if s['type'] != 'float' else 1
                                       for s in slots])
        vals = (ctypes.POINTER(ctypes.c_double) * n)()
        lens = (ctypes.POINTER(ctypes.c_uint64) * n)()
        counts = (ctypes.c_uint64 * n)()
        lines = ctypes.c_uint64()
        rc = lib.multislot_parse(buf, len(buf), n, types, vals, lens,
                                 counts, ctypes.byref(lines))
        if rc < 0:
            raise ValueError("malformed MultiSlot line %d" % (-rc))
        out = []
        for i in range(n):
            v = np.ctypeslib.as_array(vals[i], shape=(counts[i],)).copy()
            if slots[i]['type'] != 'float':
                # int64 bits traveled in the double buffer (full precision)
                v = v.view(np.int64)
            l = np.ctypeslib.as_array(lens[i],
                                      shape=(lines.value,)).copy()
            out.append((v, l.astype(np.int64)))
        lib.multislot_free(vals, lens, n)
        return out, int(lines.value)
    # fallback: python parse
    per_vals = [[] for _ in range(n)]
    per_lens = [[] for _ in range(n)]
    lines = 0
    for line in (text.splitlines() if isinstance(text, str)
                 else text.decode().splitlines()):
        toks = line.split()
        if not toks:
            continue
        pos = 0
        for i in range(n):
            cnt = int(toks[pos])
            pos += 1
            if slots[i]['type'] != 'float':
                per_vals[i].extend(int(t) for t in toks[pos:pos + cnt])
            else:
                per_vals[i].extend(float(t) for t in toks[pos:pos + cnt])
            per_lens[i].append(cnt)
            pos += cnt
        lines += 1
    return [(np.asarray(v, np.int64 if s['type'] != 'float'
                        else np.float64), np.asarray(l, np.int64))
            for (v, l), s in zip(zip(per_vals, per_lens), slots)], lines


class AsyncExecutor(object):
    """run(program, data_feed, filelist, thread_num, fetch, ...) — the
    reference's file-driven train loop, with threads on ingest."""

    def __init__(self, place=None):
        self._exe = Executor(place)

    def run(self, program, data_feed, filelist, thread_num, fetch=None,
            mode='', debug=False, epochs=1, scope=None, journal_dir=None,
            shard_id=0, num_shards=1):
        """File-driven train loop. With `journal_dir`, file dispatch runs
        through the elastic TaskService (reader/elastic.py — the Go
        master's lease/timeout/failure-cap design, go/master/service.go:89)
        with per-batch progress journaled AFTER the train step, so a
        killed run resumed with the same journal_dir skips batches already
        trained on — mid-epoch resume without loss or duplication.

        `shard_id`/`num_shards` take this host's strided slice of the
        (sorted) filelist (reader/sharded.shard_assignment — disjoint and
        covering across hosts), so a pod runs one AsyncExecutor per host
        over the same glob without double-training a file; give each
        host its own journal_dir (the journal describes ONE shard's
        progress)."""
        program = program or default_main_program()
        scope = scope or global_scope()
        if isinstance(filelist, str):
            filelist = sorted(_glob.glob(filelist))
        if not filelist:
            raise ValueError("AsyncExecutor.run: empty filelist")
        if num_shards != 1 or shard_id != 0:
            from .reader.sharded import shard_assignment
            filelist = shard_assignment(filelist, num_shards, shard_id)
            if not filelist:
                raise ValueError(
                    "AsyncExecutor.run: shard %d/%d holds no files"
                    % (shard_id, num_shards))
        # parse ALL slots (the file contains every slot), feed only is_used
        # ones — reference MultiSlotDataFeed semantics
        slots = list(data_feed.slots)
        bs = data_feed.batch_size
        fetch = fetch or []
        fetch_names = [f if isinstance(f, str) else f.name for f in fetch]

        svc = None
        if journal_dir is not None:
            from .reader.elastic import TaskService
            os.makedirs(journal_dir, exist_ok=True)
            # dispatch + training share THIS process: a leased task can't
            # outlive a live run, so lease expiry (which would re-dispatch
            # a task whose batches merely sit behind a slow consumer and
            # train them twice) is disabled — crash recovery comes from
            # the journal, not from timeouts
            svc = TaskService(
                filelist,
                journal_path=os.path.join(journal_dir, 'data_tasks.journal'),
                lease_timeout_s=1e12)
            # progress is journaled in BATCH units: a resume with another
            # batch size would mis-skip, so reject it up front
            prev_bs = svc.get_meta('batch_size')
            if prev_bs is None:
                svc.set_meta('batch_size', bs)
            elif prev_bs != bs:
                svc.close()
                raise ValueError(
                    "journal at %s was written with batch_size=%s; resuming "
                    "with batch_size=%s would skip or replay the wrong "
                    "batches" % (journal_dir, prev_bs, bs))

        batches = _queue.Queue(maxsize=max(2 * thread_num, 4))
        stop = object()
        errors = []

        def _file_batches(path):
            with open(path, 'rb') as f:
                parsed, nlines = parse_multislot_lines(f.read(), slots)
            offs = [np.concatenate([[0], np.cumsum(l)])
                    for _, l in parsed]
            out = []
            for start in range(0, nlines, bs):
                end = min(start + bs, nlines)
                feed = {}
                for (vals, lens), off, slot in zip(parsed, offs, slots):
                    if not slot['is_used']:
                        continue
                    seg = vals[off[start]:off[end]]
                    seg_lens = lens[start:end]
                    if slot['type'] == 'float':
                        arr = seg.astype(np.float32)
                    else:
                        arr = seg.astype(np.int64)
                    if slot['is_dense']:
                        feed[slot['name']] = arr.reshape(end - start, -1)
                    else:
                        feed[slot['name']] = create_lod_tensor(
                            arr.reshape(-1, 1), [list(seg_lens)])
                out.append(feed)
            return out

        def ingest(paths):
            try:
                for path in paths:
                    for feed in _file_batches(path):
                        batches.put((feed, None, 0, False))
            except Exception as e:  # propagate to the train loop
                errors.append(e)

        def ingest_elastic():
            while True:
                leased = svc.get_task()
                if leased is None:
                    if svc.epoch_done:
                        return
                    _time.sleep(0.02)  # another thread holds the last leases
                    continue
                task_id, path, skip = leased
                try:
                    file_batches = _file_batches(path)
                    if skip >= len(file_batches):
                        svc.task_finished(task_id)
                        continue
                    for bi, feed in enumerate(file_batches):
                        if bi < skip:
                            continue  # journaled: already trained on
                        batches.put((feed, task_id, bi,
                                     bi == len(file_batches) - 1))
                        # put() can block behind other tasks' batches for
                        # longer than the lease — heartbeat so the task
                        # isn't re-dispatched into duplicate training
                        svc.renew_lease(task_id)
                except Exception as e:
                    # lease-and-retry semantics (go/master/service.go:140):
                    # re-queue until the failure cap; only a DROPPED task
                    # is a hard error worth sinking the run
                    svc.task_failed(task_id)
                    if svc.is_dropped(task_id):
                        errors.append(e)
                        return

        results = []
        # epoch accounting against the journal: `epochs` is the TOTAL the
        # journal should reach, so a resumed run finishes the interrupted
        # epoch and never over-trains past the requested count
        start_epoch = 0
        if svc is not None:
            start_epoch = svc.epoch + (1 if svc.epoch_done else 0)
        try:
            self._run_epochs(range(start_epoch, max(1, int(epochs))),
                             svc, thread_num, filelist, ingest,
                             ingest_elastic, batches, stop, scope, program,
                             fetch_names, results, errors, debug)
        finally:
            if svc is not None:
                svc.close()
        return results

    def _run_epochs(self, epoch_range, svc, thread_num, filelist, ingest,
                    ingest_elastic, batches, stop, scope, program,
                    fetch_names, results, errors, debug):
        from .core.scope import scope_guard
        for _epoch in epoch_range:
            if svc is not None:
                if svc.epoch_done:
                    svc.new_epoch()
                target = ingest_elastic
                threads = [threading.Thread(target=target, daemon=True)
                           for _ in range(thread_num)]
            else:
                shards = [filelist[i::thread_num] for i in range(thread_num)]
                threads = [threading.Thread(target=ingest, args=(s,),
                                            daemon=True)
                           for s in shards if s]

            def closer(ts=threads):
                for t in ts:
                    t.join()
                batches.put(stop)

            for t in threads:
                t.start()
            threading.Thread(target=closer, daemon=True).start()

            with scope_guard(scope):
                while True:
                    item = batches.get()
                    if item is stop:
                        break
                    feed, task_id, bi, last = item
                    outs = self._exe.run(program, feed=feed,
                                         fetch_list=fetch_names)
                    if task_id is not None:
                        # journal AFTER the step: a crash replays at most
                        # the in-flight batch, never skips a trained one
                        svc.report_progress(task_id, bi + 1)
                        if last:
                            svc.task_finished(task_id)
                    if fetch_names:
                        results.append([np.asarray(o) for o in outs])
                        if debug:
                            print('AsyncExecutor:',
                                  {n: np.asarray(o).reshape(-1)[:3]
                                   for n, o in zip(fetch_names, outs)})
            if errors:
                raise errors[0]
