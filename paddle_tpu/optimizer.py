"""Optimizers (ref: python/paddle/fluid/optimizer.py — Optimizer:44,
12 subclasses, ModelAverage:1468).

`minimize` = append_backward + regularization/clip + optimizer ops, exactly
the reference pipeline; everything lands in the same program and compiles
into one XLA step function.
"""
from __future__ import annotations

from collections import defaultdict

from . import unique_name
from .backward import append_backward, OP_ROLE_OPTIMIZE
from .framework import (Variable, Parameter, default_main_program,
                        default_startup_program, program_guard)
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .clip import append_gradient_clip_ops, error_clip_callback
from .regularizer import append_regularization_ops


class Optimizer(object):
    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = defaultdict(dict)
        self.helper = None

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        helper = LayerHelper('learning_rate')
        lr_name = unique_name.generate('learning_rate')
        lr_var = helper.create_global_variable(
            name=lr_name, shape=[1], dtype='float32', persistable=True)
        helper.set_variable_initializer(
            lr_var, ConstantInitializer(float(self._learning_rate)))
        self._learning_rate_map[program] = lr_var

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = (param.optimize_attr or {}).get('learning_rate', 1.0)
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        helper = LayerHelper('param_lr')
        out = helper.create_variable_for_type_inference('float32')
        helper.append_op(type='scale', inputs={'X': [base]},
                         outputs={'Out': [out]},
                         attrs={'scale': float(param_lr),
                                'op_role': OP_ROLE_OPTIMIZE})
        return out

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper(name)
        shape = shape if shape is not None else list(param.shape)
        var = helper.create_global_variable(
            name=unique_name.generate('_'.join([param.name, name])),
            shape=shape, dtype=dtype or param.dtype, persistable=True)
        helper.set_variable_initializer(
            var, ConstantInitializer(float(fill_value)))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, parameters_and_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # -- the pipeline ------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        block = loss.block
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(block,
                                  [p for p, g in parameters_and_grads
                                   if g is not None])
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if param_and_grad[0].trainable:
                op = self._append_optimize_op(block, param_and_grad)
                optimize_ops.append(op)
        self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None, checkpoints=None):
        return append_backward(loss, parameter_list, no_grad_set,
                               callbacks, checkpoints=checkpoints)

    def apply_gradients(self, params_grads):
        loss = None
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        dummy_block = params_grads[0][0].block if params_grads else None
        # _create_optimization_pass needs a loss var only for its block
        class _L:  # minimal stand-in
            block = dummy_block
        return self._create_optimization_pass(params_grads, _L())

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, checkpoints=None):
        """checkpoints: activation-rematerialization boundaries ('auto'
        or a list of Variables/names) — see append_backward; the
        reference RecomputeOptimizer folded into minimize."""
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set, checkpoints=checkpoints)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name]},
            outputs={"ParamOut": [param_and_grad[0].name]},
            attrs={'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(self._velocity_acc_str,
                                             param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "Velocity": [velocity_acc.name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "VelocityOut": [velocity_acc.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov,
                   'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)


class LarsMomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = float(lars_coeff)
        self._lars_weight_decay = float(lars_weight_decay)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(self._velocity_acc_str,
                                             param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "Velocity": [velocity_acc.name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "VelocityOut": [velocity_acc.name]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay,
                   'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1.0e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self.type = "adagrad"
        self._epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p,
                                  fill_value=self.initial_accumulator_value)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "Moment": [moment_acc.name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "MomentOut": [moment_acc.name]},
            attrs={"epsilon": self._epsilon, 'op_role': OP_ROLE_OPTIMIZE},
            infer_shape=False)


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p,
                                  fill_value=self._beta1, shape=[1])
            self._add_accumulator(self._beta2_pow_acc_str, p,
                                  fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        moment1 = self._get_accumulator(self._moment1_acc_str, param_and_grad[0])
        moment2 = self._get_accumulator(self._moment2_acc_str, param_and_grad[0])
        beta1_pow = self._get_accumulator(self._beta1_pow_acc_str,
                                          param_and_grad[0])
        beta2_pow = self._get_accumulator(self._beta2_pow_acc_str,
                                          param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name],
                    "Moment1": [moment1.name], "Moment2": [moment2.name],
                    "Beta1Pow": [beta1_pow.name],
                    "Beta2Pow": [beta2_pow.name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "Moment1Out": [moment1.name],
                     "Moment2Out": [moment2.name],
                     "Beta1PowOut": [beta1_pow.name],
                     "Beta2PowOut": [beta2_pow.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "lazy_mode": self._lazy_mode,
                   'op_role': OP_ROLE_OPTIMIZE},
            infer_shape=False)


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"
    _beta1_pow_acc_str = "beta1_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p,
                                  fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str,
                                         param_and_grad[0])
        beta1_pow = self._get_accumulator(self._beta1_pow_acc_str,
                                          param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name],
                    "Moment": [moment.name], "InfNorm": [inf_norm.name],
                    "Beta1Pow": [beta1_pow.name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "MomentOut": [moment.name],
                     "InfNormOut": [inf_norm.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, 'op_role': OP_ROLE_OPTIMIZE},
            infer_shape=False)

    def _finish_update(self, block, parameters_and_grads):
        for param, grad in parameters_and_grads:
            if grad is None:
                continue
            beta1_pow = self._get_accumulator(self._beta1_pow_acc_str, param)
            block.append_op(
                type="scale", inputs={"X": [beta1_pow.name]},
                outputs={"Out": [beta1_pow.name]},
                attrs={"scale": self._beta1, 'op_role': OP_ROLE_OPTIMIZE},
                infer_shape=False)


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1.0e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "Moment": [moment_acc.name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "MomentOut": [moment_acc.name]},
            attrs={"epsilon": self._epsilon, "decay": self._decay,
                   'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1.0e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        avg_squared_grad = self._get_accumulator(
            self._avg_squared_grad_acc_str, param_and_grad[0])
        avg_squared_update = self._get_accumulator(
            self._avg_squared_update_acc_str, param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "AvgSquaredGrad": [avg_squared_grad.name],
                    "AvgSquaredUpdate": [avg_squared_update.name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "AvgSquaredGradOut": [avg_squared_grad.name],
                     "AvgSquaredUpdateOut": [avg_squared_update.name]},
            attrs={"epsilon": self._epsilon, "rho": self._rho,
                   'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"
    _mean_grad_acc_str = "mean_grad"

    def __init__(self, learning_rate, rho=0.95, epsilon=1.0e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)
            self._add_accumulator(self._mean_grad_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._momentum_acc_str,
                                             param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str,
                                                param_and_grad[0])
        mean_grad_acc = self._get_accumulator(self._mean_grad_acc_str,
                                              param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "Moment": [momentum_acc.name],
                    "MeanSquare": [mean_square_acc.name],
                    "MeanGrad": [mean_grad_acc.name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "MomentOut": [momentum_acc.name],
                     "MeanSquareOut": [mean_square_acc.name],
                     "MeanGradOut": [mean_grad_acc.name]},
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum, "centered": self._centered,
                   'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        squared_acc = self._get_accumulator(self._squared_acc_str,
                                            param_and_grad[0])
        linear_acc = self._get_accumulator(self._linear_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0].name],
                    "Grad": [param_and_grad[1].name],
                    "SquaredAccumulator": [squared_acc.name],
                    "LinearAccumulator": [linear_acc.name],
                    "LearningRate": [self._create_param_lr(param_and_grad).name]},
            outputs={"ParamOut": [param_and_grad[0].name],
                     "SquaredAccumOut": [squared_acc.name],
                     "LinearAccumOut": [linear_acc.name]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power,
                   'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)


# reference exports short aliases too (optimizer.py bottom)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer


class ModelAverage(Optimizer):
    """Accumulate averaged params (ref optimizer.py:1468). apply()/restore()
    swap the averaged params in and out of the scope."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        self._sum_vars = {}
        program = default_main_program()
        for param in program.global_block().all_parameters():
            if param.do_model_average:
                self._append_average_accumulate_op(param)

    def _append_average_accumulate_op(self, param):
        self.helper = LayerHelper("average_accumulate")
        sum_1 = self._add_accumulator('sum_1', param)
        sum_2 = self._add_accumulator('sum_2', param)
        sum_3 = self._add_accumulator('sum_3', param)
        num_accumulates = self._add_accumulator('num_accumulates', param,
                                                dtype='int64', shape=[1])
        old_num_accumulates = self._add_accumulator('old_num_accumulates',
                                                    param, dtype='int64',
                                                    shape=[1])
        num_updates = self._add_accumulator('num_updates', param,
                                            dtype='int64', shape=[1])
        self._sum_vars[param.name] = (sum_1, sum_2, sum_3, num_accumulates,
                                      old_num_accumulates, num_updates)
        param.block.program.global_block().append_op(
            type='average_accumulates',
            inputs={"param": [param.name], "in_sum_1": [sum_1.name],
                    "in_sum_2": [sum_2.name], "in_sum_3": [sum_3.name],
                    "in_num_accumulates": [num_accumulates.name],
                    "in_old_num_accumulates": [old_num_accumulates.name],
                    "in_num_updates": [num_updates.name]},
            outputs={"out_sum_1": [sum_1.name], "out_sum_2": [sum_2.name],
                     "out_sum_3": [sum_3.name],
                     "out_num_accumulates": [num_accumulates.name],
                     "out_old_num_accumulates": [old_num_accumulates.name],
                     "out_num_updates": [num_updates.name]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window,
                   'op_role': OP_ROLE_OPTIMIZE}, infer_shape=False)

    def apply(self, executor, need_restore=True):
        """Swap params for their accumulated averages (host-side)."""
        import numpy as np
        import contextlib
        from .core.scope import global_scope

        @contextlib.contextmanager
        def _ctx():
            scope = global_scope()
            self._restore_vals = {}
            for pname, accs in self._sum_vars.items():
                s1, s2, s3, na, ona, nu = [scope.get(a.name) for a in accs]
                n = float(np.asarray(na).sum() + np.asarray(ona).sum())
                if n == 0:
                    continue
                avg = (np.asarray(s1) + np.asarray(s2) + np.asarray(s3)) / n
                self._restore_vals[pname] = scope.get(pname)
                import jax.numpy as jnp
                scope.set(pname, jnp.asarray(avg,
                                             dtype=self._restore_vals[pname].dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)
        return _ctx()

    def restore(self, executor):
        from .core.scope import global_scope
        scope = global_scope()
        for pname, val in getattr(self, '_restore_vals', {}).items():
            scope.set(pname, val)
        self._restore_vals = {}
