"""Host-side LoDTensor construction helpers
(ref: python/paddle/fluid/lod_tensor.py)."""
from __future__ import annotations

import numpy as np

from .core.lod import LoDArray, lengths_to_offsets

# host-visible alias: a fed/fetched LoD tensor IS a LoDArray
LoDTensor = LoDArray


def create_lod_tensor(data, recursive_seq_lens, place=None, traced=False,
                      bucket_rows=None):
    """Build a LoDTensor from numpy data + nested sequence lengths
    (ref lod_tensor.py create_lod_tensor).

    traced=True makes the lod DEVICE DATA instead of compile-time structure:
    every batch with the same bucket shape (data rows padded to bucket_rows,
    same sequence count) then reuses one compiled program — see
    core/lod.py. Pair with reader.bucket_by_length."""
    if isinstance(data, LoDArray):
        return create_lod_tensor(np.asarray(data.data), recursive_seq_lens,
                                 place, traced=traced,
                                 bucket_rows=bucket_rows)
    if isinstance(data, list):
        # list of sequences: flatten, derive lengths
        flat = np.concatenate([np.asarray(s).reshape(len(s), -1) for s in data])
        seq_lens = [len(s) for s in data]
        assert [seq_lens] == recursive_seq_lens or recursive_seq_lens is None
        return create_lod_tensor(flat, [seq_lens], place, traced=traced,
                                 bucket_rows=bucket_rows)
    from .core.lod import create_lod_array
    return create_lod_array(np.asarray(data),
                            recursive_seq_lens=recursive_seq_lens,
                            traced=traced, bucket_rows=bucket_rows)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low,
                                high):
    assert isinstance(base_shape, list), "base_shape should be a list"
    converted_recursive_seq_lens = [np.cumsum([0] + l).tolist()
                                    for l in recursive_seq_lens]
    total = converted_recursive_seq_lens[-1][-1]
    data = np.random.randint(low, high + 1, size=[total] + base_shape,
                             dtype='int64')
    return create_lod_tensor(data, recursive_seq_lens, place)
