"""Dynamic-batching serving over compiled artifacts (ISSUE 1 tentpole).

The reference's deployment API serves one request per `Run` call
(inference/api/paddle_api.h:1), and small-batch serving through a remote
accelerator tunnel pays the full ~200ms dispatch floor per request
(BENCH_r05: resnet50/googlenet at bs16 run 0.2-0.5x the Xeon baseline
while bs256 runs 2-5.8x). `BatchingPredictor` amortizes that floor the
way modern serving systems do (Clipper-style adaptive batching; the
request-level simplification of ORCA's iteration scheduling, which is
what fixed-shape artifacts admit):

1. **Request queue + coalescing loop** — callers `submit()` requests
   (any row count); a worker thread coalesces them into one batch under
   a `max_batch_size` / `batch_timeout_ms` policy and dispatches ONE
   compiled call for the whole batch, slicing per-request results back
   to each caller's `Future`.
2. **Multi-bucket artifacts** — one artifact dir carries several batch
   sizes (export_compiled(..., batch_sizes=[1, 8, 32, 128])); the
   coalescer pads up to the SMALLEST bucket that fits, the batched
   analog of the LoD `bucket_rows` discipline (serve.py _build_args).
3. **Async double-buffered dispatch** — the coalescing thread hands
   dispatched (still in-flight) device results to a delivery thread
   through a depth-limited queue and immediately starts coalescing and
   padding the NEXT batch; JAX async dispatch overlaps batch N's device
   execution with batch N+1's host work, and `np.asarray` (block until
   ready) happens only at delivery.
4. **Serving metrics** — queue depth, batch occupancy (filled rows /
   bucket rows), and p50/p95/p99 request latency, readable via
   `stats.snapshot()` and surfaced through `paddle_tpu.profiler`'s
   serving report when the framework is loaded.

Determinism contract: per-request outputs are bit-identical to an
unbatched `CompiledPredictor.run` through the SAME bucket (row position
inside a compiled batch does not change per-row results); different
buckets compile different shapes and may differ in the last bit, as with
any XLA batch-size change.

Framework-free: imports only stdlib + numpy (+ sibling serve.py, which
imports jax lazily). `paddle_tpu.profiler` is touched ONLY when the
framework is already loaded in the process, so a serving process stays
tracer-free (serve.py docstring contract).
"""
import json
import os
import queue
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

try:
    from . import serve as _serve
except ImportError:  # imported by file path: serve.py sits alongside
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve as _serve

_STOP = object()
# canonical copies live in serve.py (already imported either way)
_SOURCE_SEQ = _serve._SOURCE_SEQ
_maybe_profiler = _serve._maybe_profiler


class ServerOverloaded(RuntimeError):
    """The request queue is beyond max_queue: this request was shed
    immediately (fast-fail) instead of being queued into unbounded
    latency. Back off and retry, or add capacity."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline_ms elapsed while it waited in the queue; it
    was never dispatched (no device work was wasted on it)."""


def _resolve(future, result=None, exc=None):
    """Resolve a request future, tolerating caller-side cancel(): queued
    futures are never marked running, so a client may cancel at any time —
    set_result/set_exception then raise InvalidStateError, which must not
    kill a worker thread or strand the batch's other requests."""
    try:
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
    except Exception:
        pass


def shed_if_overloaded(stats, max_queue, fail, request_id=None):
    """Load-shedding check shared by BatchingPredictor and
    decoding.DecodingPredictor. The CALLER must hold stats._lock: the
    depth check and the enqueue increment form one critical section, or
    N concurrent submits at depth max_queue-1 would ALL pass and
    overshoot the bound by the submitter concurrency. Returns True when
    the request was shed (fail(exc) already called). `request_id` (a
    caller trace id) is named in the shed message and — on stats that
    keep one — appended to the tagged-failure trace (under the lock
    the caller already holds)."""
    if max_queue is not None and stats.queue_depth >= max_queue:
        stats.shed += 1
        if request_id is not None and hasattr(stats, '_failures'):
            stats._failures.append({'request_id': str(request_id),
                                    'kind': 'shed',
                                    'time': time.time()})
        fail(ServerOverloaded(
            'queue depth %d >= max_queue %d — request shed%s'
            % (stats.queue_depth, max_queue,
               ' (request %s)' % request_id if request_id else '')))
        return True
    return False


def select_bucket(buckets, rows):
    """Smallest compiled bucket that fits `rows` — deterministic for ANY
    bucket order. Loaders sort their bucket lists once at load (this
    class, decoding.DecodingPredictor) so the scan stays a prefix walk,
    but a caller handing an unsorted list still gets the smallest fit
    rather than the first fit (a hand-edited signature once returned the
    128-bucket for a 2-row batch). Raises if even the largest bucket is
    too small."""
    fit = [b for b in buckets if rows <= b]
    if fit:
        return min(fit)
    raise ValueError(
        "batch of %d rows exceeds the largest compiled bucket %d"
        % (rows, max(buckets)))


def _batch_rows(sig):
    """The artifact's dense batch dimension: the (required-uniform) leading
    dim of every dense feed."""
    lead = set()
    for e in sig['feeds']:
        if int(e.get('lod_levels', 0)):
            continue
        if not e['shape']:
            raise ValueError(
                "feed %r has no batch dimension (shape []); the batcher "
                "needs batch-led dense feeds" % e['name'])
        lead.add(int(e['shape'][0]))
    if len(lead) != 1:
        raise ValueError(
            "artifact feeds disagree on the batch dimension (%s); the "
            "batcher needs one uniform leading batch dim" % sorted(lead))
    return lead.pop()


class _Request(object):
    __slots__ = ('arrays', 'rows', 'future', 't_submit', 'deadline')

    def __init__(self, arrays, rows, future, deadline_ms=None):
        self.arrays = arrays
        self.rows = rows
        self.future = future
        self.t_submit = time.perf_counter()
        self.deadline = (self.t_submit + deadline_ms / 1e3
                         if deadline_ms is not None else None)


class ServingStats(object):
    """Thread-safe serving counters: queue-depth gauge, cumulative batch
    occupancy, and a sliding window of per-request latencies for
    percentile reporting."""

    def __init__(self, window=8192):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=window)
        self.tier = 'bf16'   # serving tier of the source (bf16/int8)
        self.queue_depth = 0
        self.requests = 0
        self.batches = 0
        self.filled_rows = 0
        self.bucket_rows = 0
        self.shed = 0      # fast-failed at submit: queue beyond max_queue
        self.expired = 0   # deadline_ms elapsed while queued
        self.drained = 0   # shed by drain(): queued when scale-in began

    def reset(self):
        """Zero the counters and latency window (queue_depth is a live
        gauge and stays): separates a warmup/calibration phase from the
        measured run."""
        with self._lock:
            self._lat.clear()
            self.requests = 0
            self.batches = 0
            self.filled_rows = 0
            self.bucket_rows = 0
            self.shed = 0
            self.expired = 0
            self.drained = 0

    def record_batch(self, filled, bucket, latencies_s):
        with self._lock:
            self.batches += 1
            self.requests += len(latencies_s)
            self.filled_rows += filled
            self.bucket_rows += bucket
            self._lat.extend(latencies_s)

    def snapshot(self):
        """One consistent dict: queue_depth, requests, batches, occupancy
        (filled/bucket rows), p50/p95/p99_ms over the latency window."""
        with self._lock:
            lat = np.asarray(self._lat, np.float64) * 1e3
            snap = {'tier': self.tier,
                    'queue_depth': int(self.queue_depth),
                    'requests': int(self.requests),
                    'batches': int(self.batches),
                    'shed': int(self.shed),
                    'expired': int(self.expired),
                    'drained': int(self.drained),
                    'occupancy': round(self.filled_rows / self.bucket_rows, 4)
                    if self.bucket_rows else 0.0}
        if lat.size:
            p50, p95, p99 = np.percentile(lat, [50, 95, 99])
            snap.update(p50_ms=round(float(p50), 3),
                        p95_ms=round(float(p95), 3),
                        p99_ms=round(float(p99), 3))
        else:
            snap.update(p50_ms=0.0, p95_ms=0.0, p99_ms=0.0)
        return snap


class BatchingPredictor(object):
    """Coalesce concurrent requests into batched dispatches over a
    (multi-bucket) compiled artifact.

    submit(inputs) -> Future   enqueue one request (rows x feed shapes)
    run(inputs)                submit + wait (synchronous convenience)
    warmup()                   compile every bucket ahead of traffic
    stats.snapshot()           serving metrics (also via profiler report)
    close()                    drain the queue and stop worker threads

    `inputs` is a list (feed order) or dict of arrays whose leading dim is
    this request's row count (1..max_batch_size); trailing dims must match
    the artifact feeds. Dense feeds/fetches only — LoD serving keeps the
    one-artifact-per-bucket discipline of CompiledPredictor.
    """

    def __init__(self, artifact_dir, platform=None, max_batch_size=None,
                 batch_timeout_ms=5.0, inflight=2, stats_window=8192,
                 max_queue=None, tier=None):
        # tier resolution happens ONCE at the top (`tier='int8'` serves
        # the quantized tree); the per-bucket predictors below load from
        # inside the resolved tier, where no further subdir exists. The
        # profiler source keeps the ARTIFACT's name — the tier is its
        # own report column, not part of the identity
        display_dir = artifact_dir
        artifact_dir = _serve.resolve_tier(artifact_dir, tier)
        with open(os.path.join(artifact_dir, _serve._SIGNATURE)) as f:
            top_sig = json.load(f)
        self.tier = top_sig.get('tier', 'bf16')
        # lod rejection first: feeds are the same in every bucket, and
        # _batch_rows on an all-lod artifact would raise a misleading
        # "feeds disagree on the batch dimension" error
        for e in top_sig['feeds']:
            if int(e.get('lod_levels', 0)):
                raise ValueError(
                    "feed %r carries lod; the batcher serves dense feeds "
                    "only — export one artifact per lod bucket and serve "
                    "it with CompiledPredictor" % e['name'])
        sizes = top_sig.get('buckets')
        if sizes:
            preds = {int(b): _serve.CompiledPredictor(
                os.path.join(artifact_dir, _serve._BUCKET_DIR % int(b)),
                platform=platform) for b in sizes}
        else:  # single-bucket artifact (v1/v2 layout) — one bucket
            pred = _serve.CompiledPredictor(artifact_dir, platform=platform)
            preds = {_batch_rows(pred._sig): pred}
        self._buckets = sorted(preds)
        self._preds = preds
        self._sig = preds[self._buckets[-1]]._sig
        for b in self._buckets:
            for e in _serve._fetch_entries(preds[b]._sig):
                if int(e.get('lod_levels', 0)):
                    raise ValueError(
                        "fetch %r carries lod; the batcher cannot slice "
                        "per-request lod results" % e['name'])
                shape = e.get('shape')
                if shape is not None and (not shape or int(shape[0]) != b):
                    raise ValueError(
                        "fetch %r has shape %s in the %d-row bucket — not "
                        "batch-aligned, so per-request results cannot be "
                        "sliced back (e.g. a batch reduction); fetch "
                        "per-row outputs instead" % (e['name'], shape, b))
        # per-feed (name, trailing shape, dtype); batch dim is shape[0]
        _batch_rows(self._sig)  # validates uniform batch-led feeds
        self._feed_specs = [
            (e['name'], tuple(e['shape'][1:]), np.dtype(e['dtype']))
            for e in self._sig['feeds']]
        self._feed_names = [n for n, _, _ in self._feed_specs]
        largest = self._buckets[-1]
        self._max_rows = min(max_batch_size or largest, largest)
        self._timeout_s = max(batch_timeout_ms, 0.0) / 1e3
        # load-shedding bound: queued requests beyond this fast-fail with
        # ServerOverloaded instead of growing tail latency unboundedly
        # (every queued request behind a full device is pure added p99)
        self._max_queue = int(max_queue) if max_queue else None
        self._queue = queue.Queue()
        self._inflight = queue.Queue(maxsize=max(1, int(inflight)))
        self.stats = ServingStats(stats_window)
        self.stats.tier = self.tier
        self._closed = False
        self._draining = False
        # orders submit()'s closed-check+enqueue against close()'s
        # closed-set+_STOP: no request can land behind the sentinel
        self._lifecycle = threading.Lock()
        self._coalesce_t = threading.Thread(
            target=self._coalesce_loop, name='ptpu-batcher-coalesce',
            daemon=True)
        self._deliver_t = threading.Thread(
            target=self._deliver_loop, name='ptpu-batcher-deliver',
            daemon=True)
        self._coalesce_t.start()
        self._deliver_t.start()
        self._profiler_name = None
        prof = _maybe_profiler()
        if prof is not None and hasattr(prof, 'register_serving_source'):
            name = 'serving:%s#%d' % (
                os.path.basename(os.path.normpath(display_dir)),
                next(_SOURCE_SEQ))
            prof.register_serving_source(name, self.stats.snapshot)
            self._profiler_name = name

    # -- public API --------------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [e['name'] for e in _serve._fetch_entries(self._sig)]

    @property
    def buckets(self):
        return list(self._buckets)

    def submit(self, inputs, deadline_ms=None, request_id=None):
        """Enqueue one request; returns a Future resolving to the list of
        per-fetch numpy arrays sliced to this request's rows. Validation
        errors fail THIS future only (a bad request never poisons a
        batch). With `deadline_ms`, a request still queued when the
        deadline elapses resolves to DeadlineExceeded instead of being
        dispatched late. When the queue is beyond `max_queue`, the future
        resolves to ServerOverloaded immediately — load is shed at the
        door, before any padding or device work. `request_id` is an
        optional caller trace id named in the shed message."""
        if self._closed:
            raise RuntimeError('BatchingPredictor is closed')
        fut = Future()

        def _shed_locked():
            return shed_if_overloaded(self.stats, self._max_queue,
                                      fut.set_exception,
                                      request_id=request_id)

        with self.stats._lock:     # fast-fail before validation work
            if _shed_locked():
                return fut
        try:
            arrays, rows = self._validate(inputs)
        except Exception as e:
            fut.set_exception(e)
            return fut
        with self._lifecycle:
            if self._closed:
                raise RuntimeError('BatchingPredictor is closed')
            with self.stats._lock:
                if _shed_locked():  # re-check atomically with the enqueue
                    return fut
                self.stats.queue_depth += 1
            self._queue.put(_Request(arrays, rows, fut, deadline_ms))
        return fut

    def run(self, inputs, timeout=None, deadline_ms=None):
        """Synchronous single-request path: submit + wait."""
        return self.submit(inputs, deadline_ms=deadline_ms).result(timeout)

    def warmup(self):
        """Compile every bucket ahead of traffic (the reference predictor's
        Prepare; CompiledPredictor.warmup analogue)."""
        for b in self._buckets:
            args = [np.zeros((b,) + trail, dtype)
                    for _, trail, dtype in self._feed_specs]
            for o in self._preds[b]._call_flat(args):
                np.asarray(o)
        return self

    def drain(self):
        """Draining stop for scale-in (the fleet router's hook): stop
        admitting (submit() raises), SHED the queued backlog loudly —
        each queued request resolves ServerOverloaded and is counted in
        both `shed` and `drained` (it was never dispatched, so a router
        can safely re-route it) — then wait for the in-flight dispatches
        to deliver and stop the worker threads. Contrast close(), which
        serves the backlog before stopping. Idempotent."""
        with self._lifecycle:
            self._draining = True
        self.close()

    def close(self):
        """Drain queued requests, stop worker threads, unregister metrics.
        Idempotent; submit() afterwards raises."""
        with self._lifecycle:
            if self._closed:
                return
            self._closed = True
            self._queue.put(_STOP)
        self._coalesce_t.join()
        while True:  # safety net; the lifecycle lock should make this dead
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _STOP:
                with self.stats._lock:
                    self.stats.queue_depth -= 1
                _resolve(req.future,
                         exc=RuntimeError('BatchingPredictor closed'))
        self._inflight.put(_STOP)
        self._deliver_t.join()
        if self._profiler_name:
            prof = _maybe_profiler()
            if prof is not None:
                prof.unregister_serving_source(self._profiler_name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- internals ---------------------------------------------------------
    def _validate(self, inputs):
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self._feed_names):
                raise ValueError(
                    "batcher expects %d inputs (%s), got %d"
                    % (len(self._feed_names), self._feed_names, len(inputs)))
            feed = dict(zip(self._feed_names, inputs))
        else:
            feed = dict(inputs)
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError("missing feeds: %r (artifact expects %s)"
                             % (missing, self._feed_names))
        arrays, rows = [], None
        for name, trail, dtype in self._feed_specs:
            value = feed[name]
            arr = np.asarray(value, dtype=dtype)
            if arr is value:
                # snapshot the caller's own buffer: dispatch is async, and
                # a client reusing its buffer for the next request must
                # not corrupt this one (the bit-identity contract)
                arr = arr.copy()
            if arr.ndim != len(trail) + 1 or tuple(arr.shape[1:]) != trail:
                raise ValueError(
                    "feed %r: expected per-request shape [rows]+%s, got %s"
                    % (name, list(trail), list(arr.shape)))
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise ValueError(
                    "feeds disagree on request rows: %r has %d, expected %d"
                    % (name, arr.shape[0], rows))
            arrays.append(arr)
        if not rows:
            raise ValueError("empty request (0 rows)")
        if rows > self._max_rows:
            raise ValueError(
                "request of %d rows exceeds max_batch_size %d"
                % (rows, self._max_rows))
        return arrays, rows

    def _reap_expired(self, req):
        """Resolve a request whose deadline elapsed in the queue; True
        when reaped (it must not join a batch)."""
        if req.deadline is None or time.perf_counter() <= req.deadline:
            return False
        with self.stats._lock:
            self.stats.queue_depth -= 1
            self.stats.expired += 1
        _resolve(req.future, exc=DeadlineExceeded(
            'request expired after %.1f ms in queue (deadline_ms=%.1f)'
            % ((time.perf_counter() - req.t_submit) * 1e3,
               (req.deadline - req.t_submit) * 1e3)))
        return True

    def _shed_drained(self, req):
        """drain() in progress: a still-queued request sheds loudly
        (ServerOverloaded; shed+drained counters) instead of joining a
        batch — it never cost device work, so a fleet router can
        re-route it to another replica."""
        with self.stats._lock:
            self.stats.queue_depth -= 1
            self.stats.shed += 1
            self.stats.drained += 1
        _resolve(req.future, exc=ServerOverloaded(
            'request shed: predictor draining for scale-in'))

    def _coalesce_loop(self):
        carry = None
        while True:
            req = carry if carry is not None else self._queue.get()
            carry = None
            if req is _STOP:
                return
            if self._draining:
                self._shed_drained(req)
                continue
            if self._reap_expired(req):
                continue
            batch, rows = [req], req.rows
            deadline = time.perf_counter() + self._timeout_s
            while rows < self._max_rows:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    carry = _STOP  # dispatch this batch, then stop
                    break
                if self._draining:
                    self._shed_drained(nxt)
                    continue
                if self._reap_expired(nxt):
                    continue
                if rows + nxt.rows > self._max_rows:
                    carry = nxt  # seed the next batch
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._dispatch(batch, rows)

    def _dispatch(self, batch, rows):
        with self.stats._lock:
            self.stats.queue_depth -= len(batch)
        try:
            bs = select_bucket(self._buckets, rows)
            args = []
            for i, (_, trail, dtype) in enumerate(self._feed_specs):
                parts = [r.arrays[i] for r in batch]
                if rows < bs:
                    parts.append(np.zeros((bs - rows,) + trail, dtype))
                args.append(parts[0] if len(parts) == 1
                            else np.concatenate(parts, axis=0))
            outs = self._preds[bs]._call_flat(args)  # async: no sync here
        except Exception as e:
            for r in batch:
                _resolve(r.future, exc=e)
            return
        # hand off while the device (or XLA:CPU thread pool) executes; the
        # bounded queue is the double-buffer backpressure — at most
        # `inflight` batches ahead of delivery
        self._inflight.put((batch, rows, bs, outs))

    def _deliver_loop(self):
        while True:
            item = self._inflight.get()
            if item is _STOP:
                return
            batch, rows, bs, outs = item
            try:
                outs = [np.asarray(o) for o in outs]  # block_until_ready
                for e, o in zip(_serve._fetch_entries(self._sig), outs):
                    # runtime guard for v2 artifacts whose signatures do
                    # not record fetch shapes (load-time check impossible)
                    if o.ndim < 1 or o.shape[0] != bs:
                        raise ValueError(
                            "fetch %r has shape %s from the %d-row bucket "
                            "— not batch-aligned, per-request slicing is "
                            "impossible" % (e['name'], list(o.shape), bs))
            except Exception as e:
                for r in batch:
                    _resolve(r.future, exc=e)
                continue
            # record stats BEFORE resolving: a caller reading
            # stats.snapshot() right after result() returns must see this
            # batch accounted
            now = time.perf_counter()
            self.stats.record_batch(rows, bs,
                                    [now - r.t_submit for r in batch])
            off = 0
            for r in batch:
                _resolve(r.future, [o[off:off + r.rows] for o in outs])
                off += r.rows


def load_batching(artifact_dir, **kwargs):
    return BatchingPredictor(artifact_dir, **kwargs)
