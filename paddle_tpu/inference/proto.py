"""Minimal protobuf wire codec for the reference's framework.proto schema.

Hand-rolled (no protobuf runtime dependency): the subset needed to read and
write ProgramDesc / BlockDesc / VarDesc / OpDesc / VarType / TensorDesc
(message and field numbers transcribed from
/root/reference/paddle/fluid/framework/framework.proto:24-188 — the schema
IS the interoperability contract). proto2 semantics: repeated scalars are
unpacked; enums/ints are varints; strings and messages length-delimited.
"""
from __future__ import annotations

import struct


# -- wire primitives ---------------------------------------------------------
def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7


def _signed(v):
    # plain (non-zigzag) int64 varint: values >= 2^63 are negative
    return v - (1 << 64) if v >= (1 << 63) else v


def _write_varint(out, v):
    if v < 0:
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _tag(field, wire):
    return (field << 3) | wire


def parse_fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    wire 0 -> varint int; wire 1 -> 8 bytes; wire 2 -> bytes; wire 5 -> 4."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError("unsupported wire type %d" % wire)
        yield field, wire, v


class Writer(object):
    def __init__(self):
        self.out = bytearray()

    def varint(self, field, v):
        _write_varint(self.out, _tag(field, 0))
        _write_varint(self.out, v)

    def float32(self, field, v):
        _write_varint(self.out, _tag(field, 5))
        self.out += struct.pack('<f', v)

    def bytes_(self, field, b):
        _write_varint(self.out, _tag(field, 2))
        _write_varint(self.out, len(b))
        self.out += b

    def string(self, field, s):
        self.bytes_(field, s.encode('utf-8'))

    def message(self, field, writer):
        self.bytes_(field, bytes(writer.out))

    def tobytes(self):
        return bytes(self.out)


# -- framework.proto decoders ------------------------------------------------
# AttrType enum (framework.proto:26)
ATTR_INT, ATTR_FLOAT, ATTR_STRING = 0, 1, 2
ATTR_INTS, ATTR_FLOATS, ATTR_STRINGS = 3, 4, 5
ATTR_BOOLEAN, ATTR_BOOLEANS, ATTR_BLOCK = 6, 7, 8
ATTR_LONG, ATTR_BLOCKS, ATTR_LONGS = 9, 10, 11

# VarType.Type enum (framework.proto:106) — single source of truth lives in
# framework.py (convert_dtype consumes the same table)
from ..framework import _PROTO_DTYPE as DTYPE_BY_ENUM
from ..framework import PROTO_DTYPE_ENUM as ENUM_BY_DTYPE
VT_LOD_TENSOR, VT_SELECTED_ROWS, VT_FEED, VT_FETCH = 7, 8, 9, 10
VT_STEP_SCOPES, VT_RANK_TABLE, VT_TENSOR_ARRAY, VT_READER = 11, 12, 13, 15
VT_RAW = 17
TYPE_STR = {VT_LOD_TENSOR: 'lod_tensor', VT_SELECTED_ROWS: 'selected_rows',
            VT_FEED: 'lod_tensor', VT_FETCH: 'lod_tensor',
            VT_STEP_SCOPES: 'raw', VT_RANK_TABLE: 'raw',
            VT_TENSOR_ARRAY: 'tensor_array', VT_READER: 'reader',
            VT_RAW: 'raw'}


def parse_tensor_desc(buf):
    """TensorDesc (framework.proto:139): data_type=1, dims=2."""
    dtype, dims = 'float32', []
    for f, w, v in parse_fields(buf):
        if f == 1:
            dtype = DTYPE_BY_ENUM.get(v, 'float32')
        elif f == 2:
            if w == 0:
                dims.append(_signed(v))
            else:  # packed
                pos = 0
                while pos < len(v):
                    d, pos = _read_varint(v, pos)
                    dims.append(_signed(d))
    return dtype, dims


def parse_var_type(buf):
    """VarType (framework.proto:105): type=1, selected_rows=2,
    lod_tensor=3 (LoDTensorDesc: tensor=1, lod_level=2), tensor_array=4."""
    out = {'type': VT_RAW, 'dtype': None, 'shape': None, 'lod_level': 0}
    for f, w, v in parse_fields(buf):
        if f == 1:
            out['type'] = v
        elif f in (3, 4):  # LoDTensorDesc / LoDTensorArrayDesc
            for f2, w2, v2 in parse_fields(v):
                if f2 == 1:
                    out['dtype'], out['shape'] = parse_tensor_desc(v2)
                elif f2 == 2:
                    out['lod_level'] = v2
        elif f == 2:       # selected_rows TensorDesc
            out['dtype'], out['shape'] = parse_tensor_desc(v)
    return out


def parse_var_desc(buf):
    """VarDesc (framework.proto:168): name=1, type=2, persistable=3."""
    out = {'name': '', 'persistable': False, 'type': {}}
    for f, w, v in parse_fields(buf):
        if f == 1:
            out['name'] = v.decode('utf-8')
        elif f == 2:
            out['type'] = parse_var_type(v)
        elif f == 3:
            out['persistable'] = bool(v)
    return out


def parse_attr(buf):
    """OpDesc.Attr (framework.proto:44)."""
    name, atype = '', ATTR_INT
    vals = {'i': 0, 'f': 0.0, 's': '', 'ints': [], 'floats': [],
            'strings': [], 'b': False, 'bools': [], 'block': -1, 'l': 0,
            'blocks': [], 'longs': []}
    for f, w, v in parse_fields(buf):
        if f == 1:
            name = v.decode('utf-8')
        elif f == 2:
            atype = v
        elif f == 3:
            vals['i'] = _to_int32(v)
        elif f == 4:
            vals['f'] = struct.unpack('<f', v)[0]
        elif f == 5:
            vals['s'] = v.decode('utf-8')
        elif f == 6:
            vals['ints'].append(_to_int32(v))
        elif f == 7:
            vals['floats'].append(struct.unpack('<f', v)[0])
        elif f == 8:
            vals['strings'].append(v.decode('utf-8'))
        elif f == 10:
            vals['b'] = bool(v)
        elif f == 11:
            vals['bools'].append(bool(v))
        elif f == 12:
            vals['block'] = v
        elif f == 13:
            vals['l'] = _signed(v)
        elif f == 14:
            vals['blocks'].append(v)
        elif f == 15:
            vals['longs'].append(_signed(v))
    value = {ATTR_INT: vals['i'], ATTR_FLOAT: vals['f'],
             ATTR_STRING: vals['s'], ATTR_INTS: vals['ints'],
             ATTR_FLOATS: vals['floats'], ATTR_STRINGS: vals['strings'],
             ATTR_BOOLEAN: vals['b'], ATTR_BOOLEANS: vals['bools'],
             ATTR_BLOCK: vals['block'], ATTR_LONG: vals['l'],
             ATTR_BLOCKS: vals['blocks'], ATTR_LONGS: vals['longs']
             }.get(atype)
    return name, atype, value


def _to_int32(v):
    v = v - (1 << 64) if v >= (1 << 63) else v
    if v >= (1 << 31):
        v -= (1 << 32)
    return v


def parse_op_desc(buf):
    """OpDesc (framework.proto:42): inputs=1, outputs=2, type=3, attrs=4."""
    out = {'type': '', 'inputs': {}, 'outputs': {}, 'attrs': {}}
    for f, w, v in parse_fields(buf):
        if f == 3:
            out['type'] = v.decode('utf-8')
        elif f in (1, 2):
            slot, args = '', []
            for f2, w2, v2 in parse_fields(v):
                if f2 == 1:
                    slot = v2.decode('utf-8')
                elif f2 == 2:
                    args.append(v2.decode('utf-8'))
            (out['inputs'] if f == 1 else out['outputs'])[slot] = args
        elif f == 4:
            name, atype, value = parse_attr(v)
            out['attrs'][name] = value
    return out


def parse_block_desc(buf):
    """BlockDesc (framework.proto:174)."""
    out = {'idx': 0, 'parent_idx': -1, 'vars': [], 'ops': []}
    for f, w, v in parse_fields(buf):
        if f == 1:
            out['idx'] = v
        elif f == 2:
            out['parent_idx'] = _to_int32(v)
        elif f == 3:
            out['vars'].append(parse_var_desc(v))
        elif f == 4:
            out['ops'].append(parse_op_desc(v))
    return out


def parse_program_desc(buf):
    """ProgramDesc (framework.proto:184): blocks=1, version=2."""
    blocks = []
    for f, w, v in parse_fields(buf):
        if f == 1:
            blocks.append(parse_block_desc(v))
    return blocks


# -- encoders (write reference-compatible artifacts) -------------------------
def encode_tensor_desc(dtype, dims):
    wr = Writer()
    wr.varint(1, ENUM_BY_DTYPE.get(dtype, 5))
    for d in dims:
        wr.varint(2, d if d >= 0 else d + (1 << 64))
    return wr


def encode_var_desc(name, dtype, shape, lod_level=0, persistable=False,
                    vtype=VT_LOD_TENSOR):
    vt = Writer()
    vt.varint(1, vtype)
    if vtype in (VT_LOD_TENSOR, VT_FEED, VT_FETCH):
        lt = Writer()
        lt.message(1, encode_tensor_desc(dtype or 'float32',
                                         list(shape or [])))
        if lod_level:
            lt.varint(2, lod_level)
        vt.message(3, lt)
    wr = Writer()
    wr.string(1, name)
    wr.message(2, vt)
    if persistable:
        wr.varint(3, 1)
    return wr


def encode_attr(name, value):
    wr = Writer()
    wr.string(1, name)
    if isinstance(value, bool):
        wr.varint(2, ATTR_BOOLEAN)
        wr.varint(10, int(value))
    elif isinstance(value, int):
        if -(1 << 31) <= value < (1 << 31):
            wr.varint(2, ATTR_INT)
            wr.varint(3, value if value >= 0 else value + (1 << 32))
        else:
            wr.varint(2, ATTR_LONG)
            wr.varint(13, value)
    elif isinstance(value, float):
        wr.varint(2, ATTR_FLOAT)
        wr.float32(4, value)
    elif isinstance(value, str):
        wr.varint(2, ATTR_STRING)
        wr.string(5, value)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, bool) for v in value) and value:
            wr.varint(2, ATTR_BOOLEANS)
            for v in value:
                wr.varint(11, int(v))
        elif all(isinstance(v, int) for v in value):
            if value and (max(value) >= (1 << 31) or min(value) < -(1 << 31)):
                wr.varint(2, ATTR_LONGS)
                for v in value:
                    wr.varint(15, v)
            else:
                wr.varint(2, ATTR_INTS)
                for v in value:
                    wr.varint(6, v if v >= 0 else v + (1 << 32))
        elif all(isinstance(v, str) for v in value):
            wr.varint(2, ATTR_STRINGS)
            for v in value:
                wr.string(8, v)
        else:
            wr.varint(2, ATTR_FLOATS)
            for v in value:
                wr.float32(7, float(v))
    else:
        return None  # unencodable (internal) attr
    return wr


def _attr_for_encode(name, value):
    # dtype attrs: the reference stores the VarType enum INT, not a string
    # (op protos declare them as AttrType INT)
    if name in ('dtype', 'out_dtype', 'in_dtype') and isinstance(value, str):
        return ENUM_BY_DTYPE.get(value, 5)
    return value


def encode_op_desc(op_type, inputs, outputs, attrs):
    wr = Writer()
    for slot, args in inputs.items():
        var = Writer()
        var.string(1, slot)
        for a in args:
            var.string(2, a)
        wr.message(1, var)
    for slot, args in outputs.items():
        var = Writer()
        var.string(1, slot)
        for a in args:
            var.string(2, a)
        wr.message(2, var)
    wr.string(3, op_type)
    for name, value in attrs.items():
        if name.startswith('_'):
            continue  # internal bookkeeping attrs don't serialize
        a = encode_attr(name, _attr_for_encode(name, value))
        if a is not None:
            wr.message(4, a)
    return wr


def encode_program(blocks):
    """blocks: list of dicts {idx, parent_idx, vars: [(...)], ops: [...]}"""
    pr = Writer()
    for b in blocks:
        bw = Writer()
        bw.varint(1, b['idx'])
        bw.varint(2, b['parent_idx'] if b['parent_idx'] >= 0
                  else b['parent_idx'] + (1 << 32))
        for v in b['vars']:
            bw.message(3, v)
        for o in b['ops']:
            bw.message(4, o)
        pr.message(1, bw)
    ver = Writer()
    ver.varint(1, 0)
    pr.message(2, ver)
    return pr.tobytes()
