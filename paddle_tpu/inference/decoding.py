"""Continuous in-flight batching for autoregressive decode (ISSUE 8).

`DecodingPredictor` serves an `export_decode` artifact as a token-
streaming endpoint, the stateful sibling of `BatchingPredictor`'s
stateless request coalescing — the technique behind modern high-
throughput LLM servers (Orca-style iteration-level scheduling over a
vLLM-style preallocated, slot-paged KV cache):

1. **Two compiled programs, fixed shapes forever** — a PREFILL program
   per prompt-length bucket (one request: writes the prompt's K/V rows
   into one cache slot and returns first-token logits) and ONE
   DECODE-STEP program ([max_slots] requests advance one token each).
   Idle slots are masked by each slot's own attention window, so a
   partially full batch runs the same compiled shape — ZERO recompiles
   in steady state, and zero compiles at all in a warm fresh process
   (AOT sidecars per program, `tools/cache_ctl.py prewarm`).
2. **Iteration-level scheduling** — new requests join the running batch
   at step boundaries (one prefill dispatch, then their slot decodes
   with everyone else); finished sequences (eos / max_new_tokens) free
   their slot immediately for the next waiting request.
3. **Donated paged KV state** — the cache lives in device buffers
   threaded input->output through every dispatch with XLA input/output
   aliasing (in-place update). Fresh state is routed once through the
   UNDONATED reorder program, so only XLA-owned buffers ever reach a
   donated reloaded executable (the executor's round-10 ownership
   discipline).
4. **Streaming futures** — `submit()` returns a `TokenStream` yielding
   tokens as steps complete; `BatchingPredictor`'s deadline / max_queue
   shedding contract applies, including deadline expiry MID-decode
   (the slot frees at the next step boundary).

Determinism contract: a request's token stream is bit-identical whether
it decodes alone or co-resident with any other requests — every per-slot
computation is row-independent and masked rows carry exactly-zero
attention weight (ops/decode_ops.py). Greedy and fixed-width beam search
run host-side over the fetched logits with deterministic tie-breaking.

Framework-free: imports only stdlib + numpy + jax (+ sibling serve.py /
batching.py for the artifact AOT helpers and the shedding exceptions).
"""
import json
import os
import queue
import sys
import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future

import numpy as np

try:
    from . import serve as _serve
    from . import batching as _batching
except ImportError:  # imported by file path: siblings sit alongside
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve as _serve
    import batching as _batching

_STOP = object()
_WAKE = object()   # no-op queue item: rouse an idle scheduler (drain)
_SOURCE_SEQ = _serve._SOURCE_SEQ
_maybe_profiler = _serve._maybe_profiler
select_bucket = _batching.select_bucket
ServerOverloaded = _batching.ServerOverloaded
DeadlineExceeded = _batching.DeadlineExceeded

# -- artifact layout (export.py export_decode writes exactly this) ----------
_DECODE_SIGNATURE = 'decode_signature.json'
_STEP_DIR = 'decode_step'
_PREFILL_DIR = 'prefill_%05d'   # % prompt-length bucket
_REORDER_DIR = 'decode_reorder'


def _percentiles(values, qs):
    if not values:
        return [0.0 for _ in qs]
    arr = np.asarray(values, np.float64) * 1e3
    return [round(float(p), 3) for p in np.percentile(arr, qs)]


def _log_softmax(row):
    """Deterministic host log-softmax (float64): beam scoring must give
    the same bits for the same logits regardless of co-residency."""
    x = np.asarray(row, np.float64)
    x = x - x.max()
    return x - np.log(np.exp(x).sum())


class DecodeStats(object):
    """Thread-safe decode-serving counters: queue-depth gauge, token /
    dispatch totals, slot occupancy, and sliding windows of TTFT and
    inter-token latency for percentile reporting. `snapshot()` is the
    profiler serving-source contract (kind='decode' rows render in
    `profiler.serving_report()`'s decode table)."""

    def __init__(self, window=8192):
        self._lock = threading.Lock()
        self._ttft = deque(maxlen=window)
        self._itl = deque(maxlen=window)
        self.tier = 'bf16'   # KV-cache tier (bf16, or int8 paged cache)
        self.queue_depth = 0
        self.requests = 0        # completed requests
        self.tokens = 0          # tokens decoded (all beams)
        self.prefills = 0        # prefill dispatches
        self.steps = 0           # decode-step dispatches
        self.reorders = 0        # slot-gather dispatches (beam/replicate)
        self.active_slot_steps = 0
        self.slot_steps = 0
        self.shed = 0
        self.expired = 0
        self.drained = 0         # shed by drain(): queued at scale-in
        self.busy_s = 0.0        # wall time with >= 1 active slot

    def reset(self):
        """Zero counters and latency windows (queue_depth is a live gauge
        and stays): separates warmup from the measured run."""
        with self._lock:
            self._ttft.clear()
            self._itl.clear()
            self.requests = 0
            self.tokens = 0
            self.prefills = 0
            self.steps = 0
            self.reorders = 0
            self.active_slot_steps = 0
            self.slot_steps = 0
            self.shed = 0
            self.expired = 0
            self.drained = 0
            self.busy_s = 0.0

    def snapshot(self):
        with self._lock:
            ttft50, ttft99 = _percentiles(list(self._ttft), [50, 99])
            itl50, itl99 = _percentiles(list(self._itl), [50, 99])
            occ = (self.active_slot_steps / self.slot_steps
                   if self.slot_steps else 0.0)
            return {'kind': 'decode',
                    'tier': self.tier,
                    'queue_depth': int(self.queue_depth),
                    'requests': int(self.requests),
                    'tokens': int(self.tokens),
                    'prefills': int(self.prefills),
                    'steps': int(self.steps),
                    'reorders': int(self.reorders),
                    'occupancy': round(occ, 4),
                    'tokens_s': round(self.tokens / self.busy_s, 2)
                    if self.busy_s else 0.0,
                    'shed': int(self.shed),
                    'expired': int(self.expired),
                    'drained': int(self.drained),
                    'ttft_p50_ms': ttft50, 'ttft_p99_ms': ttft99,
                    'itl_p50_ms': itl50, 'itl_p99_ms': itl99}


class TokenStream(object):
    """Per-request streaming future. Greedy requests: iterate to receive
    tokens as decode steps complete (`for tok in stream: ...`), or call
    `result()` for the full generated id list (eos included when
    emitted). Beam requests: `result()` -> (ids [beam, n_tokens] int64,
    scores [beam] float64), hypotheses sorted best-first; iteration
    yields nothing until completion (beams reorder mid-flight)."""

    def __init__(self, beam=None):
        self.beam = beam
        self._q = queue.Queue()
        self._fut = Future()
        self._cancelled = False

    # -- consumer side ----------------------------------------------------
    def __iter__(self):
        while True:
            kind, payload = self._q.get()
            if kind == 'tok':
                yield payload
            elif kind == 'end':
                return
            else:
                raise payload

    def result(self, timeout=None):
        return self._fut.result(timeout)

    def done(self):
        return self._fut.done()

    def exception(self, timeout=None):
        return self._fut.exception(timeout)

    def cancel(self):
        """Best-effort: the scheduler frees the slot(s) at the next step
        boundary; already-streamed tokens remain delivered."""
        self._cancelled = True

    # -- producer side (scheduler thread) ---------------------------------
    def _push(self, tok):
        self._q.put(('tok', int(tok)))

    def _finish(self, result):
        try:
            self._fut.set_result(result)
        except Exception:
            pass
        self._q.put(('end', None))

    def _fail(self, exc):
        try:
            self._fut.set_exception(exc)
        except Exception:
            pass
        self._q.put(('err', exc))


class _Request(object):
    __slots__ = ('prompt', 'max_new', 'beam', 'stream', 't_submit',
                 'deadline', 'slots', 'produced', 'tokens', 'last_tokens',
                 'scores', 'finished', 'hyps', 't_first', 't_last')

    def __init__(self, prompt, max_new, beam, stream, deadline_ms):
        self.prompt = prompt
        self.max_new = max_new
        self.beam = beam                  # None = greedy
        self.stream = stream
        self.t_submit = time.perf_counter()
        self.deadline = (self.t_submit + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.slots = []                   # slot indices, beam order
        self.produced = 0                 # tokens generated so far
        self.tokens = []                  # greedy transcript
        self.last_tokens = []             # per beam: next step's input
        self.scores = []                  # per beam accumulated logprob
        self.finished = []                # per beam: emitted eos
        self.hyps = []                    # per beam token lists
        self.t_first = None
        self.t_last = None


class _DecodeModule(object):
    """One exported decode program: lazy StableHLO deserialize, AOT
    warm-start sidecar (zero compiles when present), fresh bookkept jit
    fallback — donated state for step/prefill (jax's own donation
    bookkeeping guards the cold path; the sidecar carries certified
    aliasing for the warm path)."""

    def __init__(self, d, donate_state, device=None):
        with open(os.path.join(d, _serve._MODULE), 'rb') as f:
            self._module_bytes = f.read()
        self._donate = bool(donate_state)
        self._fn = None
        self._aot = None
        if os.environ.get('PTPU_ARTIFACT_AOT', '1') not in ('0', 'false'):
            # sidecar keyed on the PINNED device's platform (the
            # CompiledPredictor discipline): an explicit platform= must
            # never load an executable baked for the default backend
            self._aot = _serve._load_aot(
                os.path.join(d, _serve._AOT_SIDECAR
                             % _serve._aot_platform(device)),
                _serve._module_sha(self._module_bytes))

    def _jitted(self):
        if self._fn is None:
            import jax
            from jax import export as jexport
            exp = jexport.deserialize(self._module_bytes)
            kw = {'donate_argnums': (0,)} if self._donate else {}
            self._fn = jax.jit(exp.call, **kw)
        return self._fn

    def call(self, *args):
        fn = self._aot if self._aot is not None else self._jitted()
        with warnings.catch_warnings():
            # backends without donation support (XLA:CPU) warn per call;
            # the fallback is a copy, not a correctness issue
            warnings.filterwarnings(
                'ignore', message='Some donated buffers were not usable')
            return fn(*args)


def _precompile_decode_dir(d, state_specs, arg_specs, donate, platform=None):
    """AOT-compile one decode program for `platform` and write its
    warm-start sidecar. Step/prefill compile WITH donate_argnums=(0,)
    (the paged cache updates in place on warm replicas); the reorder
    program compiles undonated — it doubles as the owned-buffer boundary
    for freshly loaded state."""
    import jax
    from jax import export as jexport
    with open(os.path.join(d, _serve._MODULE), 'rb') as f:
        module_bytes = f.read()
    plat = platform or _serve._aot_platform()
    dev = jax.devices(plat)[0]
    exp = jexport.deserialize(module_bytes)
    kw = {'donate_argnums': (0,)} if donate else {}
    with jax.default_device(dev), _serve._fresh_compile():
        compiled = jax.jit(exp.call, **kw).lower(
            state_specs, *arg_specs).compile()
    return _serve._save_aot(os.path.join(d, _serve._AOT_SIDECAR % plat),
                            compiled, _serve._module_sha(module_bytes))


def precompile_decode_artifact(artifact_dir, platform=None):
    """Prewarm a continuous-decode artifact: AOT-compile the decode-step
    program, EVERY prefill bucket, and the reorder program, writing
    warm-start sidecars — a replica that loads the artifact afterwards
    answers with zero traces and zero XLA compiles. Driven by
    `tools/cache_ctl.py prewarm` (serve.precompile_artifact detects the
    decode layout). Returns the sidecar paths written."""
    import jax
    with open(os.path.join(artifact_dir, _DECODE_SIGNATURE)) as f:
        sig = json.load(f)
    state_specs = [jax.ShapeDtypeStruct(tuple(e['shape']),
                                        np.dtype(e['dtype']))
                   for e in sig['state']]

    def feed_specs(entries):
        return [jax.ShapeDtypeStruct(tuple(e['shape']), np.dtype(e['dtype']))
                for e in entries]

    written = [_precompile_decode_dir(
        os.path.join(artifact_dir, _STEP_DIR), state_specs,
        [feed_specs(sig['step']['feeds'])], donate=True, platform=platform)]
    for b in sig['prompt_buckets']:
        written.append(_precompile_decode_dir(
            os.path.join(artifact_dir, _PREFILL_DIR % int(b)), state_specs,
            [feed_specs(sig['prefill'][str(b)]['feeds'])], donate=True,
            platform=platform))
    src_spec = jax.ShapeDtypeStruct((int(sig['max_slots']),), np.int32)
    written.append(_precompile_decode_dir(
        os.path.join(artifact_dir, _REORDER_DIR), state_specs, [src_spec],
        donate=False, platform=platform))
    return written


class DecodingPredictor(object):
    """Token-streaming decode endpoint with continuous in-flight batching
    over an `export_decode` artifact.

    submit(prompt_ids, ...) -> TokenStream   enqueue one decode request
    generate(prompt_ids, ...)                submit + wait (synchronous)
    warmup()                                 compile every program ahead
                                             of traffic (no-op when AOT
                                             sidecars loaded)
    stats.snapshot()                         decode serving metrics (also
                                             via profiler serving_report)
    close()                                  stop the scheduler; waiting
                                             and in-flight requests fail
                                             with RuntimeError

    `prompt_ids`: 1-D int sequence, 1 <= len <= the largest prompt
    bucket. `beam=` runs fixed-width beam search (the request occupies
    `beam` slots); default greedy. Admission is strict FIFO: a beam
    request at the head waits for enough free slots.
    """

    def __init__(self, artifact_dir, platform=None, max_queue=None,
                 default_max_new_tokens=32, stats_window=8192,
                 tier=None):
        import jax
        # tier resolution (ISSUE 12 satellite): `tier='int8'` serves a
        # quantized decode tier exported under <artifact>/int8/ — the
        # BatchingPredictor(tier=) contract: an EXPLICIT missing tier
        # raises, the env preference (PTPU_SERVE_TIER) degrades to the
        # top level silently
        artifact_dir = _serve.resolve_tier(artifact_dir, tier,
                                           signature=_DECODE_SIGNATURE)
        with open(os.path.join(artifact_dir, _DECODE_SIGNATURE)) as f:
            self._sig = json.load(f)
        self._S = int(self._sig['max_slots'])
        self._T = int(self._sig['max_cache_len'])
        self._eos = int(self._sig['eos_id'])
        self._vocab = int(self._sig['vocab'])
        # sorted once at load: select_bucket prefers the smallest fitting
        # bucket deterministically (inference/batching.py discipline)
        self._buckets = sorted(int(b) for b in self._sig['prompt_buckets'])
        self._default_max_new = int(default_max_new_tokens)
        self._max_queue = int(max_queue) if max_queue else None
        platform = platform or os.environ.get('PTPU_PLATFORM')
        self._device = jax.devices(platform)[0] if platform else None
        self._step_mod = _DecodeModule(
            os.path.join(artifact_dir, _STEP_DIR), donate_state=True,
            device=self._device)
        self._prefill_mods = {
            b: _DecodeModule(os.path.join(artifact_dir, _PREFILL_DIR % b),
                             donate_state=True, device=self._device)
            for b in self._buckets}
        self._reorder_mod = _DecodeModule(
            os.path.join(artifact_dir, _REORDER_DIR), donate_state=False,
            device=self._device)
        self._step_feeds = [e['name'] for e in self._sig['step']['feeds']]
        self._prefill_feeds = {
            b: [e['name'] for e in self._sig['prefill'][str(b)]['feeds']]
            for b in self._buckets}
        self._state = None
        self._slots = [None] * self._S    # slot -> (request, beam index)
        self._closed = False
        self._draining = False
        self._idle_evt = threading.Event()
        self._lifecycle = threading.Lock()
        self._queue = queue.Queue()
        self.stats = DecodeStats(stats_window)
        # int8 paged-KV artifacts serve through the same scheduler; the
        # tier rides the stats into serving_report's tier column
        self.stats.tier = ('int8' if self._sig.get('kv_cache_dtype')
                           == 'int8' else 'bf16')
        self._reset_state()
        self._sched_t = threading.Thread(
            target=self._sched_loop, name='ptpu-decode-sched', daemon=True)
        self._sched_t.start()
        self._profiler_name = None
        prof = _maybe_profiler()
        if prof is not None and hasattr(prof, 'register_serving_source'):
            name = 'decode:%s#%d' % (
                os.path.basename(os.path.normpath(artifact_dir)),
                next(_SOURCE_SEQ))
            prof.register_serving_source(name, self.stats.snapshot)
            self._profiler_name = name

    # -- public API --------------------------------------------------------
    @property
    def max_slots(self):
        return self._S

    @property
    def prompt_buckets(self):
        return list(self._buckets)

    def submit(self, prompt_ids, max_new_tokens=None, beam=None,
               deadline_ms=None):
        """Enqueue one decode request; returns a TokenStream. Validation
        errors fail THIS stream only. With `deadline_ms`, a request still
        queued — or still DECODING — when the deadline elapses resolves
        to DeadlineExceeded at the next step boundary and frees its
        slot(s). Beyond `max_queue` waiting requests, new submissions
        shed with ServerOverloaded before any device work."""
        if self._closed:
            raise RuntimeError('DecodingPredictor is closed')
        beam = int(beam) if beam else None
        stream = TokenStream(beam=beam)
        if self._draining:
            # draining for scale-in: stop admitting; shed loudly (the
            # request never cost device work — a fleet router re-routes)
            with self.stats._lock:
                self.stats.shed += 1
                self.stats.drained += 1
            stream._fail(ServerOverloaded(
                'request shed: endpoint draining for scale-in'))
            return stream

        def _shed_locked():
            return _batching.shed_if_overloaded(
                self.stats, self._max_queue, stream._fail)

        with self.stats._lock:          # fast-fail before validation work
            if _shed_locked():
                return stream
        try:
            prompt = np.asarray(prompt_ids, np.int64).reshape(-1).copy()
            if not prompt.size:
                raise ValueError('empty prompt')
            if prompt.size > self._buckets[-1]:
                raise ValueError(
                    'prompt of %d tokens exceeds the largest compiled '
                    'prompt bucket %d' % (prompt.size, self._buckets[-1]))
            max_new = int(max_new_tokens if max_new_tokens is not None
                          else self._default_max_new)
            # cache capacity: the last generated token writes position
            # len(prompt) + max_new - 2
            max_new = max(1, min(max_new, self._T - prompt.size + 1))
            if beam is not None and not 1 <= beam <= self._S:
                raise ValueError(
                    'beam width %d not in [1, max_slots=%d]'
                    % (beam, self._S))
        except Exception as e:
            stream._fail(e)
            return stream
        req = _Request(prompt, max_new, beam, stream, deadline_ms)
        with self._lifecycle:
            if self._closed:
                raise RuntimeError('DecodingPredictor is closed')
            if self._draining:
                with self.stats._lock:
                    self.stats.shed += 1
                    self.stats.drained += 1
                stream._fail(ServerOverloaded(
                    'request shed: endpoint draining for scale-in'))
                return stream
            with self.stats._lock:
                if _shed_locked():      # re-check atomically with enqueue
                    return stream
                self.stats.queue_depth += 1
            self._queue.put(req)
        return stream

    def generate(self, prompt_ids, max_new_tokens=None, beam=None,
                 deadline_ms=None, timeout=None):
        """Synchronous single-request decode: submit + wait."""
        return self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                           beam=beam, deadline_ms=deadline_ms
                           ).result(timeout)

    def warmup(self):
        """Compile every program ahead of traffic (a no-op dispatch per
        prefill bucket, one decode step, one reorder); state is re-zeroed
        afterwards. With AOT sidecars loaded this costs three dispatches
        and zero compiles. Must run BEFORE any submit(): it dispatches on
        the scheduler's donated state from this thread, so it refuses
        loudly once traffic has started."""
        if self.stats.queue_depth or any(s is not None
                                         for s in self._slots):
            raise RuntimeError(
                'warmup() must run before traffic: requests are queued or '
                'decoding, and a caller-thread dispatch would race the '
                "scheduler over the donated cache state")
        for b in self._buckets:
            self._dispatch_prefill(b, np.zeros((1, b), np.int64), 1, 0)
        self._dispatch_step(np.zeros((self._S, 1), np.int64),
                            np.zeros((self._S, 1), np.int32))
        self._reset_state()
        return self

    def drain(self, timeout=None):
        """Draining stop for scale-in (the fleet router's hook): stop
        admitting — new submissions shed ServerOverloaded (counted in
        shed+drained; never dispatched, so a router can re-route them)
        and WAITING queued requests shed the same way — while every
        ACTIVE stream finishes decoding to completion (zero dropped
        in-flight streams). Blocks until the last active slot frees (or
        `timeout`); returns True when fully drained. The endpoint stays
        open for stats/close(); it admits nothing afterwards."""
        with self._lifecycle:
            if self._closed:
                return True
            self._draining = True
            self._idle_evt.clear()
            self._queue.put(_WAKE)  # rouse an idle scheduler
        return self._idle_evt.wait(timeout)

    def close(self):
        """Stop the scheduler thread. Waiting and in-flight requests
        resolve with RuntimeError. Idempotent; submit() afterwards
        raises. Also finalizes an endpoint that already closed ITSELF
        after an unrecoverable dispatch failure (joins the scheduler,
        unregisters the profiler source)."""
        with self._lifecycle:
            if not self._closed:
                self._closed = True
                self._queue.put(_STOP)
        self._idle_evt.set()   # never strand a drain() waiter
        if threading.current_thread() is not self._sched_t:
            self._sched_t.join()
        name, self._profiler_name = self._profiler_name, None
        if name:
            prof = _maybe_profiler()
            if prof is not None:
                prof.unregister_serving_source(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- device plumbing ---------------------------------------------------
    def _dev_ctx(self):
        import jax
        import contextlib
        return (jax.default_device(self._device)
                if self._device is not None else contextlib.nullcontext())

    def _reset_state(self):
        """(Re)zero the paged KV cache. The zeros route through the
        UNDONATED reorder program so every leaf handed to the donated
        step/prefill executables is an XLA-owned buffer (a reloaded
        donating executable honors its baked-in aliasing without jax's
        external-buffer guard — round-8/10 cliff)."""
        import jax
        zeros = [np.zeros(tuple(e['shape']), np.dtype(e['dtype']))
                 for e in self._sig['state']]
        src = np.arange(self._S, dtype=np.int32)
        with self._dev_ctx():
            state = [jax.device_put(z, self._device) for z in zeros]
            self._state = list(self._reorder_mod.call(state, src))

    def _dispatch_step(self, tokens, pos):
        feed = {'tokens': tokens, 'pos': pos}
        args = [feed[n] for n in self._step_feeds]  # signature feed order
        with self._dev_ctx():
            fetches, new_state = self._step_mod.call(self._state, args)
        self._state = list(new_state)
        with self.stats._lock:
            self.stats.steps += 1
        return np.asarray(fetches[0])                      # [S, V] sync

    def _dispatch_prefill(self, bucket, padded, plen, slot):
        feed = {'prompt_ids': padded,
                'prompt_len': np.full((1, 1), plen, np.int32),
                'slot': np.full((1, 1), slot, np.int32)}
        args = [feed[n] for n in self._prefill_feeds[bucket]]
        with self._dev_ctx():
            fetches, new_state = self._prefill_mods[bucket].call(
                self._state, args)
        self._state = list(new_state)
        with self.stats._lock:
            self.stats.prefills += 1
        return np.asarray(fetches[0])[0]                   # [V] sync

    def _dispatch_reorder(self, src):
        with self._dev_ctx():
            self._state = list(self._reorder_mod.call(
                self._state, np.asarray(src, np.int32)))
        with self.stats._lock:
            self.stats.reorders += 1

    # -- scheduler ---------------------------------------------------------
    def _active_requests(self):
        seen = []
        for entry in self._slots:
            if entry is not None and entry[0] not in seen:
                seen.append(entry[0])
        return seen

    def _free_slots(self):
        return [i for i, s in enumerate(self._slots) if s is None]

    def _release(self, req):
        for s in req.slots:
            self._slots[s] = None

    def _sched_loop(self):
        waiting = deque()
        while True:
            have_work = waiting or any(s is not None for s in self._slots)
            try:
                item = self._queue.get(block=not have_work)
            except queue.Empty:
                item = None
            if item is _STOP:
                self._drain_on_close(waiting)
                return
            if item is _WAKE:
                item = None
            if item is not None:
                waiting.append(item)
                continue  # keep draining submissions before dispatching
            t0 = time.perf_counter()
            if self._draining:
                # scale-in drain: shed the waiting queue loudly (safe to
                # re-route — never dispatched); active streams keep
                # stepping to completion below
                self._shed_waiting(waiting)
            self._expire(waiting)
            if not self._draining:
                self._admit(waiting)
            if any(s is not None for s in self._slots):
                try:
                    self._step()
                except Exception as e:
                    self._fail_all(e)
                with self.stats._lock:
                    self.stats.busy_s += time.perf_counter() - t0
            if self._draining and not waiting \
                    and not any(s is not None for s in self._slots):
                self._idle_evt.set()

    def _shed_waiting(self, waiting):
        """drain() in progress: fail every WAITING request with
        ServerOverloaded (shed+drained counters) — they never reached a
        slot, so a fleet router can re-route them."""
        while waiting:
            req = waiting.popleft()
            with self.stats._lock:
                self.stats.queue_depth -= 1
                self.stats.shed += 1
                self.stats.drained += 1
            req.stream._fail(ServerOverloaded(
                'request shed: endpoint draining for scale-in'))

    def _drain_on_close(self, waiting):
        err = RuntimeError('DecodingPredictor closed')
        for req in self._active_requests():
            self._release(req)
            req.stream._fail(err)
        for req in waiting:
            with self.stats._lock:
                self.stats.queue_depth -= 1
            req.stream._fail(err)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is not _STOP:
                with self.stats._lock:
                    self.stats.queue_depth -= 1
                req.stream._fail(err)

    def _expire(self, waiting):
        now = time.perf_counter()
        # waiting requests: reap expired/cancelled before they cost work
        alive = deque()
        for req in waiting:
            cancelled = req.stream._cancelled
            if cancelled or (req.deadline is not None
                             and now > req.deadline):
                with self.stats._lock:
                    self.stats.queue_depth -= 1
                    if not cancelled:
                        self.stats.expired += 1
                if cancelled:
                    req.stream._fail(RuntimeError('request cancelled'))
                else:
                    req.stream._fail(DeadlineExceeded(
                        'request expired after %.1f ms in queue'
                        % ((now - req.t_submit) * 1e3)))
            else:
                alive.append(req)
        waiting.clear()
        waiting.extend(alive)
        # ACTIVE requests: deadline expiry mid-decode frees the slot(s)
        # at this step boundary (the satellite contract)
        for req in self._active_requests():
            if req.stream._cancelled or (req.deadline is not None
                                         and now > req.deadline):
                self._release(req)
                if req.stream._cancelled:
                    req.stream._fail(RuntimeError('request cancelled'))
                else:
                    with self.stats._lock:
                        self.stats.expired += 1
                    req.stream._fail(DeadlineExceeded(
                        'deadline elapsed mid-decode after %d token(s); '
                        'slot freed' % req.produced))

    def _admit(self, waiting):
        """Strict-FIFO admission at the step boundary: one prefill
        dispatch per admitted request; beam requests wait for enough
        free slots."""
        while waiting:
            req = waiting[0]
            need = req.beam or 1
            free = self._free_slots()
            if len(free) < need:
                return
            waiting.popleft()
            with self.stats._lock:
                self.stats.queue_depth -= 1
            req.slots = free[:need]
            try:
                self._prefill(req)
            except Exception as e:
                # the donated prefill dispatch may have consumed the
                # state even though it raised: this is the same hazard
                # as a step failure, so recover the same way (fail the
                # co-resident requests loudly, rebuild zero state)
                self._release(req)
                req.stream._fail(e)
                self._fail_all(e)
                return

    def _prefill(self, req):
        plen = int(req.prompt.size)
        bucket = select_bucket(self._buckets, plen)
        padded = np.zeros((1, bucket), np.int64)
        padded[0, :plen] = req.prompt
        logits = self._dispatch_prefill(bucket, padded, plen, req.slots[0])
        now = time.perf_counter()
        for i, s in enumerate(req.slots):
            self._slots[s] = (req, i)
        if req.beam is None:
            tok = int(np.argmax(logits))
            req.last_tokens = [tok]
            req.tokens = [tok]
            req.produced = 1
            self._record_emit(req, now)
            req.stream._push(tok)
            if tok == self._eos or req.produced >= req.max_new:
                self._finish_greedy(req)
            return
        # beam: replicate slot 0's cache to the other beam slots, then
        # seed the W beams with the top-W DISTINCT first tokens (the
        # standard first-expansion; a naive W*V step over identical
        # beams would collapse onto one token)
        if len(req.slots) > 1:
            src = np.arange(self._S, dtype=np.int32)
            for s in req.slots[1:]:
                src[s] = req.slots[0]
            self._dispatch_reorder(src)
        lp = _log_softmax(logits)
        order = np.argsort(-lp, kind='stable')[:req.beam]
        req.last_tokens = [int(t) for t in order]
        req.scores = [float(lp[t]) for t in order]
        req.finished = [int(t) == self._eos for t in order]
        req.hyps = [[int(t)] for t in order]
        req.produced = 1
        self._record_emit(req, now, count=req.beam)
        if all(req.finished) or req.produced >= req.max_new:
            self._finish_beam(req)

    def _record_emit(self, req, now, count=1):
        with self.stats._lock:
            self.stats.tokens += count
            if req.t_first is None:
                req.t_first = now
                self.stats._ttft.append(now - req.t_submit)
            else:
                self.stats._itl.append(now - req.t_last)
        req.t_last = now

    def _finish_greedy(self, req):
        self._release(req)
        with self.stats._lock:
            self.stats.requests += 1
        req.stream._finish(list(req.tokens))

    def _finish_beam(self, req):
        self._release(req)
        with self.stats._lock:
            self.stats.requests += 1
        ids = np.asarray(req.hyps, np.int64)
        scores = np.asarray(req.scores, np.float64)
        req.stream._finish((ids, scores))

    def _step(self):
        """One iteration of the continuous batch: every active slot
        advances one token through ONE fixed-shape dispatch."""
        tokens = np.zeros((self._S, 1), np.int64)
        pos = np.zeros((self._S, 1), np.int32)
        active = 0
        for s, entry in enumerate(self._slots):
            if entry is None:
                continue
            req, bi = entry
            active += 1
            tokens[s, 0] = req.last_tokens[bi]
            # this token writes at position len(prompt) + produced - 1
            pos[s, 0] = req.prompt.size + req.produced - 1
        with self.stats._lock:
            self.stats.active_slot_steps += active
            self.stats.slot_steps += self._S
        logits = self._dispatch_step(tokens, pos)
        now = time.perf_counter()
        src = np.arange(self._S, dtype=np.int32)
        for req in self._active_requests():
            if req.beam is None:
                tok = int(np.argmax(logits[req.slots[0]]))
                req.last_tokens[0] = tok
                req.tokens.append(tok)
                req.produced += 1
                self._record_emit(req, now)
                req.stream._push(tok)
                if tok == self._eos or req.produced >= req.max_new:
                    self._finish_greedy(req)
                continue
            # fixed-width beam: finished beams contribute one frozen
            # eos candidate (ops/decode_ops.py beam_search discipline)
            W, V = req.beam, self._vocab
            cand = np.full((W, V), -np.inf, np.float64)
            for i in range(W):
                if req.finished[i]:
                    cand[i, self._eos] = req.scores[i]
                else:
                    cand[i] = req.scores[i] + _log_softmax(
                        logits[req.slots[i]])
            order = np.argsort(-cand, axis=None, kind='stable')[:W]
            parents = order // V
            toks = order % V
            req.scores = [float(cand[p, t]) for p, t in zip(parents, toks)]
            req.hyps = [req.hyps[p] + [int(t)]
                        for p, t in zip(parents, toks)]
            req.finished = [req.finished[p] or int(t) == self._eos
                            for p, t in zip(parents, toks)]
            req.last_tokens = [int(t) for t in toks]
            for i in range(W):
                src[req.slots[i]] = req.slots[parents[i]]
            req.produced += 1
            self._record_emit(req, now, count=W)
            if all(req.finished) or req.produced >= req.max_new:
                self._finish_beam(req)
                for s in req.slots:   # a finished group never reorders
                    src[s] = s
        if not np.array_equal(src, np.arange(self._S, dtype=np.int32)):
            # one slot-gather for every surviving beam group: each beam's
            # cache follows its parent before the next step writes
            self._dispatch_reorder(src)

    def _fail_all(self, exc):
        """A dispatch failure mid-step may have consumed the donated
        state: fail every in-flight request loudly and rebuild a clean
        zero state so the endpoint keeps serving. If even the rebuild
        dispatch fails (wedged backend), the endpoint closes itself —
        queued and future requests fail fast instead of hanging on a
        dead scheduler."""
        for req in self._active_requests():
            self._release(req)
            req.stream._fail(exc)
        try:
            self._reset_state()
        except Exception as e:
            warnings.warn(
                'DecodingPredictor: state rebuild after a dispatch '
                'failure itself failed (%s: %s) — closing the endpoint'
                % (type(e).__name__, e), RuntimeWarning)
            # runs ON the scheduler thread: close() skips the self-join
            # and unregisters the profiler source; the loop drains the
            # queued requests when it sees _STOP
            self.close()


def load_decoding(artifact_dir, **kwargs):
    return DecodingPredictor(artifact_dir, **kwargs)
