"""Continuous in-flight batching for autoregressive decode (ISSUE 8).

`DecodingPredictor` serves an `export_decode` artifact as a token-
streaming endpoint, the stateful sibling of `BatchingPredictor`'s
stateless request coalescing — the technique behind modern high-
throughput LLM servers (Orca-style iteration-level scheduling over a
vLLM-style preallocated, slot-paged KV cache):

1. **Two compiled programs, fixed shapes forever** — a PREFILL program
   per prompt-length bucket (one request: writes the prompt's K/V rows
   into one cache slot and returns first-token logits) and ONE
   DECODE-STEP program ([max_slots] requests advance one token each).
   Idle slots are masked by each slot's own attention window, so a
   partially full batch runs the same compiled shape — ZERO recompiles
   in steady state, and zero compiles at all in a warm fresh process
   (AOT sidecars per program, `tools/cache_ctl.py prewarm`).
2. **Iteration-level scheduling** — new requests join the running batch
   at step boundaries (one prefill dispatch, then their slot decodes
   with everyone else); finished sequences (eos / max_new_tokens) free
   their slot immediately for the next waiting request.
3. **Donated paged KV state** — the cache lives in device buffers
   threaded input->output through every dispatch with XLA input/output
   aliasing (in-place update). Fresh state is routed once through the
   UNDONATED reorder program, so only XLA-owned buffers ever reach a
   donated reloaded executable (the executor's round-10 ownership
   discipline).
4. **Streaming futures** — `submit()` returns a `TokenStream` yielding
   tokens as steps complete; `BatchingPredictor`'s deadline / max_queue
   shedding contract applies, including deadline expiry MID-decode
   (the slot frees at the next step boundary).

Determinism contract: a request's token stream is bit-identical whether
it decodes alone or co-resident with any other requests — every per-slot
computation is row-independent and masked rows carry exactly-zero
attention weight (ops/decode_ops.py). Greedy and fixed-width beam search
run host-side over the fetched logits with deterministic tie-breaking.

Speculative decoding (ISSUE 17): artifacts exported with a VERIFY
program (build_decode_spec(draft_k=K)) can serve greedy streams
draft-and-verify — `DecodingPredictor(draft='ngram')` (or any object
with a `draft(tokens, k)` method, e.g. `DraftModelDrafter`) proposes up
to K tokens per slot host-side, ONE verify dispatch scores all K+1 rows
per slot, and longest-prefix acceptance against the target argmax keeps
greedy transcripts BIT-IDENTICAL to plain decode while advancing up to
K+1 tokens per dispatch. Slots without drafts ride the plain step in
the same scheduler tick; beams never draft. Rejected speculative cache
rows sit strictly above each slot's accepted frontier (rolled-back
`pos` masks them; the block layout also trims over-extended tables), so
they are overwritten before any attention window admits them. Zero
steady-state recompiles: variable per-slot acceptance lives inside the
fixed [max_slots, K+1] compiled shape as masked pad rows.

Framework-free: imports only stdlib + numpy + jax (+ sibling serve.py /
batching.py for the artifact AOT helpers and the shedding exceptions).
"""
import json
import os
import queue
import sys
import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future

import numpy as np

try:
    from . import serve as _serve
    from . import batching as _batching
    from .kv_blocks import BlockManager, BlockPoolExhausted, TRASH_BLOCK
except ImportError:  # imported by file path: siblings sit alongside
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve as _serve
    import batching as _batching
    from kv_blocks import BlockManager, BlockPoolExhausted, TRASH_BLOCK

_STOP = object()
_WAKE = object()   # no-op queue item: rouse an idle scheduler (drain)
_SOURCE_SEQ = _serve._SOURCE_SEQ
_maybe_profiler = _serve._maybe_profiler
select_bucket = _batching.select_bucket
ServerOverloaded = _batching.ServerOverloaded
DeadlineExceeded = _batching.DeadlineExceeded


class MidStreamEvicted(ServerOverloaded):
    """Overload shed of a request that ALREADY DISPATCHED device work:
    the block-pool preflight evicts the youngest DECODING stream under
    unresolvable pressure, after tokens may have streamed to the
    caller. Still a ServerOverloaded for local callers, but a fleet
    router must NOT blindly re-route it (base ServerOverloaded means
    shed at the door — no device work, always re-routable)."""

# -- artifact layout (export.py export_decode writes exactly this) ----------
_DECODE_SIGNATURE = 'decode_signature.json'
_STEP_DIR = 'decode_step'
_PREFILL_DIR = 'prefill_%05d'   # % prompt-length bucket
_REORDER_DIR = 'decode_reorder'
# block-paged layout (ISSUE 13): chunked-prefill programs + the
# block-copy program (beam CoW moves diverged BLOCKS, not slot rows)
_CHUNK_DIR = 'prefill_chunk_%05d'   # % chunk size
_BLOCKCOPY_DIR = 'decode_blockcopy'
# speculative decoding (ISSUE 17): the [S, K+1] -> [S, K+1, V] verify
# program, present iff the spec was built with draft_k > 0
_VERIFY_DIR = 'decode_verify'


def _decode_mesh(axes, platform=None):
    """Build a sharded decode mesh: the first prod(axes) devices of
    `platform` (or the default backend), row-major over the SORTED axis
    names. THE one copy of the rule — export.py delegates here, so an
    artifact exported on one host places identically on any host with
    the same device count."""
    import jax
    from jax.sharding import Mesh
    names = tuple(sorted(axes))
    shape = tuple(int(axes[a]) for a in names)
    n = 1
    for s in shape:
        n *= s
    devs = jax.devices(platform) if platform else jax.devices()
    if len(devs) < n:
        raise ValueError(
            'sharded decode mesh %r needs %d device(s); this process '
            'sees %d. Run on a host with the full mesh (or export '
            'unsharded).' % (dict(axes), n, len(devs)))
    return Mesh(np.asarray(devs[:n]).reshape(shape), names)


def _state_shardings_ns(mesh, spec_map, names):
    """Map state names through a {name: partition-spec} dict into
    concrete NamedShardings, replicated fallback for unlisted names.
    THE one copy of the rule — export-time (_decode_shard_ctx) and
    load-time (_sig_mesh_ctx) both resolve through here, so an exported
    artifact can never place state differently at serve time. Returns
    (rep, state_ns) with state_ns aligned to `names`."""
    from jax.sharding import NamedSharding, PartitionSpec
    rep = NamedSharding(mesh, PartitionSpec())
    spec_map = spec_map or {}
    state_ns = []
    for n in names:
        ps = spec_map.get(n)
        state_ns.append(NamedSharding(mesh, PartitionSpec(*ps))
                        if ps else rep)
    return rep, state_ns


def _percentiles(values, qs):
    if not values:
        return [0.0 for _ in qs]
    arr = np.asarray(values, np.float64) * 1e3
    return [round(float(p), 3) for p in np.percentile(arr, qs)]


def _log_softmax(row):
    """Deterministic host log-softmax (float64): beam scoring must give
    the same bits for the same logits regardless of co-residency."""
    x = np.asarray(row, np.float64)
    x = x - x.max()
    return x - np.log(np.exp(x).sum())


class DecodeStats(object):
    """Thread-safe decode-serving counters: queue-depth gauge, token /
    dispatch totals, slot occupancy, and sliding windows of TTFT and
    inter-token latency for percentile reporting. `snapshot()` is the
    profiler serving-source contract (kind='decode' rows render in
    `profiler.serving_report()`'s decode table)."""

    def __init__(self, window=8192):
        self._lock = threading.Lock()
        self._ttft = deque(maxlen=window)
        self._itl = deque(maxlen=window)
        # tagged-request failure trace (shed/expired for requests that
        # carried a request_id): what a gateway/operator correlates
        self._failures = deque(maxlen=16)
        self.tier = 'bf16'   # KV-cache tier (bf16, or int8 paged cache)
        self.queue_depth = 0
        self.requests = 0        # completed requests
        self.tokens = 0          # tokens decoded (all beams)
        self.prefills = 0        # prefill dispatches
        self.steps = 0           # decode-step dispatches
        self.reorders = 0        # slot-gather dispatches (beam/replicate)
        self.active_slot_steps = 0
        self.slot_steps = 0
        self.shed = 0
        self.expired = 0
        self.drained = 0         # shed by drain(): queued at scale-in
        self.busy_s = 0.0        # wall time with >= 1 active slot
        # block-paged layout (ISSUE 13); zero/absent on slot artifacts.
        # block_source is the BlockManager.stats callable (pool gauges +
        # prefix-share accounting merge into snapshot()); block_reset
        # its reset_counters, so reset() covers the merged counters too
        self.block_source = None
        self.block_reset = None
        self.cow_blocks = 0      # blocks copied for beam copy-on-write
        self.blockcopies = 0     # block-copy dispatches
        self.chunk_slices = 0    # chunked-prefill slice dispatches
        # speculative decoding (ISSUE 17). adv_* meter tokens delivered
        # per request-advancing dispatch (prefill first token, plain
        # step, beam step, verify tick) — tokens_per_dispatch is
        # exactly 1.0 for non-speculative serving
        self.verify_steps = 0    # verify-program dispatches
        self.drafted = 0         # draft tokens proposed to the verifier
        self.accepted = 0        # draft tokens accepted (prefix match)
        self.adv_tokens = 0
        self.adv_events = 0

    def reset(self):
        """Zero counters and latency windows (queue_depth is a live gauge
        and stays): separates warmup from the measured run."""
        with self._lock:
            self._ttft.clear()
            self._itl.clear()
            self._failures.clear()
            self.requests = 0
            self.tokens = 0
            self.prefills = 0
            self.steps = 0
            self.reorders = 0
            self.active_slot_steps = 0
            self.slot_steps = 0
            self.shed = 0
            self.expired = 0
            self.drained = 0
            self.busy_s = 0.0
            self.cow_blocks = 0
            self.blockcopies = 0
            self.chunk_slices = 0
            self.verify_steps = 0
            self.drafted = 0
            self.accepted = 0
            self.adv_tokens = 0
            self.adv_events = 0
            if self.block_reset is not None:
                # the BlockManager-sourced counters merge into
                # snapshot(): a reset-then-measure window must not
                # report pre-reset prefix hits / peaks
                self.block_reset()

    def record_failure(self, request_id, kind):
        """One tagged request's shed/expiry: lands in the bounded
        `recent_failures` snapshot list for wire-level correlation."""
        if request_id is None:
            return
        with self._lock:
            self._failures.append({'request_id': str(request_id),
                                   'kind': kind,
                                   'time': time.time()})

    def snapshot(self):
        with self._lock:
            ttft50, ttft99 = _percentiles(list(self._ttft), [50, 99])
            itl50, itl99 = _percentiles(list(self._itl), [50, 99])
            occ = (self.active_slot_steps / self.slot_steps
                   if self.slot_steps else 0.0)
            snap = {'kind': 'decode',
                    'tier': self.tier,
                    'queue_depth': int(self.queue_depth),
                    'requests': int(self.requests),
                    'tokens': int(self.tokens),
                    'prefills': int(self.prefills),
                    'steps': int(self.steps),
                    'reorders': int(self.reorders),
                    'occupancy': round(occ, 4),
                    'tokens_s': round(self.tokens / self.busy_s, 2)
                    if self.busy_s else 0.0,
                    'shed': int(self.shed),
                    'expired': int(self.expired),
                    'drained': int(self.drained),
                    'ttft_p50_ms': ttft50, 'ttft_p99_ms': ttft99,
                    'itl_p50_ms': itl50, 'itl_p99_ms': itl99,
                    # speculative decoding (ISSUE 17): both ratios are
                    # identically 1.0 for plain (non-drafting) serving
                    'verify_steps': int(self.verify_steps),
                    'drafted': int(self.drafted),
                    'accepted': int(self.accepted),
                    'acc_rate': round(self.accepted / self.drafted, 4)
                    if self.drafted else 1.0,
                    'tokens_per_dispatch':
                        round(self.adv_tokens / self.adv_events, 4)
                        if self.adv_events else 1.0,
                    'recent_failures': list(self._failures)}
            if self.block_source is None:
                return snap
            snap['cow_blocks'] = int(self.cow_blocks)
            snap['blockcopies'] = int(self.blockcopies)
            snap['chunk_slices'] = int(self.chunk_slices)
        # outside the stats lock: the BlockManager takes its own
        bs = self.block_source()
        snap['blocks_in_use'] = int(bs['blocks_in_use'])
        snap['blocks_peak'] = int(bs['blocks_peak'])
        snap['blocks_total'] = int(bs['num_blocks'])
        snap['prefix_hits'] = int(bs['prefix_hits'])
        snap['prefix_hit_rate'] = float(bs['prefix_hit_rate'])
        snap['prefix_tokens_reused'] = int(bs['prefix_tokens_reused'])
        snap['block_evictions'] = int(bs['evictions'])
        return snap


class TokenStream(object):
    """Per-request streaming future. Greedy requests: iterate to receive
    tokens as decode steps complete (`for tok in stream: ...`), or call
    `result()` for the full generated id list (eos included when
    emitted). Beam requests: `result()` -> (ids [beam, n_tokens] int64,
    scores [beam] float64), hypotheses sorted best-first; iteration
    yields nothing until completion (beams reorder mid-flight).

    A speculative verify tick can deliver SEVERAL tokens at once; they
    are queued as ONE batch. `__iter__` still yields token-at-a-time
    (order preserved), `batches()` yields one list per delivery event —
    the fleet wire protocol iterates batches so a verify tick costs one
    frame, not K+1."""

    def __init__(self, beam=None):
        self.beam = beam
        self._q = queue.Queue()
        self._fut = Future()
        self._cancelled = False

    # -- consumer side ----------------------------------------------------
    def __iter__(self):
        for batch in self.batches():
            for tok in batch:
                yield tok

    def batches(self):
        """Yield token DELIVERY BATCHES: one list per producer push — a
        plain decode step's singleton, or every token a speculative
        verify tick advanced at once (ISSUE 17)."""
        while True:
            kind, payload = self._q.get()
            if kind == 'tok':
                yield [payload]
            elif kind == 'toks':
                yield payload
            elif kind == 'end':
                return
            else:
                raise payload

    def result(self, timeout=None):
        return self._fut.result(timeout)

    def done(self):
        return self._fut.done()

    def exception(self, timeout=None):
        return self._fut.exception(timeout)

    def cancel(self):
        """Best-effort: the scheduler frees the slot(s) at the next step
        boundary; already-streamed tokens remain delivered."""
        self._cancelled = True

    # -- producer side (scheduler thread) ---------------------------------
    def _push(self, tok):
        self._q.put(('tok', int(tok)))

    def _push_many(self, toks):
        """One queue entry for a whole verify-tick advance: consumers
        see the multi-token delivery as a single batch (ISSUE 17)."""
        self._q.put(('toks', [int(t) for t in toks]))

    def _finish(self, result):
        try:
            self._fut.set_result(result)
        except Exception:
            pass
        self._q.put(('end', None))

    def _fail(self, exc):
        try:
            self._fut.set_exception(exc)
        except Exception:
            pass
        self._q.put(('err', exc))


class NgramDrafter(object):
    """Host-side n-gram / prompt-lookup drafter (ISSUE 17): propose the
    continuation that followed the most recent matching suffix of the
    request's own transcript (prompt + generated tokens). Deterministic,
    no device work, no extra artifact — the CPU-proxy-testable default
    (`DecodingPredictor(draft='ngram')`). Shines on self-repetitive
    text (code, structured output, retrieval-grounded answers); on
    non-repetitive text it simply proposes nothing and the slot rides
    the plain step.

    `max_ngram` is the longest suffix length tried (longest first —
    more context wins ties), `min_ngram` the shortest worth trusting."""

    def __init__(self, max_ngram=3, min_ngram=1):
        if not 1 <= int(min_ngram) <= int(max_ngram):
            raise ValueError('need 1 <= min_ngram <= max_ngram')
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def draft(self, tokens, k):
        """tokens: 1-D int array, full transcript so far. Returns up to
        `k` proposed next tokens (possibly empty)."""
        toks = np.asarray(tokens, np.int64).reshape(-1)
        n = toks.size
        if n < 2 or k < 1:
            return []
        for ng in range(min(self.max_ngram, n - 1),
                        self.min_ngram - 1, -1):
            suffix = toks[n - ng:]
            # vectorized window compare (this runs on the scheduler
            # thread every tick): hit[s] <=> toks[s:s+ng] == suffix,
            # for every window start strictly before the suffix's own
            hit = toks[:n - ng] == suffix[0]
            for j in range(1, ng):
                hit = hit & (toks[j:n - ng + j] == suffix[j])
            starts = np.flatnonzero(hit)
            if starts.size:
                # the MOST RECENT earlier occurrence of the suffix
                # predicts the continuation; past the transcript's end
                # the proposal extends periodically (a transcript in an
                # attractor cycle yields full-k proposals even when the
                # match sits near the end)
                s = int(starts[-1])
                d = (n - ng) - s
                out = []
                for i in range(k):
                    j = n + i - d
                    out.append(int(toks[j]) if j < n else out[i - d])
                return out
        return []


class DraftModelDrafter(object):
    """Draft-model drafter (ISSUE 17): propose continuations by greedy
    decode on a SECOND, smaller decode artifact. Wrap an already-warm
    `DecodingPredictor` (typically a narrower/shallower model with the
    same tokenizer — proposals are fed verbatim to the target's verify
    program, so the vocabularies must agree; out-of-vocab proposals are
    truncated by the scheduler).

    `draft()` runs synchronously on the target's scheduler thread; keep
    the draft artifact small enough that a k-token greedy decode costs
    less than the step it replaces."""

    def __init__(self, predictor):
        if not callable(getattr(predictor, 'generate', None)):
            raise ValueError('DraftModelDrafter wraps a '
                             'DecodingPredictor-like object with '
                             'generate(prompt, max_new_tokens)')
        self._pred = predictor

    def draft(self, tokens, k):
        toks = np.asarray(tokens, np.int64)
        T = getattr(self._pred, '_T', None)
        if T is not None and toks.size >= int(T):
            # keep the most recent window the draft artifact can hold
            toks = toks[toks.size - int(T) + 1:]
        out = self._pred.generate(toks, max_new_tokens=int(k))
        return [int(t) for t in np.asarray(out).reshape(-1)[:k]]


class _Request(object):
    __slots__ = ('prompt', 'max_new', 'beam', 'stream', 't_submit',
                 'deadline', 'slots', 'produced', 'tokens', 'last_tokens',
                 'scores', 'finished', 'hyps', 't_first', 't_last',
                 'tables', 'next_start', 'prefilling', 'match',
                 'match_epoch', 'draft_strikes', 'draft_cooldown',
                 'request_id')

    def __init__(self, prompt, max_new, beam, stream, deadline_ms,
                 request_id=None):
        self.prompt = prompt
        self.request_id = request_id      # caller trace id (gateway)
        self.max_new = max_new
        self.beam = beam                  # None = greedy
        self.stream = stream
        self.t_submit = time.perf_counter()
        self.deadline = (self.t_submit + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.slots = []                   # slot indices, beam order
        self.produced = 0                 # tokens generated so far
        self.tokens = []                  # greedy transcript
        self.last_tokens = []             # per beam: next step's input
        self.scores = []                  # per beam accumulated logprob
        self.finished = []                # per beam: emitted eos
        self.hyps = []                    # per beam token lists
        self.t_first = None
        self.t_last = None
        # block layout (ISSUE 13)
        self.tables = []                  # per beam: logical block ids
        self.next_start = 0               # next chunked-prefill position
        self.prefilling = False           # still admitting via chunks
        self.match = None                 # cached (shared blocks, covered)
        self.match_epoch = -1             # prefix_epoch the match saw
        # speculative decoding (ISSUE 17): acceptance-aware backoff
        self.draft_strikes = 0            # consecutive all-rejected ticks
        self.draft_cooldown = 0           # plain ticks before re-drafting


class _DecodeModule(object):
    """One exported decode program: lazy StableHLO deserialize, AOT
    warm-start sidecar (zero compiles when present), fresh bookkept jit
    fallback — donated state for step/prefill (jax's own donation
    bookkeeping guards the cold path; the sidecar carries certified
    aliasing for the warm path)."""

    def __init__(self, d, donate_state, device=None, aot_tag=None):
        with open(os.path.join(d, _serve._MODULE), 'rb') as f:
            self._module_bytes = f.read()
        self._donate = bool(donate_state)
        self._fn = None
        self._aot = None
        if os.environ.get('PTPU_ARTIFACT_AOT', '1') not in ('0', 'false'):
            # sidecar keyed on the PINNED device's platform (the
            # CompiledPredictor discipline): an explicit platform= must
            # never load an executable baked for the default backend.
            # Sharded artifacts carry a MESH TAG instead (e.g. tpu_mp2):
            # an executable partitioned for one mesh must never load
            # into an unsharded serve or a different mesh shape.
            self._aot = _serve._load_aot(
                os.path.join(d, _serve._AOT_SIDECAR
                             % (aot_tag or _serve._aot_platform(device))),
                _serve._module_sha(self._module_bytes))

    def _jitted(self):
        if self._fn is None:
            import jax
            from jax import export as jexport
            exp = jexport.deserialize(self._module_bytes)
            kw = {'donate_argnums': (0,)} if self._donate else {}
            self._fn = jax.jit(exp.call, **kw)
        return self._fn

    def call(self, *args):
        fn = self._aot if self._aot is not None else self._jitted()
        with warnings.catch_warnings():
            # backends without donation support (XLA:CPU) warn per call;
            # the fallback is a copy, not a correctness issue
            warnings.filterwarnings(
                'ignore', message='Some donated buffers were not usable')
            return fn(*args)


def _precompile_decode_dir(d, state_specs, arg_specs, donate,
                           platform=None, mesh_ctx=None):
    """AOT-compile one decode program for `platform` and write its
    warm-start sidecar. Step/prefill compile WITH donate_argnums=(0,)
    (the paged cache updates in place on warm replicas); the reorder
    program compiles undonated — it doubles as the owned-buffer boundary
    for freshly loaded state. With `mesh_ctx` (a sharded artifact) the
    state specs carry their mesh shardings and the sidecar writes under
    the MESH TAG (aot_<platform>_<axes>.jaxexec)."""
    import jax
    from jax import export as jexport
    with open(os.path.join(d, _serve._MODULE), 'rb') as f:
        module_bytes = f.read()
    exp = jexport.deserialize(module_bytes)
    kw = {'donate_argnums': (0,)} if donate else {}
    if mesh_ctx is not None:
        state_specs = [jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=ns)
                       for s, ns in zip(state_specs,
                                        mesh_ctx['state_ns'])]
        with _serve._fresh_compile():
            compiled = jax.jit(exp.call, **kw).lower(
                state_specs, *arg_specs).compile()
        return _serve._save_aot(
            os.path.join(d, _serve._AOT_SIDECAR % mesh_ctx['tag']),
            compiled, _serve._module_sha(module_bytes))
    plat = platform or _serve._aot_platform()
    dev = jax.devices(plat)[0]
    with jax.default_device(dev), _serve._fresh_compile():
        compiled = jax.jit(exp.call, **kw).lower(
            state_specs, *arg_specs).compile()
    return _serve._save_aot(os.path.join(d, _serve._AOT_SIDECAR % plat),
                            compiled, _serve._module_sha(module_bytes))


def _sig_mesh_ctx(sig, platform=None):
    """Resolve a sharded signature's mesh block into concrete
    NamedShardings for the state list; None for unsharded artifacts.
    An explicit `platform` that contradicts the artifact's recorded
    platform raises — a sharded executable is single-platform."""
    mesh_sig = sig.get('mesh')
    if not mesh_sig:
        return None
    plat = mesh_sig.get('platform')
    if platform and plat and platform != plat:
        raise ValueError(
            'sharded decode artifact was exported for platform %r; '
            'cannot serve/prewarm it on %r' % (plat, platform))
    mesh = _decode_mesh(mesh_sig['axes'], plat)
    rep, state_ns = _state_shardings_ns(
        mesh, mesh_sig.get('state_shardings'),
        [e['name'] for e in sig['state']])
    return {'mesh': mesh, 'rep': rep, 'state_ns': state_ns,
            'tag': mesh_sig['tag'], 'platform': plat}


def precompile_decode_artifact(artifact_dir, platform=None):
    """Prewarm a continuous-decode artifact: AOT-compile the decode-step
    program, EVERY prefill bucket (slot layout) or chunked-prefill size
    plus the block-copy program (block layout), and the reorder program,
    writing warm-start sidecars — a replica that loads the artifact
    afterwards answers with zero traces and zero XLA compiles. Sharded
    artifacts (signature carries a mesh) prewarm over the recorded mesh
    and write MESH-TAGGED sidecars; the host must see the full device
    count. Driven by `tools/cache_ctl.py prewarm`
    (serve.precompile_artifact detects the decode layout). Returns the
    sidecar paths written."""
    import jax
    with open(os.path.join(artifact_dir, _DECODE_SIGNATURE)) as f:
        sig = json.load(f)
    state_specs = [jax.ShapeDtypeStruct(tuple(e['shape']),
                                        np.dtype(e['dtype']))
                   for e in sig['state']]
    mesh_ctx = _sig_mesh_ctx(sig, platform)

    def feed_specs(entries):
        return [jax.ShapeDtypeStruct(tuple(e['shape']), np.dtype(e['dtype']))
                for e in entries]

    def dir_(d, args, donate):
        return _precompile_decode_dir(
            os.path.join(artifact_dir, d), state_specs, args,
            donate=donate, platform=platform, mesh_ctx=mesh_ctx)

    written = [dir_(_STEP_DIR, [feed_specs(sig['step']['feeds'])],
                    donate=True)]
    if sig.get('verify') is not None:
        # speculative artifacts (ISSUE 17): the verify program warm-
        # starts exactly like the step it rides beside
        written.append(dir_(_VERIFY_DIR,
                            [feed_specs(sig['verify']['feeds'])],
                            donate=True))
    if sig.get('layout', 'slot') == 'block':
        for c in sig['chunk_buckets']:
            written.append(dir_(
                _CHUNK_DIR % int(c),
                [feed_specs(sig['chunk'][str(c)]['feeds'])], donate=True))
        pair_spec = jax.ShapeDtypeStruct((int(sig['max_slots']),),
                                         np.int32)
        written.append(dir_(_BLOCKCOPY_DIR, [pair_spec, pair_spec],
                            donate=True))
        reorder_n = int(sig['block']['num_blocks'])
    else:
        for b in sig['prompt_buckets']:
            written.append(dir_(
                _PREFILL_DIR % int(b),
                [feed_specs(sig['prefill'][str(b)]['feeds'])],
                donate=True))
        reorder_n = int(sig['max_slots'])
    src_spec = jax.ShapeDtypeStruct((reorder_n,), np.int32)
    written.append(dir_(_REORDER_DIR, [src_spec], donate=False))
    return written


class DecodingPredictor(object):
    """Token-streaming decode endpoint with continuous in-flight batching
    over an `export_decode` artifact.

    submit(prompt_ids, ...) -> TokenStream   enqueue one decode request
    generate(prompt_ids, ...)                submit + wait (synchronous)
    warmup()                                 compile every program ahead
                                             of traffic (no-op when AOT
                                             sidecars loaded)
    stats.snapshot()                         decode serving metrics (also
                                             via profiler serving_report)
    close()                                  stop the scheduler; waiting
                                             and in-flight requests fail
                                             with RuntimeError

    `prompt_ids`: 1-D int sequence, 1 <= len <= the largest prompt
    bucket. `beam=` runs fixed-width beam search (the request occupies
    `beam` slots); default greedy. Admission is strict FIFO: a beam
    request at the head waits for enough free slots.

    Speculative decoding (ISSUE 17): on an artifact exported with
    `build_decode_spec(draft_k=K)`, pass `draft='ngram'` (host-side
    prompt-lookup NgramDrafter) or any object with a
    `draft(tokens, k) -> proposal list` method (e.g. DraftModelDrafter)
    to serve greedy requests draft-and-verify: transcripts stay
    bit-identical to plain decode, but an accepted draft advances up to
    K+1 tokens in one dispatch. `draft_k=` narrows the per-tick draft
    length below the exported K (the compiled shape is unchanged —
    unused rows ride as masked pads). Beam requests ignore the drafter.
    """

    def __init__(self, artifact_dir, platform=None, max_queue=None,
                 default_max_new_tokens=32, stats_window=8192,
                 tier=None, draft=None, draft_k=None):
        import jax
        # tier resolution (ISSUE 12 satellite): `tier='int8'` serves a
        # quantized decode tier exported under <artifact>/int8/ — the
        # BatchingPredictor(tier=) contract: an EXPLICIT missing tier
        # raises, the env preference (PTPU_SERVE_TIER) degrades to the
        # top level silently
        artifact_dir = _serve.resolve_tier(artifact_dir, tier,
                                           signature=_DECODE_SIGNATURE)
        with open(os.path.join(artifact_dir, _DECODE_SIGNATURE)) as f:
            self._sig = json.load(f)
        self._S = int(self._sig['max_slots'])
        self._T = int(self._sig['max_cache_len'])
        self._eos = int(self._sig['eos_id'])
        self._vocab = int(self._sig['vocab'])
        self._layout = self._sig.get('layout', 'slot')
        self._default_max_new = int(default_max_new_tokens)
        self._max_queue = int(max_queue) if max_queue else None
        platform = platform or os.environ.get('PTPU_PLATFORM')
        # sharded artifact (ISSUE 13): rebuild the export mesh; state
        # places per the recorded shardings, programs load through the
        # mesh-tagged AOT sidecars, feeds/fetches stay replicated
        self._mesh_ctx = _sig_mesh_ctx(self._sig, platform)
        aot_tag = None
        if self._mesh_ctx is not None:
            self._device = None     # state placement IS the mesh
            aot_tag = self._mesh_ctx['tag']
        else:
            self._device = jax.devices(platform)[0] if platform else None
        self._step_mod = _DecodeModule(
            os.path.join(artifact_dir, _STEP_DIR), donate_state=True,
            device=self._device, aot_tag=aot_tag)
        self._reorder_mod = _DecodeModule(
            os.path.join(artifact_dir, _REORDER_DIR), donate_state=False,
            device=self._device, aot_tag=aot_tag)
        self._step_feeds = [e['name'] for e in self._sig['step']['feeds']]
        # speculative decoding (ISSUE 17): load the verify program when
        # the artifact carries one; attach a drafter only on request
        self._verify_mod = None
        self._drafter = None
        self._draft_k = 0
        vsig = self._sig.get('verify')
        if vsig is not None:
            self._verify_mod = _DecodeModule(
                os.path.join(artifact_dir, _VERIFY_DIR),
                donate_state=True, device=self._device, aot_tag=aot_tag)
            self._verify_feeds = [e['name'] for e in vsig['feeds']]
            self._K = int(vsig['draft_k'])
        if draft is not None:
            if vsig is None:
                raise ValueError(
                    "draft= needs an artifact exported with a verify "
                    "program (build_decode_spec(draft_k=K)); this "
                    "artifact carries none")
            self._drafter = NgramDrafter() if draft == 'ngram' else draft
            if not callable(getattr(self._drafter, 'draft', None)):
                raise ValueError(
                    "draft= must be 'ngram' or an object with a "
                    "draft(tokens, k) method")
            self._draft_k = self._K
            if draft_k is not None:
                if not 1 <= int(draft_k) <= self._K:
                    raise ValueError(
                        'draft_k must be in [1, %d] (the exported '
                        'verify width)' % self._K)
                self._draft_k = int(draft_k)
        if self._layout == 'block':
            blk = self._sig['block']
            self._bs = int(blk['block_size'])
            self._nb = int(blk['num_blocks'])
            self._maxb = int(blk['max_blocks_per_slot'])
            self._trash = TRASH_BLOCK
            # the block allocator itself is built (and wired into
            # stats.block_source) by _reset_state — the single owner
            # chunked prefill: prompts admit in fixed slices, so the
            # prompt ceiling is the CACHE length, not a prefill bucket
            self._chunks = sorted(int(c) for c in
                                  self._sig['chunk_buckets'])
            self._max_prompt = self._T
            self._chunk_mods = {
                c: _DecodeModule(
                    os.path.join(artifact_dir, _CHUNK_DIR % c),
                    donate_state=True, device=self._device,
                    aot_tag=aot_tag)
                for c in self._chunks}
            self._chunk_feeds = {
                c: [e['name'] for e in self._sig['chunk'][str(c)]['feeds']]
                for c in self._chunks}
            self._blockcopy_mod = _DecodeModule(
                os.path.join(artifact_dir, _BLOCKCOPY_DIR),
                donate_state=True, device=self._device, aot_tag=aot_tag)
            self._buckets = list(self._chunks)
        else:
            # sorted once at load: select_bucket prefers the smallest
            # fitting bucket deterministically (batching.py discipline)
            self._buckets = sorted(int(b)
                                   for b in self._sig['prompt_buckets'])
            self._max_prompt = self._buckets[-1]
            self._prefill_mods = {
                b: _DecodeModule(
                    os.path.join(artifact_dir, _PREFILL_DIR % b),
                    donate_state=True, device=self._device,
                    aot_tag=aot_tag)
                for b in self._buckets}
            self._prefill_feeds = {
                b: [e['name']
                    for e in self._sig['prefill'][str(b)]['feeds']]
                for b in self._buckets}
        self._state = None
        self._slots = [None] * self._S    # slot -> (request, beam index)
        self._closed = False
        self._draining = False
        self._idle_evt = threading.Event()
        self._lifecycle = threading.Lock()
        self._queue = queue.Queue()
        self.stats = DecodeStats(stats_window)
        # int8 paged-KV artifacts serve through the same scheduler; the
        # tier rides the stats into serving_report's tier column
        self.stats.tier = ('int8' if self._sig.get('kv_cache_dtype')
                           == 'int8' else 'bf16')
        self._reset_state()
        self._sched_t = threading.Thread(
            target=self._sched_loop, name='ptpu-decode-sched', daemon=True)
        self._sched_t.start()
        self._profiler_name = None
        prof = _maybe_profiler()
        if prof is not None and hasattr(prof, 'register_serving_source'):
            name = 'decode:%s#%d' % (
                os.path.basename(os.path.normpath(artifact_dir)),
                next(_SOURCE_SEQ))
            prof.register_serving_source(name, self.stats.snapshot)
            self._profiler_name = name

    # -- public API --------------------------------------------------------
    @property
    def max_slots(self):
        return self._S

    @property
    def prompt_buckets(self):
        return list(self._buckets)

    @property
    def layout(self):
        """'slot' (contiguous rows, bucketed prefill) or 'block'
        (block-paged cache, chunked prefill — ISSUE 13)."""
        return self._layout

    @property
    def mesh_tag(self):
        """Mesh tag of a sharded artifact (e.g. 'tpu_mp2'); None for
        single-chip artifacts."""
        return self._mesh_ctx['tag'] if self._mesh_ctx is not None \
            else None

    @property
    def block_manager(self):
        """The live BlockManager of a block-layout artifact (None on
        slot artifacts): stats()/peak accounting for tooling, and
        evict_all_prefixes() for an explicit prefix-cache clear."""
        return self._blocks if self._layout == 'block' else None

    def submit(self, prompt_ids, max_new_tokens=None, beam=None,
               deadline_ms=None, request_id=None):
        """Enqueue one decode request; returns a TokenStream. Validation
        errors fail THIS stream only. With `deadline_ms`, a request still
        queued — or still DECODING — when the deadline elapses resolves
        to DeadlineExceeded at the next step boundary and frees its
        slot(s). Beyond `max_queue` waiting requests, new submissions
        shed with ServerOverloaded before any device work. `request_id`
        is an optional caller trace id, named in every shed/expiry
        message and surfaced in stats `recent_failures`."""
        if self._closed:
            raise RuntimeError('DecodingPredictor is closed')
        beam = int(beam) if beam else None
        stream = TokenStream(beam=beam)
        rid_sfx = (' (request %s)' % request_id) if request_id else ''
        if self._draining:
            # draining for scale-in: stop admitting; shed loudly (the
            # request never cost device work — a fleet router re-routes)
            with self.stats._lock:
                self.stats.shed += 1
                self.stats.drained += 1
            self.stats.record_failure(request_id, 'drained')
            stream._fail(ServerOverloaded(
                'request shed: endpoint draining for scale-in%s'
                % rid_sfx))
            return stream

        def _shed_locked():
            return _batching.shed_if_overloaded(
                self.stats, self._max_queue, stream._fail,
                request_id=request_id)

        with self.stats._lock:          # fast-fail before validation work
            if _shed_locked():
                return stream
        try:
            prompt = np.asarray(prompt_ids, np.int64).reshape(-1).copy()
            if not prompt.size:
                raise ValueError('empty prompt')
            if prompt.size > self._max_prompt:
                raise ValueError(
                    'prompt of %d tokens exceeds %s' % (
                        prompt.size,
                        'max_cache_len %d (chunked prefill admits up to '
                        'the cache length)' % self._max_prompt
                        if self._layout == 'block' else
                        'the largest compiled prompt bucket %d'
                        % self._max_prompt))
            max_new = int(max_new_tokens if max_new_tokens is not None
                          else self._default_max_new)
            # cache capacity: the last generated token writes position
            # len(prompt) + max_new - 2
            max_new = max(1, min(max_new, self._T - prompt.size + 1))
            if beam is not None and not 1 <= beam <= self._S:
                raise ValueError(
                    'beam width %d not in [1, max_slots=%d]'
                    % (beam, self._S))
        except Exception as e:
            stream._fail(e)
            return stream
        req = _Request(prompt, max_new, beam, stream, deadline_ms,
                       request_id=request_id)
        with self._lifecycle:
            if self._closed:
                raise RuntimeError('DecodingPredictor is closed')
            if self._draining:
                with self.stats._lock:
                    self.stats.shed += 1
                    self.stats.drained += 1
                self.stats.record_failure(request_id, 'drained')
                stream._fail(ServerOverloaded(
                    'request shed: endpoint draining for scale-in%s'
                    % rid_sfx))
                return stream
            with self.stats._lock:
                if _shed_locked():      # re-check atomically with enqueue
                    return stream
                self.stats.queue_depth += 1
            self._queue.put(req)
        return stream

    def generate(self, prompt_ids, max_new_tokens=None, beam=None,
                 deadline_ms=None, timeout=None):
        """Synchronous single-request decode: submit + wait."""
        return self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                           beam=beam, deadline_ms=deadline_ms
                           ).result(timeout)

    def warmup(self):
        """Compile every program ahead of traffic (a no-op dispatch per
        prefill bucket, one decode step, one all-pad verify tick on
        speculative artifacts, one reorder); state is re-zeroed
        afterwards. With AOT sidecars loaded this costs a handful of
        dispatches and zero compiles. Must run BEFORE any submit(): it dispatches on
        the scheduler's donated state from this thread, so it refuses
        loudly once traffic has started."""
        if self.stats.queue_depth or any(s is not None
                                         for s in self._slots):
            raise RuntimeError(
                'warmup() must run before traffic: requests are queued or '
                'decoding, and a caller-thread dispatch would race the '
                "scheduler over the donated cache state")
        if self._layout == 'block':
            trash_tables = np.full((self._S, self._maxb), self._trash,
                                   np.int32)
            for c in self._chunks:
                self._dispatch_chunk(c, np.zeros((1, c), np.int64), 0, 1,
                                     trash_tables[:1])
            self._dispatch_step(np.zeros((self._S, 1), np.int64),
                                np.zeros((self._S, 1), np.int32),
                                tables=trash_tables)
            self._dispatch_blockcopy([])      # identity (trash-to-trash)
        else:
            for b in self._buckets:
                self._dispatch_prefill(b, np.zeros((1, b), np.int64), 1, 0)
            self._dispatch_step(np.zeros((self._S, 1), np.int64),
                                np.zeros((self._S, 1), np.int32))
        if self._verify_mod is not None:
            # all-pad verify dispatch (ISSUE 17): every row at the pad
            # position, so the scatter drops (slot) / routes to the
            # trash block (block) and the dispatch is pure compile-warm
            R = self._K + 1
            pad = (self._maxb * self._bs if self._layout == 'block'
                   else self._T)
            self._dispatch_verify(
                np.zeros((self._S, R), np.int64),
                np.full((self._S, R), pad, np.int32),
                tables=(np.full((self._S, self._maxb), self._trash,
                                np.int32)
                        if self._layout == 'block' else None))
        self._reset_state()
        self.stats.reset()   # warmup dispatches must not count as traffic
        return self

    def drain(self, timeout=None):
        """Draining stop for scale-in (the fleet router's hook): stop
        admitting — new submissions shed ServerOverloaded (counted in
        shed+drained; never dispatched, so a router can re-route them)
        and WAITING queued requests shed the same way — while every
        ACTIVE stream finishes decoding to completion (zero dropped
        in-flight streams). Blocks until the last active slot frees (or
        `timeout`); returns True when fully drained. The endpoint stays
        open for stats/close(); it admits nothing afterwards."""
        with self._lifecycle:
            if self._closed:
                return True
            self._draining = True
            self._idle_evt.clear()
            self._queue.put(_WAKE)  # rouse an idle scheduler
        return self._idle_evt.wait(timeout)

    def close(self):
        """Stop the scheduler thread. Waiting and in-flight requests
        resolve with RuntimeError. Idempotent; submit() afterwards
        raises. Also finalizes an endpoint that already closed ITSELF
        after an unrecoverable dispatch failure (joins the scheduler,
        unregisters the profiler source)."""
        with self._lifecycle:
            if not self._closed:
                self._closed = True
                self._queue.put(_STOP)
        self._idle_evt.set()   # never strand a drain() waiter
        if threading.current_thread() is not self._sched_t:
            self._sched_t.join()
        name, self._profiler_name = self._profiler_name, None
        if name:
            prof = _maybe_profiler()
            if prof is not None:
                prof.unregister_serving_source(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- device plumbing ---------------------------------------------------
    def _dev_ctx(self):
        import jax
        import contextlib
        return (jax.default_device(self._device)
                if self._device is not None else contextlib.nullcontext())

    def _feed(self, a):
        """Host feed -> device arg. Sharded artifacts: every feed places
        REPLICATED over the mesh explicitly — a numpy arg next to
        mesh-sharded state would otherwise commit to one device and fail
        the multi-device dispatch."""
        if self._mesh_ctx is None:
            return a
        import jax
        return jax.device_put(a, self._mesh_ctx['rep'])

    def _reset_state(self):
        """(Re)zero the paged KV cache. The zeros route through the
        UNDONATED reorder program so every leaf handed to the donated
        step/prefill executables is an XLA-owned buffer (a reloaded
        donating executable honors its baked-in aliasing without jax's
        external-buffer guard — round-8/10 cliff). Sharded artifacts
        place each state leaf per its recorded mesh sharding; block
        artifacts also rebuild the block allocator (every table is dead
        by the time this runs)."""
        import jax
        zeros = [np.zeros(tuple(e['shape']), np.dtype(e['dtype']))
                 for e in self._sig['state']]
        n = (self._nb if self._layout == 'block' else self._S)
        src = np.arange(n, dtype=np.int32)
        with self._dev_ctx():
            if self._mesh_ctx is not None:
                state = [jax.device_put(z, ns) for z, ns in
                         zip(zeros, self._mesh_ctx['state_ns'])]
            else:
                state = [jax.device_put(z, self._device) for z in zeros]
            self._state = list(self._reorder_mod.call(state,
                                                      self._feed(src)))
        if self._layout == 'block':
            self._blocks = BlockManager(self._nb, self._bs)
            # block-cache gauges + prefix-share accounting merge into
            # stats.snapshot() (serving_report's block columns)
            self.stats.block_source = self._blocks.stats
            self.stats.block_reset = self._blocks.reset_counters

    def _dispatch_step(self, tokens, pos, tables=None):
        feed = {'tokens': tokens, 'pos': pos}
        if tables is not None:
            feed['block_tables'] = tables
        args = [self._feed(feed[n])
                for n in self._step_feeds]  # signature feed order
        with self._dev_ctx():
            fetches, new_state = self._step_mod.call(self._state, args)
        self._state = list(new_state)
        with self.stats._lock:
            self.stats.steps += 1
        return np.asarray(fetches[0])                      # [S, V] sync

    def _dispatch_verify(self, tokens, pos, tables=None):
        """One speculative verify dispatch (ISSUE 17): tokens/pos are
        [S, K+1] (row 0 the slot's pending last token, rows 1..k its
        draft; pad rows/slots at the layout's pad position), logits come
        back [S, K+1, V]. KV for all fed positions is written inside the
        program; acceptance and rollback happen host-side after."""
        feed = {'tokens': tokens, 'pos': pos}
        if tables is not None:
            feed['block_tables'] = tables
        args = [self._feed(feed[n]) for n in self._verify_feeds]
        with self._dev_ctx():
            fetches, new_state = self._verify_mod.call(self._state, args)
        self._state = list(new_state)
        with self.stats._lock:
            self.stats.verify_steps += 1
        return np.asarray(fetches[0])                   # [S, K+1, V] sync

    def _dispatch_prefill(self, bucket, padded, plen, slot):
        feed = {'prompt_ids': padded,
                'prompt_len': np.full((1, 1), plen, np.int32),
                'slot': np.full((1, 1), slot, np.int32)}
        args = [self._feed(feed[n]) for n in self._prefill_feeds[bucket]]
        with self._dev_ctx():
            fetches, new_state = self._prefill_mods[bucket].call(
                self._state, args)
        self._state = list(new_state)
        with self.stats._lock:
            self.stats.prefills += 1
        return np.asarray(fetches[0])[0]                   # [V] sync

    def _dispatch_chunk(self, size, ids, start, take, table_row):
        """One chunked-prefill slice: `take` real rows of one prompt at
        absolute positions start..start+take-1 (the rest of the `size`
        rows are pad) write through `table_row` [1, max_blocks]."""
        feed = {'chunk_ids': ids,
                'start': np.full((1, 1), start, np.int32),
                'chunk_len': np.full((1, 1), take, np.int32),
                'block_table': np.asarray(table_row, np.int32)}
        args = [self._feed(feed[n]) for n in self._chunk_feeds[size]]
        with self._dev_ctx():
            fetches, new_state = self._chunk_mods[size].call(
                self._state, args)
        self._state = list(new_state)
        with self.stats._lock:
            self.stats.prefills += 1
            self.stats.chunk_slices += 1
        return np.asarray(fetches[0])[0]                   # [V] sync

    def _dispatch_blockcopy(self, pairs):
        """One block-copy dispatch: every (dst, src) PHYSICAL-BLOCK pair
        copies pool-wide (all layers' K/V (+scale) vars). Unused pairs
        pad with (trash, trash) — a self-copy of the write-only trash
        block. This is the CoW device half: dispatch bytes scale with
        len(pairs) x block bytes, not with slot rows."""
        dst = np.full((self._S,), self._trash, np.int32)
        src = np.full((self._S,), self._trash, np.int32)
        for i, (d, s) in enumerate(pairs):
            dst[i] = d
            src[i] = s
        with self._dev_ctx():
            new_state = self._blockcopy_mod.call(
                self._state, self._feed(dst), self._feed(src))
        self._state = list(new_state)
        with self.stats._lock:
            self.stats.blockcopies += 1
            self.stats.cow_blocks += len(pairs)

    def _dispatch_reorder(self, src):
        with self._dev_ctx():
            self._state = list(self._reorder_mod.call(
                self._state, self._feed(np.asarray(src, np.int32))))
        with self.stats._lock:
            self.stats.reorders += 1

    # -- scheduler ---------------------------------------------------------
    def _active_requests(self):
        seen = []
        for entry in self._slots:
            if entry is not None and entry[0] not in seen:
                seen.append(entry[0])
        return seen

    def _free_slots(self):
        return [i for i, s in enumerate(self._slots) if s is None]

    def _release(self, req):
        for s in req.slots:
            self._slots[s] = None
        if self._layout == 'block':
            # refcount-to-zero blocks return to the pool; blocks a
            # prefix entry (or another request) still references live on
            for t in req.tables:
                self._blocks.decref(t)
            req.tables = []
            self._drop_match(req)

    def _drop_match(self, req):
        """Release a waiting request's cached prefix-match refs (held
        from the first admission attempt so the matched blocks cannot
        evict while the request waits at the head of the queue)."""
        if req.match is not None:
            self._blocks.decref(req.match[0])
            req.match = None

    def _table_row(self, table):
        """One slot's block-table row, padded to max_blocks_per_slot
        with the trash block (pad rows are never read: attention masks
        j <= pos and pos never reaches the pad span)."""
        row = np.full((1, self._maxb), self._trash, np.int32)
        row[0, :len(table)] = table
        return row

    def _sched_loop(self):
        waiting = deque()
        while True:
            have_work = waiting or any(s is not None for s in self._slots)
            try:
                item = self._queue.get(block=not have_work)
            except queue.Empty:
                item = None
            if item is _STOP:
                self._drain_on_close(waiting)
                return
            if item is _WAKE:
                item = None
            if item is not None:
                waiting.append(item)
                continue  # keep draining submissions before dispatching
            t0 = time.perf_counter()
            if self._draining:
                # scale-in drain: shed the waiting queue loudly (safe to
                # re-route — never dispatched); active streams keep
                # stepping to completion below
                self._shed_waiting(waiting)
            self._expire(waiting)
            if not self._draining:
                if self._layout == 'block':
                    self._admit_block(waiting)
                else:
                    self._admit(waiting)
            if any(s is not None for s in self._slots):
                try:
                    if self._layout == 'block':
                        # one prefill slice per admitting request, then
                        # one step for the running batch: a long prompt
                        # interleaves instead of stalling every stream
                        self._prefill_tick()
                        if any(e is not None and not e[0].prefilling
                               for e in self._slots):
                            self._step_block(waiting)
                    else:
                        self._step()
                except Exception as e:
                    self._fail_all(e, waiting)
                with self.stats._lock:
                    self.stats.busy_s += time.perf_counter() - t0
            if self._draining and not waiting \
                    and not any(s is not None for s in self._slots):
                self._idle_evt.set()

    def _shed_waiting(self, waiting):
        """drain() in progress: fail every WAITING request with
        ServerOverloaded (shed+drained counters) — they never reached a
        slot, so a fleet router can re-route them."""
        while waiting:
            req = waiting.popleft()
            self._drop_match(req)
            with self.stats._lock:
                self.stats.queue_depth -= 1
                self.stats.shed += 1
                self.stats.drained += 1
            self.stats.record_failure(req.request_id, 'drained')
            req.stream._fail(ServerOverloaded(
                'request shed: endpoint draining for scale-in%s'
                % (' (request %s)' % req.request_id
                   if req.request_id else '')))

    def _drain_on_close(self, waiting):
        err = RuntimeError('DecodingPredictor closed')
        for req in self._active_requests():
            self._release(req)
            req.stream._fail(err)
        for req in waiting:
            self._drop_match(req)
            with self.stats._lock:
                self.stats.queue_depth -= 1
            req.stream._fail(err)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is not _STOP:
                with self.stats._lock:
                    self.stats.queue_depth -= 1
                req.stream._fail(err)

    def _expire(self, waiting):
        now = time.perf_counter()
        # waiting requests: reap expired/cancelled before they cost work
        alive = deque()
        for req in waiting:
            cancelled = req.stream._cancelled
            if cancelled or (req.deadline is not None
                             and now > req.deadline):
                self._drop_match(req)
                with self.stats._lock:
                    self.stats.queue_depth -= 1
                    if not cancelled:
                        self.stats.expired += 1
                if cancelled:
                    req.stream._fail(RuntimeError('request cancelled'))
                else:
                    self.stats.record_failure(req.request_id, 'expired')
                    req.stream._fail(DeadlineExceeded(
                        'request expired after %.1f ms in queue%s'
                        % ((now - req.t_submit) * 1e3,
                           ' (request %s)' % req.request_id
                           if req.request_id else '')))
            else:
                alive.append(req)
        waiting.clear()
        waiting.extend(alive)
        # ACTIVE requests: deadline expiry mid-decode frees the slot(s)
        # at this step boundary (the satellite contract)
        for req in self._active_requests():
            if req.stream._cancelled or (req.deadline is not None
                                         and now > req.deadline):
                self._release(req)
                if req.stream._cancelled:
                    req.stream._fail(RuntimeError('request cancelled'))
                else:
                    with self.stats._lock:
                        self.stats.expired += 1
                    self.stats.record_failure(req.request_id, 'expired')
                    req.stream._fail(DeadlineExceeded(
                        'deadline elapsed mid-decode after %d token(s); '
                        'slot freed%s'
                        % (req.produced,
                           ' (request %s)' % req.request_id
                           if req.request_id else '')))

    def _admit(self, waiting):
        """Strict-FIFO admission at the step boundary: one prefill
        dispatch per admitted request; beam requests wait for enough
        free slots."""
        while waiting:
            req = waiting[0]
            need = req.beam or 1
            free = self._free_slots()
            if len(free) < need:
                return
            waiting.popleft()
            with self.stats._lock:
                self.stats.queue_depth -= 1
            req.slots = free[:need]
            try:
                self._prefill(req)
            except Exception as e:
                # the donated prefill dispatch may have consumed the
                # state even though it raised: this is the same hazard
                # as a step failure, so recover the same way (fail the
                # co-resident requests loudly, rebuild zero state)
                self._release(req)
                req.stream._fail(e)
                self._fail_all(e, waiting)
                return

    def _prefill(self, req):
        plen = int(req.prompt.size)
        bucket = select_bucket(self._buckets, plen)
        padded = np.zeros((1, bucket), np.int64)
        padded[0, :plen] = req.prompt
        logits = self._dispatch_prefill(bucket, padded, plen, req.slots[0])
        for i, s in enumerate(req.slots):
            self._slots[s] = (req, i)
        self._first_token(req, logits)

    def _first_token(self, req, logits):
        """Emit a request's first token from its prompt logits: greedy
        argmax, or the top-W DISTINCT tokens seeding a beam group (the
        standard first-expansion; a naive W*V step over identical beams
        would collapse onto one token). Beam history fan-out: the slot
        layout replicates slot 0's cache rows through the reorder
        program; the block layout FORKS the prompt's block table — a
        host-side copy + incref, zero device work."""
        now = time.perf_counter()
        if req.beam is None:
            tok = int(np.argmax(logits))
            req.last_tokens = [tok]
            req.tokens = [tok]
            req.produced = 1
            self._record_emit(req, now)
            req.stream._push(tok)
            if tok == self._eos or req.produced >= req.max_new:
                self._finish_greedy(req)
            return
        if len(req.slots) > 1:
            if self._layout == 'block':
                base = req.tables[0]
                req.tables = [base] + [list(base)
                                       for _ in req.slots[1:]]
                for t in req.tables[1:]:
                    self._blocks.incref(t)
            else:
                src = np.arange(self._S, dtype=np.int32)
                for s in req.slots[1:]:
                    src[s] = req.slots[0]
                self._dispatch_reorder(src)
        lp = _log_softmax(logits)
        order = np.argsort(-lp, kind='stable')[:req.beam]
        req.last_tokens = [int(t) for t in order]
        req.scores = [float(lp[t]) for t in order]
        req.finished = [int(t) == self._eos for t in order]
        req.hyps = [[int(t)] for t in order]
        req.produced = 1
        self._record_emit(req, now, count=req.beam)
        if all(req.finished) or req.produced >= req.max_new:
            self._finish_beam(req)

    # -- block-layout scheduling (ISSUE 13) --------------------------------
    def _admit_block(self, waiting):
        """Strict-FIFO block-layout admission: a request admits when a
        slot group AND blocks for its whole prompt span are available.
        A prefix-cache hit maps the shared blocks into the table and
        skips allocating (and later prefilling) the covered span; the
        match is cached on the request across attempts, so its refs pin
        the matched blocks against eviction while the request waits at
        the head of the queue."""
        while waiting:
            req = waiting[0]
            need = req.beam or 1
            free = self._free_slots()
            if len(free) < need:
                return
            plen = int(req.prompt.size)
            if req.match is None or (not req.match[0] and
                                     req.match_epoch
                                     != self._blocks.prefix_epoch):
                # a cached HIT's refs pin the matched blocks across
                # attempts; a cached MISS holds no refs, so re-match —
                # but only when a prefix was PUBLISHED since the last
                # attempt (e.g. by the in-flight request ahead of us):
                # the epoch gate keeps a slow-to-admit request from
                # re-hashing its prompt (and counting a fresh miss)
                # every scheduler tick
                req.match_epoch = self._blocks.prefix_epoch
                req.match = self._blocks.match_prefix(req.prompt)
            shared, covered = req.match
            try:
                fresh = self._blocks.alloc(
                    self._blocks.blocks_for(plen) - len(shared))
            except BlockPoolExhausted:
                if self._active_requests():
                    return   # head-of-line waits for blocks to free
                # nothing running will ever free blocks: this prompt can
                # never fit — shed loudly instead of deadlocking
                waiting.popleft()
                self._drop_match(req)
                with self.stats._lock:
                    self.stats.queue_depth -= 1
                    self.stats.shed += 1
                req.stream._fail(ServerOverloaded(
                    'KV block pool exhausted: prompt of %d token(s) '
                    'needs more blocks than the pool can free'
                    % plen))
                continue
            waiting.popleft()
            req.match = None        # refs transferred into the table
            with self.stats._lock:
                self.stats.queue_depth -= 1
            req.tables = [list(shared) + list(fresh)]
            req.next_start = int(covered)
            req.prefilling = True
            req.slots = free[:need]
            for i, s in enumerate(req.slots):
                self._slots[s] = (req, i)

    def _prefill_tick(self):
        """One chunked-prefill slice per ADMITTING request: the
        uncovered prompt span (a prefix hit skips the covered span's
        compute AND storage) admits in fixed-size slices, one per
        scheduler iteration, interleaved with the running batch's decode
        steps — a max-length prompt no longer stalls every stream's
        inter-token latency for its whole prefill."""
        for req in self._active_requests():
            if not req.prefilling:
                continue
            plen = int(req.prompt.size)
            remaining = plen - req.next_start
            size = select_bucket(self._chunks,
                                 min(remaining, self._chunks[-1]))
            take = min(size, remaining)
            ids = np.zeros((1, size), np.int64)
            ids[0, :take] = req.prompt[req.next_start:
                                       req.next_start + take]
            logits = self._dispatch_chunk(size, ids, req.next_start,
                                          take,
                                          self._table_row(req.tables[0]))
            req.next_start += take
            if req.next_start < plen:
                continue
            req.prefilling = False
            # publish the prompt's FULL blocks for prefix reuse (the
            # partial tail stays private: decode writes land there)
            self._blocks.register_prefix(req.prompt, req.tables[0])
            self._first_token(req, logits)

    def _live_rows(self, skip=()):
        """(request, beam index, write position) for every slot that
        writes this step: decoding requests' unfinished beams. Finished
        beams idle (trash row) — their frozen candidate needs no cache
        writes, and skipping them avoids spurious CoW/extension.
        Requests in `skip` (this tick's drafted set — they advance via
        the verify dispatch instead) are excluded."""
        rows = []
        for req in self._active_requests():
            if req.prefilling or req in skip:
                continue
            for bi in range(len(req.slots)):
                if req.beam is not None and req.finished[bi]:
                    continue
                p = int(req.prompt.size) + req.produced - 1
                rows.append((req, bi, p))
        return rows

    def _preflight_blocks(self, waiting=(), rows_fn=None):
        """Reserve this step's exact fresh-block demand (one per block
        that must extend or copy-on-write across each row's write SPAN)
        BEFORE building the dispatch. Pressure resolves in severity
        order: first un-pin WAITING requests' cached prefix matches
        (their refs can make prefix entries non-evictable; a queued
        request simply re-matches at its next admission attempt), only
        then shed the YOUNGEST decoding request — never kill an
        in-flight stream for a pin a queued request can re-acquire.
        All-or-nothing, so row building never unwinds a half-planned
        step. `rows_fn` yields (req, bi, p, span) rows — the default is
        this step's live rows with span 1; the speculative verify tick
        passes its drafted rows with span draft+1 (ISSUE 17). It is a
        CALLABLE because shedding a victim must drop its rows from the
        re-count."""
        if rows_fn is None:
            rows_fn = lambda: [(r, b, p, 1)
                               for r, b, p in self._live_rows()]
        while True:
            need = 0
            shared = {}
            for req, bi, p, span in rows_fn():
                table = req.tables[bi]
                for lblk in range(p // self._bs,
                                  (p + span - 1) // self._bs + 1):
                    if lblk >= len(table):
                        need += 1        # extension: always a fresh block
                    elif not self._blocks.writable(table[lblk]):
                        b = table[lblk]
                        shared[b] = shared.get(b, 0) + 1
            for b, k in shared.items():
                # k rows CoW the same block in table order; each CoW
                # decrefs it, so the LAST sharer writes in place when no
                # reference beyond this step's k tables remains
                need += k if self._blocks.refcount(b) > k else k - 1
            if self._blocks.reserve(need):
                return
            dropped = False
            for req in waiting:
                if req.match is not None and req.match[0]:
                    self._drop_match(req)
                    dropped = True
            if dropped:
                continue     # pins released: entries may evict now
            victims = [r for r in self._active_requests()
                       if not r.prefilling]
            if not victims:
                return
            victim = max(victims, key=lambda r: r.t_submit)
            self._release(victim)
            with self.stats._lock:
                self.stats.shed += 1
            victim.stream._fail(MidStreamEvicted(
                'evicted under KV block-pool pressure after %d '
                'token(s): pool fully pinned by older requests'
                % victim.produced))

    def _ensure_writable(self, req, bi, p, cow):
        """Make the block backing logical position p of beam `bi`
        exclusively owned before the step writes it: extend the table
        when p enters a new block, copy-on-write when the block is
        shared (beam fork or prefix sharing) — the diverged BLOCK is
        the unit of copy, not the slot row."""
        table = req.tables[bi]
        lblk = p // self._bs
        while len(table) <= lblk:
            table.extend(self._blocks.alloc(1))
        b = table[lblk]
        if not self._blocks.writable(b):
            nb = self._blocks.alloc(1)[0]
            cow.append((nb, b))
            self._blocks.decref([b])
            table[lblk] = nb

    def _step_block(self, waiting):
        """One iteration of the continuous batch over the block pool:
        CoW copies dispatch first (one block-copy for ALL diverged
        blocks), then every live slot advances one token through the
        fixed-shape step; beam reorder afterwards is pure block-table
        permutation (incref/decref, zero device work until the next
        write diverges a shared tail block). With a drafter attached,
        slots holding drafts ride ONE verify dispatch first (ISSUE 17)
        and the plain step below covers only the undrafted remainder —
        a fully-drafted batch skips the plain dispatch entirely."""
        drafted = self._collect_drafts()
        if drafted:
            self._verify_block(drafted, waiting)
        tokens = np.zeros((self._S, 1), np.int64)
        pos = np.zeros((self._S, 1), np.int32)
        tables = np.full((self._S, self._maxb), self._trash, np.int32)
        self._preflight_blocks(
            waiting,
            rows_fn=lambda: [(r, b, p, 1) for r, b, p
                             in self._live_rows(skip=drafted)])
        cow = []
        active = 0
        for req, bi, p in self._live_rows(skip=drafted):
            self._ensure_writable(req, bi, p, cow)
            s = req.slots[bi]
            active += 1
            tokens[s, 0] = req.last_tokens[bi]
            pos[s, 0] = p
            table = req.tables[bi]
            tables[s, :len(table)] = table
        if not active:
            return   # every live stream drafted (or shed): no plain step
        with self.stats._lock:
            self.stats.active_slot_steps += active
            self.stats.slot_steps += self._S
        if cow:
            self._dispatch_blockcopy(cow)
        logits = self._dispatch_step(tokens, pos, tables=tables)
        now = time.perf_counter()
        for req in self._active_requests():
            if req.prefilling or req in drafted:
                continue
            if req.beam is None:
                self._advance_greedy(req, logits, now)
                continue
            # shared beam scoring; the history move is the block
            # layout's own — table permutation instead of a slot-row
            # gather
            parents = self._score_beam(req, logits)
            if any(int(p) != i for i, p in enumerate(parents)):
                old = req.tables
                new = [list(old[int(p)]) for p in parents]
                for t in new:
                    self._blocks.incref(t)
                for t in old:
                    self._blocks.decref(t)
                req.tables = new
                with self.stats._lock:
                    self.stats.reorders += 1
            req.produced += 1
            self._record_emit(req, now, count=req.beam)
            if all(req.finished) or req.produced >= req.max_new:
                self._finish_beam(req)

    def _advance_greedy(self, req, logits, now):
        """Shared slot/block greedy advance: emit the argmax token,
        finish on eos/max_new."""
        tok = int(np.argmax(logits[req.slots[0]]))
        req.last_tokens[0] = tok
        req.tokens.append(tok)
        req.produced += 1
        self._record_emit(req, now)
        req.stream._push(tok)
        if tok == self._eos or req.produced >= req.max_new:
            self._finish_greedy(req)

    # -- speculative decoding (ISSUE 17) -----------------------------------
    def _collect_drafts(self):
        """Host-side draft collection at the tick boundary: every
        greedy, fully-prefilled request asks the drafter for up to
        min(draft_k, remaining max_new budget - 1, cache headroom)
        proposal tokens. Returns {request: draft token list}. Empty or
        failed drafts simply ride the plain step — a broken drafter can
        cost speed, never correctness or the serving loop."""
        if self._drafter is None:
            return {}
        drafted = {}
        for req in self._active_requests():
            if req.beam is not None or req.prefilling:
                continue
            if req.draft_cooldown > 0:
                # acceptance-aware backoff: a request whose drafts keep
                # getting fully rejected rides plain steps for
                # exponentially longer stretches, so a hostile context
                # (or drafter) costs ~log(max_new) verify ticks total
                # instead of one per tick
                req.draft_cooldown -= 1
                continue
            p = int(req.prompt.size) + req.produced - 1
            # verify rows write positions p..p+k: k is bounded by the
            # cache (p + k <= T-1) and by the emission budget (a draft
            # of k can emit k+1 tokens, so k <= max_new - produced - 1;
            # the final token always comes from a plain step or the
            # verify bonus row)
            k_max = min(self._draft_k, req.max_new - req.produced - 1,
                        self._T - 1 - p)
            if k_max < 1:
                continue
            ctx = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int64)])
            try:
                d = self._drafter.draft(ctx, k_max)
            except Exception:
                d = None
            if d is None or len(d) == 0:
                continue
            toks = []
            for t in list(d)[:k_max]:
                t = int(t)
                if not 0 <= t < self._vocab:
                    break   # an out-of-vocab proposal cannot be fed
                toks.append(t)
            if toks:
                drafted[req] = toks
        return drafted

    def _advance_spec(self, req, draft, row_logits, now):
        """Longest-prefix acceptance against the target argmax: row i
        of `row_logits` [K+1, V] was computed with rows < i's tokens in
        context, so its logits equal the plain step's EXACTLY while the
        draft prefix matches. Emitting greedily row by row until the
        draft diverges (the diverging row still contributes its
        CORRECTED token; full acceptance adds the K+1'th bonus token),
        or eos / max_new truncates, reproduces the plain greedy
        transcript bit-for-bit. Returns the emitted token list."""
        k = len(draft)
        emitted = []
        for i in range(k + 1):
            g = int(np.argmax(row_logits[i]))
            emitted.append(g)
            if g == self._eos \
                    or req.produced + len(emitted) >= req.max_new:
                break   # transcript truncates exactly as plain decode
            if i == k or draft[i] != g:
                break   # row i+1 was fed a token != true continuation
        accepted = sum(1 for i in range(min(len(emitted), k))
                       if draft[i] == emitted[i])
        if accepted == 0:
            req.draft_strikes += 1
            req.draft_cooldown = 1 << min(req.draft_strikes, 6)
        else:
            req.draft_strikes = 0
        req.last_tokens[0] = emitted[-1]
        req.tokens.extend(emitted)
        req.produced += len(emitted)
        with self.stats._lock:
            self.stats.drafted += k
            self.stats.accepted += accepted
        self._record_emit(req, now, count=len(emitted), events=1)
        req.stream._push_many(emitted)
        if emitted[-1] == self._eos or req.produced >= req.max_new:
            self._finish_greedy(req)
        return emitted

    def _verify_slot(self, drafted):
        """Verify tick, slot layout: ONE [S, K+1] dispatch scores every
        drafted slot's pending token + draft. Undrafted rows ride at
        pos = max_cache_len — the cache scatter DROPS out-of-bounds
        rows, so they neither write nor perturb anyone. Rejected
        speculative rows land strictly above the accepted frontier
        (req.produced rolls the next write position back), where the
        write-before-attend program order overwrites them before any
        mask admits them."""
        R = self._K + 1
        tokens = np.zeros((self._S, R), np.int64)
        pos = np.full((self._S, R), self._T, np.int32)
        live = self._active_requests()
        rows = [(req, d) for req, d in drafted.items() if req in live]
        if not rows:
            return
        for req, draft in rows:
            s = req.slots[0]
            p = int(req.prompt.size) + req.produced - 1
            k = len(draft)
            tokens[s, 0] = req.last_tokens[0]
            tokens[s, 1:1 + k] = draft
            pos[s, :k + 1] = p + np.arange(k + 1, dtype=np.int32)
        with self.stats._lock:
            self.stats.active_slot_steps += len(rows)
            self.stats.slot_steps += self._S
        logits = self._dispatch_verify(tokens, pos)
        now = time.perf_counter()
        for req, draft in rows:
            self._advance_spec(req, draft, logits[req.slots[0]], now)

    def _verify_block(self, drafted, waiting):
        """Verify tick, block layout: preflight/extend/CoW every block
        in each drafted slot's speculative span, dispatch ONE verify
        program (undrafted rows ride as all-pad trash-table rows), then
        ROLL each table BACK to the accepted frontier — blocks covering
        only rejected speculative positions free immediately, and the
        trimmed table re-extends on demand next tick."""
        R = self._K + 1
        pad_pos = self._maxb * self._bs

        def rows_fn():
            live = self._active_requests()
            return [(req, 0,
                     int(req.prompt.size) + req.produced - 1,
                     len(d) + 1)
                    for req, d in drafted.items() if req in live]

        self._preflight_blocks(waiting, rows_fn=rows_fn)
        rows = rows_fn()
        if not rows:
            return   # preflight shed every drafted stream
        cow = []
        tokens = np.zeros((self._S, R), np.int64)
        pos = np.full((self._S, R), pad_pos, np.int32)
        tables = np.full((self._S, self._maxb), self._trash, np.int32)
        for req, bi, p, span in rows:
            for q in range(p, p + span):
                self._ensure_writable(req, bi, q, cow)
            draft = drafted[req]
            s = req.slots[0]
            k = len(draft)
            tokens[s, 0] = req.last_tokens[0]
            tokens[s, 1:1 + k] = draft
            pos[s, :k + 1] = p + np.arange(k + 1, dtype=np.int32)
            table = req.tables[0]
            tables[s, :len(table)] = table
        with self.stats._lock:
            self.stats.active_slot_steps += len(rows)
            self.stats.slot_steps += self._S
        # a speculative span can CoW/extend more blocks than one
        # blockcopy dispatch's S pairs: chunk
        for i in range(0, len(cow), self._S):
            self._dispatch_blockcopy(cow[i:i + self._S])
        logits = self._dispatch_verify(tokens, pos, tables=tables)
        now = time.perf_counter()
        for req, bi, p, span in rows:
            s = req.slots[0]
            self._advance_spec(req, drafted[req], logits[s], now)
            if self._slots[s] is not None and self._slots[s][0] is req:
                # still decoding: positions 0..plen+produced-2 hold real
                # KV (the newest emitted token writes NEXT tick); drop
                # the wholly-speculative tail blocks
                self._blocks.rollback(
                    req.tables[0],
                    int(req.prompt.size) + req.produced - 1)

    def _score_beam(self, req, logits):
        """Fixed-width beam candidate scoring (finished beams
        contribute one frozen eos candidate — ops/decode_ops.py
        beam_search discipline): updates scores/hyps/finished/
        last_tokens and returns `parents` for the layout's own history
        move (slot-row gather vs block-table permutation). ONE copy, so
        the two layouts can never drift out of the bit-identity the
        cross-tier tests and rollout 'bit' promotion depend on."""
        W, V = req.beam, self._vocab
        cand = np.full((W, V), -np.inf, np.float64)
        for i in range(W):
            if req.finished[i]:
                cand[i, self._eos] = req.scores[i]
            else:
                cand[i] = req.scores[i] + _log_softmax(
                    logits[req.slots[i]])
        order = np.argsort(-cand, axis=None, kind='stable')[:W]
        parents = order // V
        toks = order % V
        req.scores = [float(cand[p, t]) for p, t in zip(parents, toks)]
        req.hyps = [req.hyps[p] + [int(t)]
                    for p, t in zip(parents, toks)]
        req.finished = [req.finished[p] or int(t) == self._eos
                        for p, t in zip(parents, toks)]
        req.last_tokens = [int(t) for t in toks]
        return parents

    def _record_emit(self, req, now, count=1, events=None):
        with self.stats._lock:
            self.stats.tokens += count
            # advance accounting (ISSUE 17): `events` defaults to
            # `count` (greedy step / beam step / prefill first token
            # all deliver count tokens over count per-row advances), so
            # plain serving meters tokens_per_dispatch exactly 1.0; a
            # verify tick passes events=1 for its multi-token advance
            self.stats.adv_tokens += count
            self.stats.adv_events += (count if events is None
                                      else events)
            if req.t_first is None:
                req.t_first = now
                self.stats._ttft.append(now - req.t_submit)
            else:
                self.stats._itl.append(now - req.t_last)
        req.t_last = now

    def _finish_greedy(self, req):
        self._release(req)
        with self.stats._lock:
            self.stats.requests += 1
        req.stream._finish(list(req.tokens))

    def _finish_beam(self, req):
        self._release(req)
        with self.stats._lock:
            self.stats.requests += 1
        ids = np.asarray(req.hyps, np.int64)
        scores = np.asarray(req.scores, np.float64)
        req.stream._finish((ids, scores))

    def _step(self):
        """One iteration of the continuous batch: every active slot
        advances one token through ONE fixed-shape dispatch. With a
        drafter attached, slots holding drafts ride ONE verify dispatch
        first (ISSUE 17); in the plain step they idle at the TOP cache
        position — always strictly above an active slot's frontier, so
        the garbage row is overwritten by a real write before any
        attention mask admits it — and a fully-drafted batch skips the
        plain dispatch entirely."""
        drafted = self._collect_drafts()
        if drafted:
            self._verify_slot(drafted)
        tokens = np.zeros((self._S, 1), np.int64)
        pos = np.zeros((self._S, 1), np.int32)
        active = 0
        for s, entry in enumerate(self._slots):
            if entry is None:
                continue
            req, bi = entry
            if req in drafted:
                pos[s, 0] = self._T - 1   # advanced via verify this tick
                continue
            active += 1
            tokens[s, 0] = req.last_tokens[bi]
            # this token writes at position len(prompt) + produced - 1
            pos[s, 0] = req.prompt.size + req.produced - 1
        if not active:
            return   # every live stream drafted: no plain step
        with self.stats._lock:
            self.stats.active_slot_steps += active
            self.stats.slot_steps += self._S
        logits = self._dispatch_step(tokens, pos)
        now = time.perf_counter()
        src = np.arange(self._S, dtype=np.int32)
        for req in self._active_requests():
            if req in drafted:
                continue
            if req.beam is None:
                self._advance_greedy(req, logits, now)
                continue
            # shared beam scoring; the history move is the slot
            # layout's own — a slot-row gather
            parents = self._score_beam(req, logits)
            for i in range(req.beam):
                src[req.slots[i]] = req.slots[parents[i]]
            req.produced += 1
            self._record_emit(req, now, count=req.beam)
            if all(req.finished) or req.produced >= req.max_new:
                self._finish_beam(req)
                for s in req.slots:   # a finished group never reorders
                    src[s] = s
        if not np.array_equal(src, np.arange(self._S, dtype=np.int32)):
            # one slot-gather for every surviving beam group: each beam's
            # cache follows its parent before the next step writes
            self._dispatch_reorder(src)

    def _fail_all(self, exc, waiting=()):
        """A dispatch failure mid-step may have consumed the donated
        state: fail every in-flight request loudly and rebuild a clean
        zero state so the endpoint keeps serving. If even the rebuild
        dispatch fails (wedged backend), the endpoint closes itself —
        queued and future requests fail fast instead of hanging on a
        dead scheduler."""
        for req in self._active_requests():
            self._release(req)
            req.stream._fail(exc)
        for req in waiting:
            # cached prefix matches hold block ids of the manager the
            # rebuild below discards: a stale HIT would map dead blocks
            # (zeroed, re-allocatable) into a fresh table — drop them
            # so the next admission attempt re-matches the new pool
            req.match = None
            req.match_epoch = -1
        try:
            self._reset_state()
        except Exception as e:
            warnings.warn(
                'DecodingPredictor: state rebuild after a dispatch '
                'failure itself failed (%s: %s) — closing the endpoint'
                % (type(e).__name__, e), RuntimeWarning)
            # runs ON the scheduler thread: close() skips the self-join
            # and unregisters the profiler source; the loop drains the
            # queued requests when it sees _STOP
            self.close()


def load_decoding(artifact_dir, **kwargs):
    return DecodingPredictor(artifact_dir, **kwargs)
