"""Reference artifact formats: `__model__` + SerializeToStream params.

Byte layouts (re-derived from the reference sources, clean-room):
- tensor stream (framework/tensor_util.cc:372 TensorToStream):
    u32 version(0); i32 desc_size; TensorDesc proto; raw data bytes.
- LoDTensor stream (framework/lod_tensor.cc:245 SerializeToStream):
    u32 version(0); u64 lod_level; per level: u64 nbytes + raw u64 offsets;
    then the tensor stream.
- `__model__`: serialized ProgramDesc (framework/framework.proto:184);
  save_inference_model writes it with params in separate files named by
  var (io.py:570) or one combined file (save_combine).

Loading builds a native paddle_tpu Program (ops keep their reference
attrs; lowerings consume them directly), so reference-trained models run
on TPU unchanged; saving emits artifacts the reference can load.
"""
from __future__ import annotations

import os
import struct

import numpy as np

from . import proto
from ..framework import Program
from ..core.lod import LoDArray

_VERSION = struct.pack('<I', 0)


# -- tensors -----------------------------------------------------------------
def write_tensor_stream(f, array, lod=None, with_lod=True):
    array = np.ascontiguousarray(array)
    if with_lod:
        # LoDTensor framing is always present (SerializeToStream writes
        # lod_level 0 for plain tensors)
        f.write(_VERSION)
        lod = lod or []
        f.write(struct.pack('<Q', len(lod)))
        for level in lod:
            level = np.asarray(level, np.uint64)
            f.write(struct.pack('<Q', level.nbytes))
            f.write(level.tobytes())
    f.write(_VERSION)
    desc = proto.encode_tensor_desc(str(array.dtype), list(array.shape))
    db = desc.tobytes()
    f.write(struct.pack('<i', len(db)))
    f.write(db)
    f.write(array.tobytes())


def read_tensor_stream(f, has_lod=True):
    """Returns (np array, lod list) — lod [] for plain tensors."""
    ver = struct.unpack('<I', f.read(4))[0]
    if ver != 0:
        raise ValueError("unsupported tensor version %d" % ver)
    lod = []
    if has_lod:
        (lod_level,) = struct.unpack('<Q', f.read(8))
        for _ in range(lod_level):
            (nbytes,) = struct.unpack('<Q', f.read(8))
            lod.append(np.frombuffer(f.read(nbytes), np.uint64)
                       .astype(np.int64))
        ver = struct.unpack('<I', f.read(4))[0]
        if ver != 0:
            raise ValueError("unsupported tensor version %d" % ver)
    (desc_size,) = struct.unpack('<i', f.read(4))
    dtype, dims = proto.parse_tensor_desc(f.read(desc_size))
    count = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(f.read(count * np.dtype(dtype).itemsize),
                        dtype).reshape(dims)
    return arr, lod


def load_reference_var(path):
    with open(path, 'rb') as f:
        return read_tensor_stream(f, has_lod=True)


# -- programs ----------------------------------------------------------------
def program_from_desc_bytes(buf):
    """Reference ProgramDesc bytes -> native Program."""
    from ..framework import Block, Operator, Variable, Parameter
    blocks = proto.parse_program_desc(buf)
    p = Program()
    p.blocks = []
    for bd in blocks:
        b = Block(p, bd['idx'], bd['parent_idx'])
        p.blocks.append(b)
    for bd, b in zip(blocks, p.blocks):
        for vd in bd['vars']:
            t = vd['type']
            b.vars[vd['name']] = Variable(
                b, vd['name'], shape=t.get('shape'),
                dtype=t.get('dtype') or 'float32',
                lod_level=t.get('lod_level', 0),
                persistable=vd['persistable'],
                type=proto.TYPE_STR.get(t.get('type'), 'lod_tensor'))
        for od in bd['ops']:
            b.ops.append(Operator(b, od['type'], od['inputs'],
                                  od['outputs'], od['attrs']))
    p._op_uid_counter = sum(len(b.ops) for b in p.blocks)
    return p


def program_to_desc_bytes(program):
    """Native Program -> reference ProgramDesc bytes."""
    blocks = []
    for b in program.blocks:
        vars_enc = []
        for name, v in b.vars.items():
            vtype = {'lod_tensor': proto.VT_LOD_TENSOR,
                     'selected_rows': proto.VT_SELECTED_ROWS,
                     'tensor_array': proto.VT_TENSOR_ARRAY,
                     'reader': proto.VT_READER,
                     'raw': proto.VT_RAW}.get(v.type, proto.VT_LOD_TENSOR)
            vars_enc.append(proto.encode_var_desc(
                name, v.dtype, v.shape, v.lod_level, v.persistable, vtype))
        ops_enc = [proto.encode_op_desc(op.type, op.inputs, op.outputs,
                                        op.attrs) for op in b.ops]
        blocks.append({'idx': b.idx, 'parent_idx': b.parent_idx
                       if b.parent_idx is not None else -1,
                       'vars': vars_enc, 'ops': ops_enc})
    return proto.encode_program(blocks)


# -- inference model dirs ----------------------------------------------------
def _feed_fetch_from_program(program):
    feeds, fetches = [], []
    for op in program.global_block().ops:
        if op.type == 'feed':
            feeds.append((int(op.attrs.get('col', 0)),
                          op.outputs['Out'][0]))
        elif op.type == 'fetch':
            fetches.append((int(op.attrs.get('col', 0)),
                            op.inputs['X'][0]))
    # block order of prepended feed ops is reversed; 'col' is authoritative
    return ([n for _, n in sorted(feeds)],
            [n for _, n in sorted(fetches)])


def load_reference_inference_model(dirname, executor=None,
                                   model_filename=None,
                                   params_filename=None, scope=None):
    """Load a reference save_inference_model directory (ref io.py:704).
    Returns (program, feed_names, fetch_vars)."""
    from ..core.scope import global_scope
    import jax.numpy as jnp
    model_path = os.path.join(dirname, model_filename or '__model__')
    with open(model_path, 'rb') as f:
        program = program_from_desc_bytes(f.read())
    scope = scope or global_scope()
    persistables = [v for v in program.list_vars()
                    if v.persistable and v.type == 'lod_tensor']
    if params_filename:
        with open(os.path.join(dirname, params_filename), 'rb') as f:
            # save_combine order = sorted var names (ref io.py:570)
            for v in sorted(persistables, key=lambda v: v.name):
                arr, lod = read_tensor_stream(f)
                scope.set(v.name, jnp.asarray(arr) if not lod
                          else LoDArray(jnp.asarray(arr), lod))
    else:
        for v in persistables:
            path = os.path.join(dirname, v.name)
            if not os.path.exists(path):
                continue
            arr, lod = load_reference_var(path)
            scope.set(v.name, jnp.asarray(arr) if not lod
                      else LoDArray(jnp.asarray(arr), lod))
    feed_names, fetch_names = _feed_fetch_from_program(program)
    program._feed_names = list(feed_names)
    program._fetch_names = list(fetch_names)
    fetch_vars = [program.global_block()._find_var_recursive(n)
                  for n in fetch_names]
    return program, feed_names, fetch_vars


def save_reference_inference_model(dirname, feeded_var_names, target_vars,
                                   executor, main_program=None,
                                   model_filename=None,
                                   params_filename=None, scope=None):
    """Write a reference-format inference dir from a native program
    (ref io.py:570 save_inference_model)."""
    from ..framework import default_main_program
    from ..io import prune_program
    from ..core.scope import global_scope
    program = main_program or default_main_program()
    scope = scope or global_scope()
    target_names = [v.name if not isinstance(v, str) else v
                    for v in target_vars]
    pruned = prune_program(program, feeded_var_names, target_names)
    # append reference-style feed/fetch ops so the roundtrip is faithful
    block = pruned.global_block()
    have_feeds = {op.outputs['Out'][0] for op in block.ops
                  if op.type == 'feed'}
    for i, n in enumerate(feeded_var_names):
        if n not in have_feeds:
            block.prepend_op(type='feed', inputs={},
                             outputs={'Out': [n]}, attrs={'col': i},
                             infer_shape=False)
    if not any(op.type == 'fetch' for op in block.ops):
        for i, n in enumerate(target_names):
            block.append_op(type='fetch', inputs={'X': [n]},
                            outputs={'Out': ['fetch']},
                            attrs={'col': i}, infer_shape=False)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, model_filename or '__model__'),
              'wb') as f:
        f.write(program_to_desc_bytes(pruned))
    persistables = sorted(
        {v.name for v in pruned.list_vars() if v.persistable})
    if params_filename:
        with open(os.path.join(dirname, params_filename), 'wb') as f:
            for name in persistables:
                val = scope.get(name)
                if val is None:
                    # the combined stream is positional: a silent skip would
                    # shift every later var's bytes onto the wrong weight
                    raise ValueError(
                        "persistable %r has no value in the scope; run the "
                        "startup program (or load a checkpoint) before "
                        "saving a combined-params model" % name)
                arr, lod = _split(val)
                write_tensor_stream(f, arr, lod)
    else:
        for name in persistables:
            val = scope.get(name)
            if val is None:
                continue
            arr, lod = _split(val)
            with open(os.path.join(dirname, name), 'wb') as f:
                write_tensor_stream(f, arr, lod)
    return pruned


def load_reference_persistables(dirname, program, scope=None):
    """Load per-var reference checkpoint files into the scope."""
    from ..core.scope import global_scope
    import jax.numpy as jnp
    scope = scope or global_scope()
    n = 0
    for v in program.list_vars():
        if not v.persistable:
            continue
        path = os.path.join(dirname, v.name)
        if os.path.exists(path):
            arr, lod = load_reference_var(path)
            scope.set(v.name, jnp.asarray(arr) if not lod
                      else LoDArray(jnp.asarray(arr), lod))
            n += 1
    return n


def _split(val):
    if isinstance(val, LoDArray):
        return np.asarray(val.data), [np.asarray(l) for l in val.lod]
    return np.asarray(val), None
