"""HTTP serving gateway (ISSUE 19 tentpole): the network front door
over the replica fleet.

`Gateway` fronts a `fleet.FleetRouter` — or any single predictor that
honors the existing submit/`drain()` contract (`DecodingPredictor`,
`BatchingPredictor`) — with a stdlib-only threaded HTTP/1.1 server:

1. **JSON + base64-npz codec** — request bodies are JSON; arrays ride
   as a base64-encoded npz blob (numpy's own validated binary format,
   `allow_pickle=False`, the fleet wire discipline) under the `npz`
   key, with the `<name>` / `<name>.lodN` convention for LoD feeds.
   Decode prompts may be a plain JSON int list instead.
2. **SSE token streaming** — `POST /v1/decode` with `stream` (greedy
   only) answers `text/event-stream`: one `data: {"toks": [...]}`
   event per DELIVERY BATCH (a speculative verify tick's coalesced
   multi-token advance — ISSUE 17 — stays one event), then an
   `event: done` carrying the full transcript. The stream rides the
   existing `TokenStream.batches()` / fleet `on_token` frames.
3. **Multi-tenant admission control** — per-tenant API keys
   (`X-API-Key` / `Authorization: Bearer`), token-bucket rate limiting
   (429 + Retry-After), and per-tenant `max_inflight` quotas, all
   applied at the door BEFORE the backend sees the request. Backend
   shedding maps onto HTTP statuses, never a silent drop:
   `ServerOverloaded`/`FleetUnavailable` -> 503 + Retry-After,
   `DeadlineExceeded` -> 504, `ReplicaFailed` -> 502, validation ->
   400. A failure after the SSE headers are out arrives as an
   `event: error` frame with the same code.
4. **Deadline propagation** — a request's `deadline_ms` budget starts
   at HTTP accept: the gateway sheds at its own door when the budget
   is already gone, and passes the REMAINING budget to the backend so
   router-queue and mid-decode expiry (the existing semantics) share
   one clock.
5. **Request ids** — every request gets a `request_id` (client
   `X-Request-Id` wins), echoed in the response header, threaded
   through the fleet frame headers into replica stats, and surfaced in
   every error message end to end.
6. **Observability** — `/healthz`, `/stats.json` (gateway + backend
   snapshot), `/metrics` (Prometheus text exposition: per-tenant
   request/shed/rate-limit counters, TTFB/TTFT percentiles, plus the
   existing fleet/serving/decode counters flattened). The snapshot
   also registers with `profiler.register_gateway_source` so
   `gateway_report()` renders next to the serving tables.
7. **Graceful drain** — `drain()` stops admitting (new data requests
   answer 503 'gateway draining'), finishes every in-flight request /
   stream, then stops the listener; the `serve.py gateway` CLI wires
   SIGTERM to exactly this and exits 0.

Framework-free: stdlib + numpy + the sibling serving modules only; a
gateway process never imports jax — the replicas do the serving.
"""
import base64
import io
import itertools
import json
import os
import queue as _queue_mod
import sys
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

try:
    from . import serve as _serve
    from . import batching as _batching
    from . import decoding as _decoding
    from . import fleet as _fleet
except ImportError:  # imported by file path: siblings sit alongside
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve as _serve
    import batching as _batching
    import decoding as _decoding
    import fleet as _fleet

_maybe_profiler = _serve._maybe_profiler
_SOURCE_SEQ = _serve._SOURCE_SEQ
_percentiles = _decoding._percentiles
ServerOverloaded = _batching.ServerOverloaded
DeadlineExceeded = _batching.DeadlineExceeded
ReplicaFailed = _fleet.ReplicaFailed
FleetUnavailable = _fleet.FleetUnavailable

_MAX_BODY = 1 << 28          # request-body sanity bound (protocol, not data)
_STREAM_RESULT_TIMEOUT = 600.0


def status_for(exc):
    """The HTTP status one backend error maps to — the gateway's whole
    error-code contract in one place (never a silent drop)."""
    if isinstance(exc, DeadlineExceeded):
        return 504
    if isinstance(exc, (ReplicaFailed,)):
        return 502
    if isinstance(exc, (ServerOverloaded, FleetUnavailable)):
        return 503
    if isinstance(exc, (ValueError, TypeError, KeyError)):
        return 400
    if isinstance(exc, TimeoutError):
        return 504
    return 500


_CATEGORY = {429: 'rate_limited', 503: 'shed', 504: 'expired',
             502: 'failed', 500: 'failed'}


def _category(code):
    """Counter bucket for a response code: ok / bad (4xx client) /
    rate_limited / shed / expired / failed."""
    if code < 300:
        return 'ok'
    return _CATEGORY.get(code, 'bad')


def encode_arrays(arrays):
    """{name: array} -> base64 npz string (the response/request codec)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    return base64.b64encode(buf.getvalue()).decode('ascii')


def decode_arrays(b64):
    """base64 npz string -> {name: array}; pickle stays off (the fleet
    wire discipline — a gateway must never unpickle client bytes)."""
    raw = base64.b64decode(b64, validate=True)
    with np.load(io.BytesIO(raw), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def _feeds_from_arrays(arrays):
    """Flat '<name>' + '<name>.lodN' arrays -> the submit() convention:
    {name: array} with LoD feeds as (data, [offsets...]) pairs."""
    feeds, lods = {}, {}
    for k, v in arrays.items():
        if '.lod' in k:
            name, idx = k.rsplit('.lod', 1)
            lods.setdefault(name, {})[int(idx)] = np.asarray(v, np.int32)
        else:
            feeds[k] = v
    for name, offs in lods.items():
        if name not in feeds:
            raise ValueError('lod offsets for unknown feed %r' % name)
        feeds[name] = (feeds[name],
                       [offs[i] for i in sorted(offs)])
    return feeds


class TenantConfig(object):
    """One tenant's admission policy. `rate` is a req/s token-bucket
    refill (None = unlimited) with `burst` capacity; `max_inflight`
    bounds the tenant's concurrently-admitted requests (None =
    unlimited); `admin` grants the control endpoints (/admin/*)."""

    def __init__(self, name, rate=None, burst=None, max_inflight=None,
                 admin=False):
        self.name = name
        self.rate = float(rate) if rate else None
        self.burst = float(burst if burst is not None
                           else max(1.0, self.rate or 1.0))
        self.max_inflight = (int(max_inflight)
                             if max_inflight is not None else None)
        self.admin = bool(admin)
        # token bucket (guarded by the gateway stats lock)
        self.tokens = self.burst
        self.t_refill = time.perf_counter()

    def acquire(self):
        """(admitted, retry_after_s). Caller holds the gateway lock."""
        if self.rate is None:
            return True, 0.0
        now = time.perf_counter()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t_refill) * self.rate)
        self.t_refill = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self.tokens) / self.rate


def tenants_from_json(path_or_dict):
    """{api_key: {tenant, rate, burst, max_inflight, admin}} (a path to
    a JSON file, or the dict itself) -> {api_key: TenantConfig}."""
    cfg = path_or_dict
    if isinstance(cfg, str):
        with open(cfg) as f:
            cfg = json.load(f)
    out = {}
    for key, spec in cfg.items():
        spec = dict(spec or {})
        out[key] = TenantConfig(
            spec.get('tenant') or spec.get('name') or key,
            rate=spec.get('rate'), burst=spec.get('burst'),
            max_inflight=spec.get('max_inflight'),
            admin=spec.get('admin', False))
    return out


class GatewayStats(object):
    """Thread-safe gateway counters: per-tenant request outcomes by
    code, inflight gauges, and TTFB/TTFT sliding windows. `snapshot()`
    is the profiler gateway-source contract (kind='gateway')."""

    _CATS = ('ok', 'bad', 'rate_limited', 'quota', 'shed', 'expired',
             'failed')

    def __init__(self, window=8192):
        self._lock = threading.Lock()
        self._ttfb = deque(maxlen=window)
        self._ttft = deque(maxlen=window)
        self.tenants = {}        # name -> {requests, codes, <cats>...}
        self.inflight = 0
        self.streams = 0         # SSE streams served to completion
        self.disconnects = 0     # client gone mid-response
        self.draining = False

    def _tenant(self, name):
        t = self.tenants.get(name)
        if t is None:
            t = {'requests': 0, 'inflight': 0,
                 'codes': {}}
            t.update({c: 0 for c in self._CATS})
            self.tenants[name] = t
        return t

    def record(self, tenant, code, category=None, ttfb_s=None,
               ttft_s=None):
        """One resolved request: every admitted-or-rejected request
        lands here exactly once — the zero-silent-drops ledger."""
        with self._lock:
            t = self._tenant(tenant)
            t['requests'] += 1
            t['codes'][str(code)] = t['codes'].get(str(code), 0) + 1
            cat = category or _category(code)
            t[cat] = t.get(cat, 0) + 1
            if ttfb_s is not None:
                self._ttfb.append(ttfb_s)
            if ttft_s is not None:
                self._ttft.append(ttft_s)

    def snapshot(self):
        with self._lock:
            b50, b99 = _percentiles(list(self._ttfb), [50, 99])
            t50, t99 = _percentiles(list(self._ttft), [50, 99])
            tenants = {name: dict(t, codes=dict(t['codes']))
                       for name, t in self.tenants.items()}
            totals = {c: sum(t[c] for t in tenants.values())
                      for c in self._CATS}
            return dict(totals,
                        kind='gateway',
                        requests=sum(t['requests']
                                     for t in tenants.values()),
                        inflight=int(self.inflight),
                        streams=int(self.streams),
                        disconnects=int(self.disconnects),
                        draining=bool(self.draining),
                        ttfb_p50_ms=b50, ttfb_p99_ms=b99,
                        ttft_p50_ms=t50, ttft_p99_ms=t99,
                        tenants=tenants)


# -- Prometheus text exposition ----------------------------------------------

def _prom_escape(v):
    return str(v).replace('\\', r'\\').replace('"', r'\"').replace(
        '\n', r'\n')


def _prom_line(lines, name, value, labels=None):
    lab = ''
    if labels:
        lab = '{%s}' % ','.join('%s="%s"' % (k, _prom_escape(v))
                                for k, v in sorted(labels.items()))
    lines.append('%s%s %s' % (name, lab, repr(float(value))))


def _prom_scalars(lines, prefix, snap, labels=None, _seen=None):
    """Flatten one snapshot dict's numeric scalars into metric lines
    (nested dicts/lists are rendered by the callers that know their
    shape; bools count as 0/1)."""
    for key in sorted(snap):
        v = snap[key]
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, (int, float)) and not isinstance(v, complex):
            _prom_line(lines, '%s_%s' % (prefix, key), v, labels)


def render_metrics(gateway_snap, backend_snap=None):
    """Prometheus text exposition (version 0.0.4) for the gateway
    counters plus the backend (fleet/serving/decode) snapshot."""
    lines = []
    g = gateway_snap
    lines.append('# HELP ptpu_gateway_requests_total Requests resolved '
                 'per tenant and HTTP status code.')
    lines.append('# TYPE ptpu_gateway_requests_total counter')
    for tenant, t in sorted(g.get('tenants', {}).items()):
        for code, n in sorted(t['codes'].items()):
            _prom_line(lines, 'ptpu_gateway_requests_total', n,
                       {'tenant': tenant, 'code': code})
    for cat, help_ in (('rate_limited', 'Requests answered 429 by the '
                        'token bucket.'),
                       ('quota', 'Requests rejected on the per-tenant '
                        'max_inflight quota.'),
                       ('shed', 'Requests shed with 503.'),
                       ('expired', 'Requests expired with 504.'),
                       ('failed', 'Requests failed with 502/500.')):
        lines.append('# HELP ptpu_gateway_%s_total %s' % (cat, help_))
        lines.append('# TYPE ptpu_gateway_%s_total counter' % cat)
        for tenant, t in sorted(g.get('tenants', {}).items()):
            _prom_line(lines, 'ptpu_gateway_%s_total' % cat,
                       t.get(cat, 0), {'tenant': tenant})
    lines.append('# HELP ptpu_gateway_inflight Requests currently '
                 'admitted and unresolved.')
    lines.append('# TYPE ptpu_gateway_inflight gauge')
    _prom_line(lines, 'ptpu_gateway_inflight', g.get('inflight', 0))
    lines.append('# TYPE ptpu_gateway_draining gauge')
    _prom_line(lines, 'ptpu_gateway_draining',
               1 if g.get('draining') else 0)
    for met, desc in (('ttfb_ms', 'Time to first response byte'),
                      ('ttft_ms', 'Time to first streamed token')):
        lines.append('# HELP ptpu_gateway_%s %s (sliding window).'
                     % (met, desc))
        lines.append('# TYPE ptpu_gateway_%s summary' % met)
        for q, key in (('0.5', '%s_p50_ms'), ('0.99', '%s_p99_ms')):
            _prom_line(lines, 'ptpu_gateway_%s' % met,
                       g.get(key % met[:4], 0.0), {'quantile': q})
    if backend_snap:
        kind = backend_snap.get('kind', 'backend')
        prefix = 'ptpu_%s' % kind
        lines.append('# HELP %s_info Backend serving counters '
                     '(profiler snapshot contract).' % prefix)
        lines.append('# TYPE %s_info gauge' % prefix)
        _prom_scalars(lines, prefix, backend_snap)
        for rid, rep in sorted(backend_snap.get('replicas',
                                                {}).items()):
            _prom_scalars(lines, '%s_replica' % prefix,
                          {k: v for k, v in rep.items()
                           if k != 'stats'},
                          {'replica': str(rid)})
    return '\n'.join(lines) + '\n'


# -- backend adapters --------------------------------------------------------

class _Backend(object):
    """Uniform view over FleetRouter / DecodingPredictor /
    BatchingPredictor: kind, snapshot, healthy, and the two dispatch
    shapes (request/response + streamed decode)."""

    def __init__(self, target):
        self.target = target
        if isinstance(target, _fleet.FleetRouter):
            self.flavor = 'fleet'
            self.kind = target.kind
        elif isinstance(target, _decoding.DecodingPredictor):
            self.flavor, self.kind = 'direct', 'decoding'
        elif isinstance(target, _batching.BatchingPredictor):
            self.flavor, self.kind = 'direct', 'batching'
        else:
            raise TypeError(
                'gateway backend must be a FleetRouter, '
                'DecodingPredictor or BatchingPredictor, got %r'
                % type(target).__name__)

    def snapshot(self):
        if self.flavor == 'fleet':
            return self.target.fleet_snapshot()
        return self.target.stats.snapshot()

    def healthy(self):
        if self.flavor == 'fleet':
            try:
                return self.target.status()['serving'] >= 1
            except Exception:
                return False
        return not getattr(self.target, '_closed', False)

    def infer(self, feeds, deadline_ms, request_id):
        """Dense request/response; returns (outputs list, lod levels)."""
        if self.kind == 'decoding':
            raise ValueError(
                'this gateway serves a decode artifact — POST '
                '/v1/decode (request %s)' % request_id)
        fut = self.target.submit(feeds, deadline_ms=deadline_ms,
                                 request_id=request_id)
        outs = fut.result(_STREAM_RESULT_TIMEOUT)
        lod = [len(o[1]) if isinstance(o, tuple) else 0 for o in outs]
        return outs, lod

    def decode(self, prompt, max_new, beam, deadline_ms, request_id):
        """Non-streamed decode; returns the transcript result."""
        if self.kind != 'decoding':
            raise ValueError('this gateway serves a dense artifact — '
                             'POST /v1/infer (request %s)' % request_id)
        if self.flavor == 'fleet':
            fut = self.target.submit(prompt, deadline_ms=deadline_ms,
                                     max_new_tokens=max_new, beam=beam,
                                     request_id=request_id)
            return fut.result(_STREAM_RESULT_TIMEOUT)
        stream = self.target.submit(prompt, max_new_tokens=max_new,
                                    beam=beam, deadline_ms=deadline_ms,
                                    request_id=request_id)
        return stream.result(_STREAM_RESULT_TIMEOUT)

    def decode_stream(self, prompt, max_new, deadline_ms, request_id):
        """Streamed greedy decode: yields ('toks', [ints]) delivery
        batches then ('done', transcript); backend errors raise."""
        if self.kind != 'decoding':
            raise ValueError('this gateway serves a dense artifact — '
                             'POST /v1/infer (request %s)' % request_id)
        if self.flavor == 'direct':
            stream = self.target.submit(
                prompt, max_new_tokens=max_new, deadline_ms=deadline_ms,
                request_id=request_id)
            for batch in stream.batches():
                yield 'toks', batch
            yield 'done', stream.result(_STREAM_RESULT_TIMEOUT)
            return
        # fleet: ride on_token from the router reader thread. Tokens of
        # one coalesced 'toks' frame fire back-to-back with no network
        # round-trip between them, so the greedy drain below re-batches
        # them into one SSE event.
        q = _queue_mod.Queue()
        fut = self.target.submit(prompt, deadline_ms=deadline_ms,
                                 max_new_tokens=max_new,
                                 on_token=q.put, request_id=request_id)
        fut.add_done_callback(lambda f: q.put(_DONE))
        while True:
            item = q.get(timeout=_STREAM_RESULT_TIMEOUT)
            if item is _DONE:
                break
            batch = [item]
            while True:
                try:
                    nxt = q.get_nowait()
                except _queue_mod.Empty:
                    break
                if nxt is _DONE:
                    yield 'toks', batch
                    yield 'done', fut.result(0)
                    return
                batch.append(nxt)
            yield 'toks', batch
        yield 'done', fut.result(0)


_DONE = object()


# -- the HTTP server ---------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    protocol_version = 'HTTP/1.1'
    server_version = 'ptpu-gateway'
    gateway = None  # set by the per-Gateway handler subclass

    # quiet by default: one line per request through the gateway's own
    # counters, not BaseHTTPRequestHandler's stderr chatter
    def log_message(self, fmt, *args):
        if os.environ.get('PTPU_GATEWAY_LOG'):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def do_GET(self):
        gw = self.gateway
        path = self.path.split('?', 1)[0]
        if path == '/healthz':
            return gw._h_healthz(self)
        if path == '/stats.json':
            return gw._h_stats(self)
        if path == '/metrics':
            return gw._h_metrics(self)
        gw._reply_json(self, 404, {'error': 'no route %s' % path,
                                   'etype': 'NotFound'},
                       tenant='-')

    def do_POST(self):
        gw = self.gateway
        path = self.path.split('?', 1)[0]
        if path == '/v1/infer':
            return gw._h_infer(self)
        if path == '/v1/decode':
            return gw._h_decode(self)
        if path == '/admin/drain':
            return gw._h_drain(self)
        gw._reply_json(self, 404, {'error': 'no route %s' % path,
                                   'etype': 'NotFound'},
                       tenant='-')


class Gateway(object):
    """The HTTP front door. `backend` is a FleetRouter or a single
    predictor; the gateway NEVER owns it (close() stops the HTTP tier
    only — the caller closes the backend, mirroring who opened it).

    Endpoints:
        GET  /healthz     liveness + serving capacity (200 / 503)
        GET  /stats.json  gateway + backend snapshot
        GET  /metrics     Prometheus text exposition
        POST /v1/infer    dense request/response (base64-npz feeds)
        POST /v1/decode   decode; `stream` answers SSE token events
        POST /admin/drain graceful drain (admin tenants only)

    `tenants` is {api_key: TenantConfig} (see tenants_from_json); None
    serves anonymously with no limits. `max_inflight` bounds the
    gateway-wide admitted requests (503 beyond it)."""

    def __init__(self, backend, host='127.0.0.1', port=0, tenants=None,
                 max_inflight=None, default_deadline_ms=None,
                 stats_window=8192):
        self.backend = _Backend(backend)
        self._tenants = dict(tenants) if tenants else None
        self._anon = TenantConfig('anonymous', admin=True)
        self._max_inflight = (int(max_inflight)
                              if max_inflight is not None else None)
        self._default_deadline_ms = default_deadline_ms
        self.stats = GatewayStats(stats_window)
        # one lock for admission + counters: the tenant table is
        # touched by both the door checks and the outcome ledger
        self._lock = self.stats._lock
        self._idle = threading.Condition(self._lock)
        self._draining = False
        self._req_seq = itertools.count()
        self.drain_requested = threading.Event()

        handler = type('_BoundHandler', (_Handler,), {'gateway': self})
        self._server = ThreadingHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self._serve_t = None
        self._served = threading.Event()  # a serve loop has run

        self._profiler_name = None
        prof = _maybe_profiler()
        if prof is not None and hasattr(prof, 'register_gateway_source'):
            name = 'gateway:%s:%d#%d' % (self.address[0],
                                         self.address[1],
                                         next(_SOURCE_SEQ))
            prof.register_gateway_source(name, self.snapshot)
            self._profiler_name = name

    # -- lifecycle ---------------------------------------------------------
    @property
    def address(self):
        return self._server.server_address[:2]

    @property
    def url(self):
        return 'http://%s:%d' % self.address

    def start(self):
        """Serve in a background thread; returns self."""
        if self._serve_t is None:
            self._served.set()
            self._serve_t = threading.Thread(
                target=self._server.serve_forever,
                kwargs={'poll_interval': 0.1},
                name='ptpu-gateway', daemon=True)
            self._serve_t.start()
        return self

    def serve_forever(self):
        self._served.set()
        self._server.serve_forever(poll_interval=0.1)

    def _shutdown_server(self):
        # BaseServer.shutdown() blocks until serve_forever() exits; if
        # no serve loop ever ran it would wait forever, so only signal
        # a loop that actually started.
        if self._served.is_set():
            self._server.shutdown()

    def drain(self, timeout=None):
        """Graceful drain: stop admitting (new data requests answer 503
        'gateway draining'), finish every in-flight request/stream, then
        stop accepting connections. Returns True when the gateway went
        idle within `timeout` — in-flight work is never cut off early
        either way (the zero-dropped-streams contract)."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._lock:
            self._draining = True
            self.stats.draining = True
            while self.stats.inflight > 0:
                wait = None
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        break
                self._idle.wait(wait if wait is not None else 1.0)
            idle = self.stats.inflight == 0
        self._shutdown_server()
        return idle

    def close(self):
        """Stop the HTTP tier (the backend stays up — its owner closes
        it). Idempotent."""
        with self._lock:
            self._draining = True
            self.stats.draining = True
        self._shutdown_server()
        self._server.server_close()
        if self._serve_t is not None:
            self._serve_t.join(timeout=5)
            self._serve_t = None
        name, self._profiler_name = self._profiler_name, None
        if name:
            prof = _maybe_profiler()
            if prof is not None and hasattr(prof,
                                            'unregister_gateway_source'):
                prof.unregister_gateway_source(name)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- snapshots ---------------------------------------------------------
    def snapshot(self):
        """Profiler gateway-source contract: gateway counters + the
        backend snapshot under 'backend'."""
        snap = self.stats.snapshot()
        snap['addr'] = '%s:%d' % self.address
        try:
            snap['backend'] = self.backend.snapshot()
        except Exception:
            snap['backend'] = {}
        return snap

    # -- admission ---------------------------------------------------------
    def _auth(self, handler):
        """-> TenantConfig, or None after answering 401."""
        if self._tenants is None:
            return self._anon
        key = handler.headers.get('X-API-Key')
        if not key:
            auth = handler.headers.get('Authorization', '')
            if auth.startswith('Bearer '):
                key = auth[len('Bearer '):].strip()
        tenant = self._tenants.get(key) if key else None
        if tenant is None:
            self._reply_json(handler, 401,
                            {'error': 'missing or unknown API key',
                             'etype': 'Unauthorized'}, tenant='-')
            return None
        return tenant

    def _admit(self, handler, tenant, rid):
        """Door checks under one lock: drain, rate, quotas. Returns
        True when admitted (inflight charged); False after replying."""
        with self._lock:
            if self._draining:
                code, cat, hdrs, msg = 503, 'shed', \
                    {'Retry-After': '1'}, 'gateway draining'
            else:
                ok, retry_s = tenant.acquire()
                if not ok:
                    code, cat = 429, 'rate_limited'
                    hdrs = {'Retry-After': '%d' % max(1, int(retry_s
                                                             + 0.999))}
                    msg = ('tenant %s over its %.3g req/s rate — '
                           'request rate-limited'
                           % (tenant.name, tenant.rate))
                elif tenant.max_inflight is not None and \
                        self.stats._tenant(tenant.name)['inflight'] \
                        >= tenant.max_inflight:
                    code, cat, hdrs = 429, 'quota', {'Retry-After': '1'}
                    msg = ('tenant %s at max_inflight %d — request '
                           'shed at the gateway door'
                           % (tenant.name, tenant.max_inflight))
                elif self._max_inflight is not None and \
                        self.stats.inflight >= self._max_inflight:
                    code, cat, hdrs = 503, 'shed', {'Retry-After': '1'}
                    msg = ('gateway at max_inflight %d — request shed '
                           'at the gateway door' % self._max_inflight)
                else:
                    self.stats.inflight += 1
                    self.stats._tenant(tenant.name)['inflight'] += 1
                    return True
        self._reply_json(handler, code,
                        {'error': '%s (request %s)' % (msg, rid),
                         'etype': 'ServerOverloaded'
                         if code == 503 else 'RateLimited',
                         'request_id': rid},
                        tenant=tenant.name, category=cat,
                        headers=hdrs)
        return False

    def _release(self, tenant):
        with self._lock:
            self.stats.inflight -= 1
            self.stats._tenant(tenant.name)['inflight'] -= 1
            if self.stats.inflight == 0:
                self._idle.notify_all()

    # -- response helpers --------------------------------------------------
    def _reply_json(self, handler, code, obj, tenant, category=None,
                    headers=None, t0=None):
        body = json.dumps(obj).encode('utf-8')
        ttfb = (time.perf_counter() - t0) if t0 is not None else None
        try:
            handler.send_response(code)
            handler.send_header('Content-Type', 'application/json')
            handler.send_header('Content-Length', str(len(body)))
            if obj.get('request_id'):
                handler.send_header('X-Request-Id', obj['request_id'])
            for k, v in (headers or {}).items():
                handler.send_header(k, v)
            handler.end_headers()
            handler.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            with self._lock:
                self.stats.disconnects += 1
        if tenant != '-':
            self.stats.record(tenant, code, category, ttfb_s=ttfb)

    def _reply_error(self, handler, exc, rid, tenant, t0=None):
        code = status_for(exc)
        headers = {'Retry-After': '1'} if code in (429, 503) else None
        self._reply_json(handler, code,
                        {'error': str(exc),
                         'etype': type(exc).__name__,
                         'request_id': rid, 'code': code},
                        tenant=tenant, headers=headers, t0=t0)

    @staticmethod
    def _read_body(handler):
        n = int(handler.headers.get('Content-Length') or 0)
        if n > _MAX_BODY:
            raise ValueError('request body of %d bytes exceeds the %d '
                             'gateway bound' % (n, _MAX_BODY))
        raw = handler.rfile.read(n) if n else b'{}'
        return json.loads(raw.decode('utf-8'))

    def _request_id(self, handler):
        return (handler.headers.get('X-Request-Id')
                or 'gw-%d-%d' % (os.getpid(), next(self._req_seq)))

    def _remaining_ms(self, body, t0):
        """The budget left when the backend is about to see the request
        (time at the gateway counts); None = no deadline. Raises
        DeadlineExceeded when already spent — the gateway-door shed."""
        budget = body.get('deadline_ms', self._default_deadline_ms)
        if budget is None:
            return None
        remaining = float(budget) - (time.perf_counter() - t0) * 1e3
        if remaining <= 0:
            raise DeadlineExceeded(
                'deadline elapsed at the gateway door (budget %.1f ms)'
                % float(budget))
        return remaining

    # -- route handlers ----------------------------------------------------
    def _h_healthz(self, handler):
        healthy = self.backend.healthy() and not self._draining
        code = 200 if healthy else 503
        self._reply_json(handler, code,
                        {'ok': healthy, 'draining': self._draining,
                         'kind': self.backend.kind,
                         'inflight': self.stats.inflight},
                        tenant='-')

    def _h_stats(self, handler):
        self._reply_json(handler, 200, self.snapshot(), tenant='-')

    def _h_metrics(self, handler):
        snap = self.snapshot()
        text = render_metrics(snap, snap.get('backend'))
        body = text.encode('utf-8')
        try:
            handler.send_response(200)
            handler.send_header('Content-Type',
                                'text/plain; version=0.0.4')
            handler.send_header('Content-Length', str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            with self._lock:
                self.stats.disconnects += 1

    def _h_drain(self, handler):
        tenant = self._auth(handler)
        if tenant is None:
            return
        if not tenant.admin:
            self._reply_json(handler, 403,
                            {'error': 'tenant %s is not an admin'
                             % tenant.name, 'etype': 'Forbidden'},
                            tenant=tenant.name, category='bad')
            return
        self.drain_requested.set()
        with self._lock:
            self._draining = True
            self.stats.draining = True
        self._reply_json(handler, 202,
                        {'draining': True,
                         'inflight': self.stats.inflight},
                        tenant=tenant.name)

    def _h_infer(self, handler):
        t0 = time.perf_counter()
        rid = self._request_id(handler)
        tenant = self._auth(handler)
        if tenant is None:
            return
        if not self._admit(handler, tenant, rid):
            return
        try:
            try:
                body = self._read_body(handler)
                if 'npz' not in body:
                    raise ValueError(
                        "infer body needs 'npz' (base64 npz of the "
                        "feed arrays)")
                feeds = _feeds_from_arrays(decode_arrays(body['npz']))
                remaining = self._remaining_ms(body, t0)
                outs, lod = self.backend.infer(feeds, remaining, rid)
            except Exception as e:
                self._reply_error(handler, e, rid, tenant.name, t0=t0)
                return
            flat = {}
            for j, o in enumerate(outs):
                if isinstance(o, tuple):
                    flat['o%d' % j] = o[0]
                    for i, off in enumerate(o[1]):
                        flat['o%d.lod%d' % (j, i)] = off
                else:
                    flat['o%d' % j] = o
            self._reply_json(handler, 200,
                            {'npz': encode_arrays(flat), 'lod': lod,
                             'n': len(outs), 'request_id': rid},
                            tenant=tenant.name, t0=t0)
        finally:
            self._release(tenant)

    def _h_decode(self, handler):
        t0 = time.perf_counter()
        rid = self._request_id(handler)
        tenant = self._auth(handler)
        if tenant is None:
            return
        if not self._admit(handler, tenant, rid):
            return
        try:
            try:
                body = self._read_body(handler)
                prompt = body.get('prompt')
                if prompt is None and 'npz' in body:
                    prompt = decode_arrays(body['npz']).get('prompt')
                if prompt is None:
                    raise ValueError(
                        "decode body needs 'prompt' (JSON int list) or "
                        "'npz' with a 'prompt' array")
                prompt = np.asarray(prompt, np.int64).reshape(-1)
                max_new = body.get('max_new_tokens')
                beam = body.get('beam')
                stream = bool(body.get('stream', beam is None))
                remaining = self._remaining_ms(body, t0)
            except Exception as e:
                self._reply_error(handler, e, rid, tenant.name, t0=t0)
                return
            if stream and beam is None:
                self._decode_sse(handler, tenant, rid, prompt, max_new,
                                 remaining, t0)
                return
            try:
                res = self.backend.decode(prompt, max_new, beam,
                                          remaining, rid)
            except Exception as e:
                self._reply_error(handler, e, rid, tenant.name, t0=t0)
                return
            if beam is None:
                out = {'tokens': [int(t) for t in res],
                       'request_id': rid}
            else:
                ids, scores = res
                out = {'ids': np.asarray(ids).tolist(),
                       'scores': np.asarray(scores).tolist(),
                       'request_id': rid}
            self._reply_json(handler, 200, out, tenant=tenant.name,
                            t0=t0)
        finally:
            self._release(tenant)

    def _decode_sse(self, handler, tenant, rid, prompt, max_new,
                    deadline_ms, t0):
        """Greedy streamed decode as Server-Sent Events. The error
        contract survives the streaming split: before the first byte a
        failure is a plain HTTP status; after it, an `event: error`
        frame carrying the same code — never a silent cut."""
        events = self.backend.decode_stream(prompt, max_new,
                                            deadline_ms, rid)
        headers_out = False
        ttfb = None
        ttft = None
        n_sent = 0
        try:
            for kind, payload in events:
                if not headers_out:
                    handler.send_response(200)
                    handler.send_header('Content-Type',
                                        'text/event-stream')
                    handler.send_header('Cache-Control', 'no-cache')
                    handler.send_header('X-Request-Id', rid)
                    handler.send_header('Connection', 'close')
                    handler.end_headers()
                    handler.close_connection = True
                    headers_out = True
                    ttfb = time.perf_counter() - t0
                if kind == 'toks':
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    n_sent += len(payload)
                    self._sse(handler, None,
                              {'toks': [int(t) for t in payload]})
                else:  # done
                    self._sse(handler, 'done',
                              {'tokens': [int(t) for t in payload],
                               'n': len(payload), 'request_id': rid})
            with self._lock:
                self.stats.streams += 1
            self.stats.record(tenant.name, 200, ttfb_s=ttfb,
                              ttft_s=ttft)
        except (BrokenPipeError, ConnectionResetError):
            with self._lock:
                self.stats.disconnects += 1
            self.stats.record(tenant.name, 499, category='failed')
        except Exception as e:
            code = status_for(e)
            if not headers_out:
                self._reply_error(handler, e, rid, tenant.name, t0=t0)
                return
            try:
                self._sse(handler, 'error',
                          {'error': str(e), 'etype': type(e).__name__,
                           'code': code, 'request_id': rid,
                           'n_sent': n_sent})
            except (BrokenPipeError, ConnectionResetError):
                with self._lock:
                    self.stats.disconnects += 1
            self.stats.record(tenant.name, code, ttft_s=ttft)

    @staticmethod
    def _sse(handler, event, obj):
        frame = b''
        if event:
            frame += b'event: %s\n' % event.encode('ascii')
        frame += b'data: %s\n\n' % json.dumps(obj).encode('utf-8')
        handler.wfile.write(frame)
        handler.wfile.flush()
