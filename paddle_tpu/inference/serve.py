"""Tracer-free serving of a compiled inference artifact.

Counterpart to export.py — the deployment half of the reference's
non-Python serving story (inference/api/paddle_api.h:1): load a
`jax.export` artifact + signature and run it. This module imports ONLY
json/numpy/jax — no Program IR, no op registry, no tracer — so a serving
process carries none of the framework. It is also runnable as a script:

    python -m paddle_tpu.inference.serve ARTIFACT_DIR IN.npz OUT.npz

(or `python paddle_tpu/inference/serve.py ...` to avoid importing the
package __init__ entirely; the test exercises that path and asserts the
framework modules never load).
"""
import json
import os
import sys

import numpy as np

_SIGNATURE = 'signature.json'
_MODULE = 'module.jaxexport'


class CompiledPredictor(object):
    """PaddlePredictor-shaped API over an exported artifact.

    `platform` (or env PTPU_PLATFORM) pins execution, e.g. 'cpu' or 'tpu';
    default is the process's default jax backend."""

    def __init__(self, artifact_dir, platform=None):
        import jax
        from jax import export as jexport
        with open(os.path.join(artifact_dir, _SIGNATURE)) as f:
            self._sig = json.load(f)
        with open(os.path.join(artifact_dir, _MODULE), 'rb') as f:
            self._exported = jexport.deserialize(f.read())
        self._feed_names = [e['name'] for e in self._sig['feeds']]
        platform = platform or os.environ.get('PTPU_PLATFORM')
        self._device = jax.devices(platform)[0] if platform else None

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._sig['fetches'])

    def run(self, inputs):
        """inputs: list (feed order) or dict name -> array.
        Returns list of numpy outputs."""
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self._feed_names):
                raise ValueError("artifact expects %d inputs (%s), got %d"
                                 % (len(self._feed_names), self._feed_names,
                                    len(inputs)))
            feed = dict(zip(self._feed_names, inputs))
        else:
            feed = dict(inputs)
        args = []
        for e in self._sig['feeds']:
            arr = np.asarray(feed[e['name']], dtype=np.dtype(e['dtype']))
            if list(arr.shape) != e['shape']:
                raise ValueError(
                    "feed %r: expected shape %s (artifacts are compiled for "
                    "fixed shapes), got %s"
                    % (e['name'], e['shape'], list(arr.shape)))
            args.append(arr)
        if self._device is not None:
            import jax
            with jax.default_device(self._device):
                outs = self._exported.call(*args)
        else:
            outs = self._exported.call(*args)
        return [np.asarray(o) for o in outs]


def load_compiled(artifact_dir):
    return CompiledPredictor(artifact_dir)


def main(argv):
    if len(argv) != 4:
        print("usage: serve.py ARTIFACT_DIR IN.npz OUT.npz", file=sys.stderr)
        return 2
    artifact_dir, in_path, out_path = argv[1:]
    pred = CompiledPredictor(artifact_dir)
    with np.load(in_path) as data:
        feed = {k: data[k] for k in data.files}
    outs = pred.run(feed)
    np.savez(out_path, **{n: o for n, o in
                          zip(pred.get_output_names(), outs)})
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv))
