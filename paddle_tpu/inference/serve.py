"""Tracer-free serving of a compiled inference artifact.

Counterpart to export.py — the deployment half of the reference's
non-Python serving story (inference/api/paddle_api.h:1): load a
`jax.export` artifact + signature and run it. This module imports ONLY
json/numpy/jax — no Program IR, no op registry, no tracer — so a serving
process carries none of the framework. It is also runnable as a script:

    python -m paddle_tpu.inference.serve ARTIFACT_DIR IN.npz OUT.npz

(or `python paddle_tpu/inference/serve.py ...` to avoid importing the
package __init__ entirely; the test exercises that path and asserts the
framework modules never load).

Bulk offline/eval inference: `CompiledPredictor.run_batches(batches)`
scans the exported module over K pre-staged batches in ONE device
dispatch (`serve.py loop ...` from the CLI) — the inference mirror of
the Executor's multi-step training dispatch.
"""
import itertools
import json
import os
import sys
import time
import warnings

import numpy as np

_SOURCE_SEQ = itertools.count()  # unique profiler source names per process


def _maybe_profiler():
    """paddle_tpu.profiler, but ONLY if the framework is already imported —
    importing it from here would drag the framework into a tracer-free
    serving process (canonical copy; batching.py reuses it)."""
    if sys.modules.get('paddle_tpu') is None:
        return None
    try:
        from paddle_tpu import profiler
        return profiler
    except Exception:
        return None

def _np_threefry_fold(seed, step):
    """fold_in(key(seed), step) raw key data with numpy only — the
    Threefry-2x32 core, bit-identical to jax's (the same math as
    executor.py's _np_threefry_key_group, duplicated because this module
    must import only json/numpy/jax and also run by file path). Used when
    no cpu backend is registered (JAX_PLATFORMS=tpu): eager key math on a
    remote accelerator would cost dispatch round-trips per step."""
    rot = ((13, 15, 26, 6), (17, 29, 16, 24))
    seed = int(seed)
    import jax
    with np.errstate(over='ignore'):
        # mirror jax's seed canonicalization: with x64 disabled (the
        # default) an int seed becomes int32, so the upper word is zero
        k0 = (np.uint32((seed >> 32) & 0xFFFFFFFF)
              if jax.config.jax_enable_x64 else np.uint32(0))
        k1 = np.uint32(seed & 0xFFFFFFFF)
        ks = (k0, k1, k0 ^ k1 ^ np.uint32(0x1BD11BDA))
        x0 = np.uint32(0) + ks[0]
        x1 = np.uint32(step) + ks[1]
        for i in range(5):
            for r in rot[i % 2]:
                x0 = x0 + x1
                x1 = (x1 << np.uint32(r)) | (x1 >> np.uint32(32 - r))
                x1 = x0 ^ x1
            x0 = x0 + ks[(i + 1) % 3]
            x1 = x1 + ks[(i + 2) % 3] + np.uint32(i + 1)
    return np.stack([x0, x1])


_SIGNATURE = 'signature.json'
_MODULE = 'module.jaxexport'
_BUCKET_DIR = 'bucket_%05d'  # per-bucket subdir of a multi-bucket artifact
# quantized artifact tier (ISSUE 11): export_compiled(quantize='int8')
# writes a COMPLETE second artifact tree under <artifact>/int8/ — same
# buckets, own AOT sidecars, calibration metadata in its signature —
# next to the default ('bf16') tier at the top level
_TIER_INT8 = 'int8'
_TRAIN_SIGNATURE = 'train_signature.json'
_TRAIN_MODULE = 'train_module.jaxexport'
_TRAIN_STATE0 = 'train_state0.npz'
# AOT warm-start sidecars (ISSUE 5): the module's XLA executable,
# serialized per platform next to the module it was compiled from —
# loading one skips BOTH the StableHLO deserialize-compile and the trace,
# so a fresh serving replica answers its first request without paying
# cold-start compile latency. Written by export (default), or after the
# fact by `tools/cache_ctl.py prewarm ARTIFACT`.
_AOT_SIDECAR = 'aot_%s.jaxexec'              # % platform
_TRAIN_AOT_SIDECAR = 'aot_train_%s.jaxexec'  # % platform


def _module_sha(module_bytes):
    import hashlib
    return hashlib.sha256(module_bytes).hexdigest()


def resolve_tier(artifact_dir, tier=None, signature=_SIGNATURE):
    """Resolve a serving-tier request to the artifact directory to load.

    `tier` (or env PTPU_SERVE_TIER): 'bf16' (default) serves the top
    level; 'int8' serves the quantized tier subdir. An EXPLICIT tier
    argument on an artifact without that tier raises; the env preference
    degrades silently to the default tier so one fleet-wide setting can
    cover mixed artifact generations (and per-bucket loads inside an
    already-resolved tier). `signature` names the file a valid tier dir
    must carry — continuous-decode artifacts resolve against
    decode_signature.json (DecodingPredictor(tier=), same contract)."""
    req = tier or os.environ.get('PTPU_SERVE_TIER')
    if not req or req == 'bf16':
        return artifact_dir
    sub = os.path.join(artifact_dir, req)
    # a tier dir counts only with its signature: a partial/interrupted
    # export must surface the designed "has no tier" error, not a raw
    # FileNotFoundError from deep inside the loader
    if os.path.isdir(sub) and os.path.exists(os.path.join(sub,
                                                          signature)):
        return sub
    if tier:
        tiers = ['bf16']
        try:
            with open(os.path.join(artifact_dir, signature)) as f:
                tiers = json.load(f).get('tiers', ['bf16'])
        except Exception:
            pass
        raise ValueError(
            "artifact %s has no %r tier (tiers: %s) — export with "
            "export_compiled(..., quantize='int8') (or export_decode "
            "the quantized spec into <artifact>/%s) to add one"
            % (artifact_dir, req, tiers, req))
    return artifact_dir


def _aot_platform(device=None):
    """The platform an AOT sidecar is keyed on: the pinned device's, else
    PTPU_PLATFORM, else the process's default jax backend."""
    if device is not None:
        return device.platform
    env = os.environ.get('PTPU_PLATFORM')
    if env:
        return env
    import jax
    return jax.default_backend()


def _fresh_compile():
    """Context: compile with jax's persistent compilation cache
    DISABLED. An executable the persistent cache satisfied re-serializes
    into a blob other processes cannot deserialize ('Symbols not found'
    at load) — every AOT warm-start sidecar must come from a genuinely
    fresh XLA compile (framework-free copy of
    core.compile_cache.fresh_compile; this module imports only
    json/numpy/jax). jax latches cache-enablement once per process
    (is_cache_used caches its verdict), so the latch is reset around
    the scope too."""
    import contextlib
    import jax

    def _unlatch():
        try:
            from jax._src import compilation_cache as _jcc
            _jcc.reset_cache()
        except Exception:
            pass

    @contextlib.contextmanager
    def ctx():
        try:
            old = bool(jax.config.jax_enable_compilation_cache)
        except AttributeError:
            yield
            return
        try:
            jax.config.update('jax_enable_compilation_cache', False)
            _unlatch()
            yield
        finally:
            jax.config.update('jax_enable_compilation_cache', old)
            _unlatch()
    return ctx()


def _save_aot(path, compiled, module_sha):
    """Serialize a compiled executable as a warm-start sidecar (atomic
    tmp+rename; pickle of the serialized executable + validation facts)."""
    import pickle
    import jax
    import jaxlib
    from jax.experimental.serialize_executable import serialize
    payload, in_tree, out_tree = serialize(compiled)
    blob = pickle.dumps({'v': 1, 'jax': jax.__version__,
                         'jaxlib': jaxlib.__version__, 'sha': module_sha,
                         'payload': payload, 'in_tree': in_tree,
                         'out_tree': out_tree})
    tmp = '%s.tmp-%d' % (path, os.getpid())
    with open(tmp, 'wb') as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def _load_aot(path, module_sha):
    """Deserialize a warm-start sidecar; None when absent. A stale or
    corrupt sidecar warns LOUDLY and is ignored (the module still serves
    through the normal compile path — never silently, never fatally)."""
    if not os.path.exists(path):
        return None
    import pickle
    import jax
    import jaxlib
    try:
        with open(path, 'rb') as f:
            d = pickle.loads(f.read())
        if d.get('sha') != module_sha:
            raise ValueError('sidecar was compiled from a different module')
        if (d.get('jax'), d.get('jaxlib')) != (jax.__version__,
                                               jaxlib.__version__):
            raise ValueError(
                'sidecar built with jax %s / jaxlib %s, process runs %s/%s'
                % (d.get('jax'), d.get('jaxlib'), jax.__version__,
                   jaxlib.__version__))
        from jax.experimental.serialize_executable import (
            deserialize_and_load)
        return deserialize_and_load(d['payload'], d['in_tree'],
                                    d['out_tree'])
    except Exception as e:
        warnings.warn('AOT sidecar %s unusable (%s: %s) — falling back to '
                      'compiling the module; re-run `cache_ctl.py prewarm` '
                      'to refresh it' % (path, type(e).__name__, e),
                      RuntimeWarning)
        return None


def _infer_flat_specs(sig):
    """The module's flat arg specs from signature.json: per feed, data then
    one int32 offsets array per lod level (export.py's flat convention)."""
    import jax
    specs = []
    for e in sig['feeds']:
        specs.append(jax.ShapeDtypeStruct(tuple(e['shape']),
                                          np.dtype(e['dtype'])))
        if int(e.get('lod_levels', 0)):
            for n in e['lod_sizes']:
                specs.append(jax.ShapeDtypeStruct((int(n),), np.int32))
    return specs


def _precompile_infer_dir(d, platform=None):
    """AOT-compile the inference module in artifact dir `d` for this
    process's platform and write the sidecar. Returns the sidecar path."""
    import jax
    from jax import export as jexport
    with open(os.path.join(d, _MODULE), 'rb') as f:
        module_bytes = f.read()
    with open(os.path.join(d, _SIGNATURE)) as f:
        sig = json.load(f)
    plat = platform or _aot_platform()
    dev = jax.devices(plat)[0]
    exp = jexport.deserialize(module_bytes)
    with jax.default_device(dev), _fresh_compile():
        compiled = jax.jit(exp.call).lower(*_infer_flat_specs(sig)).compile()
    return _save_aot(os.path.join(d, _AOT_SIDECAR % plat), compiled,
                     _module_sha(module_bytes))


def _precompile_train_dir(d, platform=None):
    """AOT-compile the train-step module in artifact dir `d` (sidecar per
    platform), mirroring CompiledTrainer.step's calling convention."""
    import jax
    from jax import export as jexport
    with open(os.path.join(d, _TRAIN_MODULE), 'rb') as f:
        module_bytes = f.read()
    with open(os.path.join(d, _TRAIN_SIGNATURE)) as f:
        sig = json.load(f)
    plat = platform or _aot_platform()
    dev = jax.devices(plat)[0]
    state_specs = [jax.ShapeDtypeStruct(tuple(e['shape']),
                                        np.dtype(e['dtype']))
                   for e in sig['state']]
    feed_specs = [jax.ShapeDtypeStruct(tuple(e['shape']),
                                       np.dtype(e['dtype']))
                  for e in sig['feeds']]
    rng_spec = jax.ShapeDtypeStruct(tuple(sig['rng']['key_shape']),
                                    np.dtype(sig['rng']['key_dtype']))
    exp = jexport.deserialize(module_bytes)
    with jax.default_device(dev), _fresh_compile():
        compiled = jax.jit(exp.call).lower(state_specs, feed_specs,
                                           rng_spec).compile()
    return _save_aot(os.path.join(d, _TRAIN_AOT_SIDECAR % plat), compiled,
                     _module_sha(module_bytes))


def _decoding_module():
    """Sibling decoding.py (the continuous-decode tier), importable both
    as a package module and by file path (this module's own contract)."""
    try:
        from . import decoding
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import decoding
    return decoding


def precompile_artifact(artifact_dir, platform=None):
    """Prewarm a serving artifact: AOT-compile EVERY bucket's module (and
    the train module when present) for this process's platform, writing
    warm-start sidecars — a replica that loads the artifact afterwards
    performs zero traces and zero XLA compiles before its first answer.
    Continuous-decode artifacts (export_decode's two-program layout)
    prewarm BOTH tiers: every prompt-length prefill bucket plus the
    decode-step and reorder programs. The engine behind
    `tools/cache_ctl.py prewarm`. Returns the sidecar paths written."""
    import shutil
    written = []
    plat = platform or _aot_platform()
    decoding = _decoding_module()
    if os.path.exists(os.path.join(artifact_dir,
                                   decoding._DECODE_SIGNATURE)):
        written.extend(decoding.precompile_decode_artifact(
            artifact_dir, platform=plat))
    sig_p = os.path.join(artifact_dir, _SIGNATURE)
    if os.path.exists(sig_p):
        with open(sig_p) as f:
            buckets = json.load(f).get('buckets')
        if buckets:
            for b in buckets:
                written.append(_precompile_infer_dir(
                    os.path.join(artifact_dir, _BUCKET_DIR % int(b)),
                    platform=plat))
            # the top level mirrors (hardlinks) the LARGEST bucket's
            # module, so its sidecar is byte-for-byte reusable — link,
            # don't recompile
            src = written[-1]
            top = os.path.join(artifact_dir, _AOT_SIDECAR % plat)
            if os.path.exists(top):
                os.remove(top)
            try:
                os.link(src, top)
            except OSError:
                shutil.copyfile(src, top)
            written.append(top)
        else:
            written.append(_precompile_infer_dir(artifact_dir,
                                                 platform=plat))
    if os.path.exists(os.path.join(artifact_dir, _TRAIN_MODULE)):
        written.append(_precompile_train_dir(artifact_dir, platform=plat))
    # quantized artifact tier (ISSUE 11): a complete bucket tree under
    # int8/ prewarms exactly like the top level, so warm int8 replicas
    # answer with zero compiles too
    tier_dir = os.path.join(artifact_dir, _TIER_INT8)
    if os.path.isdir(tier_dir) and os.path.exists(
            os.path.join(tier_dir, _SIGNATURE)):
        written.extend(precompile_artifact(tier_dir, platform=plat))
    return written


def _split_lod_value(name, value, levels):
    """A LoD feed arrives as (values, lod) — lod nested offsets, or flat
    for one level — or any object with .data/.off_t (duck-typed LoDArray,
    so in-framework callers can pass LoDTensors without this module
    importing the framework)."""
    if hasattr(value, 'off_t') and hasattr(value, 'data'):
        return (np.asarray(value.data),
                [np.asarray(value.off_t(i)) for i in range(levels)])
    if isinstance(value, tuple) and len(value) == 2:
        data, lod = value
        if isinstance(lod, np.ndarray):
            lod = [lod] if lod.ndim == 1 else list(lod)
        elif len(lod) and np.isscalar(lod[0]):
            lod = [lod]
        return np.asarray(data), [np.asarray(l) for l in lod]
    raise ValueError(
        "feed %r carries %d lod level(s): pass a (values, offsets) pair"
        % (name, levels))


def _build_args(sig_feeds, feed_names, inputs, allow_pad=False):
    """Normalize list-or-dict inputs against the artifact signature:
    feed-order list, dtype cast, fixed-shape check; LoD feeds contribute
    their data plus one int32 offsets array per level. Shared by
    CompiledPredictor.run and CompiledTrainer.step.

    With allow_pad, a PARTIAL dense batch — every dense feed arriving with
    the same rows r below the artifact's (uniform) leading batch dim B —
    is zero-padded up to B, the dense analog of the LoD bucket_rows
    padding below. Returns (args, pad) where pad is None or (rows, B) so
    the caller can slice batch-led fetches back to r (and error loudly on
    row-count-dependent fetches)."""
    if isinstance(inputs, (list, tuple)):
        if len(inputs) != len(feed_names):
            raise ValueError("artifact expects %d inputs (%s), got %d"
                             % (len(feed_names), feed_names, len(inputs)))
        feed = dict(zip(feed_names, inputs))
    else:
        feed = dict(inputs)
    missing = [e['name'] for e in sig_feeds if e['name'] not in feed]
    if missing:
        raise ValueError("missing feeds: %r (artifact expects %s)"
                         % (missing, feed_names))
    pad = None
    dense_arrs = {}
    if allow_pad:
        dense = [(e, np.asarray(feed[e['name']],
                                dtype=np.dtype(e['dtype'])))
                 for e in sig_feeds if not int(e.get('lod_levels', 0))]
        dense_arrs = {e['name']: a for e, a in dense}
        if dense and all(
                e['shape'] and a.ndim == len(e['shape'])
                and list(a.shape[1:]) == e['shape'][1:] for e, a in dense):
            expect = {int(e['shape'][0]) for e, _ in dense}
            got = {int(a.shape[0]) for _, a in dense}
            if len(expect) == 1 and len(got) == 1:
                bucket, rows = expect.pop(), got.pop()
                if 0 < rows < bucket:
                    pad = (rows, bucket)
    args = []
    for e in sig_feeds:
        levels = int(e.get('lod_levels', 0))
        value = feed[e['name']]
        if levels:
            data, offs = _split_lod_value(e['name'], value, levels)
            if len(offs) != levels:
                raise ValueError("feed %r: expected %d lod level(s), got %d"
                                 % (e['name'], levels, len(offs)))
            data = np.asarray(data, dtype=np.dtype(e['dtype']))
            rows = data.shape[0]
            bucket_rows = e['shape'][0]
            if rows < bucket_rows \
                    and list(data.shape[1:]) == e['shape'][1:]:
                # pad up to the bucket capacity (the executor's
                # bucket_rows discipline, core/lod.py create_lod_array)
                fill = np.zeros((bucket_rows - rows,) + data.shape[1:],
                                data.dtype)
                data = np.concatenate([data, fill], axis=0)
            if list(data.shape) != e['shape']:
                raise ValueError(
                    "feed %r: expected bucket shape %s, got %s"
                    % (e['name'], e['shape'], list(data.shape)))
            args.append(data)
            for i, (o, want) in enumerate(zip(offs, e['lod_sizes'])):
                o = np.asarray(o, np.int32).reshape(-1)
                if o.shape[0] != want:
                    raise ValueError(
                        "feed %r lod level %d: artifact bucket has %d "
                        "offsets (nseq=%d), got %d"
                        % (e['name'], i, want, want - 1, o.shape[0]))
                args.append(o)
            continue
        arr = dense_arrs.get(e['name'])
        if arr is None:
            arr = np.asarray(value, dtype=np.dtype(e['dtype']))
        if pad is not None and arr.shape[0] == pad[0]:
            arr = np.concatenate(
                [arr, np.zeros((pad[1] - pad[0],) + arr.shape[1:],
                               arr.dtype)], axis=0)
        if list(arr.shape) != e['shape']:
            raise ValueError(
                "feed %r: expected shape %s (artifacts are compiled for "
                "fixed shapes), got %s"
                % (e['name'], e['shape'], list(arr.shape)))
        args.append(arr)
    return args, pad


def _fetch_entries(sig):
    """Fetch signature entries across artifact versions: v1 stored plain
    names (dense-only), v2 stores {name, lod_levels}."""
    return [{'name': f, 'lod_levels': 0} if isinstance(f, str) else f
            for f in sig['fetches']]


def _structure_outputs(sig, flat):
    """Group the module's flat outputs per the fetch signature: dense
    fetches yield an array, LoD fetches a (values, [offsets...]) pair."""
    flat = list(flat)
    out, i = [], 0
    for e in _fetch_entries(sig):
        levels = int(e.get('lod_levels', 0))
        data = np.asarray(flat[i])
        i += 1
        if levels:
            offs = [np.asarray(flat[i + k]) for k in range(levels)]
            i += levels
            out.append((data, offs))
        else:
            out.append(data)
    return out


class CompiledPredictor(object):
    """PaddlePredictor-shaped API over an exported artifact.

    `platform` (or env PTPU_PLATFORM) pins execution, e.g. 'cpu' or 'tpu';
    default is the process's default jax backend."""

    def __init__(self, artifact_dir, platform=None, tier=None):
        import jax
        artifact_dir = resolve_tier(artifact_dir, tier)
        with open(os.path.join(artifact_dir, _SIGNATURE)) as f:
            self._sig = json.load(f)
        # the tier actually LOADED, from the artifact's own signature
        # (the request may have resolved through env/default)
        self.tier = self._sig.get('tier', 'bf16')
        with open(os.path.join(artifact_dir, _MODULE), 'rb') as f:
            module_bytes = f.read()
        # the StableHLO module deserializes LAZILY: a warm replica that
        # loads an AOT sidecar never parses it at all (cold-start cost is
        # the sidecar deserialize alone)
        self._module_bytes = module_bytes
        self._exported_cached = None
        self._feed_names = [e['name'] for e in self._sig['feeds']]
        platform = platform or os.environ.get('PTPU_PLATFORM')
        self._device = jax.devices(platform)[0] if platform else None
        # AOT warm start: a precompiled sidecar for this platform skips
        # the first-request XLA compile entirely (PTPU_ARTIFACT_AOT=0
        # opts out; a stale sidecar warns and falls back)
        self._aot = None
        if os.environ.get('PTPU_ARTIFACT_AOT', '1') not in ('0', 'false'):
            self._aot = _load_aot(
                os.path.join(artifact_dir,
                             _AOT_SIDECAR % _aot_platform(self._device)),
                _module_sha(module_bytes))
        # bulk-inference loop state (run_batches): one jitted scan over the
        # exported module; XLA caches one executable per group size
        self._loop = None
        self._bulk = {'dispatches': 0, 'batches': 0, 'tail_flushes': 0,
                      'stage_s': 0.0, 'dispatch_s': 0.0, 'total_s': 0.0}
        self._prof_name = None
        self._artifact_dir = artifact_dir

    @property
    def _exported(self):
        if self._exported_cached is None:
            from jax import export as jexport
            self._exported_cached = jexport.deserialize(self._module_bytes)
        return self._exported_cached

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [e['name'] for e in _fetch_entries(self._sig)]

    def drain(self):
        """Fleet scale-in hook (inference/fleet.py): CompiledPredictor
        is synchronous — it holds no queue and no in-flight work beyond
        the caller's own run(), so draining is a no-op. BatchingPredictor
        and DecodingPredictor override this with real drains."""
        return self

    def _call_flat(self, args):
        """Dispatch the exported module on the pinned device; returns the
        FLAT device outputs without a host sync (async serving loops —
        e.g. batching.BatchingPredictor — sync once at delivery). With a
        warm-start sidecar loaded, this calls the deserialized executable
        directly — no trace, no compile, same flat convention."""
        fn = self._aot if self._aot is not None else self._exported.call
        if self._device is not None:
            import jax
            with jax.default_device(self._device):
                return fn(*args)
        return fn(*args)

    def run(self, inputs, pad_partial=True):
        """inputs: list (feed order) or dict name -> array; LoD feeds as
        (values, offsets) pairs. Returns a list with a numpy array per
        dense fetch and a (values, [offsets...]) pair per LoD fetch.

        A PARTIAL dense batch (every dense feed with the same rows r below
        the compiled batch dim B) is zero-padded up to B and batch-led
        fetches are sliced back to r; fetches whose leading dim is NOT the
        batch (e.g. a batch reduction — their value depends on the padded
        row count) error loudly, flagged ahead of dispatch when the
        signature records fetch shapes (v3 exports) and at delivery
        otherwise. Caveat: a shape-preserving CROSS-ROW op (rows coupled
        but the fetch stays batch-led, e.g. x - mean(x, axis=0)) is
        undetectable from shapes — such programs would fold the zero rows
        into every result; pass pad_partial=False to restore the strict
        fixed-shape rejection."""
        args, pad = _build_args(self._sig['feeds'], self._feed_names,
                                inputs, allow_pad=pad_partial)
        if pad is not None:
            self._check_pad_fetches(pad)
        outs = _structure_outputs(self._sig, self._call_flat(args))
        if pad is None:
            return outs
        return self._slice_pad(outs, pad)

    def _check_pad_fetches(self, pad):
        """Pre-dispatch rejection of row-count-dependent fetches when the
        signature records fetch shapes (v3 exports)."""
        for e in _fetch_entries(self._sig):
            shape = e.get('shape')
            if int(e.get('lod_levels', 0)) or (
                    shape is not None
                    and (not shape or int(shape[0]) != pad[1])):
                raise ValueError(
                    "feed rows were padded %d->%d but fetch %r (shape "
                    "%s in the signature) is not batch-aligned — its "
                    "value would depend on the padded rows; run with "
                    "the exact compiled batch" % (pad + (e['name'],
                                                         shape)))

    def _slice_pad(self, outs, pad):
        """Slice batch-led fetches of a padded partial batch back to the
        caller's rows; delivery-time guard for v2 signatures."""
        rows, bucket = pad
        sliced = []
        for e, o in zip(_fetch_entries(self._sig), outs):
            if isinstance(o, tuple) or o.ndim < 1 or o.shape[0] != bucket:
                raise ValueError(
                    "feed rows were padded %d->%d but fetch %r has shape "
                    "%s — not batch-aligned, its value depends on the "
                    "padded row count (e.g. a batch reduction); run with "
                    "the exact compiled batch"
                    % (rows, bucket, e['name'],
                       'lod' if isinstance(o, tuple) else list(o.shape)))
            sliced.append(o[:rows])
        return sliced

    # -- bulk inference: one dispatch, K batches ---------------------------
    def _loop_jit(self):
        """jit of a lax.scan over the exported module: each scanned step is
        the exact per-batch program `run()` dispatches, so per-batch
        results are bit-identical through the same bucket. Every stacked
        input is donated — the buffers are staged copies this class owns
        (run_batches never hands a caller-visible array to the jit), so
        XLA may reuse them for the scan's intermediates. One jitted fn
        serves every group size: jit compiles one executable per leading
        dim, which is exactly the multi-bucket tail discipline."""
        if self._loop is None:
            import jax
            exported = self._exported
            nargs = sum(1 + int(e.get('lod_levels', 0))
                        for e in self._sig['feeds'])

            def loop(*stacked):
                def body(carry, xs):
                    return carry, tuple(exported.call(*xs))
                _, ys = jax.lax.scan(body, (), stacked)
                return ys
            self._loop = jax.jit(loop,
                                 donate_argnums=tuple(range(nargs)))
        return self._loop

    def _register_bulk_source(self):
        if self._prof_name is not None:
            return
        prof = _maybe_profiler()
        if prof is None or not hasattr(prof, 'register_infer_source'):
            return
        name = 'bulk_infer:%s#%d' % (
            os.path.basename(os.path.normpath(self._artifact_dir)),
            next(_SOURCE_SEQ))
        # weakref, the Executor's discipline: a predictor dropped by its
        # owner must not stay pinned (module + per-group executables) in
        # the profiler registry forever
        import weakref
        ref = weakref.ref(self)

        def snap():
            pred = ref()
            if pred is None:
                prof.unregister_infer_source(name)
                raise ReferenceError('predictor collected')
            return pred.bulk_stats()
        prof.register_infer_source(name, snap)
        self._prof_name = name

    def bulk_stats(self):
        """Bulk-inference counters (profiler.infer_report contract):
        dispatches, batches, batches_per_dispatch, tail_flushes,
        host_stall_ms (staging: stacking + device transfer), occupancy
        (device-call share of run_batches wall time)."""
        st = self._bulk
        d = max(st['dispatches'], 1)
        return {'dispatches': st['dispatches'], 'batches': st['batches'],
                'batches_per_dispatch': st['batches'] / d,
                'tail_flushes': st['tail_flushes'],
                'host_stall_ms': st['stage_s'] * 1e3,
                'occupancy': (st['dispatch_s'] / st['total_s']
                              if st['total_s'] else 0.0)}

    def run_batches(self, batches, group=None, pad_partial=True):
        """Bulk offline/eval inference: ONE device dispatch runs a
        lax.scan over K pre-staged input batches, amortizing the fixed
        per-dispatch cost (the ~200ms remote-tunnel round-trip floor)
        across all K. Per-batch results are bit-identical to K sequential
        `run()` calls through the same bucket (matmul models exactly;
        XLA:CPU rounds conv scan bodies to ~1e-6, PERF_NOTES.md).

        batches: list of K per-batch inputs, each a list (feed order) or
        dict exactly as `run()` takes — LoD feeds as (values, offsets)
        pairs ride the scan as stacked runtime data, dense partial
        batches pad per-batch under `pad_partial` (run()'s discipline).

        group: dispatch at most `group` batches per compiled loop;
        the tail chunk (m < group) flushes through a smaller compiled
        group, the multi-bucket discipline of prefetch_to_device.
        Default: all K in one dispatch.

        Returns a list of K per-batch fetch lists (run()'s structure)."""
        t_all = time.perf_counter()
        batches = list(batches)
        if not batches:
            return []
        k = len(batches)
        g = k if group is None else int(group)
        if g < 1:
            raise ValueError("run_batches: group must be >= 1, got %d" % g)
        st = self._bulk
        t0 = time.perf_counter()
        flat, pads = [], []
        for b in batches:
            args, pad = _build_args(self._sig['feeds'], self._feed_names,
                                    b, allow_pad=pad_partial)
            if pad is not None:
                self._check_pad_fetches(pad)
            flat.append(args)
            pads.append(pad)
        st['stage_s'] += time.perf_counter() - t0
        loop = self._loop_jit()
        try:
            return self._run_chunks(loop, flat, pads, k, g)
        finally:
            # total accrues even when a chunk raises mid-call: dispatched
            # chunks' stage/dispatch seconds were already committed, and
            # occupancy (dispatch_s / total_s) must stay <= 1
            st['total_s'] += time.perf_counter() - t_all
            self._register_bulk_source()

    def _run_chunks(self, loop, flat, pads, k, g):
        import jax
        st = self._bulk
        results = []
        for off in range(0, k, g):
            chunk = flat[off:off + g]
            m = len(chunk)
            t0 = time.perf_counter()
            # np.stack materializes fresh host buffers (even for device-
            # array inputs), so the donated arrays below are ours alone
            stacked = [np.stack([c[j] for c in chunk])
                       for j in range(len(chunk[0]))]
            if self._device is not None:
                stacked = [jax.device_put(a, self._device) for a in stacked]
            else:
                stacked = [jax.device_put(a) for a in stacked]
            for a in stacked:
                a.block_until_ready()
            t1 = time.perf_counter()
            with warnings.catch_warnings():
                # backends without donation support (XLA:CPU) warn per
                # compile; the fallback is a copy, not a correctness issue
                warnings.filterwarnings(
                    'ignore', message='Some donated buffers were not usable')
                if self._device is not None:
                    with jax.default_device(self._device):
                        ys = loop(*stacked)
                else:
                    ys = loop(*stacked)
                ys = [np.asarray(y) for y in ys]  # ONE sync per dispatch
            t2 = time.perf_counter()
            st['dispatches'] += 1
            st['batches'] += m
            if m < g and off > 0:
                # a genuine tail: full chunks preceded this smaller one —
                # a single sub-group call (k < group) compiles only its
                # own size and is not a tail flush
                st['tail_flushes'] += 1
            st['stage_s'] += t1 - t0
            st['dispatch_s'] += t2 - t1
            for i in range(m):
                outs = _structure_outputs(self._sig, [y[i] for y in ys])
                pad = pads[off + i]
                results.append(outs if pad is None
                               else self._slice_pad(outs, pad))
        return results


def load_compiled(artifact_dir, tier=None):
    return CompiledPredictor(artifact_dir, tier=tier)


class CompiledTrainer(object):
    """Tracer-free TRAINING from an export_train_step artifact — the
    deployment-side counterpart of the reference's C++ trainer
    (train/demo_trainer.cc:1). Parameters and optimizer state flow
    through each call as arrays (nothing baked); this class carries them
    between steps and reproduces the Executor's per-step rng stream
    (fold_in(key(seed, impl), step)), so losses bit-match in-framework
    training. Imports only json/numpy/jax."""

    def __init__(self, artifact_dir, platform=None, seed=None):
        import jax
        with open(os.path.join(artifact_dir, _TRAIN_SIGNATURE)) as f:
            self._sig = json.load(f)
        with open(os.path.join(artifact_dir, _TRAIN_MODULE), 'rb') as f:
            module_bytes = f.read()
        # lazy, as in CompiledPredictor: an AOT-warm trainer never parses
        # the StableHLO module
        self._module_bytes = module_bytes
        self._exported_cached = None
        self._state_names = [e['name'] for e in self._sig['state']]
        with np.load(os.path.join(artifact_dir, _TRAIN_STATE0)) as z:
            self._state = [z[n] for n in self._state_names]
        self._feed_names = [e['name'] for e in self._sig['feeds']]
        self._seed = int(self._sig['rng']['seed'] if seed is None else seed)
        self._impl = self._sig['rng']['impl']
        self._step_count = 0
        platform = platform or os.environ.get('PTPU_PLATFORM')
        self._device = jax.devices(platform)[0] if platform else None
        self._aot = None
        if os.environ.get('PTPU_ARTIFACT_AOT', '1') not in ('0', 'false'):
            self._aot = _load_aot(
                os.path.join(artifact_dir, _TRAIN_AOT_SIDECAR
                             % _aot_platform(self._device)),
                _module_sha(module_bytes))

    @property
    def _exported(self):
        if self._exported_cached is None:
            from jax import export as jexport
            self._exported_cached = jexport.deserialize(self._module_bytes)
        return self._exported_cached

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._sig['fetches'])

    @property
    def state(self):
        """Current state as {name: numpy array} (a checkpoint)."""
        return {n: np.asarray(v)
                for n, v in zip(self._state_names, self._state)}

    def _rng(self):
        # derived on the host cpu backend when one is registered: eager
        # key math on a remote accelerator costs dispatch round-trips per
        # step (the Executor does the same; PERF_NOTES.md r5 note).
        # Under JAX_PLATFORMS=tpu the cpu platform is absent (ADVICE r5
        # item 3): threefry keys derive numpy-side (bit-identical,
        # dispatch-free); other impls fall back to the default device —
        # derivation is deterministic math, same stream either way.
        import contextlib
        import jax
        try:
            dev_ctx = jax.default_device(
                jax.local_devices(backend='cpu')[0])
        except RuntimeError:
            if self._impl == 'threefry2x32':
                return _np_threefry_fold(self._seed, self._step_count)
            dev_ctx = contextlib.nullcontext()
        with dev_ctx:
            key = jax.random.key(self._seed, impl=self._impl)
            return np.asarray(jax.random.key_data(
                jax.random.fold_in(key, self._step_count)))

    def step(self, inputs):
        """Run one train step. inputs: list (feed order) or dict.
        Advances the carried state and rng; returns numpy fetches.
        Strict shapes: a train step never pads (padded rows would corrupt
        the loss and every batch statistic)."""
        args, _ = _build_args(self._sig['feeds'], self._feed_names, inputs)
        fn = self._aot if self._aot is not None else self._exported.call

        def call():
            return fn(self._state, args, self._rng())
        if self._device is not None:
            import jax
            with jax.default_device(self._device):
                fetches, new_state = call()
        else:
            fetches, new_state = call()
        self._state = new_state
        self._step_count += 1
        return [np.asarray(f) for f in fetches]

    def save_state(self, path):
        """Checkpoint the carried state plus the step counter (so a
        resumed trainer continues the exact rng stream); same npz tensor
        format as the artifact's train_state0.npz."""
        np.savez(path, __step_count__=np.int64(self._step_count),
                 **self.state)

    def load_state(self, path):
        with np.load(path) as z:
            missing = [n for n in self._state_names if n not in z.files]
            if missing:
                raise ValueError("checkpoint missing state vars: %r"
                                 % missing)
            self._state = [z[n] for n in self._state_names]
            # a checkpoint without a counter (e.g. train_state0.npz) means
            # "restart from step 0" — keeping the old counter would
            # silently shift the rng stream off the bit-match trajectory
            self._step_count = (int(z['__step_count__'])
                                if '__step_count__' in z.files else 0)


def load_trainer(artifact_dir, platform=None, seed=None):
    return CompiledTrainer(artifact_dir, platform=platform, seed=seed)


def _bench_cli(argv):
    # serve.py bench ARTIFACT_DIR IN.npz N_REQUESTS [TIMEOUT_MS]
    # replays IN.npz N times through the dynamic batcher and prints
    # throughput + latency percentiles, with a sequential
    # one-request-per-run reference — serving perf measurable without the
    # full bench.py harness.
    if len(argv) not in (5, 6):
        print("usage: serve.py bench ARTIFACT_DIR IN.npz N_REQUESTS "
              "[TIMEOUT_MS]", file=sys.stderr)
        return 2
    artifact_dir, in_path, n = argv[2], argv[3], int(argv[4])
    timeout_ms = float(argv[5]) if len(argv) == 6 else 5.0
    try:
        from . import batching
    except ImportError:  # run by file path: batching.py sits alongside
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import batching
    with np.load(in_path) as z:
        feed = {k: z[k] for k in z.files}
    rows = int(next(iter(feed.values())).shape[0])

    batcher = batching.BatchingPredictor(artifact_dir,
                                         batch_timeout_ms=timeout_ms)
    batcher.warmup()
    # sequential reference: the old serving path, one run() per request
    # (pads each request up to the compiled batch)
    seq = CompiledPredictor(artifact_dir)
    k = min(n, 8)
    seq.run(feed)  # warm
    t0 = time.perf_counter()
    for _ in range(k):
        seq.run(feed)
    seq_req_s = k / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    futs = [batcher.submit(feed) for _ in range(n)]
    for f in futs:
        f.result()
    wall = time.perf_counter() - t0
    snap = batcher.stats.snapshot()
    batcher.close()
    req_s = n / wall
    print("buckets=%s requests=%d rows/request=%d" %
          (batcher.buckets, n, rows))
    print("batched:    %10.1f req/s  %10.1f rows/s  (%d batches, "
          "occupancy %.2f)" % (req_s, req_s * rows, snap['batches'],
                               snap['occupancy']))
    print("sequential: %10.1f req/s  %10.1f rows/s  (CompiledPredictor."
          "run per request)" % (seq_req_s, seq_req_s * rows))
    print("latency ms: p50=%.2f p95=%.2f p99=%.2f" %
          (snap['p50_ms'], snap['p95_ms'], snap['p99_ms']))
    print(json.dumps({'req_s': round(req_s, 2),
                      'rows_s': round(req_s * rows, 2),
                      'seq_req_s': round(seq_req_s, 2),
                      'speedup': round(req_s / seq_req_s, 2),
                      'occupancy': snap['occupancy'],
                      'p50_ms': snap['p50_ms'], 'p95_ms': snap['p95_ms'],
                      'p99_ms': snap['p99_ms']}))
    return 0


def _feed_from_npz(sig_feeds, raw, index=None):
    """Rebuild one feed dict from npz arrays ('<name>' plus
    '<name>.lod<i>' offsets for LoD feeds); with `index`, slice batch
    `index` out of arrays stacked over a leading K axis."""
    feed = {}
    for e in sig_feeds:
        n, levels = e['name'], int(e.get('lod_levels', 0))
        pick = (lambda a: a[index]) if index is not None else (lambda a: a)
        if levels:
            feed[n] = (pick(raw[n]), [pick(raw['%s.lod%d' % (n, i)])
                                      for i in range(levels)])
        else:
            feed[n] = pick(raw[n])
    return feed


def _loop_cli(argv):
    # serve.py loop ARTIFACT_DIR IN.npz OUT.npz [GROUP]
    # IN.npz arrays carry a leading K batch axis (LoD feeds as '<name>'
    # [K, rows, ...] plus '<name>.lod<i>' [K, n] offsets); all K batches
    # run through run_batches — ONE compiled dispatch per group — and
    # OUT.npz holds each fetch stacked over the same K axis.
    if len(argv) not in (5, 6):
        print("usage: serve.py loop ARTIFACT_DIR IN.npz OUT.npz [GROUP]",
              file=sys.stderr)
        return 2
    artifact_dir, in_path, out_path = argv[2:5]
    group = int(argv[5]) if len(argv) == 6 else None
    pred = CompiledPredictor(artifact_dir)
    with np.load(in_path) as data:
        raw = {k: data[k] for k in data.files}
    k = int(next(iter(raw.values())).shape[0])
    batches = [_feed_from_npz(pred._sig['feeds'], raw, index=i)
               for i in range(k)]
    results = pred.run_batches(batches, group=group)
    save = {}
    for j, n in enumerate(pred.get_output_names()):
        outs = [r[j] for r in results]
        if isinstance(outs[0], tuple):
            save[n] = np.stack([o[0] for o in outs])
            for i in range(len(outs[0][1])):
                save['%s.lod%d' % (n, i)] = np.stack([o[1][i]
                                                      for o in outs])
        else:
            save[n] = np.stack(outs)
    np.savez(out_path, **save)
    return 0


def _pop_flag(argv, name):
    """Extract `--NAME VALUE` (or `--NAME=VALUE`) from argv anywhere;
    returns (value or None, argv without the flag) — the positional CLIs
    here stay positional, flags ride on top."""
    out, value, it = [], None, iter(argv)
    for a in it:
        if a == '--%s' % name:
            value = next(it, None)
            if value is None:
                raise SystemExit('--%s needs a value' % name)
        elif a.startswith('--%s=' % name):
            value = a.split('=', 1)[1]
        else:
            out.append(a)
    return value, out


def _decode_cli(argv):
    # serve.py decode ARTIFACT_DIR PROMPTS.npz OUT.npz [MAX_NEW [BEAM]]
    #          [--tier T]
    # PROMPTS.npz: 'prompts' [N, L] int64 (0-padded) + optional 'lens'
    # [N]. Greedy (default) writes OUT.npz 'tokens' [N, max_new] padded
    # with -1 after each transcript plus 'n_tokens' [N]; with BEAM, the
    # best hypothesis per request plus 'scores' [N]. Every request runs
    # through the continuous-batching scheduler — submit all, then wait.
    # --tier serves an explicit artifact tier (e.g. the quantized-KV
    # decode tier under <artifact>/int8/) with the same
    # explicit-missing-tier-raises contract as BatchingPredictor(tier=);
    # without it, PTPU_SERVE_TIER applies as a silent preference.
    tier, argv = _pop_flag(argv, 'tier')
    if len(argv) not in (5, 6, 7):
        print("usage: serve.py decode ARTIFACT_DIR PROMPTS.npz OUT.npz "
              "[MAX_NEW [BEAM]] [--tier T]", file=sys.stderr)
        return 2
    artifact_dir, in_path, out_path = argv[2:5]
    max_new = int(argv[5]) if len(argv) >= 6 else 32
    beam = int(argv[6]) if len(argv) == 7 else None
    decoding = _decoding_module()
    with np.load(in_path) as z:
        prompts = np.asarray(z['prompts'], np.int64)
        lens = (np.asarray(z['lens'], np.int64) if 'lens' in z.files
                else np.full(prompts.shape[0], prompts.shape[1], np.int64))
    with decoding.DecodingPredictor(artifact_dir, tier=tier) as pred:
        streams = [pred.submit(prompts[i, :lens[i]], max_new_tokens=max_new,
                               beam=beam) for i in range(prompts.shape[0])]
        results = [s.result() for s in streams]
        snap = pred.stats.snapshot()
    toks = np.full((len(results), max_new), -1, np.int64)
    n_tok = np.zeros(len(results), np.int64)
    scores = np.zeros(len(results), np.float64)
    for i, r in enumerate(results):
        ids = r[0][0] if beam else np.asarray(r, np.int64)
        if beam:
            scores[i] = r[1][0]
        n_tok[i] = len(ids)
        toks[i, :len(ids)] = ids
    save = {'tokens': toks, 'n_tokens': n_tok}
    if beam:
        save['scores'] = scores
    np.savez(out_path, **save)
    print(json.dumps({'requests': len(results),
                      'tier': snap.get('tier', 'bf16'),
                      'tokens': int(snap['tokens']),
                      'tokens_s': snap['tokens_s'],
                      'occupancy': snap['occupancy'],
                      'ttft_p50_ms': snap['ttft_p50_ms'],
                      'ttft_p99_ms': snap['ttft_p99_ms']}))
    return 0


def _fleet_cli(argv):
    # serve.py fleet ARTIFACT_DIR IN.npz N_REQUESTS [REPLICAS]
    #          [--tier T] [--kind K]
    # Spin up a replica fleet (subprocess workers over the fleet.py
    # frame protocol), replay IN.npz N times through FleetRouter.submit
    # with least-outstanding-work routing, and print fleet throughput,
    # latency percentiles and the per-replica table as JSON — serving-
    # fleet perf measurable without the full bench.py harness.
    # Batching/compiled artifacts: IN.npz holds one request's feed
    # arrays. Decode artifacts: the decode-CLI convention — 'prompts'
    # [N, L] int64 (0-padded) + optional 'lens' [N]; requests cycle
    # through the prompt rows.
    tier, argv = _pop_flag(argv, 'tier')
    kind, argv = _pop_flag(argv, 'kind')
    if len(argv) not in (5, 6):
        print("usage: serve.py fleet ARTIFACT_DIR IN.npz N_REQUESTS "
              "[REPLICAS] [--tier T] [--kind K]", file=sys.stderr)
        return 2
    artifact_dir, in_path, n = argv[2], argv[3], int(argv[4])
    replicas = int(argv[5]) if len(argv) == 6 else 2
    try:
        from . import fleet as _fleet
    except ImportError:  # run by file path: fleet.py sits alongside
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import fleet as _fleet
    with np.load(in_path) as z:
        raw = {k: z[k] for k in z.files}
    with _fleet.FleetRouter(artifact_dir, replicas=replicas,
                            kind=kind or 'auto', tier=tier) as router:
        if router.kind == 'decoding':
            prompts = np.asarray(raw['prompts'], np.int64)
            lens = (np.asarray(raw['lens'], np.int64)
                    if 'lens' in raw else np.full(
                        prompts.shape[0], prompts.shape[1], np.int64))
            requests = [prompts[i % prompts.shape[0],
                                :lens[i % prompts.shape[0]]]
                        for i in range(n)]
        else:
            requests = [raw] * n
        t0 = time.perf_counter()
        futs = [router.submit(r) for r in requests]
        for f in futs:
            f.result(600)
        wall = time.perf_counter() - t0
        snap = router.fleet_snapshot()
    out = {'requests': n, 'replicas': replicas,
           'req_s': round(n / wall, 2), 'tier': snap['tier'],
           'p50_ms': snap['p50_ms'], 'p99_ms': snap['p99_ms'],
           'rerouted': snap['rerouted'], 'failed': snap['failed'],
           'per_replica': {rid: {'requests': s['requests'],
                                 'occupancy': s['occupancy'],
                                 'spinup_s': s['spinup_s'],
                                 'compiles': s['compiles']}
                           for rid, s in snap['replicas'].items()}}
    print(json.dumps(out))
    return 0


def _gateway_cli(argv):
    # serve.py gateway ARTIFACT_DIR [PORT] [--host H] [--replicas N]
    #          [--tier T] [--kind K] [--tenants TENANTS.json]
    #          [--max-queue N] [--max-inflight N]
    # Serve a replica fleet over HTTP (ISSUE 19): spin up REPLICAS
    # workers behind a FleetRouter, front them with gateway.Gateway,
    # print one {'url': ...} JSON line (flushed — callers poll it),
    # and serve until SIGTERM/SIGINT or an authenticated POST
    # /admin/drain. Shutdown is the graceful-drain contract: stop
    # admitting, finish every in-flight request/stream, close the
    # fleet, exit 0. TENANTS.json: {api_key: {tenant, rate, burst,
    # max_inflight, admin}}; omitted = open/anonymous serving.
    host, argv = _pop_flag(argv, 'host')
    tier, argv = _pop_flag(argv, 'tier')
    kind, argv = _pop_flag(argv, 'kind')
    tenants_path, argv = _pop_flag(argv, 'tenants')
    replicas, argv = _pop_flag(argv, 'replicas')
    max_queue, argv = _pop_flag(argv, 'max-queue')
    max_inflight, argv = _pop_flag(argv, 'max-inflight')
    if len(argv) not in (3, 4):
        print("usage: serve.py gateway ARTIFACT_DIR [PORT] [--host H] "
              "[--replicas N] [--tier T] [--kind K] "
              "[--tenants TENANTS.json] [--max-queue N] "
              "[--max-inflight N]", file=sys.stderr)
        return 2
    artifact_dir = argv[2]
    port = int(argv[3]) if len(argv) == 4 else 0
    try:
        from . import fleet as _fleet
        from . import gateway as _gateway
    except ImportError:  # run by file path: siblings sit alongside
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import fleet as _fleet
        import gateway as _gateway
    import signal
    import threading
    tenants = (_gateway.tenants_from_json(tenants_path)
               if tenants_path else None)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    with _fleet.FleetRouter(
            artifact_dir, replicas=int(replicas) if replicas else 2,
            kind=kind or 'auto', tier=tier,
            max_queue=int(max_queue) if max_queue else None) as router:
        gw = _gateway.Gateway(
            router, host=host or '127.0.0.1', port=port,
            tenants=tenants,
            max_inflight=int(max_inflight) if max_inflight else None)
        gw.start()
        print(json.dumps({'url': gw.url, 'pid': os.getpid(),
                          'kind': router.kind}), flush=True)
        try:
            while not stop.is_set() \
                    and not gw.drain_requested.is_set():
                if stop.wait(0.2):
                    break
            # SIGTERM/drain: stop admitting, finish in-flight streams
            # (the fleet drain path closes the router after us), exit 0
            gw.drain()
        finally:
            gw.close()
    return 0


def main(argv):
    if len(argv) >= 2 and argv[1] == 'bench':
        return _bench_cli(argv)
    if len(argv) >= 2 and argv[1] == 'loop':
        return _loop_cli(argv)
    if len(argv) >= 2 and argv[1] == 'decode':
        return _decode_cli(argv)
    if len(argv) >= 2 and argv[1] == 'fleet':
        return _fleet_cli(argv)
    if len(argv) >= 2 and argv[1] == 'gateway':
        return _gateway_cli(argv)
    if len(argv) >= 2 and argv[1] == 'train':
        # serve.py train ARTIFACT_DIR FEEDS.npz OUT.npz STEPS [CKPT.npz]
        # runs STEPS train steps on the (fixed) feeds; OUT.npz holds each
        # fetch stacked over steps; CKPT.npz (optional) the final state.
        if len(argv) not in (6, 7):
            print("usage: serve.py train ARTIFACT_DIR FEEDS.npz OUT.npz "
                  "STEPS [CKPT.npz]", file=sys.stderr)
            return 2
        artifact_dir, in_path, out_path, steps = argv[2:6]
        trainer = CompiledTrainer(artifact_dir)
        with np.load(in_path) as data:
            feed = {k: data[k] for k in data.files}
        per_step = [trainer.step(feed) for _ in range(int(steps))]
        np.savez(out_path, **{
            n: np.stack([s[i] for s in per_step])
            for i, n in enumerate(trainer.get_output_names())})
        if len(argv) == 7:
            trainer.save_state(argv[6])
        return 0
    if len(argv) != 4:
        print("usage: serve.py ARTIFACT_DIR IN.npz OUT.npz\n"
              "       serve.py loop ARTIFACT_DIR IN.npz OUT.npz [GROUP]\n"
              "       serve.py train ARTIFACT_DIR FEEDS.npz OUT.npz STEPS "
              "[CKPT.npz]\n"
              "       serve.py bench ARTIFACT_DIR IN.npz N_REQUESTS "
              "[TIMEOUT_MS]\n"
              "       serve.py decode ARTIFACT_DIR PROMPTS.npz OUT.npz "
              "[MAX_NEW [BEAM]] [--tier T]\n"
              "       serve.py fleet ARTIFACT_DIR IN.npz N_REQUESTS "
              "[REPLICAS] [--tier T] [--kind K]\n"
              "       serve.py gateway ARTIFACT_DIR [PORT] [--host H] "
              "[--replicas N] [--tier T] [--kind K] "
              "[--tenants TENANTS.json]", file=sys.stderr)
        return 2
    artifact_dir, in_path, out_path = argv[1:]
    pred = CompiledPredictor(artifact_dir)
    with np.load(in_path) as data:
        raw = {k: data[k] for k in data.files}
    # LoD feeds ride npz as '<name>' plus '<name>.lod<i>' offset arrays
    feed = _feed_from_npz(pred._sig['feeds'], raw)
    outs = pred.run(feed)
    save = {}
    for n, o in zip(pred.get_output_names(), outs):
        if isinstance(o, tuple):
            save[n] = o[0]
            for i, off in enumerate(o[1]):
                save['%s.lod%d' % (n, i)] = off
        else:
            save[n] = o
    np.savez(out_path, **save)
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv))
