"""Predictor serving API (ref: inference/api/analysis_predictor.cc:77-153,
paddle_api.h PaddlePredictor).

TPU-native equivalent of the reference pipeline (load -> IR analysis ->
NaiveExecutor): load -> prune to the feed/fetch subgraph -> jit. The
reference's analysis passes (conv+bn fold, fc fuse, TensorRT subgraphs)
are subsumed by XLA fusion; `clone(for_test)` semantics (BN/dropout in
inference mode) are applied at load when the model was saved from a train
program. The first run compiles (warmable via `warmup`); subsequent runs
hit the executor's compiled-step cache, the NaiveExecutor analogue.
"""
from __future__ import annotations

import os

import numpy as np


class Config(object):
    """AnalysisConfig equivalent: where the model lives + how to run it."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self.ref_format = None   # None = autodetect, True/False to force
        self._place = None

    def set_model(self, model_dir, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file

    def enable_tpu(self):
        from ..framework import TPUPlace
        self._place = TPUPlace()
        return self

    def disable_gpu(self):
        from ..framework import CPUPlace
        self._place = CPUPlace()
        return self


class Predictor(object):
    def __init__(self, config):
        from ..executor import Executor
        from ..core.scope import Scope
        from ..framework import TPUPlace
        self._config = config
        self._scope = Scope()
        self._exe = Executor(config._place or TPUPlace())
        self._program, self._feed_names, self._fetch_vars = self._load()

    # -- loading -----------------------------------------------------------
    def _load(self):
        from ..core.scope import scope_guard
        from .. import io as ptpu_io
        from . import ref_format
        cfg = self._config
        dirname = cfg.model_dir
        model_file = cfg.prog_file
        ref = cfg.ref_format
        if ref is None:
            # autodetect: our save_inference_model writes JSON ('{' first);
            # the reference writes protobuf
            path = os.path.join(dirname, model_file or '__model__')
            with open(path, 'rb') as f:
                first = f.read(1)
            ref = first != b'{'
        with scope_guard(self._scope):
            if ref:
                return ref_format.load_reference_inference_model(
                    dirname, self._exe, model_filename=model_file,
                    params_filename=cfg.params_file, scope=self._scope)
            return ptpu_io.load_inference_model(
                dirname, self._exe, model_filename=model_file,
                params_filename=cfg.params_file)

    # -- serving -----------------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [v.name for v in self._fetch_vars if v is not None]

    def run(self, inputs, return_numpy=True):
        """inputs: list (feed order) or dict name -> array/LoDTensor.
        Returns list of numpy outputs; return_numpy=False skips the host
        sync and returns device arrays (async serving loops sync once)."""
        from ..core.scope import scope_guard
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self._feed_names):
                raise ValueError(
                    "predictor expects %d inputs (%s), got %d"
                    % (len(self._feed_names), self._feed_names, len(inputs)))
            feed = dict(zip(self._feed_names, inputs))
        else:
            feed = dict(inputs)
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=[v.name for v in
                                             self._fetch_vars
                                             if v is not None],
                                 return_numpy=return_numpy)
        if not return_numpy:
            return list(outs)
        return [np.asarray(o) for o in outs]

    def warmup(self, sample_inputs):
        """Compile ahead of serving (the reference predictor's Prepare)."""
        self.run(sample_inputs)
        return self

    def clone(self):
        """A predictor sharing this one's weights (ref scope sharing for
        multi-thread serving, analysis_predictor.cc Clone)."""
        twin = Predictor.__new__(Predictor)
        twin._config = self._config
        twin._scope = self._scope           # shared weights
        twin._exe = self._exe               # shared compiled cache
        twin._program = self._program
        twin._feed_names = self._feed_names
        twin._fetch_vars = self._fetch_vars
        return twin


def create_predictor(config):
    return Predictor(config)
