"""Predictor serving API (ref: inference/api/analysis_predictor.cc:77-153,
paddle_api.h PaddlePredictor).

TPU-native equivalent of the reference pipeline (load -> IR analysis ->
NaiveExecutor): load -> prune to the feed/fetch subgraph -> jit. The
reference's analysis passes (conv+bn fold, fc fuse, TensorRT subgraphs)
are subsumed by XLA fusion; `clone(for_test)` semantics (BN/dropout in
inference mode) are applied at load when the model was saved from a train
program. The first run compiles (warmable via `warmup`); subsequent runs
hit the executor's compiled-step cache, the NaiveExecutor analogue.
"""
from __future__ import annotations

import os

import numpy as np


class Config(object):
    """AnalysisConfig equivalent: where the model lives + how to run it."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self.ref_format = None   # None = autodetect, True/False to force
        self._place = None

    def set_model(self, model_dir, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file

    def enable_tpu(self):
        from ..framework import TPUPlace
        self._place = TPUPlace()
        return self

    def disable_gpu(self):
        from ..framework import CPUPlace
        self._place = CPUPlace()
        return self


class Predictor(object):
    def __init__(self, config):
        from ..executor import Executor
        from ..core.scope import Scope
        from ..framework import TPUPlace
        self._config = config
        self._scope = Scope()
        self._exe = Executor(config._place or TPUPlace())
        # bulk dispatches (run_batches) report as an inference source in
        # the profiler, not a training one
        self._exe._profile_role = 'infer'
        self._program, self._feed_names, self._fetch_vars = self._load()

    # -- loading -----------------------------------------------------------
    def _load(self):
        from ..core.scope import scope_guard
        from .. import io as ptpu_io
        from . import ref_format
        cfg = self._config
        dirname = cfg.model_dir
        model_file = cfg.prog_file
        ref = cfg.ref_format
        if ref is None:
            # autodetect: our save_inference_model writes JSON ('{' first);
            # the reference writes protobuf
            path = os.path.join(dirname, model_file or '__model__')
            with open(path, 'rb') as f:
                first = f.read(1)
            ref = first != b'{'
        with scope_guard(self._scope):
            if ref:
                return ref_format.load_reference_inference_model(
                    dirname, self._exe, model_filename=model_file,
                    params_filename=cfg.params_file, scope=self._scope)
            return ptpu_io.load_inference_model(
                dirname, self._exe, model_filename=model_file,
                params_filename=cfg.params_file)

    # -- serving -----------------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [v.name for v in self._fetch_vars if v is not None]

    def _normalize_feed(self, inputs):
        """List (feed order) or dict -> feed dict; shared by run() and
        run_batches()."""
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self._feed_names):
                raise ValueError(
                    "predictor expects %d inputs (%s), got %d"
                    % (len(self._feed_names), self._feed_names, len(inputs)))
            return dict(zip(self._feed_names, inputs))
        return dict(inputs)

    def run(self, inputs, return_numpy=True):
        """inputs: list (feed order) or dict name -> array/LoDTensor.
        Returns list of numpy outputs; return_numpy=False skips the host
        sync and returns device arrays (async serving loops sync once)."""
        from ..core.scope import scope_guard
        feed = self._normalize_feed(inputs)
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=[v.name for v in
                                             self._fetch_vars
                                             if v is not None],
                                 return_numpy=return_numpy)
        if not return_numpy:
            return list(outs)
        return [np.asarray(o) for o in outs]

    def run_batches(self, batches, return_numpy=True):
        """Bulk offline/eval inference: run K pre-staged batches in ONE
        device dispatch (the Executor's multi-step lax.scan machinery,
        fetch_policy='stack'), amortizing the fixed per-dispatch cost
        across all K — per-batch results are bit-identical to K
        sequential `run()` calls.

        batches: list of K per-batch inputs, each a list (feed order) or
        dict name -> array/LoDTensor exactly as `run()` takes; every
        batch must share one compiled shape (LoD batches one bucket).
        Returns a list of K per-batch output lists."""
        from ..core.scope import scope_guard
        batches = list(batches)
        if not batches:
            return []
        feeds = [self._normalize_feed(b) for b in batches]
        missing = [n for n in self._feed_names
                   if any(n not in f for f in feeds)]
        if missing:
            raise ValueError("batches missing feeds: %r (predictor "
                             "expects %s)" % (missing, self._feed_names))
        grouped = {n: [f[n] for f in feeds] for n in self._feed_names}
        with scope_guard(self._scope):
            outs = self._exe.run_steps(
                self._program, feed=grouped,
                fetch_list=[v.name for v in self._fetch_vars
                            if v is not None],
                fetch_policy='stack', return_numpy=return_numpy)
        k = len(batches)
        return [[o[i] if not return_numpy else np.asarray(o[i])
                 for o in outs] for i in range(k)]

    def warmup(self, sample_inputs):
        """Compile ahead of serving (the reference predictor's Prepare)."""
        self.run(sample_inputs)
        return self

    def clone(self):
        """A predictor sharing this one's weights (ref scope sharing for
        multi-thread serving, analysis_predictor.cc Clone)."""
        twin = Predictor.__new__(Predictor)
        twin._config = self._config
        twin._scope = self._scope           # shared weights
        twin._exe = self._exe               # shared compiled cache
        twin._program = self._program
        twin._feed_names = self._feed_names
        twin._fetch_vars = self._fetch_vars
        return twin


def create_predictor(config):
    return Predictor(config)
