"""Serving fleet control plane (ISSUE 12 tentpole).

`FleetRouter` fronts N warm replicas of any existing predictor
(`BatchingPredictor`, `DecodingPredictor`, `CompiledPredictor`) behind
one `submit()` API — the fleet tier the single-process serving stack
(rounds 6/8/11/14) was built to feed:

1. **Replica subprocess workers** — each replica is a `fleet_worker.py`
   subprocess that loads the artifact FRAMEWORK-FREE (AOT sidecars +
   `cache_ctl prewarm` make spin-up warm and compile-free) and speaks a
   small length-prefixed frame protocol over a unix socket (JSON header
   + optional npz body; `_send_frame`/`_recv_frame` below are the whole
   wire format).
2. **Least-outstanding-work routing with deadline propagation** — a
   request goes to the serving replica with the fewest outstanding +
   queued requests; at most `inflight_per_replica` frames are in a
   replica at once, the rest wait in a router-side per-replica queue
   (re-routable). A request's `deadline_ms` is re-computed to the
   REMAINING budget when the frame is actually written, so time spent
   queued at the router counts against the same deadline the replica
   enforces.
3. **Health-checked failover** — replicas write heartbeat files (the
   round-13 pod pattern: atomic replace, mtime = liveness, payload =
   serving stats); the router's watchdog detects a dead replica (socket
   EOF / process exit) or a HUNG one (heartbeat stale -> SIGKILL) in
   bounded time. Its router-side queued requests re-route to healthy
   replicas; its in-flight requests fail LOUDLY with `ReplicaFailed` —
   never silently dropped. Replica-shed requests (`ServerOverloaded`,
   never dispatched to the device) re-route automatically.
4. **Autoscaler** — scales out/in on the occupancy / queue-depth /
   shed-rate counters the serving stats already measure; scale-in
   DRAINS: the victim stops admitting, finishes its in-flight decode
   streams / batch dispatches (predictor `drain()` hooks), hands its
   queue back for re-routing, then retires.
5. **RollingRollout** — canaries a new artifact tier (e.g. `int8/` from
   round 14) on one replica, promotes on parity + latency-budget checks
   against the incumbent (canary determinism is checked BIT-exactly;
   incumbent agreement per tier policy: 'bit' for same-tier, 'top1' /
   transcript for quantized), then rolls the fleet one replica at a
   time (spawn-before-drain, capacity never dips). Any failed check
   rolls back LOUDLY (`RolloutRolledBack`).

Serving metrics flow to `paddle_tpu.profiler` via
`register_fleet_source` / `fleet_report` (per-replica occupancy, queue
depth, reroutes, p50/p99 TTFT and latency, scale events, rollout
state), rendered alongside the existing serving tables.

Framework-free: imports only stdlib + numpy (+ sibling serve.py /
batching.py / decoding.py, all framework-free); a router process never
imports jax at all — the replicas do the serving.
"""
import io
import itertools
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future

import numpy as np

try:
    from . import serve as _serve
    from . import batching as _batching
    from . import decoding as _decoding
except ImportError:  # imported by file path: siblings sit alongside
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve as _serve
    import batching as _batching
    import decoding as _decoding

_maybe_profiler = _serve._maybe_profiler
_SOURCE_SEQ = _serve._SOURCE_SEQ
_percentiles = _decoding._percentiles
_resolve = _batching._resolve
ServerOverloaded = _batching.ServerOverloaded
DeadlineExceeded = _batching.DeadlineExceeded

class _Unset(object):
    """Keyword-default sentinel (tier=_UNSET means "keep the current
    tier", while tier=None means "the bf16 default tier"). Stable repr
    so API.spec stays reproducible across processes."""
    __slots__ = ()

    def __repr__(self):
        return '<keep-current>'


_UNSET = _Unset()
# wire sanity bound: a frame beyond this is protocol corruption, not data
_MAX_FRAME = 1 << 31


class ReplicaFailed(RuntimeError):
    """The replica serving this request died (or hung past the heartbeat
    timeout and was killed) while the request was IN FLIGHT. The request
    may or may not have produced device work; the fleet fails it loudly
    rather than retrying (a side-effect-free caller may resubmit)."""


class FleetUnavailable(RuntimeError):
    """No serving replica exists to route to (all dead/draining and the
    autoscaler has not replaced them)."""


class RolloutRolledBack(RuntimeError):
    """A rolling rollout failed a parity/latency check and was rolled
    back: the canary is retired, the incumbent fleet is untouched."""


# -- wire protocol -----------------------------------------------------------
# frame := u64 len | u32 header_len | header json | [npz body]
# The npz body carries every array of the message (numpy's own binary
# format — versioned, validated, no pickle). fleet_worker.py imports
# these two functions; together they are the complete wire format.

def _send_frame(sock, header, arrays=None):
    hb = json.dumps(header).encode('utf-8')
    body = b''
    if arrays:
        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
        body = buf.getvalue()
    payload = struct.pack('>I', len(hb)) + hb + body
    sock.sendall(struct.pack('>Q', len(payload)) + payload)


def _recv_exact(sock, n):
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b''.join(chunks)


def _recv_frame(sock):
    """One (header dict, {name: array}) message; None on clean EOF."""
    head = _recv_exact(sock, 8)
    if head is None:
        return None
    (n,) = struct.unpack('>Q', head)
    if not 4 <= n <= _MAX_FRAME:
        raise IOError('fleet protocol: bad frame length %d' % n)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    (hn,) = struct.unpack('>I', payload[:4])
    header = json.loads(payload[4:4 + hn].decode('utf-8'))
    arrays = {}
    if len(payload) > 4 + hn:
        with np.load(io.BytesIO(payload[4 + hn:]),
                     allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    return header, arrays


# -- replica heartbeat files (round-13 pattern, framework-free copy) ---------

def write_heartbeat(path, payload):
    """Atomic heartbeat refresh: mtime is the liveness signal, the JSON
    payload carries the replica's serving stats (flock-free by design —
    a hung filesystem lock must never stall the writer)."""
    rec = dict(payload)
    rec['time'] = time.time()
    tmp = '%s.%d.tmp' % (path, os.getpid())
    with open(tmp, 'w') as f:
        f.write(json.dumps(rec))
    os.replace(tmp, path)
    return path


def read_heartbeat(path):
    """(payload, age_s); ({}, inf) when absent/unreadable."""
    try:
        age = time.time() - os.path.getmtime(path)
        with open(path) as f:
            return json.load(f), age
    except (OSError, ValueError):
        try:
            return {}, time.time() - os.path.getmtime(path)
        except OSError:
            return {}, float('inf')


def detect_kind(artifact_dir):
    """The worker kind an artifact serves through: 'decoding' for
    export_decode's two-program layout, 'batching' for (multi-bucket)
    dense compiled artifacts. 'compiled' (synchronous CompiledPredictor,
    LoD-capable) is never auto-detected — request it explicitly."""
    if os.path.exists(os.path.join(artifact_dir,
                                   _decoding._DECODE_SIGNATURE)):
        return 'decoding'
    if os.path.exists(os.path.join(artifact_dir, _serve._SIGNATURE)):
        return 'batching'
    raise ValueError(
        '%s is not a serving artifact (no %s / %s)'
        % (artifact_dir, _decoding._DECODE_SIGNATURE, _serve._SIGNATURE))


_EXC_TYPES = {
    'DeadlineExceeded': DeadlineExceeded,
    'ServerOverloaded': ServerOverloaded,
    'ValueError': ValueError,
    'TimeoutError': TimeoutError,
}


def _rebuild_exc(header):
    cls = _EXC_TYPES.get(header.get('etype'), RuntimeError)
    return cls(header.get('error', 'replica error'))


class _FleetRequest(object):
    __slots__ = ('id', 'header', 'arrays', 'future', 't_submit',
                 'deadline', 'attempts', 'on_token', 't_first', 'replica',
                 'request_id')

    def __init__(self, rid, header, arrays, deadline_ms, on_token=None,
                 request_id=None):
        self.id = rid
        self.header = header        # op + per-op fields (no id/deadline)
        self.arrays = arrays
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = (self.t_submit + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.attempts = 0
        self.on_token = on_token
        self.t_first = None         # first token/result arrival
        self.replica = None
        self.request_id = request_id  # caller trace id (gateway etc.)


def _rid_suffix(req):
    """' (request <id>)' when the caller tagged the request — every
    router-originated error names something the caller can correlate."""
    return ' (request %s)' % req.request_id if req.request_id else ''


class _Replica(object):
    """Router-side view of one replica subprocess."""

    def __init__(self, rid, spec):
        self.rid = rid
        self.spec = dict(spec)      # artifact/tier/kind/opts (+canary)
        self.proc = None
        self.sock = None
        self.state = 'starting'     # -> serving|canary -> draining ->
        #                              retiring -> retired; or dead
        self.outstanding = {}       # request id -> _FleetRequest
        self.pending = deque()      # router-side queue (re-routable)
        self.send_lock = threading.Lock()
        self.hello = {}
        self.hb = {}
        self.hb_age = float('inf')
        self.ready_evt = threading.Event()
        self.drained_evt = threading.Event()
        self.reader_t = None
        self.t_spawn = time.perf_counter()
        self.spinup_s = None

    @property
    def load(self):
        return len(self.outstanding) + len(self.pending)

    def snapshot(self):
        stats = self.hb.get('stats', {}) or {}
        return {'state': self.state,
                'pid': self.proc.pid if self.proc else None,
                # the artifact the worker REPORTS serving (hello, then
                # heartbeats): lets an operator map a wedged replica
                # row to a process + on-disk artifact (ISSUE 19)
                'artifact': (self.hb.get('artifact')
                             or self.hello.get('artifact')
                             or self.spec.get('artifact')),
                'tier': self.hello.get('tier', self.spec.get('tier')
                                       or 'bf16'),
                # decode artifacts: cache layout + mesh tag the worker
                # actually loaded (ISSUE 13 block/sharded tiers)
                'layout': self.hello.get('layout'),
                'mesh': self.hello.get('mesh'),
                'outstanding': len(self.outstanding),
                'pending': len(self.pending),
                'hb_age_s': (round(self.hb_age, 3)
                             if self.hb_age != float('inf') else None),
                'compiles': self.hello.get('compiles'),
                'spinup_s': self.spinup_s,
                'occupancy': stats.get('occupancy', 0.0),
                'queue_depth': stats.get('queue_depth', 0),
                'requests': stats.get('requests', 0),
                'shed': stats.get('shed', 0),
                'stats': stats}


class FleetStats(object):
    """Thread-safe fleet counters + latency/TTFT windows + a bounded
    event log (deaths, reroutes, scale and rollout transitions)."""

    def __init__(self, window=8192, max_events=512):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=window)
        self._ttft = deque(maxlen=window)
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rerouted = 0
        self.shed = 0
        self.expired = 0
        self.replica_deaths = 0
        self.scale_out = 0
        self.scale_in = 0
        self.events = deque(maxlen=max_events)
        self.rollout = {'state': 'idle'}

    def reset(self):
        """Zero the counters and latency/TTFT windows (the event log
        stays): separates a warmup/calibration phase from the measured
        run — the ServingStats.reset discipline."""
        with self._lock:
            self._lat.clear()
            self._ttft.clear()
            self.submitted = 0
            self.completed = 0
            self.failed = 0
            self.rerouted = 0
            self.shed = 0
            self.expired = 0

    def record_event(self, kind, replica=None, reason=None):
        with self._lock:
            self.events.append({'time': time.time(), 'kind': kind,
                                'replica': replica, 'reason': reason})

    def record_done(self, latency_s, ttft_s):
        with self._lock:
            self.completed += 1
            self._lat.append(latency_s)
            if ttft_s is not None:
                self._ttft.append(ttft_s)

    def snapshot(self):
        with self._lock:
            p50, p99 = _percentiles(list(self._lat), [50, 99])
            t50, t99 = _percentiles(list(self._ttft), [50, 99])
            return {'submitted': int(self.submitted),
                    'completed': int(self.completed),
                    'failed': int(self.failed),
                    'rerouted': int(self.rerouted),
                    'shed': int(self.shed),
                    'expired': int(self.expired),
                    'replica_deaths': int(self.replica_deaths),
                    'scale_out': int(self.scale_out),
                    'scale_in': int(self.scale_in),
                    'p50_ms': p50, 'p99_ms': p99,
                    'ttft_p50_ms': t50, 'ttft_p99_ms': t99,
                    'rollout': dict(self.rollout),
                    'events': list(self.events)[-8:]}


def _worker_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'fleet_worker.py')


class FleetRouter(object):
    """Route requests across N warm replica subprocesses of one serving
    artifact.

    submit(...) -> Future        route one request (kind-dependent args)
    scale_out() / scale_in()     add a replica / drain + retire one
    drain_replica(rid)           draining stop: finish in-flight, retire
    status()                     full fleet view (also fleet_dir/status.json)
    fleet_snapshot()             profiler fleet-source contract
    close()                      stop every replica and router thread

    `kind` ('auto' default) picks the worker endpoint: 'batching'
    (dense request/response through BatchingPredictor), 'decoding'
    (token streams through DecodingPredictor), or 'compiled'
    (synchronous CompiledPredictor — the LoD-capable fallback).
    `tier` spawns every replica on that artifact tier (the
    BatchingPredictor(tier=) explicit-missing-raises contract applies
    in the worker). `fleet_dir` holds the control socket, heartbeat
    files, control files and status.json (a temp dir by default).
    """

    def __init__(self, artifact_dir, replicas=2, kind='auto', tier=None,
                 platform=None, fleet_dir=None, max_queue=None,
                 inflight_per_replica=8, hb_timeout_s=5.0, poll_s=0.2,
                 spinup_timeout_s=300.0, max_route_attempts=4,
                 worker_opts=None, warmup=True, stats_window=8192):
        self.artifact_dir = artifact_dir
        self.kind = detect_kind(artifact_dir) if kind == 'auto' else kind
        if self.kind not in ('batching', 'decoding', 'compiled'):
            raise ValueError('unknown fleet kind %r' % (self.kind,))
        self._spec = {'artifact': artifact_dir, 'tier': tier,
                      'kind': self.kind, 'platform': platform,
                      'warmup': bool(warmup),
                      'opts': dict(worker_opts or {})}
        self._max_queue = int(max_queue) if max_queue else None
        self._inflight = max(1, int(inflight_per_replica))
        self.hb_timeout_s = float(hb_timeout_s)
        self._poll_s = float(poll_s)
        self._spinup_timeout_s = float(spinup_timeout_s)
        self._max_attempts = max(1, int(max_route_attempts))
        self._feed_names = self._load_feed_names(artifact_dir)
        self.stats = FleetStats(stats_window)
        self._replicas = {}
        self._next_rid = itertools.count()
        self._req_ids = itertools.count()
        self._lock = threading.RLock()
        self._closed = False
        if fleet_dir is None:
            fleet_dir = tempfile.mkdtemp(prefix='ptpu_fleet_')
        self.fleet_dir = fleet_dir
        os.makedirs(os.path.join(fleet_dir, 'hb'), exist_ok=True)
        os.makedirs(os.path.join(fleet_dir, 'ctl'), exist_ok=True)
        self._sock_path = self._make_sock_path(fleet_dir)
        self._listener = socket.socket(socket.AF_UNIX,
                                       socket.SOCK_STREAM)
        self._listener.bind(self._sock_path)
        self._listener.listen(64)
        self._accept_t = threading.Thread(
            target=self._accept_loop, name='ptpu-fleet-accept',
            daemon=True)
        self._accept_t.start()
        self._stop_evt = threading.Event()
        self._watchdog_t = threading.Thread(
            target=self._watchdog_loop, name='ptpu-fleet-watchdog',
            daemon=True)
        self._watchdog_t.start()
        self._profiler_name = None
        prof = _maybe_profiler()
        if prof is not None and hasattr(prof, 'register_fleet_source'):
            name = 'fleet:%s#%d' % (
                os.path.basename(os.path.normpath(artifact_dir)),
                next(_SOURCE_SEQ))
            prof.register_fleet_source(name, self.fleet_snapshot)
            self._profiler_name = name
        try:
            rids = [self._spawn(self._spec, wait=False)
                    for _ in range(int(replicas))]
            for rid in rids:
                self._await_ready(rid)
        except Exception:
            self.close()
            raise
        self._write_status()

    # -- construction helpers ---------------------------------------------
    def _make_sock_path(self, fleet_dir):
        p = os.path.join(fleet_dir, 'router.sock')
        self._sock_tmpdir = None
        if len(p) > 96:  # AF_UNIX sun_path limit (~107); pytest tmp
            # paths routinely exceed it — fall back to a short /tmp dir
            # (remembered so close() can remove it)
            self._sock_tmpdir = tempfile.mkdtemp(prefix='ptpu_fl_')
            p = os.path.join(self._sock_tmpdir, 'router.sock')
        if os.path.exists(p):
            os.unlink(p)
        return p

    def _load_feed_names(self, artifact_dir):
        if self.kind == 'decoding':
            return None
        try:
            with open(os.path.join(artifact_dir, _serve._SIGNATURE)) as f:
                return [e['name'] for e in json.load(f)['feeds']]
        except Exception:
            return None

    # -- replica lifecycle -------------------------------------------------
    def _hb_path(self, rid):
        return os.path.join(self.fleet_dir, 'hb',
                            'replica_%d.json' % rid)

    def _spawn(self, spec, wait=True, canary=False):
        """Start one replica subprocess; returns its rid. With wait, the
        call blocks until the worker's hello (warm + ready) or raises."""
        with self._lock:
            if self._closed:
                raise RuntimeError('FleetRouter is closed')
            rid = next(self._next_rid)
            sp = dict(spec)
            sp['canary'] = bool(canary)
            rep = _Replica(rid, sp)
            self._replicas[rid] = rep
        hb = self._hb_path(rid)
        if os.path.exists(hb):
            os.unlink(hb)
        opts = dict(sp.get('opts') or {})
        opts.setdefault('kind', sp['kind'])
        if sp.get('tier'):
            opts.setdefault('tier', sp['tier'])
        if sp.get('platform'):
            opts.setdefault('platform', sp['platform'])
        opts.setdefault('warmup', sp.get('warmup', True))
        argv = [sys.executable, _worker_path(), self._sock_path,
                str(rid), sp['artifact'], hb, json.dumps(opts)]
        rep.proc = subprocess.Popen(
            argv, stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
            start_new_session=True)
        self.stats.record_event('spawn', rid,
                                'tier=%s' % (sp.get('tier') or 'bf16'))
        if wait:
            self._await_ready(rid)
        return rid

    def _await_ready(self, rid):
        rep = self._replicas[rid]
        if not rep.ready_evt.wait(self._spinup_timeout_s) \
                or rep.state not in ('serving', 'canary'):
            self._on_replica_failure(rep, 'failed to start (state %r)'
                                     % rep.state)
            raise RuntimeError(
                'fleet replica %d failed to start within %.0fs '
                '(state %r) — see its stderr above'
                % (rid, self._spinup_timeout_s, rep.state))
        return rid

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._handshake, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn):
        try:
            conn.settimeout(self._spinup_timeout_s)
            fr = _recv_frame(conn)
            if fr is None:
                conn.close()
                return
            hdr, _ = fr
            if hdr.get('op') != 'hello':
                raise IOError('expected hello, got %r' % hdr.get('op'))
            rid = int(hdr['replica'])
            conn.settimeout(None)
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None or rep.state != 'starting':
                    conn.close()
                    return
                rep.sock = conn
                rep.hello = hdr
                rep.spinup_s = round(
                    time.perf_counter() - rep.t_spawn, 3)
                rep.state = ('canary' if rep.spec.get('canary')
                             else 'serving')
                rep.reader_t = threading.Thread(
                    target=self._reader_loop, args=(rep,),
                    name='ptpu-fleet-reader-%d' % rid, daemon=True)
                rep.reader_t.start()
            rep.ready_evt.set()
        except Exception as e:
            warnings.warn('fleet: replica handshake failed (%s: %s)'
                          % (type(e).__name__, e), RuntimeWarning)
            try:
                conn.close()
            except OSError:
                pass

    # -- request path ------------------------------------------------------
    def submit(self, inputs, deadline_ms=None, max_new_tokens=None,
               beam=None, on_token=None, request_id=None):
        """Route one request; returns a Future.

        batching/compiled fleets: `inputs` is a dict (or feed-order
        list) of per-request arrays, exactly as the underlying
        predictor's submit/run takes; the future resolves to the
        per-fetch output list. decoding fleets: `inputs` is the prompt
        id sequence; `max_new_tokens`/`beam` as DecodingPredictor; the
        future resolves to the transcript (greedy: token list, beam:
        (ids, scores)); `on_token(tok)` streams greedy tokens as they
        decode. `deadline_ms` propagates: router queue time counts
        against the same budget the replica enforces.

        `on_token` contract: called once per token, in transcript
        order, from the router's reader thread. Delivery granularity
        follows the replica's advance granularity — a speculatively
        decoding replica (ISSUE 17) coalesces each verify tick's whole
        multi-token advance into ONE wire frame, and the router then
        fires `on_token` for each token of the batch back-to-back, so
        several calls may land with no network round-trip between them.
        Callbacks must not assume one frame (or one decode step) per
        call; exceptions are swallowed (a streaming callback can never
        kill the reader).

        `request_id` is an optional caller trace id: it rides the wire
        frame header into the replica's serving stats and is named in
        every shed/expiry/failure message for this request."""
        if self._closed:
            raise RuntimeError('FleetRouter is closed')
        header, arrays = self._encode_request(inputs, max_new_tokens,
                                              beam, on_token)
        req = _FleetRequest(next(self._req_ids), header, arrays,
                            deadline_ms, on_token, request_id=request_id)
        with self.stats._lock:
            self.stats.submitted += 1
        self._route(req)
        return req.future

    def run(self, inputs, timeout=None, **kw):
        """Synchronous convenience: submit + wait."""
        return self.submit(inputs, **kw).result(timeout)

    def _encode_request(self, inputs, max_new_tokens, beam, on_token):
        if self.kind == 'decoding':
            prompt = np.asarray(inputs, np.int64).reshape(-1)
            header = {'op': 'decode',
                      'stream': beam is None}
            if max_new_tokens is not None:
                header['max_new'] = int(max_new_tokens)
            if beam is not None:
                header['beam'] = int(beam)
            return header, {'prompt': prompt}
        if max_new_tokens is not None or beam is not None \
                or on_token is not None:
            raise ValueError('max_new_tokens/beam/on_token apply to '
                             'decoding fleets only')
        if isinstance(inputs, (list, tuple)):
            if self._feed_names is None \
                    or len(inputs) != len(self._feed_names):
                raise ValueError(
                    'fleet expects %s inputs, got %d'
                    % (self._feed_names, len(inputs)))
            inputs = dict(zip(self._feed_names, inputs))
        arrays = {}
        for name, value in inputs.items():
            if isinstance(value, tuple) and len(value) == 2:
                data, offs = value  # LoD pair -> npz convention
                if isinstance(offs, np.ndarray) and offs.ndim == 1:
                    offs = [offs]
                arrays[name] = np.asarray(data)
                for i, o in enumerate(offs):
                    arrays['%s.lod%d' % (name, i)] = np.asarray(
                        o, np.int32)
            else:
                arrays[name] = np.asarray(value)
        for name, arr in arrays.items():
            if arr.dtype.kind == 'O':
                # npz needs pickle for object arrays and the worker
                # loads with allow_pickle=False: fail THIS request at
                # submit instead of poisoning a replica's frame stream
                raise ValueError(
                    'feed %r is an object array (dtype=object) — the '
                    'fleet protocol carries numeric/bytes arrays only'
                    % name)
        return {'op': 'infer'}, arrays

    def _route(self, req):
        """Pick the serving replica with the least outstanding work;
        send now if it has frame capacity, else queue router-side
        (re-routable on replica death/drain)."""
        send_to = None
        with self._lock:
            if req.attempts >= self._max_attempts:
                self._fail_req(req, RuntimeError(
                    'request re-routed %d times without finding a '
                    'stable replica%s' % (req.attempts,
                                          _rid_suffix(req))))
                return
            candidates = [r for r in self._replicas.values()
                          if r.state == 'serving']
            if not candidates:
                self._fail_req(req, FleetUnavailable(
                    'no serving replicas (fleet %s)%s'
                    % ('closed' if self._closed else 'degraded',
                       _rid_suffix(req))))
                return
            if self._max_queue is not None and not req.attempts:
                depth = sum(len(r.pending) for r in candidates)
                if depth >= self._max_queue:
                    with self.stats._lock:
                        self.stats.shed += 1
                    self._fail_req(req, ServerOverloaded(
                        'fleet queue depth %d >= max_queue %d — '
                        'request shed%s' % (depth, self._max_queue,
                                            _rid_suffix(req))),
                        count_failed=False)
                    return
            rep = min(candidates, key=lambda r: (r.load, r.rid))
            req.attempts += 1
            req.replica = rep.rid
            if len(rep.outstanding) < self._inflight:
                rep.outstanding[req.id] = req
                send_to = rep
            else:
                rep.pending.append(req)
        if send_to is not None:
            self._send(send_to, req)

    def _send(self, rep, req):
        """Write the request frame (OUTSIDE the router lock: a wedged
        worker's full socket must never block the watchdog)."""
        remaining = None
        if req.deadline is not None:
            remaining = (req.deadline - time.perf_counter()) * 1e3
            if remaining <= 0:
                with self._lock:
                    rep.outstanding.pop(req.id, None)
                with self.stats._lock:
                    self.stats.expired += 1
                self._fail_req(req, DeadlineExceeded(
                    'request expired in the router queue%s'
                    % _rid_suffix(req)),
                    count_failed=False)
                # NO _pump here: _pump calls _send, and a burst of
                # simultaneously-expired queued requests would recurse
                # _pump->_send->_pump into a RecursionError inside the
                # reader thread. _pump's own while-loop (and the
                # watchdog tick) refills the freed slot iteratively.
                return
        hdr = dict(req.header)
        hdr['id'] = req.id
        if remaining is not None:
            hdr['deadline_ms'] = remaining
        if req.request_id is not None:
            hdr['request_id'] = req.request_id
        try:
            # no send timeout: a wedged worker's full socket buffer can
            # block sendall only until the watchdog SIGKILLs it
            # (hb_timeout_s) — the close unblocks the send with an error
            with rep.send_lock:
                _send_frame(rep.sock, hdr, req.arrays)
        except Exception as e:
            # the worker never received the frame: re-route this request
            # and declare the replica failed. Re-route ONLY if we still
            # own the entry — the watchdog may have declared the replica
            # dead concurrently and already failed this future with
            # ReplicaFailed (re-routing then would re-execute a request
            # the caller already saw fail)
            with self._lock:
                owned = rep.outstanding.pop(req.id, None) is not None
            self._on_replica_failure(rep, 'send failed: %s' % (e,))
            if owned and not req.future.done():
                with self.stats._lock:
                    self.stats.rerouted += 1
                self._route(req)

    def _pump(self, rep):
        """Move router-side queued requests into the replica as frame
        capacity frees up."""
        while True:
            with self._lock:
                if rep.state not in ('serving', 'canary') \
                        or not rep.pending \
                        or len(rep.outstanding) >= self._inflight:
                    return
                req = rep.pending.popleft()
                rep.outstanding[req.id] = req
            self._send(rep, req)

    def _fail_req(self, req, exc, count_failed=True):
        if count_failed:
            with self.stats._lock:
                self.stats.failed += 1
        if req.request_id is not None:
            # tagged requests leave a correlatable trace in the fleet
            # event log (surfaces in fleet_snapshot()['events'])
            self.stats.record_event(
                'request_failed', req.replica,
                '%s: %s' % (req.request_id, type(exc).__name__))
        _resolve(req.future, exc=exc)

    # -- replica -> router frames ------------------------------------------
    def _reader_loop(self, rep):
        sock = rep.sock
        while True:
            try:
                fr = _recv_frame(sock)
            except Exception as e:
                # EOF surfaces as None below; anything else (bad frame
                # length, unparseable header/body) means the stream is
                # desynced — the connection is unusable either way, and
                # the reader dying SILENTLY would strand every
                # outstanding future on a replica still marked serving
                if not isinstance(e, (OSError, IOError)):
                    warnings.warn(
                        'fleet: protocol error from replica %d (%s: '
                        '%s)' % (rep.rid, type(e).__name__, e),
                        RuntimeWarning)
                fr = None
            if fr is None:
                if rep.state not in ('retiring', 'retired', 'dead'):
                    self._on_replica_failure(rep, 'connection lost')
                return
            hdr, arrays = fr
            op = hdr.get('op')
            if op == 'result':
                self._on_result(rep, hdr, arrays)
            elif op == 'tok':
                self._on_tok(rep, hdr)
            elif op == 'toks':
                # coalesced multi-token frame (ISSUE 17): one frame per
                # speculative verify tick, on_token fired per token
                self._on_toks(rep, hdr)
            elif op == 'drained':
                rep.drained_evt.set()
            # 'bye' and unknown ops: nothing to do

    def _on_tok(self, rep, hdr):
        req = rep.outstanding.get(hdr.get('id'))
        if req is None:
            return
        now = time.perf_counter()
        if req.t_first is None:
            req.t_first = now
        if req.on_token is not None:
            try:
                req.on_token(int(hdr['tok']))
            except Exception:
                pass  # a streaming callback must never kill the reader

    def _on_toks(self, rep, hdr):
        """One coalesced frame per speculative verify tick (ISSUE 17):
        `on_token` fires per token, in order — the callback contract is
        unchanged, only the framing is batched."""
        req = rep.outstanding.get(hdr.get('id'))
        if req is None:
            return
        now = time.perf_counter()
        if req.t_first is None:
            req.t_first = now
        if req.on_token is not None:
            for t in hdr.get('toks', ()):
                try:
                    req.on_token(int(t))
                except Exception:
                    pass  # a streaming callback must never kill the reader

    def _on_result(self, rep, hdr, arrays):
        with self._lock:
            req = rep.outstanding.pop(hdr.get('id'), None)
        if req is not None:
            if hdr.get('ok'):
                now = time.perf_counter()
                result = self._decode_result(hdr, arrays)
                # TTFT is recorded only when a first token was actually
                # MEASURED (greedy decode streams): for request/response
                # kinds and beam decodes the column would silently
                # duplicate total latency
                ttft = (req.t_first - req.t_submit
                        if req.t_first is not None else None)
                self.stats.record_done(now - req.t_submit, ttft)
                _resolve(req.future, result)
            else:
                self._on_error_result(rep, hdr, req)
        self._pump(rep)

    def _on_error_result(self, rep, hdr, req):
        etype = hdr.get('etype')
        if hdr.get('requeue') and not self._closed \
                and req.attempts < self._max_attempts:
            # shed before any device work (overload / drain): safe to
            # re-route to another replica
            with self.stats._lock:
                self.stats.rerouted += 1
            self._route(req)
            return
        exc = _rebuild_exc(hdr)
        with self.stats._lock:
            if etype == 'DeadlineExceeded':
                self.stats.expired += 1
            elif etype == 'ServerOverloaded':
                self.stats.shed += 1
        self._fail_req(req, exc,
                       count_failed=etype not in ('DeadlineExceeded',
                                                  'ServerOverloaded'))

    @staticmethod
    def _decode_result(hdr, arrays):
        kind = hdr.get('kind')
        if kind == 'greedy':
            return [int(t) for t in arrays['tokens']]
        if kind == 'beam':
            return (arrays['ids'], arrays['scores'])
        outs = []
        for j in range(int(hdr.get('n', 0))):
            levels = (hdr.get('lod') or [])
            lv = int(levels[j]) if j < len(levels) else 0
            data = arrays['o%d' % j]
            if lv:
                outs.append((data, [arrays['o%d.lod%d' % (j, i)]
                                    for i in range(lv)]))
            else:
                outs.append(data)
        return outs

    # -- failure handling --------------------------------------------------
    def _on_replica_failure(self, rep, reason):
        """Declare one replica dead: SIGKILL what's left of it, fail its
        in-flight requests LOUDLY, re-route its router-side queue."""
        with self._lock:
            if rep.state in ('dead', 'retired'):
                return
            rep.state = 'dead'
            outstanding = list(rep.outstanding.values())
            rep.outstanding.clear()
            pending = list(rep.pending)
            rep.pending.clear()
        # a replica that died while STARTING must release _await_ready
        # immediately (state is already 'dead', so the waiter raises)
        # instead of letting it sit out the full spin-up timeout
        rep.ready_evt.set()
        with self.stats._lock:
            self.stats.replica_deaths += 1
        self.stats.record_event('replica_dead', rep.rid, reason)
        self._kill(rep)
        warnings.warn(
            'fleet replica %d FAILED (%s): %d in-flight request(s) '
            'failed loudly, %d queued re-routed'
            % (rep.rid, reason, len(outstanding), len(pending)),
            RuntimeWarning)
        for req in outstanding:
            self._fail_req(req, ReplicaFailed(
                'fleet replica %d died (%s) with this request in '
                'flight%s' % (rep.rid, reason, _rid_suffix(req))))
        if pending:
            # re-route in a THROWAWAY thread: this path runs on the
            # watchdog (and reader) threads, and _route -> _send can
            # block on a second wedged replica's full socket — the
            # watchdog must stay free to deliver the SIGKILL that
            # unblocks exactly that send
            def _reroute():
                for req in pending:
                    with self.stats._lock:
                        self.stats.rerouted += 1
                    self._route(req)
            threading.Thread(target=_reroute, daemon=True).start()
        self._write_status()

    def _kill(self, rep):
        try:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.kill()
                rep.proc.wait(timeout=10)
        except Exception:
            pass
        try:
            if rep.sock is not None:
                rep.sock.close()
        except OSError:
            pass

    # -- watchdog ----------------------------------------------------------
    def _watchdog_loop(self):
        last_status = 0.0
        while not self._stop_evt.wait(self._poll_s):
            now = time.perf_counter()
            with self._lock:
                reps = list(self._replicas.values())
            for rep in reps:
                if rep.state in ('retired', 'dead'):
                    continue
                hb, age = read_heartbeat(self._hb_path(rep.rid))
                rep.hb, rep.hb_age = hb, age
                if rep.proc is not None and rep.proc.poll() is not None \
                        and rep.state != 'retiring':
                    self._on_replica_failure(
                        rep, 'process exited rc=%s'
                        % rep.proc.returncode)
                    continue
                if rep.state in ('serving', 'canary', 'draining') \
                        and age > self.hb_timeout_s:
                    self._on_replica_failure(
                        rep, 'heartbeat stale %.1fs > %.1fs — '
                        'replica hung, SIGKILL' % (age,
                                                   self.hb_timeout_s))
                    continue
                self._reap_pending(rep)
                # backstop pump: a slot freed by an expired send (which
                # deliberately does not pump) refills within one poll.
                # In a THROWAWAY thread: _send can block on a wedged
                # replica's full socket, and the watchdog must stay
                # free to deliver the SIGKILL that unblocks it
                if rep.pending \
                        and len(rep.outstanding) < self._inflight:
                    threading.Thread(target=self._pump, args=(rep,),
                                     daemon=True).start()
            self._process_ctl()
            if time.time() - last_status > 1.0:
                self._write_status()
                last_status = time.time()

    def _reap_pending(self, rep):
        """Expire router-side queued requests whose deadline elapsed."""
        now = time.perf_counter()
        expired = []
        with self._lock:
            alive = deque()
            for req in rep.pending:
                if req.deadline is not None and now > req.deadline:
                    expired.append(req)
                else:
                    alive.append(req)
            rep.pending = alive
        for req in expired:
            with self.stats._lock:
                self.stats.expired += 1
            self._fail_req(req, DeadlineExceeded(
                'request expired in the router queue%s'
                % _rid_suffix(req)),
                count_failed=False)

    def _process_ctl(self):
        """tools/fleet_ctl.py drops {'cmd': 'drain', 'replica': rid}
        JSON files into fleet_dir/ctl/; execute and remove them."""
        ctl = os.path.join(self.fleet_dir, 'ctl')
        try:
            names = sorted(os.listdir(ctl))
        except OSError:
            return
        for name in names:
            if not name.endswith('.json'):
                continue  # fleet_ctl writes '*.tmp' then os.replace's:
                #           touching the tmp would race the rename
            path = os.path.join(ctl, name)
            # one malformed or racing command file must never kill the
            # watchdog thread — it is the fleet's failure detector
            try:
                with open(path) as f:
                    cmd = json.load(f)
                os.unlink(path)
                if cmd.get('cmd') == 'drain':
                    rid = int(cmd.get('replica', -1))
                    threading.Thread(target=self._ctl_drain,
                                     args=(rid,), daemon=True).start()
            except Exception as e:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                warnings.warn('fleet: bad control file %s ignored '
                              '(%s: %s)' % (name, type(e).__name__, e),
                              RuntimeWarning)

    def _ctl_drain(self, rid):
        try:
            self.drain_replica(rid)
        except Exception as e:
            warnings.warn('fleet_ctl drain of replica %d failed: %s'
                          % (rid, e), RuntimeWarning)

    # -- scaling -----------------------------------------------------------
    def serving_replicas(self):
        with self._lock:
            return [r.rid for r in self._replicas.values()
                    if r.state == 'serving']

    def scale_out(self, tier=_UNSET, artifact=None, wait=True,
                  canary=False, reason='scale_out'):
        """Spawn one more replica (warm, compile-free with AOT
        sidecars). Returns its rid."""
        spec = dict(self._spec)
        if tier is not _UNSET:
            spec['tier'] = tier
        if artifact is not None:
            spec['artifact'] = artifact
        rid = self._spawn(spec, wait=wait, canary=canary)
        if not canary:
            with self.stats._lock:
                self.stats.scale_out += 1
            self.stats.record_event('scale_out', rid, reason)
        self._write_status()
        return rid

    def scale_in(self, rid=None, reason='scale_in', timeout=120.0):
        """Drain + retire one replica (least-loaded by default). The
        drain finishes in-flight work and hands queued requests back
        for re-routing — zero dropped streams."""
        with self._lock:
            serving = [r for r in self._replicas.values()
                       if r.state == 'serving']
            if rid is None:
                if len(serving) <= 1:
                    raise RuntimeError(
                        'refusing to scale in the last serving replica')
                rid = min(serving, key=lambda r: (r.load, -r.rid)).rid
        ok = self.drain_replica(rid, timeout=timeout)
        with self.stats._lock:
            self.stats.scale_in += 1
        self.stats.record_event('scale_in', rid, reason)
        return ok

    def drain_replica(self, rid, timeout=120.0):
        """Draining stop for one replica: stop routing to it, hand its
        router-side queue back, let it finish in-flight work
        (predictor drain() hooks), then retire it. Returns True when
        the drain completed inside `timeout` (the replica is retired
        either way — by force if it would not drain)."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                raise ValueError('no replica %d' % rid)
            if rep.state not in ('serving', 'canary'):
                raise RuntimeError('replica %d is %r, not drainable'
                                   % (rid, rep.state))
            rep.state = 'draining'
            pending = list(rep.pending)
            rep.pending.clear()
        for req in pending:
            with self.stats._lock:
                self.stats.rerouted += 1
            self._route(req)
        ok = False
        try:
            with rep.send_lock:
                _send_frame(rep.sock, {'op': 'drain'})
            ok = rep.drained_evt.wait(timeout)
            # results may still be in the socket behind the drained
            # frame's send; give them a moment to resolve
            deadline = time.monotonic() + 5.0
            while rep.outstanding and time.monotonic() < deadline:
                time.sleep(0.01)
        except Exception as e:
            warnings.warn('fleet: drain of replica %d errored (%s) — '
                          'retiring by force' % (rid, e),
                          RuntimeWarning)
        if not ok:
            warnings.warn(
                'fleet replica %d did not finish draining in %.0fs — '
                'retiring by force; its in-flight requests fail loudly'
                % (rid, timeout), RuntimeWarning)
        self._retire(rep)
        return ok

    def _retire(self, rep):
        with self._lock:
            rep.state = 'retiring'
            # pending can be non-empty again here: submit_to() accepts a
            # DRAINING replica (rollout probes) and queues when the
            # frame window is full — those must fail loudly too, never
            # strand an unresolved future
            leftovers = (list(rep.outstanding.values())
                         + list(rep.pending))
            rep.outstanding.clear()
            rep.pending.clear()
        try:
            with rep.send_lock:
                _send_frame(rep.sock, {'op': 'stop'})
            rep.proc.wait(timeout=15)
        except Exception:
            self._kill(rep)
        with self._lock:
            rep.state = 'retired'
        if leftovers:
            for req in leftovers:
                self._fail_req(req, ReplicaFailed(
                    'fleet replica %d retired with this request still '
                    'in flight (drain timeout)%s'
                    % (rep.rid, _rid_suffix(req))))
        try:
            if rep.sock is not None:
                rep.sock.close()
        except OSError:
            pass
        self._write_status()

    # -- rollout / probe plumbing ------------------------------------------
    def submit_to(self, rid, inputs, deadline_ms=None,
                  max_new_tokens=None, beam=None, request_id=None):
        """Route one request to a SPECIFIC replica (rollout probes;
        bypasses least-work selection, still honors frame capacity)."""
        header, arrays = self._encode_request(inputs, max_new_tokens,
                                              beam, None)
        req = _FleetRequest(next(self._req_ids), header, arrays,
                            deadline_ms, request_id=request_id)
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.state not in ('serving', 'canary',
                                                'draining'):
                raise RuntimeError('replica %r not available' % rid)
            req.attempts = self._max_attempts  # never re-route a probe
            req.replica = rid
            if len(rep.outstanding) < self._inflight:
                rep.outstanding[req.id] = req
                send = True
            else:
                rep.pending.append(req)
                send = False
        if send:
            self._send(rep, req)
        return req.future

    def set_default_spec(self, tier=_UNSET, artifact=None):
        """Re-point the fleet's default artifact spec (rollout promote):
        future spawns — autoscaler included — use it."""
        with self._lock:
            if tier is not _UNSET:
                self._spec['tier'] = tier
            if artifact is not None:
                self._spec['artifact'] = artifact

    def promote_canary(self, rid):
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.state != 'canary':
                raise RuntimeError('replica %r is not a canary' % rid)
            rep.state = 'serving'
            rep.spec['canary'] = False
        self._write_status()

    # -- status / reporting ------------------------------------------------
    def status(self):
        with self._lock:
            reps = {r.rid: r.snapshot()
                    for r in self._replicas.values()}
            spec = dict(self._spec)
        snap = self.stats.snapshot()
        return {'time': time.time(), 'pid': os.getpid(),
                'artifact': spec['artifact'],
                'tier': spec.get('tier') or 'bf16',
                'kind': self.kind,
                'closed': self._closed,
                'serving': sum(1 for s in reps.values()
                               if s['state'] == 'serving'),
                'replicas': reps, 'counters': snap}

    def fleet_snapshot(self):
        """Profiler fleet-source contract (register_fleet_source)."""
        st = self.status()
        snap = st['counters']
        snap.update(kind='fleet', artifact=st['artifact'],
                    tier=st['tier'], serving=st['serving'],
                    replicas=st['replicas'],
                    # backlog, not in-flight: a dispatched frame shows
                    # up in the worker's queue_depth already — adding
                    # outstanding would read ~2x the true queue
                    queue_depth=sum(s['pending'] + s['queue_depth']
                                    for s in st['replicas'].values()))
        return snap

    def _write_status(self):
        try:
            path = os.path.join(self.fleet_dir, 'status.json')
            tmp = '%s.%d.tmp' % (path, os.getpid())
            with open(tmp, 'w') as f:
                json.dump(self.status(), f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError:
            pass

    # -- shutdown ----------------------------------------------------------
    def close(self):
        """Stop every replica and router thread. Outstanding requests
        fail with RuntimeError. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            reps = list(self._replicas.values())
        self._stop_evt.set()
        for rep in reps:
            with self._lock:
                outstanding = list(rep.outstanding.values())
                rep.outstanding.clear()
                pending = list(rep.pending)
                rep.pending.clear()
                if rep.state in ('serving', 'canary', 'draining'):
                    rep.state = 'retiring'
            exc = RuntimeError('FleetRouter closed')
            for req in outstanding + pending:
                self._fail_req(req, exc, count_failed=False)
            # bounded stop-send: the watchdog (which normally SIGKILLs
            # a wedged worker out of a blocked sendall) is already
            # stopping, so close() must not wait on a full socket or a
            # send_lock held by a blocked _send — the proc.wait/kill
            # loop below reaps workers that never saw the stop frame
            try:
                if rep.sock is not None \
                        and rep.send_lock.acquire(timeout=2.0):
                    try:
                        rep.sock.settimeout(2.0)
                        _send_frame(rep.sock, {'op': 'stop'})
                    finally:
                        rep.send_lock.release()
            except Exception:
                pass
        for rep in reps:
            try:
                if rep.proc is not None:
                    rep.proc.wait(timeout=10)
            except Exception:
                self._kill(rep)
            with self._lock:
                if rep.state != 'dead':
                    rep.state = 'retired'
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            if self._watchdog_t is not None:
                self._watchdog_t.join(timeout=5)
        except Exception:
            pass
        try:
            os.unlink(self._sock_path)
        except OSError:
            pass
        if self._sock_tmpdir is not None:
            try:
                os.rmdir(self._sock_tmpdir)
            except OSError:
                pass
        self._write_status()
        name, self._profiler_name = self._profiler_name, None
        if name:
            prof = _maybe_profiler()
            if prof is not None and hasattr(prof,
                                            'unregister_fleet_source'):
                prof.unregister_fleet_source(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class Autoscaler(object):
    """Scale the fleet out/in on the occupancy / queue-depth / shed-rate
    counters the serving stats already measure.

    step() evaluates once (deterministic — tests drive it directly);
    start() runs it on a background interval. Scale-out spawns a warm
    replica when queue depth per replica or the shed rate since the
    last step crosses its threshold, or occupancy exceeds
    `high_occupancy` WITH a non-empty backlog (the occupancy gauges
    are lifetime-cumulative and freeze while idle — gating on backlog
    stops an idle post-surge fleet from ping-ponging), or serving
    replicas fell below `min_replicas` (failover replacement);
    scale-in DRAINS the least-loaded replica once the fleet has been
    IDLE — zero queued or outstanding work, zero sheds — for
    `idle_steps` consecutive evaluations (occupancy counters are
    cumulative, so sustained idleness is the reliable low-load
    signal). A cooldown separates consecutive scale events.
    """

    def __init__(self, router, min_replicas=1, max_replicas=8,
                 high_queue_per_replica=4.0, high_occupancy=0.85,
                 idle_steps=3, cooldown_s=5.0, interval_s=1.0):
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.high_queue = float(high_queue_per_replica)
        self.high_occ = float(high_occupancy)
        self.idle_steps = max(1, int(idle_steps))
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self._last_scale = 0.0
        self._last_shed = None
        self._idle_streak = 0
        self._stop_evt = threading.Event()
        self._thread = None

    # -- one evaluation ----------------------------------------------------
    def metrics(self):
        st = self.router.status()
        reps = st['replicas'].values()
        serving = [s for s in reps if s['state'] == 'serving']
        n = len(serving)
        # backlog = router-side queues + worker-side predictor queues.
        # `outstanding` is deliberately EXCLUDED: a frame sent to the
        # worker shows up in its predictor's queue_depth already, and
        # counting it twice reads ~2x the true backlog (spurious
        # scale-outs at moderate load)
        queue = sum(s['pending'] + s['queue_depth'] for s in serving)
        # the IDLE signal still counts in-flight frames: a fleet whose
        # slots are all decoding has queue 0 but is not idle
        work = queue + sum(s['outstanding'] for s in serving)
        occ = (sum(s['occupancy'] for s in serving) / n) if n else 0.0
        # shed totals sum over EVERY replica (retired/dead included):
        # cumulative counters vanishing from the sum when a replica
        # retires would read as a negative shed delta
        shed = (st['counters']['shed']
                + sum(s['shed'] for s in reps))
        return {'serving': n, 'queue': queue, 'work': work,
                'queue_per_replica': queue / n if n else float('inf'),
                'occupancy': occ, 'shed_total': shed}

    def step(self):
        """Evaluate once; returns 'out', 'in', or None. Never raises on
        a scaling failure — the event is recorded and the next step
        retries."""
        m = self.metrics()
        shed_delta = (0 if self._last_shed is None
                      else max(0, m['shed_total'] - self._last_shed))
        self._last_shed = m['shed_total']
        if m['work'] == 0 and shed_delta == 0:
            self._idle_streak += 1
        else:
            self._idle_streak = 0
        now = time.monotonic()
        try:
            if m['serving'] < self.min_replicas:
                self.router.scale_out(reason='below min_replicas')
                self._last_scale = now
                self._idle_streak = 0
                return 'out'
            if now - self._last_scale < self.cooldown_s:
                return None
            # occupancy is a lifetime-cumulative gauge that freezes at
            # its last value while a replica idles (and, for batching,
            # measures batch PACKING): alone it would ping-pong an idle
            # post-surge fleet forever — it only counts alongside a
            # real backlog
            if m['serving'] < self.max_replicas and (
                    m['queue_per_replica'] > self.high_queue
                    or (m['occupancy'] > self.high_occ
                        and m['queue'] > 0)
                    or shed_delta > 0):
                self.router.scale_out(
                    reason='queue/replica %.1f occ %.2f shed +%d'
                    % (m['queue_per_replica'], m['occupancy'],
                       shed_delta))
                self._last_scale = now
                self._idle_streak = 0
                return 'out'
            if m['serving'] > self.min_replicas \
                    and self._idle_streak >= self.idle_steps:
                self.router.scale_in(
                    reason='idle for %d evaluations'
                    % self._idle_streak)
                self._last_scale = now
                self._idle_streak = 0
                return 'in'
        except Exception as e:
            self.router.stats.record_event('scale_error', None, str(e))
        return None

    # -- background mode ---------------------------------------------------
    def start(self):
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name='ptpu-fleet-autoscaler',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self):
        while not self._stop_evt.wait(self.interval_s):
            if self.router._closed:
                return
            self.step()


def bit_agreement(a, b):
    """Exact agreement between two probe results (same-tier rollouts)."""
    an, bn = _flatten_result(a), _flatten_result(b)
    return 1.0 if len(an) == len(bn) and all(
        np.array_equal(x, y) for x, y in zip(an, bn)) else 0.0


def top1_agreement(a, b):
    """Quantized-tier parity measure (round 14): per-row argmax
    agreement on the FIRST fetch for classification probes; decode
    transcripts (1-D integer token sequences — logits never leave the
    replica) compare EXACTLY per probe, so the rollout's mean over
    probes is the round-14 transcript-agreement fraction."""
    x, y = _flatten_result(a)[0], _flatten_result(b)[0]
    x, y = np.asarray(x), np.asarray(y)
    if x.ndim < 2 or x.dtype.kind in 'iu':
        return 1.0 if x.shape == y.shape and np.array_equal(x, y) \
            else 0.0
    if x.shape != y.shape:
        return 0.0
    return float(np.mean(np.argmax(x, -1) == np.argmax(y, -1)))


def _flatten_result(res):
    if isinstance(res, tuple):        # beam (ids, scores)
        return [np.asarray(r) for r in res]
    if isinstance(res, list) and res and np.isscalar(res[0]):
        return [np.asarray(res)]      # greedy transcript
    flat = []
    for o in (res if isinstance(res, list) else [res]):
        if isinstance(o, tuple):
            flat.append(np.asarray(o[0]))
            flat.extend(np.asarray(x) for x in o[1])
        else:
            flat.append(np.asarray(o))
    return flat


_AGREEMENT = {'bit': bit_agreement, 'top1': top1_agreement}


class RollingRollout(object):
    """Canary -> check -> promote (or roll back loudly) a new artifact
    tier across the fleet.

    run() spawns ONE canary replica on the new tier/artifact, replays
    `probes` (per-request feed dicts, or prompts for decoding fleets)
    against it and an incumbent, and promotes only when ALL of:

      * canary determinism: two sweeps of the probe set on the canary
        are BIT-identical (an unstable artifact never ships);
      * incumbent agreement >= `min_agreement` under `agreement`
        ('bit' exact for same-tier artifacts — the default, 'top1'
        argmax for quantized tiers, or any callable(a, b) -> [0, 1]);
      * latency budget: canary probe p50 <= `latency_budget` x the
        incumbent's p50.

    Promotion is ROLLING: the canary joins the fleet, the default spec
    re-points (the autoscaler spawns the new tier from now on), then
    each incumbent is replaced spawn-before-drain — capacity never
    dips and no in-flight stream drops. Any failed check retires the
    canary, leaves the incumbents untouched, and raises
    `RolloutRolledBack` (set raise_on_rollback=False to inspect the
    returned report instead)."""

    def __init__(self, router, tier=_UNSET, artifact=None, probes=(),
                 agreement='bit', min_agreement=1.0,
                 latency_budget=3.0, probe_kwargs=None,
                 raise_on_rollback=True):
        if tier is _UNSET and artifact is None:
            raise ValueError('rollout needs a new tier= or artifact=')
        if not probes:
            raise ValueError('rollout needs probe requests to measure '
                             'parity and latency on')
        self.router = router
        self.tier = tier
        self.artifact = artifact
        self.probes = list(probes)
        self.agree_name = (agreement if isinstance(agreement, str)
                           else getattr(agreement, '__name__',
                                        'custom'))
        self.agreement = (_AGREEMENT[agreement]
                          if isinstance(agreement, str) else agreement)
        self.min_agreement = float(min_agreement)
        self.latency_budget = float(latency_budget)
        self.probe_kwargs = dict(probe_kwargs or {})
        self.raise_on_rollback = bool(raise_on_rollback)

    def _sweep(self, rid):
        results, lat = [], []
        for probe in self.probes:
            t0 = time.perf_counter()
            results.append(self.router.submit_to(
                rid, probe, **self.probe_kwargs).result(300))
            lat.append(time.perf_counter() - t0)
        return results, lat

    def _set_state(self, **kw):
        st = self.router.stats
        with st._lock:
            st.rollout.update(kw)
        self.router.stats.record_event('rollout', kw.get('canary'),
                                       kw.get('state'))
        self.router._write_status()

    def run(self):
        """Execute the rollout; returns the check report dict."""
        router = self.router
        new_desc = ('tier=%s' % self.tier if self.tier is not _UNSET
                    else 'artifact=%s' % self.artifact)
        self._set_state(state='canary', target=new_desc, canary=None)
        incumbents = router.serving_replicas()
        if not incumbents:
            raise RolloutRolledBack('no serving incumbent to roll from')
        inc = incumbents[0]
        canary = router.scale_out(tier=self.tier, artifact=self.artifact,
                                  canary=True, reason='rollout canary')
        self._set_state(state='checking', canary=canary)
        report = {'canary': canary, 'incumbent': inc,
                  'target': new_desc, 'probes': len(self.probes),
                  'agreement_mode': self.agree_name}
        try:
            inc_res, inc_lat = self._sweep(inc)
            can_res, can_lat = self._sweep(canary)
            can_res2, _ = self._sweep(canary)
            det = bit_agreement(_flat2(can_res), _flat2(can_res2))
            agree = float(np.mean([self.agreement(c, i) for c, i
                                   in zip(can_res, inc_res)]))
            p50c = float(np.percentile(can_lat, 50)) * 1e3
            p50i = float(np.percentile(inc_lat, 50)) * 1e3
            report.update(
                deterministic=det == 1.0, agreement=round(agree, 6),
                canary_p50_ms=round(p50c, 3),
                incumbent_p50_ms=round(p50i, 3),
                latency_ratio=round(p50c / p50i, 3) if p50i else None)
            failures = []
            if det != 1.0:
                failures.append('canary output not deterministic '
                                'across probe sweeps')
            if agree < self.min_agreement:
                failures.append(
                    'agreement %.4f < %.4f (%s parity)'
                    % (agree, self.min_agreement, self.agree_name))
            if p50i and p50c > self.latency_budget * p50i:
                failures.append(
                    'canary p50 %.1fms > %.1fx incumbent %.1fms'
                    % (p50c, self.latency_budget, p50i))
        except Exception as e:
            failures = ['probe sweep failed: %s: %s'
                        % (type(e).__name__, e)]
        if failures:
            return self._rollback(canary, report, failures)
        return self._promote(canary, report)

    def _rollback(self, canary, report, failures):
        report.update(promoted=False, failures=failures)
        self._set_state(state='rolled_back', canary=canary,
                        failures=failures)
        try:
            self.router.drain_replica(canary, timeout=60)
        except Exception:
            pass
        msg = ('ROLLOUT ROLLED BACK (%s): %s — canary replica %d '
               'retired, incumbent fleet untouched'
               % (report['target'], '; '.join(failures), canary))
        warnings.warn(msg, RuntimeWarning)
        if self.raise_on_rollback:
            raise RolloutRolledBack(msg)
        return report

    def _promote(self, canary, report):
        router = self.router
        self._set_state(state='promoting', canary=canary)
        router.set_default_spec(tier=self.tier, artifact=self.artifact)
        router.promote_canary(canary)
        replaced = []
        replace_failures = []
        first = True
        for rid in router.serving_replicas():
            if rid == canary or rid in replaced:
                continue
            rep = router._replicas[rid]
            if rep.spec.get('tier') == router._spec.get('tier') \
                    and rep.spec.get('artifact') \
                    == router._spec.get('artifact'):
                continue
            # an incumbent dying mid-roll (or a spawn failing) must not
            # abort a promotion that already happened: the default spec
            # is re-pointed, so the autoscaler heals capacity on the
            # new tier — record, warn, keep rolling
            try:
                if first:
                    # the canary itself replaces the first incumbent:
                    # the fleet ends the roll at its original count
                    first = False
                else:
                    new = router.scale_out(
                        reason='rollout replace %d' % rid)
                    replaced.append(new)
                router.drain_replica(rid)
            except Exception as e:
                replace_failures.append(
                    {'replica': rid, 'error': '%s: %s'
                     % (type(e).__name__, e)})
                warnings.warn(
                    'rollout: replacing incumbent %d failed (%s: %s) '
                    '— promotion stands; the autoscaler heals '
                    'capacity on the new spec' % (rid,
                                                  type(e).__name__, e),
                    RuntimeWarning)
        report.update(promoted=True, replaced=replaced,
                      replace_failures=replace_failures)
        self._set_state(state='promoted', canary=canary)
        return report


def _flat2(results):
    """Concatenate a probe sweep's per-result flat arrays (for the
    canary determinism bit-check)."""
    return [a for r in results for a in _flatten_result(r)]


def load_fleet(artifact_dir, **kwargs):
    return FleetRouter(artifact_dir, **kwargs)
