"""Compiled-artifact export: serve without the Python tracer.

The reference ships a non-Python deployment path — a C++ API over a saved
program (inference/api/paddle_api.h:1 PaddlePredictor,
api/analysis_predictor.cc:359 CreatePaddlePredictor) and a C++ trainer demo
(train/demo_trainer.cc:1). The TPU-native equivalent of "deploy without the
framework" is an ahead-of-time compiled XLA artifact: the inference program
is traced ONCE here, parameters are baked in as constants, and the result
is serialized with `jax.export` (StableHLO + calling convention). The
loader (serve.py) needs only jax + numpy — it never imports the Program IR,
the op registry, or the tracer.

Artifact layout (out_dir/):
  module.jaxexport   serialized jax.export artifact (StableHLO, params baked)
  signature.json     {"feeds": [{name, shape, dtype}...], "fetches": [...]}

Shapes are fixed at export (XLA compiles static shapes); export one artifact
per served batch shape, as with any AOT deployment.
"""
from __future__ import annotations

import json
import os

import numpy as np

_SIGNATURE = 'signature.json'
_MODULE = 'module.jaxexport'


def export_compiled(predictor, sample_inputs, out_dir):
    """Export `predictor`'s program as a tracer-free compiled artifact.

    sample_inputs: list (feed order) or dict of arrays fixing shapes/dtypes.
    Returns out_dir. Load with inference/serve.py (no framework imports).
    """
    import jax
    from jax import export as jexport
    from ..core.lowering import Tracer
    from ..core.lod import LoDArray

    program = predictor._program
    feed_names = list(predictor._feed_names)
    fetch_names = [v.name for v in predictor._fetch_vars]
    if isinstance(sample_inputs, (list, tuple)):
        sample = dict(zip(feed_names, sample_inputs))
    else:
        sample = dict(sample_inputs)
    missing = [n for n in feed_names if n not in sample]
    if missing:
        raise ValueError("sample_inputs missing feeds: %r" % missing)

    for name in feed_names:
        v = program.global_block().var(name)
        if getattr(v, 'lod_level', 0):
            raise ValueError(
                "export_compiled serves dense tensors only; feed %r is a "
                "LoD tensor — serve it through the Python Predictor" % name)

    # parameters / BN stats become baked-in constants
    state = {}
    for v in program.list_vars():
        if v.persistable:
            val = predictor._scope.get(v.name)
            if val is not None:
                state[v.name] = val.data if isinstance(val, LoDArray) else val
    rng = jax.random.key(0)  # inference programs draw no randomness

    def fn(*feeds):
        tracer = Tracer(program, rng)
        tracer.env.update(state)
        tracer.env.update(dict(zip(feed_names, feeds)))
        tracer.run_block(program.global_block())
        return tuple(tracer.env[n] for n in fetch_names)

    specs = [jax.ShapeDtypeStruct(np.shape(sample[n]),
                                  np.asarray(sample[n]).dtype)
             for n in feed_names]
    # multi-platform artifact: serves on TPU or CPU hosts. Numerics follow
    # the executing platform's matmul precision (MXU bf16-input on TPU,
    # full f32 on CPU) — the same contract the Executor has.
    exp = jexport.export(jax.jit(fn), platforms=['cpu', 'tpu'])(*specs)

    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, _MODULE), 'wb') as f:
        f.write(exp.serialize())
    sig = {'version': 1,
           'feeds': [{'name': n, 'shape': list(np.shape(sample[n])),
                      'dtype': np.asarray(sample[n]).dtype.name}
                     for n in feed_names],
           'fetches': fetch_names}
    with open(os.path.join(out_dir, _SIGNATURE), 'w') as f:
        json.dump(sig, f, indent=1)
    return out_dir
