"""Compiled-artifact export: serve without the Python tracer.

The reference ships a non-Python deployment path — a C++ API over a saved
program (inference/api/paddle_api.h:1 PaddlePredictor,
api/analysis_predictor.cc:359 CreatePaddlePredictor) and a C++ trainer demo
(train/demo_trainer.cc:1). The TPU-native equivalent of "deploy without the
framework" is an ahead-of-time compiled XLA artifact: the inference program
is traced ONCE here, parameters are baked in as constants, and the result
is serialized with `jax.export` (StableHLO + calling convention). The
loader (serve.py) needs only jax + numpy — it never imports the Program IR,
the op registry, or the tracer.

Artifact layout (out_dir/):
  module.jaxexport   serialized jax.export artifact (StableHLO, params baked)
  signature.json     {"feeds": [{name, shape, dtype}...], "fetches": [...]}

Shapes are fixed at export (XLA compiles static shapes); export one artifact
per served batch shape, as with any AOT deployment. With
`batch_sizes=[1, 8, 32, ...]` ONE artifact dir carries several compiled
batch buckets (dense feeds only): each bucket is a complete standard
artifact under bucket_<n>/, and the top level mirrors the LARGEST bucket
plus a "buckets" signature key — so CompiledPredictor(out_dir) keeps
working unchanged while batching.BatchingPredictor picks the smallest
bucket that fits each coalesced batch.
"""
from __future__ import annotations

import json
import os
import shutil

import numpy as np

# the artifact layout contract lives in serve.py (the loader); export
# writes exactly what serve reads
from .serve import (_SIGNATURE, _MODULE, _BUCKET_DIR, _TIER_INT8,
                    _TRAIN_SIGNATURE, _TRAIN_MODULE, _TRAIN_STATE0,
                    _AOT_SIDECAR, _aot_platform, _precompile_infer_dir,
                    _precompile_train_dir)


def _should_precompile(precompile):
    """Export-time AOT sidecars default ON (PTPU_EXPORT_PRECOMPILE=0 opts
    out): the exporting host pays one XLA compile per bucket so every
    serving replica that loads the artifact pays none."""
    if precompile is not None:
        return bool(precompile)
    return os.environ.get('PTPU_EXPORT_PRECOMPILE', '1') not in ('0',
                                                                 'false')


def _try_precompile(out_dir, train=False):
    """Best-effort sidecar write: a backend without executable
    serialization must never fail the export itself."""
    import warnings
    try:
        if train:
            _precompile_train_dir(out_dir)
        else:
            _precompile_infer_dir(out_dir)
    except Exception as e:
        warnings.warn(
            'export: could not precompile a warm-start sidecar for %s '
            '(%s: %s); the artifact still serves through the normal '
            'compile path' % (out_dir, type(e).__name__, e),
            RuntimeWarning)


def _normalize_lod_sample(name, value, lod_level):
    """Normalize a LoD feed sample to (data ndarray, [int32 offsets per
    level]). Accepts a LoDArray/LoDTensor or a (values, lod) pair where
    lod is nested offsets (or a flat list for one level)."""
    from ..core.lod import LoDArray
    if isinstance(value, LoDArray):
        data = np.asarray(value.data)
        offs = [np.asarray(value.off_t(i)) for i in range(value.nlevels)]
    elif isinstance(value, tuple) and len(value) == 2:
        data, lod = value
        data = np.asarray(data)
        if isinstance(lod, np.ndarray):
            lod = [lod] if lod.ndim == 1 else list(lod)
        elif len(lod) and np.isscalar(lod[0]):
            lod = [lod]
        offs = [np.asarray(l) for l in lod]
    else:
        raise ValueError(
            "feed %r has lod_level=%d: pass a LoDTensor "
            "(fluid.create_lod_tensor) or a (values, offsets) pair"
            % (name, lod_level))
    if len(offs) != lod_level:
        raise ValueError("feed %r: expected %d lod level(s), got %d"
                         % (name, lod_level, len(offs)))
    return data, [o.astype(np.int32).reshape(-1) for o in offs]


def export_compiled(predictor, sample_inputs, out_dir, batch_sizes=None,
                    precompile=None, quantize=None, calibration=None,
                    quantize_mode='abs_max', calibration_q=99.9):
    """Export `predictor`'s program as a tracer-free compiled artifact.

    sample_inputs: list (feed order) or dict of arrays fixing shapes and
    dtypes. LoD feeds take a LoDTensor or (values, offsets) pair; they
    export in TRACED-lod form (core/lod.py), so the artifact carries the
    offsets as runtime inputs and one export serves every batch of the
    same BUCKET shape (rows, nseq) — export one artifact per bucket, the
    same discipline the Executor's lod-generic cache uses. LoD fetches
    come back as (values, offsets...) with the levels recorded in
    signature.json (the reference's PaddleTensor.lod contract,
    inference/api/paddle_api.h:1).

    batch_sizes: optional list of batch buckets (e.g. [1, 8, 32, 128]) for
    a MULTI-BUCKET artifact (dense feeds only): the program is exported
    once per bucket into out_dir/bucket_<n>/, the top level mirrors the
    largest bucket (backward-compatible with CompiledPredictor), and the
    top signature records the bucket list for batching.BatchingPredictor.

    precompile: write AOT warm-start sidecars (serve.py _AOT_SIDECAR) per
    bucket for the exporting host's platform, so loaders skip the
    first-request XLA compile. Default: on (PTPU_EXPORT_PRECOMPILE=0
    opts out); other platforms prewarm with `tools/cache_ctl.py prewarm`.

    quantize='int8' (ISSUE 11): ALSO write a post-training-quantized
    bucket tier under out_dir/int8/ — a complete artifact tree (same
    buckets, own AOT sidecars) whose program went through
    passes/quantize.py: calibrated per-tensor activation quant +
    per-channel int8 weights, dequant fused into consumers. `calibration`
    is required: a list of representative feed batches (dicts, or lists
    in feed order) swept through the executor to observe activation
    ranges; `quantize_mode` picks the observer ('abs_max'|'percentile',
    percentile at `calibration_q`). The tier signature carries the full
    calibration metadata INCLUDING every op left in float with its
    machine-checkable reason code; the top-level signature records
    'tiers' so loaders can pick per artifact
    (CompiledPredictor/BatchingPredictor `tier='int8'`). The bf16 tier
    is byte-identical to a quantize=None export.

    Returns out_dir. Load with inference/serve.py (no framework imports).
    """
    feed_names = list(predictor._feed_names)
    if isinstance(sample_inputs, (list, tuple)):
        sample = dict(zip(feed_names, sample_inputs))
    else:
        sample = dict(sample_inputs)
    missing = [n for n in feed_names if n not in sample]
    if missing:
        raise ValueError("sample_inputs missing feeds: %r" % missing)
    program = _optimize_for_export(predictor)
    sizes = None
    if batch_sizes is not None:
        sizes = sorted({int(b) for b in batch_sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError("batch_sizes must be positive ints, got %r"
                             % (batch_sizes,))
        for name in feed_names:
            v = program.global_block().var(name)
            if int(getattr(v, 'lod_level', 0) or 0):
                raise ValueError(
                    "multi-bucket export serves dense feeds only; feed %r "
                    "carries lod — export one artifact per lod bucket "
                    "instead (the Executor's bucket_rows discipline)"
                    % name)
    quant_meta = None
    if quantize is not None:
        if quantize != 'int8':
            raise ValueError("quantize must be None or 'int8', got %r"
                             % (quantize,))
        qprogram, quant_meta = _quantize_for_export(
            predictor, calibration, quantize_mode, calibration_q)
    _export_tier(predictor, program, sample, out_dir, sizes, precompile)
    if quantize is None:
        # a re-export WITHOUT quantize must not leave a previous export's
        # int8 tier behind: resolve_tier would serve the STALE quantized
        # weights against the fresh bf16 artifact with no error. A
        # signature-less partial tier (interrupted export) is dead
        # weight either way — remove it too.
        stale = os.path.join(out_dir, _TIER_INT8)
        if os.path.isdir(stale):
            import warnings
            warnings.warn(
                'export_compiled: removing stale int8 tier %s (this '
                "export did not request quantize='int8')" % stale,
                RuntimeWarning)
            shutil.rmtree(stale)
        return out_dir
    tier_sig = {'tier': 'int8', 'quantization': quant_meta}
    _export_tier(predictor, qprogram, sample,
                 os.path.join(out_dir, _TIER_INT8), sizes, precompile,
                 extra_sig=tier_sig)
    # record the tier inventory + calibration audit at the top level so
    # a loader (or a fleet operator) discovers the quantized tier without
    # probing subdirectories
    sig_path = os.path.join(out_dir, _SIGNATURE)
    with open(sig_path) as f:
        sig = json.load(f)
    sig['tiers'] = ['bf16', 'int8']
    sig['quantization'] = quant_meta
    with open(sig_path, 'w') as f:
        json.dump(sig, f, indent=1)
    return out_dir


def _export_tier(predictor, program, sample, out_dir, sizes,
                 precompile, extra_sig=None):
    """Write one complete artifact tree for `program`: single artifact
    when `sizes` is None, else the multi-bucket tree (bucket_<n>/ per
    size, top level mirroring the LARGEST bucket, top signature carrying
    the bucket list)."""
    feed_names = list(predictor._feed_names)
    if sizes is None:
        return _export_single(predictor, sample, out_dir, program=program,
                              precompile=precompile, extra_sig=extra_sig)
    arrs = {n: np.asarray(sample[n]) for n in feed_names}
    flat = [n for n, a in arrs.items() if a.ndim < 1]
    if flat:
        raise ValueError("feeds %r have no batch dimension to bucket on"
                         % flat)
    lead = {a.shape[0] for a in arrs.values()}
    if len(lead) != 1:
        raise ValueError(
            "multi-bucket export needs one uniform leading batch dim; "
            "sample feeds disagree: %s" % sorted(lead))
    os.makedirs(out_dir, exist_ok=True)
    for b in sizes:
        # np.resize tiles the sample rows up/down to the bucket — only
        # shapes and dtypes matter for the export trace
        resized = {n: np.resize(a, (b,) + a.shape[1:])
                   for n, a in arrs.items()}
        _export_single(predictor, resized,
                       os.path.join(out_dir, _BUCKET_DIR % b),
                       program=program, precompile=precompile,
                       extra_sig=extra_sig)
    # top level mirrors the LARGEST bucket so CompiledPredictor(out_dir)
    # keeps working unchanged on a multi-bucket dir
    top = os.path.join(out_dir, _BUCKET_DIR % sizes[-1])
    top_module = os.path.join(out_dir, _MODULE)
    if os.path.exists(top_module):
        os.remove(top_module)
    try:  # params are baked in: the module can be ~100MB — link, not copy
        os.link(os.path.join(top, _MODULE), top_module)
    except OSError:  # cross-device or no-hardlink filesystem
        shutil.copyfile(os.path.join(top, _MODULE), top_module)
    # the largest bucket's AOT sidecar serves the mirrored top module too
    # (same module bytes; the sidecar validates by content hash)
    side = _AOT_SIDECAR % _aot_platform()
    if os.path.exists(os.path.join(top, side)):
        top_side = os.path.join(out_dir, side)
        if os.path.exists(top_side):
            os.remove(top_side)
        try:
            os.link(os.path.join(top, side), top_side)
        except OSError:
            shutil.copyfile(os.path.join(top, side), top_side)
    with open(os.path.join(top, _SIGNATURE)) as f:
        sig = json.load(f)
    sig['buckets'] = sizes
    with open(os.path.join(out_dir, _SIGNATURE), 'w') as f:
        json.dump(sig, f, indent=1)
    return out_dir


def _quantize_for_export(predictor, calibration, mode, q):
    """Calibrate + quantize the predictor's program for the int8 tier.
    Returns (optimized quantized program, signature metadata). The sweep
    runs through the predictor's OWN executor and scope (the 'existing
    executor' calibration path, PAPER.md §6); the quantized program then
    goes through the standard inference pass pipeline, so constant
    folding/DCE/act-fusion apply to the int8 form exactly as to the
    float one."""
    from .. import passes
    if not calibration:
        raise ValueError(
            "quantize='int8' requires calibration=[feed batches...]: a "
            "representative sweep is what defines the activation scales "
            "(passes/quantize.calibrate_program)")
    feed_names = list(predictor._feed_names)
    fetch_names = [v.name for v in predictor._fetch_vars if v is not None]
    batches = []
    for b in calibration:
        batches.append(dict(zip(feed_names, b))
                       if isinstance(b, (list, tuple)) else dict(b))
    calib = passes.calibrate_program(
        predictor._program, batches, predictor._exe,
        scope=predictor._scope, q=q)
    qprog, report = passes.quantize_program(
        predictor._program, calib, predictor._scope, mode=mode,
        fetch_names=fetch_names, feed_names=feed_names)
    try:
        qprog, _ = passes.apply_inference_pipeline(
            qprog, fetch_names=fetch_names, feed_names=feed_names)
    except passes.ProgramVerifyError:
        raise
    except Exception as e:
        import warnings
        warnings.warn(
            "int8 tier optimization pipeline failed (%s: %s); exporting "
            "the unoptimized quantized program"
            % (type(e).__name__, e), RuntimeWarning)
    d = report.details
    meta = {'method': 'post_training_int8', 'mode': d['mode'],
            'percentile_q': float(q), 'calibration_batches': len(batches),
            'quantized_ops': d['quantized_ops'],
            'float_ops': d['float_ops'],
            'float_op_reasons': d['float_op_reasons'],
            'act_scales': d['act_scales'],
            'weight_bytes_before': d['weight_bytes_before'],
            'weight_bytes_after': d['weight_bytes_after']}
    return qprog, meta


def _decode_mesh(axes, platform=None):
    """Build the compile mesh for a sharded decode export. Delegates to
    the load-time reconstruction in decoding.py — ONE copy of the
    device-ordering rule, so an exported artifact can never place
    differently at serve time."""
    from . import decoding as _decoding
    return _decoding._decode_mesh(axes, platform)


def _mesh_tag(platform, axes):
    """Mesh-tagged AOT sidecar key: aot_<platform>_<axes>.jaxexec (e.g.
    aot_cpu_mp2.jaxexec) — a sharded executable must never load into an
    unsharded serve (or a different mesh shape), so the tag carries the
    axis layout next to the platform."""
    return '%s_%s' % (platform, ''.join(
        '%s%d' % (a, int(axes[a])) for a in sorted(axes)))


def _decode_shard_ctx(spec, state_names, platform=None):
    """Resolve the spec's mesh annotations into concrete NamedShardings:
    returns None for unsharded specs, else {mesh, rep, state_ns (aligned
    with state_names), param_ns, axes, tag}."""
    axes = spec.get('mesh_axes')
    if not axes:
        return None
    from jax.sharding import NamedSharding, PartitionSpec
    from .decoding import _state_shardings_ns
    mesh = _decode_mesh(axes, platform)
    rep, state_ns = _state_shardings_ns(
        mesh, spec.get('state_shardings'), state_names)
    param_ns = {n: NamedSharding(mesh, PartitionSpec(*ps))
                for n, ps in (spec.get('param_shardings') or {}).items()}
    plat = np.asarray(mesh.devices).reshape(-1)[0].platform
    return {'mesh': mesh, 'rep': rep, 'state_ns': state_ns,
            'param_ns': param_ns, 'axes': dict(axes),
            'platform': plat, 'tag': _mesh_tag(plat, axes)}


def export_decode(spec, out_dir, scope=None, precompile=None,
                  kv_cache_dtype=None):
    """Export a TWO-PROGRAM continuous-decode serving artifact (ISSUE 8).

    `spec` is the dict a decode model builder produces (e.g.
    models/transformer.build_decode_spec):

      startup      Program that initializes every shared parameter and
                   zeroes the KV cache state — run it in `scope` BEFORE
                   exporting.
      step         {'program', 'feeds', 'samples', 'fetches'}: the
                   decode-step program. Feeds must be named exactly
                   'tokens' [max_slots, 1] int64 and 'pos'
                   [max_slots, 1] int32; fetch 0 is the per-slot logits
                   [max_slots, vocab].
      prefill      {bucket_len: {...}}: one prefill program per prompt-
                   length bucket. Feeds must be named 'prompt_ids'
                   [1, bucket] int64, 'prompt_len' [1, 1] int32, 'slot'
                   [1, 1] int32; fetch 0 is the last-real-position
                   logits [1, vocab].
      cache_vars   persistable KV-cache state vars present in every
                   program ([max_slots, max_cache_len, ...]).
      max_slots / max_cache_len / eos_id / vocab.

    Every program is traced ONCE as fn(state, feeds) -> (fetches,
    new_state): parameters bake in as constants, the cache state threads
    through as donated inputs/outputs. The artifact also carries a
    REORDER program (state gathered by a per-slot source index — beam
    reordering, cache replication, and the serving tier's owned-buffer
    init boundary) and per-program AOT warm-start sidecars, the step and
    prefill tiers compiled WITH state donation (the paged cache updates
    in place; the loader passes only XLA-owned buffers, the executor's
    round-10 ownership discipline).

    Artifact layout (out_dir/):
      decode_signature.json   shapes, buckets, state specs, eos/vocab
      decode_step/            module.jaxexport (+ aot_<platform>.jaxexec)
      prefill_<bucket>/       one per prompt bucket
      decode_reorder/         slot-gather program (undonated)

    kv_cache_dtype='int8' (ISSUE 11): assert-and-record that the spec
    was built with the quantized paged cache (build_decode_spec's
    kv_cache_dtype) — the int8 pages + per-slot-page f32 scales thread
    through as state like any other cache var, halving cache HBM so the
    same budget serves ~2x max_slots. The signature records the dtype
    and the per-state byte accounting for capacity planning.

    Block-paged specs (ISSUE 13, build_decode_spec(block_size=...))
    export the BLOCK layout: the cache pool is addressed through block
    tables fed at dispatch time, prefill is chunked (prefill_chunk_<C>/
    one program per chunk size), and the artifact carries a BLOCKCOPY
    program (decode_blockcopy/: up to max_slots (dst, src) block pairs
    copy per dispatch — beam copy-on-write moves diverged BLOCKS, not
    slot rows) next to the reorder program (which gathers over blocks
    and remains the owned-buffer init boundary).

    Specs annotated for tensor-model sharding (build_decode_spec
    mp_shard=k) trace every program over the composed mesh: params bake
    in as mp-sharded constants, the KV block pool threads through as
    mp-sharded donated state (round-13 output-sharding pinning keeps
    the step a sharding-stable loop), and AOT sidecars are MESH-TAGGED
    (aot_<platform>_mp<k>.jaxexec). The signature records the mesh so
    DecodingPredictor rebuilds it at load; serving needs prod(axes)
    devices. Sharded artifacts are single-platform (the exporting
    backend).

    Speculative-decode specs (ISSUE 17, build_decode_spec(draft_k=K))
    export a THIRD program, decode_verify/: [max_slots, K+1] token and
    position rows score in one dispatch over the same donated cache
    state, with its own AOT warm-start sidecar. The signature bumps to
    version 3 and gains an optional 'verify' block ({feeds, fetches,
    draft_k}); version-2 artifacts keep loading (speculative decode
    simply unavailable).

    Load with inference/decoding.py DecodingPredictor (framework-free).
    Returns out_dir.
    """
    import jax
    from .. import global_scope
    from . import decoding as _decoding

    spec_kv = spec.get('kv_cache_dtype', 'float32')
    if kv_cache_dtype is not None and kv_cache_dtype != spec_kv:
        raise ValueError(
            "export_decode(kv_cache_dtype=%r) but the spec was built "
            "with kv_cache_dtype=%r — rebuild the decode spec with the "
            "requested cache dtype (build_decode_spec(kv_cache_dtype=...))"
            % (kv_cache_dtype, spec_kv))
    scope = scope if scope is not None else global_scope()
    layout = spec.get('layout', 'slot')
    state_names = list(spec['cache_vars'])
    state0 = []
    for n in state_names:
        val = scope.get(n)
        if val is None:
            raise ValueError(
                "cache var %r has no value in the scope — run the spec's "
                "startup program before export_decode" % n)
        state0.append(np.asarray(val))
    shard = _decode_shard_ctx(spec, state_names)
    step = spec['step']
    step_want = (['block_tables', 'pos', 'tokens'] if layout == 'block'
                 else ['pos', 'tokens'])
    if sorted(step['feeds']) != step_want:
        raise ValueError("decode-step feeds must be %r, got %r"
                         % (step_want, step['feeds']))
    os.makedirs(out_dir, exist_ok=True)

    step_feeds = _export_decode_program(
        step, state_names, state0, scope,
        os.path.join(out_dir, _decoding._STEP_DIR), shard=shard)
    verify_sig = None
    verify = spec.get('verify')
    if verify is not None:
        # ISSUE 17: third program — same feed NAMES as the step (the
        # verify tick is a step with R = draft_k + 1 rows per slot)
        if sorted(verify['feeds']) != step_want:
            raise ValueError("decode-verify feeds must be %r, got %r"
                             % (step_want, verify['feeds']))
        verify_sig = {
            'feeds': _export_decode_program(
                verify, state_names, state0, scope,
                os.path.join(out_dir, _decoding._VERIFY_DIR),
                shard=shard),
            'fetches': list(verify['fetches']),
            'draft_k': int(spec['draft_k'])}
    prefill_sig = {}
    chunk_sig = {}
    if layout == 'block':
        chunks = sorted(int(c) for c in spec['chunk'])
        if not chunks:
            raise ValueError("block-layout export needs at least one "
                             "chunk size")
        for C in chunks:
            p = spec['chunk'][C]
            if sorted(p['feeds']) != ['block_table', 'chunk_ids',
                                      'chunk_len', 'start']:
                raise ValueError(
                    "chunk feeds must be ['chunk_ids', 'start', "
                    "'chunk_len', 'block_table'], got %r" % (p['feeds'],))
            chunk_sig[str(C)] = {
                'feeds': _export_decode_program(
                    p, state_names, state0, scope,
                    os.path.join(out_dir, _decoding._CHUNK_DIR % C),
                    shard=shard),
                'fetches': list(p['fetches'])}
        _export_decode_blockcopy(
            state0, int(spec['max_slots']),
            os.path.join(out_dir, _decoding._BLOCKCOPY_DIR), shard=shard)
        reorder_n = int(spec['num_blocks'])
    else:
        buckets = sorted(int(b) for b in spec['prefill'])
        if not buckets:
            raise ValueError("export_decode needs at least one prompt "
                             "bucket")
        for L in buckets:
            p = spec['prefill'][L]
            if sorted(p['feeds']) != ['prompt_ids', 'prompt_len', 'slot']:
                raise ValueError(
                    "prefill feeds must be ['prompt_ids', 'prompt_len', "
                    "'slot'], got %r" % (p['feeds'],))
            prefill_sig[str(L)] = {
                'feeds': _export_decode_program(
                    p, state_names, state0, scope,
                    os.path.join(out_dir, _decoding._PREFILL_DIR % L),
                    shard=shard),
                'fetches': list(p['fetches'])}
        reorder_n = int(spec['max_slots'])
    _export_decode_reorder(state0, reorder_n,
                           os.path.join(out_dir, _decoding._REORDER_DIR),
                           shard=shard)

    sig = {'version': 3, 'kind': 'decode',
           'layout': layout,
           'max_slots': int(spec['max_slots']),
           'max_cache_len': int(spec['max_cache_len']),
           'eos_id': int(spec['eos_id']), 'vocab': int(spec['vocab']),
           'kv_cache_dtype': spec_kv,
           # fixed-HBM capacity planning: what the paged cache state
           # costs per replica (int8 tier: int8 pages + f32 page scales)
           'cache_bytes': int(sum(a.nbytes for a in state0)),
           'state': [{'name': n, 'shape': list(a.shape),
                      'dtype': a.dtype.name}
                     for n, a in zip(state_names, state0)],
           'step': {'feeds': step_feeds, 'fetches': list(step['fetches'])}}
    if verify_sig is not None:
        sig['verify'] = verify_sig
    if layout == 'block':
        sig['block'] = {'block_size': int(spec['block_size']),
                        'num_blocks': int(spec['num_blocks']),
                        'max_blocks_per_slot':
                            int(spec['max_blocks_per_slot'])}
        sig['chunk_buckets'] = chunks
        sig['chunk'] = chunk_sig
    else:
        sig['prompt_buckets'] = buckets
        sig['prefill'] = prefill_sig
    if shard is not None:
        sig['mesh'] = {'axes': {a: int(n) for a, n in
                                shard['axes'].items()},
                       'platform': shard['platform'],
                       'tag': shard['tag'],
                       'state_shardings':
                           {n: list(ps) for n, ps in
                            (spec.get('state_shardings') or {}).items()}}
    with open(os.path.join(out_dir, _decoding._DECODE_SIGNATURE), 'w') as f:
        json.dump(sig, f, indent=1)
    if _should_precompile(precompile):
        import warnings
        try:
            _decoding.precompile_decode_artifact(out_dir)
        except Exception as e:
            warnings.warn(
                'export_decode: could not precompile warm-start sidecars '
                'for %s (%s: %s); the artifact still serves through the '
                'normal compile path' % (out_dir, type(e).__name__, e),
                RuntimeWarning)
    return out_dir


def _shard_trace_ctx(shard):
    """Trace-time context for a sharded decode export: the spec's
    sharding_hint ops resolve against the mesh via the round-13
    trace_mesh_scope machinery. Null context when unsharded."""
    import contextlib
    if shard is None:
        return contextlib.nullcontext()
    from ..parallel.mesh import trace_mesh_scope
    return trace_mesh_scope(shard['mesh'])


def _export_serialize(fn, in_specs, out_dir, shard=None,
                      out_shardings=None):
    """jit + jax.export one decode program and write its module. An
    unsharded program exports cross-platform (cpu+tpu); a sharded one is
    single-platform (the mesh's) with the state pinned input AND output
    to its annotated shardings — the round-13 fixed-point discipline
    that keeps the step a sharding-stable loop under the AOT warm
    path."""
    import jax
    from jax import export as jexport
    if shard is None:
        jitted = jax.jit(fn)
        platforms = ['cpu', 'tpu']
    else:
        def rep_like(spec_tree):
            return jax.tree_util.tree_map(lambda _: shard['rep'],
                                          spec_tree)
        in_sh = (list(shard['state_ns']),) + tuple(
            rep_like(s) for s in in_specs[1:])
        out_sh = (out_shardings if out_shardings is not None
                  else (None, list(shard['state_ns'])))
        jitted = jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh)
        platforms = [shard['platform']]
    with _shard_trace_ctx(shard):
        exp = jexport.export(jitted, platforms=platforms)(*in_specs)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, _MODULE), 'wb') as f:
        f.write(exp.serialize())


def _export_decode_program(entry, state_names, state0, scope, out_dir,
                           shard=None):
    """Trace one decode program as fn(state, feeds) -> (fetches,
    new_state) — export_train_step's state-threading convention minus
    the rng (decode programs draw no randomness) — and serialize it.
    With `shard` (_decode_shard_ctx), the trace runs over the composed
    mesh: baked params CONSTRAIN to their annotated shardings (so the
    weights genuinely partition across the mesh instead of replicating
    as constants), the KV state threads through mp-sharded input->output
    (fixed-point pinned), and feeds/fetches stay replicated (the host
    scheduler sees full arrays). Returns the feed signature entries."""
    import jax
    import jax.numpy as jnp
    from ..core.lowering import Tracer
    from ..core.lod import LoDArray
    from .. import passes

    program = entry['program']
    feed_names = list(entry['feeds'])
    fetch_names = list(entry['fetches'])
    samples = {n: np.asarray(entry['samples'][n]) for n in feed_names}
    state_set = set(state_names)
    try:
        # liveness roots include the cache state: its in-place writes are
        # program outputs even though they are not fetched
        program, _ = passes.apply_inference_pipeline(
            program, fetch_names=fetch_names + list(state_names),
            feed_names=feed_names)
    except passes.ProgramVerifyError:
        raise
    except Exception as e:
        import warnings
        warnings.warn(
            "export_decode optimization pipeline failed (%s: %s); "
            "exporting the unoptimized program" % (type(e).__name__, e),
            RuntimeWarning)
        program = entry['program']

    baked = {}
    for v in program.list_vars():
        if v.persistable and v.name not in state_set:
            val = scope.get(v.name)
            if val is not None:
                baked[v.name] = np.asarray(
                    val.data if isinstance(val, LoDArray) else val)
    rng = jax.random.key(0)  # decode programs draw no randomness
    param_ns = shard['param_ns'] if shard is not None else {}

    def fn(state_list, feed_list):
        tracer = Tracer(program, rng)
        for n, v in baked.items():
            ns = param_ns.get(n)
            if ns is not None:
                # baked constant -> sharded resident weight: without the
                # constraint GSPMD may replicate the constant and the
                # model stops fitting the per-chip HBM the mesh buys
                v = jax.lax.with_sharding_constraint(jnp.asarray(v), ns)
            tracer.env[n] = v
        tracer.env.update(dict(zip(state_names, state_list)))
        tracer.env.update(dict(zip(feed_names, feed_list)))
        tracer.run_block(program.global_block())
        return ([tracer.env[n] for n in fetch_names],
                [tracer.env[n] for n in state_names])

    state_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in state0]
    feed_specs = [jax.ShapeDtypeStruct(samples[n].shape, samples[n].dtype)
                  for n in feed_names]
    out_sh = None
    if shard is not None:
        out_sh = ([shard['rep']] * len(fetch_names),
                  list(shard['state_ns']))
    _export_serialize(fn, (state_specs, feed_specs), out_dir, shard=shard,
                      out_shardings=out_sh)
    return [{'name': n, 'shape': list(samples[n].shape),
             'dtype': samples[n].dtype.name} for n in feed_names]


def _export_decode_reorder(state0, n_rows, out_dir, shard=None):
    """Serialize the axis-0 gather program: new_state[i] = state[i][src]
    per cache var (src [n_rows] int32 — slot rows in the slot layout,
    PHYSICAL BLOCKS in the block layout). Pure structural jax — no
    Program IR needed. Undonated by design: besides beam reordering, the
    serving tier routes freshly loaded state through it once so every
    buffer reaching the DONATED step/prefill executables is XLA-owned."""
    import jax
    import jax.numpy as jnp

    def fn(state_list, src):
        return [jnp.take(s, src, axis=0) for s in state_list]

    state_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in state0]
    src_spec = jax.ShapeDtypeStruct((n_rows,), np.int32)
    out_sh = None
    if shard is not None:
        out_sh = list(shard['state_ns'])
    _export_serialize(fn, (state_specs, src_spec), out_dir, shard=shard,
                      out_shardings=out_sh)


def _export_decode_blockcopy(state0, max_pairs, out_dir, shard=None):
    """Serialize the block-copy program (block layout only): up to
    `max_pairs` (dst, src) PHYSICAL-BLOCK pairs copy per dispatch —
    new_state[i] = state[i].at[dst].set(state[i][src]) for every pool
    var. This is beam copy-on-write's device half: the scheduler copies
    only the DIVERGED partial tail blocks of a reordered beam group (and
    pads unused pairs with (0, 0) — a trash-to-trash self-copy), so
    reorder dispatch bytes scale with diverged blocks instead of whole
    slot rows. Donated at load (in-place on the live pool)."""
    import jax

    def fn(state_list, dst, src):
        return [s.at[dst].set(s[src]) for s in state_list]

    state_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in state0]
    idx_spec = jax.ShapeDtypeStruct((max_pairs,), np.int32)
    out_sh = None
    if shard is not None:
        out_sh = list(shard['state_ns'])
    _export_serialize(fn, (state_specs, idx_spec, idx_spec), out_dir,
                      shard=shard, out_shardings=out_sh)


def _optimize_for_export(predictor):
    """Run the optimization pass pipeline (paddle_tpu/passes/) on the
    predictor's program before lowering: constant chains fold, dead
    branches drop, activations fuse into their producers — the exported
    StableHLO traces the optimized graph. Falls back to the raw program
    if the pipeline declines (export must never fail on an optimizer
    bug); strict-verify errors (PTPU_STRICT_VERIFY=1) propagate."""
    from .. import passes
    program = predictor._program
    try:
        program, _ = passes.apply_inference_pipeline(
            program,
            fetch_names=[v.name for v in predictor._fetch_vars
                         if v is not None],
            feed_names=list(predictor._feed_names))
    except passes.ProgramVerifyError:
        raise
    except Exception as e:
        import warnings
        warnings.warn(
            "export optimization pipeline failed (%s: %s); exporting the "
            "unoptimized program" % (type(e).__name__, e), RuntimeWarning)
        program = predictor._program
    return program


def _peak_bytes_est(program, feed_names, fetch_names, feed_sig):
    """Static peak-memory estimate of one export bucket, from the
    dataflow analyzer at the bucket's batch (the sample's leading dim).
    None when estimation declines — the signature must never fail an
    export over an analysis bug."""
    try:
        from ..passes import dataflow as _dataflow
        # the bucket batch = the largest leading dim across the feeds (a
        # rank-1 auxiliary feed like im_shape must not win over the real
        # batched inputs)
        batch = 1
        for e in feed_sig:
            shp = e.get('shape') or ()
            if shp:
                batch = max(batch, int(shp[0]))
        dfa = _dataflow.analyze_program(program, feed_names=feed_names,
                                        fetch_names=fetch_names)
        return int(dfa.peak_memory(batch=batch).peak_bytes)
    except Exception:
        return None


def _export_single(predictor, sample, out_dir, program=None,
                   precompile=None, extra_sig=None):
    """One fixed-shape export (the original export_compiled body);
    `sample` is a {feed name: value} dict covering every feed;
    `extra_sig` entries merge into signature.json (the quantized tier's
    tier/calibration metadata)."""
    import jax
    from jax import export as jexport
    from ..core.lowering import Tracer
    from ..core.lod import LoDArray

    if program is None:
        program = _optimize_for_export(predictor)
    feed_names = list(predictor._feed_names)
    fetch_names = [v.name for v in predictor._fetch_vars]

    # flat calling convention: per feed, data then one int32 offsets array
    # per lod level (traced mode — offsets are runtime data)
    feed_plan = []           # (name, lod_levels)
    flat_specs = []
    feed_sig = []
    for name in feed_names:
        v = program.global_block().var(name)
        ll = int(getattr(v, 'lod_level', 0) or 0)
        if ll:
            data, offs = _normalize_lod_sample(name, sample[name], ll)
            flat_specs.append(jax.ShapeDtypeStruct(data.shape, data.dtype))
            flat_specs.extend(jax.ShapeDtypeStruct(o.shape, np.int32)
                              for o in offs)
            feed_sig.append({'name': name, 'shape': list(data.shape),
                             'dtype': data.dtype.name, 'lod_levels': ll,
                             'lod_sizes': [int(o.shape[0]) for o in offs]})
        else:
            arr = np.asarray(sample[name])
            flat_specs.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
            feed_sig.append({'name': name, 'shape': list(arr.shape),
                             'dtype': arr.dtype.name})
        feed_plan.append((name, ll))

    # parameters / BN stats become baked-in constants
    state = {}
    for v in program.list_vars():
        if v.persistable:
            val = predictor._scope.get(v.name)
            if val is not None:
                state[v.name] = val.data if isinstance(val, LoDArray) else val
    rng = jax.random.key(0)  # inference programs draw no randomness

    def run_env(*flat):
        it = iter(flat)
        tracer = Tracer(program, rng)
        tracer.env.update(state)
        for name, ll in feed_plan:
            data = next(it)
            if ll:
                tracer.env[name] = LoDArray.traced(
                    data, [next(it) for _ in range(ll)])
            else:
                tracer.env[name] = data
        tracer.run_block(program.global_block())
        return tuple(tracer.env[n] for n in fetch_names)

    # the export trace below records which fetches are LoD, with how many
    # levels, and their shapes (serve.py uses fetch shapes to pre-flag
    # row-count-dependent fetches when padding partial dense batches) —
    # the output flattening must be plain arrays (the serving process has
    # no LoDArray class to unflatten into)
    fetch_levels = []
    fetch_shapes = []

    def fn(*flat):
        outs = run_env(*flat)
        del fetch_levels[:]
        del fetch_shapes[:]
        flat_out = []
        for o in outs:
            if isinstance(o, LoDArray):
                fetch_levels.append(o.nlevels)
                fetch_shapes.append(list(o.data.shape))
                flat_out.append(o.data)
                flat_out.extend(o.off_t(i) for i in range(o.nlevels))
            else:
                fetch_levels.append(0)
                fetch_shapes.append(list(np.shape(o)))
                flat_out.append(o)
        return tuple(flat_out)

    # multi-platform artifact: serves on TPU or CPU hosts. Numerics follow
    # the executing platform's matmul precision (MXU bf16-input on TPU,
    # full f32 on CPU) — the same contract the Executor has.
    exp = jexport.export(jax.jit(fn), platforms=['cpu', 'tpu'])(*flat_specs)

    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, _MODULE), 'wb') as f:
        f.write(exp.serialize())
    fetch_sig = [{'name': n, 'lod_levels': ll, 'shape': shp}
                 for n, ll, shp in zip(fetch_names, fetch_levels,
                                       fetch_shapes)]
    sig = {'version': 3, 'feeds': feed_sig, 'fetches': fetch_sig}
    est = _peak_bytes_est(program, feed_names, fetch_names, feed_sig)
    if est is not None:
        # static peak-bytes at THIS bucket's batch (passes/dataflow.py):
        # capacity planning reads it per bucket_<n>/signature.json before
        # ever loading the module
        sig['peak_bytes_est'] = est
    if extra_sig:
        sig.update(extra_sig)
    with open(os.path.join(out_dir, _SIGNATURE), 'w') as f:
        json.dump(sig, f, indent=1)
    if _should_precompile(precompile):
        _try_precompile(out_dir)
    return out_dir


def export_train_step(program, sample_inputs, fetch_list, out_dir,
                      scope=None, seed=None, precompile=None):
    """Export a full TRAIN step as a tracer-free compiled artifact.

    The reference can train from a saved program with no Python
    (train/demo_trainer.cc:1, train/test_train_recognize_digits.cc:1); the
    TPU-native counterpart is this: the train step (forward + backward +
    optimizer update) is traced ONCE, with parameters AND optimizer state
    as pytree inputs -> outputs — nothing baked — plus an rng input, and
    serialized with jax.export. The loader (serve.py CompiledTrainer) runs
    steps from numpy state in a process that imports only json/numpy/jax.

    program: the built train program (optimizer already applied).
    sample_inputs: dict name -> array fixing feed shapes/dtypes.
    fetch_list: Variables/names to fetch each step (put the loss here).
    scope: initialized scope (run the startup program first); its
      persistable values become the artifact's initial state
      (train_state0.npz) and define the state signature.
    seed: rng seed recorded in the artifact (default program.random_seed).
      The loader reproduces the Executor's per-step stream:
      fold_in(key(seed, impl), step).

    Artifact files: train_module.jaxexport, train_signature.json,
    train_state0.npz. Returns out_dir.
    """
    import jax
    from jax import export as jexport
    from ..core.lowering import Tracer
    from ..core import amp
    from ..core import config as _config
    from ..core.lod import LoDArray
    from ..executor import _program_analysis
    from ..framework import Variable
    from .. import global_scope

    if int(getattr(program, '_grad_accum_k', 1) or 1) > 1:
        raise ValueError(
            "export_train_step does not support gradient-merge programs; "
            "export the k=1 form and accumulate in the serving loop")
    scope = scope if scope is not None else global_scope()
    sample = dict(sample_inputs)
    feed_names = sorted(sample)
    fetch_names = [f.name if isinstance(f, Variable) else str(f)
                   for f in fetch_list]
    for name in feed_names:
        v = program.global_block()._find_var_recursive(name)
        if v is not None and getattr(v, 'lod_level', 0):
            raise ValueError(
                "export_train_step serves dense tensors only; feed %r is "
                "a LoD tensor" % name)
    for name in fetch_names:
        v = program.global_block()._find_var_recursive(name)
        if v is not None and getattr(v, 'lod_level', 0):
            raise ValueError(
                "export_train_step fetches must be dense; %r carries lod "
                "(the framework-free trainer has no LoD output "
                "convention) — fetch the loss or a dense metric" % name)

    persist, persist_written = _program_analysis(program)
    state = {}
    for name in persist:
        val = scope.get(name)
        if val is not None:
            state[name] = np.asarray(
                val.data if isinstance(val, LoDArray) else val)
    extra = sorted(set(persist_written) - set(state))
    if extra:
        raise ValueError(
            "train-step state %r is written by the program but absent "
            "from the scope — run the startup program before export so "
            "every optimizer slot is materialized" % (extra,))
    state_names = sorted(state)

    amp_on = bool(getattr(program, '_amp_bf16', False))
    rng_impl = _config.rng_impl()
    if seed is None:
        # mirror the Executor's fallback exactly (executor.py run()):
        # an unseeded program under the deterministic flag uses 1234567,
        # otherwise per-process entropy — so in-process bit-match always
        # holds; cross-process an unseeded stream is random by intent
        seed = int(program.random_seed or 0)
        if not seed:
            from ..executor import _process_entropy
            seed = (1234567 if _config.get_flag('deterministic')
                    else _process_entropy())

    def fn(state_list, feed_list, rng_raw):
        rng = jax.random.wrap_key_data(rng_raw, impl=rng_impl)
        with amp.scope(amp_on):
            tracer = Tracer(program, rng)
            tracer.env.update(dict(zip(state_names, state_list)))
            tracer.env.update(dict(zip(feed_names, feed_list)))
            tracer.run_block(program.global_block())
            fetches = [tracer.env[n] for n in fetch_names]
            new_state = [tracer.env[n] for n in state_names]
        return fetches, new_state

    state_specs = [jax.ShapeDtypeStruct(state[n].shape, state[n].dtype)
                   for n in state_names]
    feed_specs = [jax.ShapeDtypeStruct(np.shape(sample[n]),
                                       np.asarray(sample[n]).dtype)
                  for n in feed_names]
    key_data = jax.random.key_data(jax.random.key(0, impl=rng_impl))
    rng_spec = jax.ShapeDtypeStruct(key_data.shape, key_data.dtype)
    exp = jexport.export(jax.jit(fn), platforms=['cpu', 'tpu'])(
        state_specs, feed_specs, rng_spec)

    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, _TRAIN_MODULE), 'wb') as f:
        f.write(exp.serialize())
    sig = {'version': 1,
           'feeds': [{'name': n, 'shape': list(np.shape(sample[n])),
                      'dtype': np.asarray(sample[n]).dtype.name}
                     for n in feed_names],
           'fetches': fetch_names,
           'state': [{'name': n, 'shape': list(state[n].shape),
                      'dtype': state[n].dtype.name} for n in state_names],
           'rng': {'impl': rng_impl, 'seed': int(seed),
                   'key_shape': list(key_data.shape),
                   'key_dtype': key_data.dtype.name}}
    with open(os.path.join(out_dir, _TRAIN_SIGNATURE), 'w') as f:
        json.dump(sig, f, indent=1)
    np.savez(os.path.join(out_dir, _TRAIN_STATE0),
             **{n: state[n] for n in state_names})
    if _should_precompile(precompile):
        _try_precompile(out_dir, train=True)
    return out_dir
