"""Block-granular KV-cache management (ISSUE 13 tentpole).

The slot-paged decode cache (rounds 11/14) reserves one contiguous
`[max_cache_len, d]` row per slot, which makes two per-request costs
structural: beam reorder gathers WHOLE slot rows (the only way to move a
beam's history under contiguous addressing), and two requests with the
same prompt prefix — system prompts, the production common case — store
and recompute that prefix once EACH. This module is the vLLM-style fix:
the cache becomes a pool of fixed-size BLOCKS `[num_blocks, block_size,
d]`, each slot addresses it through a per-slot BLOCK TABLE (logical
position p lives at `cache[table[p // bs], p % bs]`), and blocks are
refcounted so histories are SHARED instead of copied:

  * beam fork      = copy the parent's table + incref (zero device work);
                     the first divergent WRITE copy-on-writes only the
                     partial tail block — reorder bytes scale with
                     diverged blocks, not slot rows
  * prefix sharing = full blocks of a finished prompt register in a
                     prefix cache keyed by a token-prefix hash (hits
                     verify EXACT token equality — a hash collision can
                     never alias two different prefixes); a new request
                     with the same prefix maps those blocks into its
                     table and skips both the storage and the prefill
                     compute for the shared span
  * free list      = refcount-to-zero blocks return to the pool;
                     under pressure the LRU prefix entries evict first
                     (eviction accounting in `stats`)

`BlockManager` is pure host bookkeeping — stdlib only, framework-free —
and deliberately knows nothing about devices: the scheduler
(inference/decoding.py) owns the numpy block tables it feeds the
block-addressed programs, and asks this class which physical block backs
each logical write. Physical block 0 is RESERVED as the trash block:
idle step-program rows scatter their garbage there and no real table
ever maps it, so stale bits can never reach an active slot's attention
window (the round-11 masked-idle-slot contract, block form).
"""
import hashlib
import threading
from collections import OrderedDict, deque

__all__ = ['BlockManager', 'BlockPoolExhausted', 'TRASH_BLOCK']

# physical block 0: write target for idle/padded rows, never allocated,
# never read (attention masks it out and no table maps it)
TRASH_BLOCK = 0


class BlockPoolExhausted(RuntimeError):
    """No free block and nothing evictable: the pool is fully pinned by
    active requests. The scheduler sheds the youngest active request
    LOUDLY rather than deadlocking (reader: this is capacity pressure,
    not a bug — add blocks or admit less)."""


def _default_hash(token_bytes):
    return hashlib.sha1(token_bytes).hexdigest()


class _PrefixEntry(object):
    __slots__ = ('key', 'own', 'blocks', 'parent')

    def __init__(self, key, own, blocks, parent):
        self.key = key
        self.own = own                # THIS boundary's block tokens only
        self.blocks = list(blocks)    # one cache ref held per block
        self.parent = parent          # boundary m-1 entry: exact-token
        #   verification walks the chain one block per link, so the
        #   collision guard costs O(L) tokens per prompt, not O(L^2)


class BlockManager(object):
    """Refcounted allocator over `num_blocks` physical cache blocks of
    `block_size` token positions each (block 0 reserved as trash).

    alloc(n)                 -> n fresh blocks (evicts LRU prefix
                                entries under pressure; raises
                                BlockPoolExhausted when fully pinned)
    incref/decref(blocks)       share / release block references;
                                refcount-to-zero returns to the free list
    writable(block)          -> True when a table may write the block in
                                place (refcount 1, not trash)
    match_prefix(tokens)     -> (blocks, covered) longest verified
                                full-block prefix hit (incref'd)
    register_prefix(tokens, blocks)  publish a prompt's full blocks
    stats() / in_use()          accounting for serving_report

    Thread-safe: the scheduler thread and stats snapshots race only on
    counters, but submit-side validation may also size against in_use().
    """

    def __init__(self, num_blocks, block_size, hash_fn=None,
                 max_prefix_entries=1024):
        if num_blocks < 2:
            raise ValueError('need >= 2 blocks (block 0 is reserved), '
                             'got %d' % num_blocks)
        if block_size < 1:
            raise ValueError('block_size must be >= 1')
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._hash = hash_fn or _default_hash
        self._max_prefix = int(max_prefix_entries)
        self._lock = threading.Lock()
        self._ref = [0] * self.num_blocks
        self._free = deque(range(1, self.num_blocks))
        # prefix cache: hash key -> list of entries (collision buckets);
        # _lru orders entry ids oldest-first for eviction
        self._prefix = {}
        self._lru = OrderedDict()
        # bumped whenever a NEW prefix entry publishes: a waiting
        # request re-matches a cached miss only when this moved, so a
        # slow-to-admit prompt is not re-hashed every scheduler tick
        self.prefix_epoch = 0
        self._peak = 0
        self.allocs = 0
        self.frees = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_reused = 0
        self.evictions = 0

    # -- allocation --------------------------------------------------------
    def capacity(self):
        """Allocatable blocks (excludes the reserved trash block)."""
        return self.num_blocks - 1

    def in_use(self):
        with self._lock:
            return self.capacity() - len(self._free)

    def peak_in_use(self):
        with self._lock:
            return self._peak

    def free_blocks(self):
        with self._lock:
            return len(self._free)

    def blocks_for(self, n_tokens):
        """Blocks a span of n_tokens occupies."""
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, n=1):
        """Allocate n blocks (refcount 1 each). Under pressure the LRU
        prefix entries evict until the pool covers the request; when
        every block is pinned by a live reference, raises
        BlockPoolExhausted WITHOUT allocating (all-or-nothing, so a
        failed multi-block alloc never leaks)."""
        n = int(n)
        with self._lock:
            if len(self._free) < n and \
                    len(self._free) + self._evictable_locked() >= n:
                while len(self._free) < n and self._lru:
                    self._evict_one_locked()
            if len(self._free) < n:
                raise BlockPoolExhausted(
                    'need %d block(s), %d free, eviction cannot cover '
                    'the rest (%d/%d pinned by live requests)'
                    % (n, len(self._free), self.in_use_locked(),
                       self.capacity()))
            out = [self._free.popleft() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
            self.allocs += n
            self._peak = max(self._peak,
                             self.capacity() - len(self._free))
            return out

    def in_use_locked(self):
        return self.capacity() - len(self._free)

    def reserve(self, n):
        """Evict LRU prefix entries until >= n blocks are FREE, without
        allocating any. The scheduler preflights a decode step with the
        step's exact fresh-block demand (extensions + CoW targets), so
        row building never has to unwind a half-planned step: after a
        True reserve, that many alloc(1) calls cannot fail. False when
        the pool cannot cover n even with every prefix entry evicted —
        capacity pressure the scheduler resolves by shedding."""
        n = int(n)
        with self._lock:
            if len(self._free) < n and \
                    len(self._free) + self._evictable_locked() < n:
                return False
            while len(self._free) < n and self._lru:
                self._evict_one_locked()
            return len(self._free) >= n

    def _evictable_locked(self):
        """Blocks a full prefix-cache wipe could actually FREE: those
        whose every reference is a prefix entry's. The rest are pinned
        by live tables — evicting their entries frees nothing, so
        alloc/reserve check this BEFORE evicting and a doomed
        over-capacity request no longer wipes the cache for zero
        gain."""
        prefix_refs = {}
        for e in self._lru.values():
            for b in e.blocks:
                prefix_refs[b] = prefix_refs.get(b, 0) + 1
        return sum(1 for b, k in prefix_refs.items()
                   if self._ref[b] == k)

    def incref(self, blocks):
        with self._lock:
            for b in blocks:
                if b == TRASH_BLOCK:
                    continue
                if self._ref[b] <= 0:
                    raise RuntimeError(
                        'incref of unallocated block %d' % b)
                self._ref[b] += 1

    def decref(self, blocks):
        """Release references; refcount-to-zero blocks return to the
        free list immediately."""
        with self._lock:
            for b in blocks:
                if b == TRASH_BLOCK:
                    continue
                r = self._ref[b]
                if r <= 0:
                    raise RuntimeError(
                        'decref of free block %d (double free)' % b)
                self._ref[b] = r - 1
                if r == 1:
                    self._free.append(b)
                    self.frees += 1

    def rollback(self, table, n_tokens):
        """Truncate `table` (in place) to the blocks an n_tokens span
        occupies, releasing the tail. The speculative verify tick
        (ISSUE 17) extends a table to cover its whole draft span BEFORE
        dispatch; after host-side acceptance, blocks covering ONLY
        rejected positions are dead weight — rolling back returns them
        to the pool immediately instead of stranding them until the
        request finishes. Returns the number of blocks released."""
        keep = self.blocks_for(n_tokens)
        if len(table) <= keep:
            return 0
        tail = list(table[keep:])
        del table[keep:]
        self.decref(tail)
        return len(tail)

    def refcount(self, block):
        with self._lock:
            return self._ref[block]

    def writable(self, block):
        """A table may write `block` in place only while it is the SOLE
        owner; shared blocks copy-on-write first."""
        if block == TRASH_BLOCK:
            return False
        with self._lock:
            return self._ref[block] == 1

    # -- prefix sharing ----------------------------------------------------
    def _block_keys(self, tokens, n_full):
        """Chained per-block keys: keys[m-1] identifies tokens[:m*bs]
        (each key hashes the PREVIOUS key + one block's bytes, rolling
        vLLM-style), so computing every boundary key of an L-token
        prompt hashes each token once — O(L), not O(L^2) as re-hashing
        the full prefix per boundary would be."""
        bs = self.block_size
        keys = []
        prev = b''
        for m in range(1, n_full + 1):
            blk = b','.join(b'%d' % t for t in tokens[(m - 1) * bs:
                                                      m * bs])
            key = self._hash(prev + b'|' + blk)
            keys.append(key)
            prev = key.encode() if isinstance(key, str) else bytes(key)
        return keys

    def match_prefix(self, tokens):
        """Longest verified full-block prefix of `tokens` present in the
        cache -> (blocks, covered_tokens), blocks already incref'd for
        the caller's table; ([], 0) on miss. At least the FINAL token of
        the prompt is always left uncovered — the admitting request must
        compute something to produce its first-token logits. Hash hits
        verify exact token equality (collision safety): a colliding key
        whose stored tokens differ is a miss, never an alias."""
        bs = self.block_size
        tokens = [int(t) for t in tokens]
        # cap below len(tokens): never cover the whole prompt
        max_full = (len(tokens) - 1) // bs
        keys = self._block_keys(tokens, max_full)
        for m in range(max_full, 0, -1):
            with self._lock:
                bucket = self._prefix.get(keys[m - 1])
                if not bucket:
                    continue          # no candidate: skip token compare
                for e in bucket:
                    if not self._chain_matches_locked(e, tokens, m):
                        continue      # hash collision: different tokens
                    for b in e.blocks:
                        self._ref[b] += 1
                    self._refresh_chain_locked(e)
                    self.prefix_hits += 1
                    self.prefix_tokens_reused += m * bs
                    return list(e.blocks), m * bs
        with self._lock:
            self.prefix_misses += 1
        return [], 0

    def _chain_matches_locked(self, e, tokens, m):
        """Exact-token verification of a boundary-m candidate: walk the
        parent chain comparing ONE block's tokens per link — the
        collision guard stays exact while storing and comparing O(L)
        tokens per prompt instead of a full prefix copy per boundary.
        The chain must be exactly m links long."""
        bs = self.block_size
        j = m
        while e is not None and j > 0:
            if e.own != tuple(tokens[(j - 1) * bs:j * bs]):
                return False
            e = e.parent
            j -= 1
        return e is None and j == 0

    def _refresh_chain_locked(self, e):
        """LRU-refresh a hit entry AND its parent chain, deepest first,
        so parents end NEWEST: under pressure the deepest (tail) entries
        evict before their parents. Evicting a parent while a child
        survives frees zero blocks (the child still refs every parent
        block) yet destroys the hot prefix's shorter-boundary matches;
        child-first eviction actually frees the tail blocks and degrades
        to the shorter shared prefix gracefully."""
        while e is not None:
            if id(e) in self._lru:   # parents may already be evicted
                self._lru.move_to_end(id(e))
            e = e.parent

    def register_prefix(self, tokens, blocks):
        """Publish a prompt's FULL blocks for reuse: `blocks` backs
        tokens[:len(blocks) * block_size] exactly. One entry registers
        per full-block boundary (so shorter prefixes of the same prompt
        also hit); each entry holds one cache reference per block,
        released on eviction. Idempotent for already-registered
        prefixes."""
        bs = self.block_size
        tokens = [int(t) for t in tokens]
        n_full = min(len(blocks), len(tokens) // bs)
        keys = self._block_keys(tokens, n_full)
        with self._lock:
            parent = None
            for m in range(1, n_full + 1):
                own = tuple(tokens[(m - 1) * bs:m * bs])
                bucket = self._prefix.setdefault(keys[m - 1], [])
                found = None
                for e in bucket:
                    # fast path: the boundary m-1 candidate was already
                    # verified this call, so `is parent` + own-block
                    # equality proves the whole chain in O(block_size)
                    if (e.own == own and e.parent is parent) or \
                            self._chain_matches_locked(e, tokens, m):
                        found = e
                        break
                if found is not None:
                    parent = found
                    continue
                e = _PrefixEntry(keys[m - 1], own, blocks[:m], parent)
                for b in e.blocks:
                    self._ref[b] += 1
                bucket.append(e)
                self._lru[id(e)] = e
                self.prefix_epoch += 1
                if len(self._lru) > self._max_prefix:
                    self._evict_one_locked()
                parent = e
            if parent is not None:
                self._refresh_chain_locked(parent)

    def _evict_one_locked(self):
        _, e = self._lru.popitem(last=False)
        bucket = self._prefix.get(e.key, [])
        if e in bucket:
            bucket.remove(e)
        if not bucket:
            self._prefix.pop(e.key, None)
        for b in e.blocks:
            r = self._ref[b]
            self._ref[b] = r - 1
            if r == 1:
                self._free.append(b)
                self.frees += 1
        self.evictions += 1

    def evict_all_prefixes(self):
        """Drop every cached prefix (tests / explicit cache clear)."""
        with self._lock:
            while self._lru:
                self._evict_one_locked()

    def prefix_entries(self):
        with self._lock:
            return len(self._lru)

    def reset_counters(self):
        """Zero the cumulative counters and re-base the peak gauge
        (A/B measurement arms). Allocation state and cached prefixes
        are untouched — pair with evict_all_prefixes() when the next
        arm must not inherit the previous arm's shared prefixes."""
        with self._lock:
            self._peak = self.in_use_locked()
            self.allocs = 0
            self.frees = 0
            self.prefix_hits = 0
            self.prefix_misses = 0
            self.prefix_tokens_reused = 0
            self.evictions = 0

    # -- accounting --------------------------------------------------------
    def stats(self):
        with self._lock:
            looked = self.prefix_hits + self.prefix_misses
            return {
                'num_blocks': self.capacity(),
                'block_size': self.block_size,
                'blocks_in_use': self.in_use_locked(),
                'blocks_peak': self._peak,
                'blocks_free': len(self._free),
                'allocs': self.allocs,
                'frees': self.frees,
                'prefix_entries': len(self._lru),
                'prefix_hits': self.prefix_hits,
                'prefix_misses': self.prefix_misses,
                'prefix_hit_rate': (self.prefix_hits / looked
                                    if looked else 0.0),
                'prefix_tokens_reused': self.prefix_tokens_reused,
                'evictions': self.evictions,
            }
