"""One fleet replica subprocess (ISSUE 12).

Spawned by `fleet.FleetRouter`:

    python fleet_worker.py SOCKET_PATH REPLICA_ID ARTIFACT_DIR \
                           HEARTBEAT_PATH OPTS_JSON

Loads the artifact FRAMEWORK-FREE (file-path imports of the sibling
serving modules; with AOT sidecars present the spin-up performs zero
XLA compiles — the count is reported in the hello frame), serves
requests over fleet.py's length-prefixed frame protocol, and writes a
heartbeat file (atomic replace; mtime = liveness, payload = serving
stats) on an interval — the round-13 liveness pattern the router's
watchdog reads. A SIGSTOP'd (hung) worker stops heartbeating and is
detected in bounded time; a SIGKILL'd one drops the socket.

OPTS keys: kind ('batching'|'decoding'|'compiled'), tier, platform,
warmup, hb_interval_s, max_queue, batch_timeout_ms, max_batch_size,
inflight, default_max_new.

Frames handled: infer / decode (per-request), drain (predictor drain()
hook: stop admitting, finish in-flight, shed the queue re-routably),
stop. Replies: result (ok or etype/error/requeue), tok (greedy decode
streaming), drained, bye. The hello frame carries the artifact tier the
endpoint ACTUALLY serves plus — for decode artifacts — the cache layout
('slot' or 'block') and mesh tag ('cpu_mp2', None unsharded), so
block-paged and mp-sharded decode tiers (ISSUE 13) route through the
same protocol with the router able to audit what each replica loaded.
"""
import json
import os
import socket
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

import numpy as np  # noqa: E402

import fleet as _fleet  # noqa: E402
import serve as _serve  # noqa: E402
import batching as _batching  # noqa: E402
import decoding as _decoding  # noqa: E402


class _Conn(object):
    """Socket with a send lock: results/toks/heartbeats come from
    predictor callback threads concurrently."""

    def __init__(self, sock):
        self.sock = sock
        self.lock = threading.Lock()

    def send(self, header, arrays=None):
        with self.lock:
            _fleet._send_frame(self.sock, header, arrays)

    def reply_err(self, req_id, exc, requeue=False):
        self.send({'op': 'result', 'id': req_id, 'ok': False,
                   'etype': type(exc).__name__, 'error': str(exc),
                   'requeue': bool(requeue)})


def _is_requeueable(exc, draining):
    """SUBMIT-SITE only: shed-at-the-door errors never cost device work
    — the router can safely re-route them; a draining/closed refusal
    raised by submit() itself is the same no-work case. Errors from a
    request that already DISPATCHED (delivery callbacks, stream pumps)
    must use _stream_requeueable instead — a mid-execution error may
    have cost device work and the fleet contract forbids blind retries
    of those."""
    return isinstance(exc, _batching.ServerOverloaded) or (
        draining and isinstance(exc, RuntimeError))


def _stream_requeueable(exc):
    """POST-DISPATCH (stream pump / delivery callback) re-route
    decision: only a shed that provably cost no device work may
    re-route. MidStreamEvicted is a ServerOverloaded whose victim
    already streamed tokens — re-routing would replay them to the
    client and blindly retry device work."""
    return (isinstance(exc, _batching.ServerOverloaded)
            and not isinstance(exc, _decoding.MidStreamEvicted))


class _BatchingEndpoint(object):
    kind = 'batching'

    def __init__(self, artifact, opts):
        kw = {}
        for k in ('tier', 'platform', 'max_queue', 'max_batch_size'):
            if opts.get(k) is not None:
                kw[k] = opts[k]
        kw['batch_timeout_ms'] = float(opts.get('batch_timeout_ms', 2.0))
        kw['inflight'] = int(opts.get('inflight', 2))
        self.pred = _batching.BatchingPredictor(artifact, **kw)
        if opts.get('warmup', True):
            self.pred.warmup()
        self.tier = self.pred.tier
        self._levels = [int(e.get('lod_levels', 0)) for e in
                        _serve._fetch_entries(self.pred._sig)]
        self.draining = False

    def submit(self, hdr, arrays, conn):
        req_id = hdr['id']
        lod_keys = [k for k in arrays if '.lod' in k]
        if lod_keys:
            # the batcher serves dense feeds only (its own load-time
            # contract): dropping offsets silently could return wrong
            # results — fail THIS request loudly instead
            conn.reply_err(req_id, ValueError(
                'batching fleet serves dense feeds only; request '
                'carries lod offsets %r — serve LoD artifacts with '
                "kind='compiled'" % lod_keys))
            return
        feed = dict(arrays)

        def _done(fut):
            exc = fut.exception()
            if exc is not None:
                # post-submit resolution: only a genuine shed (never
                # dispatched) is safe to re-route
                conn.reply_err(req_id, exc, _stream_requeueable(exc))
                return
            outs = fut.result()
            conn.send({'op': 'result', 'id': req_id, 'ok': True,
                       'n': len(outs), 'lod': self._levels},
                      {'o%d' % j: o for j, o in enumerate(outs)})
        try:
            fut = self.pred.submit(feed,
                                   deadline_ms=hdr.get('deadline_ms'),
                                   request_id=hdr.get('request_id'))
        except Exception as e:
            conn.reply_err(req_id, e,
                           _is_requeueable(e, self.draining))
            return
        fut.add_done_callback(_done)

    def drain(self):
        self.draining = True
        self.pred.drain()

    def snapshot(self):
        return self.pred.stats.snapshot()

    def close(self):
        self.pred.close()


class _DecodingEndpoint(object):
    kind = 'decoding'

    def __init__(self, artifact, opts):
        kw = {}
        # 'draft' (ISSUE 17): 'ngram' attaches the host-side prompt-
        # lookup drafter — the only drafter expressible in a spawn
        # config; 'draft_k' narrows the per-tick draft length
        for k in ('tier', 'platform', 'max_queue', 'draft', 'draft_k'):
            if opts.get(k) is not None:
                kw[k] = opts[k]
        if opts.get('default_max_new') is not None:
            kw['default_max_new_tokens'] = int(opts['default_max_new'])
        self.pred = _decoding.DecodingPredictor(artifact, **kw)
        if opts.get('warmup', True):
            self.pred.warmup()
        self.tier = self.pred.stats.tier
        # ISSUE 13: block-paged and mp-sharded decode artifacts load
        # through the same endpoint (DecodingPredictor reads the layout
        # and mesh from the signature); surface both so the router and
        # fleet_ctl can audit which tier a replica actually serves
        self.layout = self.pred.layout
        self.mesh = self.pred.mesh_tag
        self.draining = False

    def submit(self, hdr, arrays, conn):
        req_id = hdr['id']
        try:
            stream = self.pred.submit(
                arrays['prompt'], max_new_tokens=hdr.get('max_new'),
                beam=hdr.get('beam'),
                deadline_ms=hdr.get('deadline_ms'),
                request_id=hdr.get('request_id'))
        except Exception as e:
            conn.reply_err(req_id, e,
                           _is_requeueable(e, self.draining))
            return
        threading.Thread(target=self._pump,
                         args=(req_id, hdr, stream, conn),
                         daemon=True).start()

    def _pump(self, req_id, hdr, stream, conn):
        try:
            if stream.beam is None and hdr.get('stream'):
                # one frame per DELIVERY BATCH (ISSUE 17): a plain step
                # sends the singleton 'tok' frame, a speculative verify
                # tick coalesces its whole multi-token advance into one
                # 'toks' frame instead of K+1 round-trips
                for batch in stream.batches():
                    if len(batch) == 1:
                        conn.send({'op': 'tok', 'id': req_id,
                                   'tok': int(batch[0])})
                    else:
                        conn.send({'op': 'toks', 'id': req_id,
                                   'toks': [int(t) for t in batch]})
            res = stream.result(600)
        except Exception as e:
            # stream-side failure: the request may have decoded tokens
            # already — only a genuine shed re-routes
            conn.reply_err(req_id, e, _stream_requeueable(e))
            return
        if stream.beam is None:
            conn.send({'op': 'result', 'id': req_id, 'ok': True,
                       'kind': 'greedy'},
                      {'tokens': np.asarray(res, np.int64)})
        else:
            ids, scores = res
            conn.send({'op': 'result', 'id': req_id, 'ok': True,
                       'kind': 'beam'},
                      {'ids': np.asarray(ids, np.int64),
                       'scores': np.asarray(scores, np.float64)})

    def drain(self):
        self.draining = True
        self.pred.drain()

    def snapshot(self):
        return self.pred.stats.snapshot()

    def close(self):
        self.pred.close()


class _CompiledEndpoint(object):
    """Synchronous CompiledPredictor behind a one-thread queue: the
    LoD-capable fallback kind. Requests execute in submit order;
    drain() sheds the queue (re-routable) and waits for the in-flight
    run to deliver."""

    kind = 'compiled'

    def __init__(self, artifact, opts):
        kw = {}
        if opts.get('tier') is not None:
            kw['tier'] = opts['tier']
        if opts.get('platform') is not None:
            kw['platform'] = opts['platform']
        self.pred = _serve.CompiledPredictor(artifact, **kw)
        self.tier = self.pred.tier
        self.draining = False
        self._lock = threading.Lock()
        self._queue = []
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stats = {'requests': 0, 'shed': 0, 'expired': 0}
        self._closed = False
        self._t = threading.Thread(target=self._loop,
                                   name='ptpu-fleet-compiled',
                                   daemon=True)
        self._t.start()
        if opts.get('warmup', True):
            sig = self.pred._sig
            feed = {}
            for e in sig['feeds']:
                data = np.zeros(tuple(e['shape']),
                                np.dtype(e['dtype']))
                lv = int(e.get('lod_levels', 0))
                if lv:
                    offs = [np.zeros(n, np.int32)
                            for n in e['lod_sizes']]
                    feed[e['name']] = (data, offs)
                else:
                    feed[e['name']] = data
            for o in self.pred.run(feed, pad_partial=False):
                np.asarray(o[0] if isinstance(o, tuple) else o)

    def submit(self, hdr, arrays, conn):
        with self._lock:
            if self.draining or self._closed:
                conn.reply_err(hdr['id'],
                               _batching.ServerOverloaded(
                                   'replica draining'), requeue=True)
                return
            # deadline_ms is the REMAINING budget when the frame was
            # written: stamp arrival so endpoint queue time counts too
            self._queue.append((hdr, arrays, conn,
                                time.perf_counter()))
            self._idle.clear()
            self._wake.set()

    def _loop(self):
        while True:
            self._wake.wait()
            with self._lock:
                if not self._queue:
                    self._wake.clear()
                    self._idle.set()
                    if self._closed:
                        return
                    continue
                hdr, arrays, conn, t_in = self._queue.pop(0)
            self._run_one(hdr, arrays, conn, t_in)

    def _run_one(self, hdr, arrays, conn, t_in):
        req_id = hdr['id']
        dl = hdr.get('deadline_ms')
        try:
            if dl is not None and \
                    (time.perf_counter() - t_in) * 1e3 >= dl:
                raise _batching.DeadlineExceeded(
                    'deadline elapsed in the replica queue before '
                    'dispatch%s'
                    % (' (request %s)' % hdr['request_id']
                       if hdr.get('request_id') else ''))
            feed = _serve._feed_from_npz(self.pred._sig['feeds'],
                                         arrays)
            outs = self.pred.run(feed)
        except Exception as e:
            with self._lock:
                key = ('expired' if isinstance(
                    e, _batching.DeadlineExceeded) else None)
                if key:
                    self._stats[key] += 1
            # the run may have dispatched: only sheds re-route
            conn.reply_err(req_id, e, _stream_requeueable(e))
            return
        with self._lock:
            self._stats['requests'] += 1
        lod, flat = [], {}
        for j, o in enumerate(outs):
            if isinstance(o, tuple):
                lod.append(len(o[1]))
                flat['o%d' % j] = o[0]
                for i, off in enumerate(o[1]):
                    flat['o%d.lod%d' % (j, i)] = off
            else:
                lod.append(0)
                flat['o%d' % j] = o
        conn.send({'op': 'result', 'id': req_id, 'ok': True,
                   'n': len(outs), 'lod': lod}, flat)

    def drain(self):
        with self._lock:
            self.draining = True
            shed = list(self._queue)
            self._queue[:] = []
            self._stats['shed'] += len(shed)
        for hdr, _arrays, conn, _t_in in shed:
            conn.reply_err(hdr['id'], _batching.ServerOverloaded(
                'request shed: replica draining for scale-in'),
                requeue=True)
        self._idle.wait(600)

    def snapshot(self):
        with self._lock:
            return {'tier': self.tier,
                    'queue_depth': len(self._queue),
                    'requests': self._stats['requests'],
                    'shed': self._stats['shed'],
                    'expired': self._stats['expired'],
                    'occupancy': 0.0 if self._idle.is_set() else 1.0}

    def close(self):
        with self._lock:
            self._closed = True
            self._wake.set()


_ENDPOINTS = {'batching': _BatchingEndpoint,
              'decoding': _DecodingEndpoint,
              'compiled': _CompiledEndpoint}


def main():
    sock_path, rid, artifact, hb_path, opts_json = sys.argv[1:6]
    rid = int(rid)
    opts = json.loads(opts_json)
    plat = opts.get('platform')
    if plat:
        os.environ.setdefault('JAX_PLATFORMS', plat)
        os.environ.setdefault('PTPU_PLATFORM', plat)

    compiles = [0]
    try:
        from jax import monitoring

        def _listener(event, secs, **kw):
            if event == '/jax/core/compile/backend_compile_duration':
                compiles[0] += 1
        monitoring.register_event_duration_secs_listener(_listener)
    except Exception:
        compiles[0] = -1  # unknown

    kind = opts.get('kind') or _fleet.detect_kind(artifact)
    endpoint = _ENDPOINTS[kind](artifact, opts)
    state = ['serving']

    hb_stop = threading.Event()

    def _hb_loop():
        interval = float(opts.get('hb_interval_s', 0.5))
        while True:
            try:
                _fleet.write_heartbeat(hb_path, {
                    'replica': rid, 'pid': os.getpid(),
                    'artifact': artifact,
                    'state': state[0], 'kind': kind,
                    'compiles': compiles[0],
                    'stats': endpoint.snapshot()})
            except Exception:
                pass
            if hb_stop.wait(interval):
                return

    hb_t = threading.Thread(target=_hb_loop, name='ptpu-fleet-hb',
                            daemon=True)
    hb_t.start()

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    conn = _Conn(sock)
    conn.send({'op': 'hello', 'replica': rid, 'pid': os.getpid(),
               'artifact': artifact,
               'kind': kind, 'tier': endpoint.tier,
               'layout': getattr(endpoint, 'layout', None),
               'mesh': getattr(endpoint, 'mesh', None),
               'compiles': compiles[0],
               'framework_free': 'paddle_tpu' not in sys.modules})

    def _drain_then_ack():
        try:
            endpoint.drain()
        finally:
            state[0] = 'drained'
            try:
                conn.send({'op': 'drained', 'replica': rid})
            except OSError:
                pass

    while True:
        try:
            fr = _fleet._recv_frame(sock)
        except Exception:
            fr = None  # EOF or desynced stream: exit; the router's
            #            reader sees the close and fails over
        if fr is None:
            break  # router gone
        hdr, arrays = fr
        op = hdr.get('op')
        if op in ('infer', 'decode'):
            try:
                endpoint.submit(hdr, arrays, conn)
            except Exception as e:
                conn.reply_err(hdr.get('id'), e)
        elif op == 'drain':
            state[0] = 'draining'
            threading.Thread(target=_drain_then_ack,
                             daemon=True).start()
        elif op == 'stop':
            break
    state[0] = 'stopped'
    try:
        endpoint.close()
    except Exception:
        pass
    hb_stop.set()
    hb_t.join(timeout=5)
    try:
        _fleet.write_heartbeat(hb_path, {
            'replica': rid, 'pid': os.getpid(), 'state': 'stopped',
            'compiles': compiles[0]})
    except Exception:
        pass
    try:
        conn.send({'op': 'bye', 'replica': rid})
    except OSError:
        pass
    sock.close()


if __name__ == '__main__':
    main()
