"""Inference stack (ref: paddle/fluid/inference/).

- predictor: AnalysisPredictor-equivalent serving API (load -> jit -> run
  with a warm compile cache; ref inference/api/analysis_predictor.cc).
- ref_format: byte-level readers/writers for the reference's artifact
  formats — `__model__` ProgramDesc protobuf (framework/framework.proto)
  and SerializeToStream tensors (framework/lod_tensor.cc:245,
  tensor_util.cc:372) — so models trained with the reference run here and
  vice versa.
- export/serve: the non-Python deploy path (ref inference/api/paddle_api.h
  C++ API): export.py AOT-compiles the program to a `jax.export` artifact
  with params baked in (optionally several batch-size buckets per dir);
  serve.py loads and runs it without the tracer.
- batching: BatchingPredictor — dynamic request coalescing over the
  compiled artifacts (multi-bucket selection, async double-buffered
  dispatch, serving metrics through profiler).
- decoding: DecodingPredictor — continuous in-flight batching for
  autoregressive decode over export_decode's two-program artifact
  (prompt-bucketed prefill + fixed-slot decode step over a paged,
  donated KV cache; token-streaming futures); speculative decoding
  rides an optional third verify program with host-side drafters
  (NgramDrafter / DraftModelDrafter).
- fleet: FleetRouter — the replica-fleet control plane over any of the
  predictors above (subprocess workers via fleet_worker.py,
  least-outstanding-work routing with deadline propagation,
  heartbeat-watchdog failover, Autoscaler, RollingRollout canary/
  promote/rollback).
- gateway: Gateway — the HTTP/1.1 network front door over a FleetRouter
  or single predictor (JSON + base64-npz codec, SSE token streaming,
  per-tenant API keys with token-bucket rate limits and inflight
  quotas, deadline propagation from the HTTP door, request_id tracing,
  /healthz //stats.json //metrics, graceful drain).
The reference's analysis/TensorRT/MKLDNN pass zoo is subsumed by XLA:
clone(for_test) freezes BN/dropout, XLA does the fusion.
"""
from .predictor import Config, Predictor, create_predictor
from .ref_format import (load_reference_inference_model,
                         save_reference_inference_model,
                         load_reference_persistables)
from .export import export_compiled, export_train_step, export_decode
from .serve import (CompiledPredictor, load_compiled,
                    CompiledTrainer, load_trainer)
from .batching import (BatchingPredictor, ServingStats, load_batching,
                       ServerOverloaded, DeadlineExceeded)
from .decoding import (DecodingPredictor, DecodeStats, TokenStream,
                       MidStreamEvicted, load_decoding,
                       NgramDrafter, DraftModelDrafter)
from .fleet import (FleetRouter, FleetStats, Autoscaler, RollingRollout,
                    ReplicaFailed, FleetUnavailable, RolloutRolledBack,
                    load_fleet)
from .gateway import (Gateway, GatewayStats, TenantConfig,
                      tenants_from_json, render_metrics)

__all__ = ['Config', 'Predictor', 'create_predictor',
           'load_reference_inference_model',
           'save_reference_inference_model',
           'load_reference_persistables',
           'export_compiled', 'CompiledPredictor', 'load_compiled',
           'export_train_step', 'CompiledTrainer', 'load_trainer',
           'export_decode', 'DecodingPredictor', 'DecodeStats',
           'TokenStream', 'MidStreamEvicted', 'load_decoding',
           'NgramDrafter', 'DraftModelDrafter',
           'BatchingPredictor', 'ServingStats', 'load_batching',
           'ServerOverloaded', 'DeadlineExceeded',
           'FleetRouter', 'FleetStats', 'Autoscaler', 'RollingRollout',
           'ReplicaFailed', 'FleetUnavailable', 'RolloutRolledBack',
           'load_fleet',
           'Gateway', 'GatewayStats', 'TenantConfig',
           'tenants_from_json', 'render_metrics']
