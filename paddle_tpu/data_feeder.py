"""DataFeeder: python rows -> feed dict (ref: fluid/data_feeder.py:100)."""
from __future__ import annotations

import numpy as np

from .framework import Variable, default_main_program
from .lod_tensor import create_lod_tensor


class DataToLoDTensorConverter(object):
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [int(s) for s in shape if s is not None and s > 0]
        self.dtype = dtype
        self._reset()

    def _reset(self):
        self.data = []
        self.lod = [[] for _ in range(self.lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self):
        if self.lod_level == 0:
            arr = np.asarray(self.data, dtype=self.dtype)
            per_sample = int(np.prod(arr.shape[1:])) if arr.ndim > 1 else 1
            declared = int(np.prod(self.shape)) if self.shape else per_sample
            if self.shape and per_sample == declared and \
                    list(arr.shape[1:]) != self.shape:
                arr = arr.reshape([arr.shape[0]] + self.shape)
            out = arr
        else:
            rows = [np.asarray(r) for r in self.data]
            flat = (np.stack(rows).astype(self.dtype) if rows
                    else np.zeros([0] + self.shape, dtype=self.dtype))
            if self.shape and list(flat.shape[1:]) != self.shape and \
                    int(np.prod(flat.shape[1:])) == int(np.prod(self.shape)):
                flat = flat.reshape([flat.shape[0]] + self.shape)
            out = create_lod_tensor(flat, self.lod)
        self._reset()
        return out


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        # cumulative rows->arrays conversion seconds: feed() runs on the
        # feeder thread, so this is feeder-side work the data plane
        # surfaces (profiler feeder_report conv(ms)), not step-loop stall
        self.convert_s = 0.0
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("Feed list should contain Variables or names")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(list(each_var.shape or ()))
        self.place = place

    def feed(self, iterable):
        import time as _time
        t0 = _time.perf_counter()
        converters = []
        for lod_level, shape, dtype in zip(self.feed_lod_level,
                                           self.feed_shapes, self.feed_dtypes):
            converters.append(DataToLoDTensorConverter(
                place=self.place, lod_level=lod_level,
                shape=[s for s in shape if s != -1], dtype=dtype))
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "The number of fields in data (%d) does not match the number "
                "of feed variables (%d)" % (len(each_sample), len(converters)))
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        ret_dict = {}
        for each_name, each_converter in zip(self.feed_names, converters):
            ret_dict[each_name] = each_converter.done()
        self.convert_s += _time.perf_counter() - t0
        return ret_dict

    def feed_parallel(self, iterable, num_places=None):
        """Split samples round-robin per place (ref data_feeder.py
        feed_parallel); with SPMD we instead return one batch dict — the
        mesh shards it — so this simply concatenates."""
        for item in iterable:
            yield self.feed(item)

    def decorate_reader(self, reader, multi_devices=False, num_places=None,
                        drop_last=True):
        def _reader():
            for item in reader():
                yield self.feed(item)
        return _reader
