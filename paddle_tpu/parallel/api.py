"""User-facing sharding annotations (TPU-native extension).

The reference's tensor-model-parallelism story was layer-device placement in
the legacy stack (ParallelNeuralNetwork.h:34) and sharded embedding tables on
pservers (distribute_transpiler.py:1012). Here both collapse into GSPMD
partition specs on parameters: annotate, and XLA partitions the matmuls and
inserts the collectives (all-gather/reduce-scatter over ICI).
"""
from __future__ import annotations

from ..framework import Variable
from .mesh import MODEL_AXIS, EXPERT_AXIS


def shard_parameter(param, spec):
    """Attach a partition spec to a parameter.

    spec: tuple with one entry per tensor dim — a mesh axis name to shard
    that dim over, or None to replicate it. e.g. for an fc weight [in, out]:
    shard_parameter(w, (None, 'mp')) = column-parallel (Megatron-style).
    """
    assert isinstance(param, Variable)
    param.sharding_spec = tuple(spec)
    return param


def shard_embedding(param, axis=0, mesh_axis=EXPERT_AXIS):
    """Shard an embedding table over a mesh axis (row-sharded vocab) — the
    dist-lookup-table capability (SURVEY §2.3): XLA turns the gathers into
    all-to-all traffic on the mesh."""
    spec = [None] * len(param.shape)
    spec[axis] = mesh_axis
    return shard_parameter(param, spec)


class MultiStepTrainer(object):
    """Multi-step training dispatch driver (the training-side counterpart
    of inference.BatchingPredictor, with a CompiledTrainer-style surface):
    owns the executor, the steps-per-dispatch policy, and the epoch loop
    with EOF tail flushing over Executor.run_steps — one device dispatch
    advances optimizer state K steps, so dispatch-bound workloads divide
    the per-run() floor by K (PERF_NOTES.md "Training dispatch floor").

        trainer = MultiStepTrainer(main_prog, steps_per_dispatch=16,
                                   fetch_list=[loss])
        trainer.startup(startup_prog)
        reader.prefetch_to_device(16)          # optional fast path
        for fetches in trainer.iter_epoch(reader):
            ...                                # one entry per DISPATCH
    """

    def __init__(self, program, steps_per_dispatch=8, fetch_list=None,
                 fetch_policy='final', place=None, scope=None,
                 executor=None, checkpoint=None, preemptible=False):
        from ..executor import Executor
        from ..framework import TPUPlace
        if int(steps_per_dispatch) < 1:
            raise ValueError("steps_per_dispatch must be >= 1, got %d"
                             % int(steps_per_dispatch))
        self.program = program
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.fetch_list = list(fetch_list or [])
        self.fetch_policy = fetch_policy
        self.scope = scope
        self.executor = executor if executor is not None else Executor(
            place if place is not None else TPUPlace())
        # fault-tolerance policy (core/checkpoint.py): evaluated at every
        # dispatch boundary; startup() restores from the newest committed
        # checkpoint so a SIGKILLed trainer resumes where it stopped
        self.checkpoint = checkpoint
        # preemptible=True routes SIGTERM (the scheduler's preemption
        # notice) to a graceful drain: run_steps writes one final
        # checkpoint at the next step boundary and exits 0 — a clean
        # resume instead of a crash (requires checkpoint=)
        self.preemptible = bool(preemptible)
        self.resume_info = None

    def startup(self, startup_program):
        """Run the startup program so every state var the K-step scan
        carries is materialized (run_steps refuses to create scan-carry
        entries mid-loop). With a checkpoint manager attached, then
        restore from the newest fully-committed checkpoint when one
        exists — kill-and-resume is the SAME script run twice. Returns
        self; resume_info/resume_step tell whether (and where) a restore
        happened."""
        self.executor.run(startup_program, scope=self.scope)
        if self.checkpoint is not None:
            if self.preemptible:
                from ..core import checkpoint as _ckpt
                _ckpt.install_preemption_handler()
            self.resume_info = self.checkpoint.restore(
                executor=self.executor, program=self.program,
                scope=self.scope)
        return self

    @property
    def resume_step(self):
        """Steps already trained before this incarnation (0 on a cold
        start)."""
        return int(self.resume_info['step']) if self.resume_info else 0

    def step_group(self, feed=None, reader=None, steps=None):
        """One dispatch of up to steps_per_dispatch steps; returns the
        fetches per fetch_policy ('final': last step only; 'stack':
        [K, ...] per fetch)."""
        return self.executor.run_steps(
            self.program, reader=reader, feed=feed,
            fetch_list=self.fetch_list,
            steps=int(steps) if steps is not None
            else self.steps_per_dispatch,
            scope=self.scope, fetch_policy=self.fetch_policy,
            checkpoint=self.checkpoint)

    def iter_epoch(self, reader):
        """Drive one epoch from a PyReader, yielding fetches per dispatch;
        starts the reader when needed, flushes the EOF tail group through
        its smaller compiled bucket, and resets the reader on exit. With
        a sharded/pooled reader decorated in (reader/sharded.py), the
        feeder-side counters land in profiler.training_report() next to
        this loop's host-stall column."""
        from ..core import EOFException
        # start when never started OR drained; the reader rejoins its
        # feeder thread the moment EOF is consumed (pipeline._pop), so
        # repeated sessions never accumulate dead threads and a drained
        # reader is indistinguishable from a fresh one here
        if getattr(reader, '_thread', None) is None \
                or getattr(reader, '_closed', True):
            reader.start()
        try:
            while True:
                try:
                    yield self.step_group(reader=reader)
                except EOFException:
                    return
        finally:
            reader.reset()

    @property
    def stats(self):
        """Per-dispatch counters (dispatches, steps, tail_flushes,
        host_stall_s) — also surfaced by profiler.training_report()."""
        return dict(self.executor._dispatch_stats)
