"""User-facing sharding annotations (TPU-native extension).

The reference's tensor-model-parallelism story was layer-device placement in
the legacy stack (ParallelNeuralNetwork.h:34) and sharded embedding tables on
pservers (distribute_transpiler.py:1012). Here both collapse into GSPMD
partition specs on parameters: annotate, and XLA partitions the matmuls and
inserts the collectives (all-gather/reduce-scatter over ICI).
"""
from __future__ import annotations

from ..framework import Variable
from .mesh import MODEL_AXIS, EXPERT_AXIS


def shard_parameter(param, spec):
    """Attach a partition spec to a parameter.

    spec: tuple with one entry per tensor dim — a mesh axis name to shard
    that dim over, or None to replicate it. e.g. for an fc weight [in, out]:
    shard_parameter(w, (None, 'mp')) = column-parallel (Megatron-style).
    """
    assert isinstance(param, Variable)
    param.sharding_spec = tuple(spec)
    return param


def shard_embedding(param, axis=0, mesh_axis=EXPERT_AXIS):
    """Shard an embedding table over a mesh axis (row-sharded vocab) — the
    dist-lookup-table capability (SURVEY §2.3): XLA turns the gathers into
    all-to-all traffic on the mesh."""
    spec = [None] * len(param.shape)
    spec[axis] = mesh_axis
    return shard_parameter(param, spec)
