"""Topology-change resharding (ISSUE 14 tentpole).

A pod checkpoint written by N hosts describes GLOBAL arrays; restoring it
onto N' != N hosts means re-laying those arrays out over a DIFFERENT mesh.
This module owns the three pieces that make that safe:

- `state_shardings_for(program, mesh, names)` — THE state-sharding rule
  (parameter annotations + optimizer slots inheriting their param's spec
  by name-prefix + shape match), factored out of the executor's mesh
  dispatch so checkpoint restore and step dispatch can never disagree
  about where a tensor lives. One copy, two callers (the round-16
  "_decode_mesh delegates" discipline applied to training state).
- `check_reshardable(...)` — the loud, actionable gate: a checkpoint
  axis that does not divide the new mesh axis raises `ReshardError`
  naming the param, the old/new shardings, and the nearest VALID axis
  sizes (= host counts when that axis spans hosts) instead of letting
  the operator meet a bare XLA shape error three layers down.
- `reshard_to_mesh(values, shardings, mesh)` — the resharding program:
  each assembled host-side global value is scattered onto the new mesh
  as a global jax.Array in its target NamedSharding (every process
  serves its local shards from its own assembled copy — the same
  construction the executor's `_mesh_put` uses at dispatch, done once
  at restore so the first step starts from device-resident state and a
  divisibility error surfaces HERE, not mid-dispatch).

`reshard_stats` counts resharding work (distinct placement programs,
arrays, bytes, seconds). The same-shape restore path never touches this
module — `reshard_stats['programs'] == 0` after a same-shape restore is
a pinned regression (tests/test_elastic_pod.py): topology-change resume
must never tax the bit-exact common case.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ['ReshardError', 'state_shardings_for', 'check_reshardable',
           'reshard_to_mesh', 'reshard_stats', 'reset_reshard_stats',
           'nearest_valid_sizes']


class ReshardError(ValueError):
    """A checkpoint cannot be resharded onto the requested mesh; the
    message names every offending param, its old/new sharding, and the
    nearest valid mesh-axis sizes (host counts when the axis spans
    hosts)."""


# stitch (assembling globals from per-host shards) is timed by
# PodCheckpointManager.restore() itself and returned as info['stitch_s'];
# this dict books only the RESHARD work this module performs
reshard_stats = {'programs': 0, 'arrays': 0, 'bytes': 0, 'place_s': 0.0}


def reset_reshard_stats():
    reshard_stats.update(programs=0, arrays=0, bytes=0, place_s=0.0)


def _prog_vars(program, names):
    out = {}
    for n in names:
        for b in program.blocks:
            v = b.vars.get(n)
            if v is not None:
                out[n] = v
                break
    return out


def state_shardings_for(program, mesh, state_names):
    """The ONE state-sharding rule, shared by the executor's mesh
    dispatch and PodCheckpointManager's topology-change restore.

    Parameters carrying a `sharding_spec` annotation (parallel.api.
    shard_parameter) shard accordingly; optimizer slots
    (<param>_velocity_0, <param>_moment_0, ...) inherit their param's
    annotation when the name is prefixed by the param's and the shapes
    match — an unannotated same-shape slot replicated next to a sharded
    param would force a gather/scatter every update. Everything else is
    replicated. Specs naming axes the mesh does not carry fall back to
    replicated (the executor's long-standing forgiving rule).

    Returns (shardings, specs): {name: NamedSharding} over ALL
    state_names, and {name: partition-spec tuple} for just the names
    that resolved to a non-replicated sharding (the surface
    check_reshardable validates)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from .mesh import replicated
    rep = replicated(mesh)
    prog_vars = _prog_vars(program, state_names)
    annotated = {n: tuple(prog_vars[n].sharding_spec)
                 for n in state_names
                 if prog_vars.get(n) is not None
                 and getattr(prog_vars[n], 'sharding_spec', None)}
    shardings, specs = {}, {}
    for n in state_names:
        spec = annotated.get(n)
        if spec is None:
            v = prog_vars.get(n)
            for pn, pspec in annotated.items():
                pv = prog_vars.get(pn)
                if v is not None and pv is not None \
                        and n.startswith(pn + '_') \
                        and tuple(v.shape) == tuple(pv.shape):
                    spec = pspec
                    break
        if spec is not None and all(a is None or a in mesh.shape
                                    for a in spec):
            shardings[n] = NamedSharding(mesh, PartitionSpec(*spec))
            specs[n] = spec
        else:
            shardings[n] = rep
    return shardings, specs


def nearest_valid_sizes(dim, size):
    """The nearest divisors of `dim` around `size`: (largest divisor
    <= size, smallest divisor >= size). These are the nearest VALID
    mesh-axis sizes — i.e. the nearest valid host counts when the axis
    spans one device per host."""
    dim, size = int(dim), int(size)
    below = max((d for d in range(1, min(dim, size) + 1)
                 if dim % d == 0), default=1)
    above = next((d for d in range(max(size, 1), dim + 1)
                  if dim % d == 0), dim)
    return below, above


def check_reshardable(shapes, specs, mesh, old_num_hosts=None,
                      new_num_hosts=None):
    """Validate that every annotated state var divides the new mesh.
    `shapes`: {name: tuple}, `specs`: {name: partition-spec tuple} (the
    non-replicated surface from state_shardings_for). Collects EVERY
    violation into one ReshardError so the operator fixes the topology
    once, not once per param."""
    problems = []
    for name in sorted(specs):
        spec, shape = specs[name], shapes.get(name)
        if shape is None:
            continue
        for dim, axis in enumerate(spec):
            if axis is None or axis not in mesh.shape:
                continue
            k, s = int(mesh.shape[axis]), int(shape[dim])
            if s % k == 0:
                continue
            below, above = nearest_valid_sizes(s, k)
            if above > k:
                hint = '%d (shrink) / %d (grow)' % (below, above)
            else:
                # no divisor of the dim is >= the requested size: the
                # dim itself is the ceiling — never label it a "grow"
                hint = '%d (largest valid)' % below
            problems.append(
                "param %r dim %d (=%d) is not divisible by mesh axis "
                "%r (=%d) [spec %r, shape %r]; nearest valid %r sizes: "
                "%s" % (name, dim, s, axis, k, tuple(spec),
                        tuple(shape), axis, hint))
    if problems:
        topo = ''
        if old_num_hosts is not None and new_num_hosts is not None:
            topo = (' while restoring a %d-host checkpoint onto %d '
                    'host(s)' % (int(old_num_hosts), int(new_num_hosts)))
        raise ReshardError(
            'cannot reshard the checkpoint onto mesh %r%s:\n  %s\n'
            'pick a host count whose mesh axes divide every sharded '
            'param (the nearest valid sizes above are host counts when '
            'the axis spans hosts)'
            % (dict(mesh.shape), topo, '\n  '.join(problems)))


def reshard_to_mesh(values, shardings, mesh):
    """Scatter assembled host-side global values onto `mesh` per their
    target shardings. Only names with a NON-replicated sharding are
    placed (replicated state rides the executor's dispatch-time
    placement for free); non-ndarray values (LoD wrappers, scalars) are
    passed through untouched. Returns a new {name: value} dict; books
    the work into `reshard_stats`."""
    import jax
    from .mesh import replicated
    rep = replicated(mesh)
    out = dict(values)
    seen_programs = set()
    t0 = time.perf_counter()
    for name in sorted(values):
        ns = shardings.get(name)
        if ns is None or ns == rep:
            continue
        host = values[name]
        if not isinstance(host, np.ndarray):
            continue
        key = (tuple(host.shape), str(host.dtype), str(ns.spec))
        if key not in seen_programs:
            seen_programs.add(key)
            reshard_stats['programs'] += 1
        arr = jax.make_array_from_callback(
            host.shape, ns, lambda idx, _h=host: _h[idx])
        out[name] = arr
        reshard_stats['arrays'] += 1
        reshard_stats['bytes'] += int(host.nbytes)
    reshard_stats['place_s'] += time.perf_counter() - t0
    return out
