"""Device mesh management.

TPU-native replacement for the reference's device bookkeeping
(NCCLContextMap platform/nccl_helper.h:86, gen_nccl_id rendezvous,
ParallelExecutor place lists): one jax.sharding.Mesh names the axes
(dp/tp/pp/sp/ep) and XLA's GSPMD inserts the collectives the reference built
op handles for (details/all_reduce_op_handle.cc). Multi-host: the same code
— jax.devices() spans hosts under jax.distributed, collectives ride ICI
within a slice and DCN across slices; no id exchange needed.
"""
from __future__ import annotations

import contextlib
import os

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = 'dp'
MODEL_AXIS = 'mp'
PIPE_AXIS = 'pp'
SEQ_AXIS = 'sp'
EXPERT_AXIS = 'ep'


def _accel_devices(backend=None):
    if backend is not None:
        return jax.devices(backend)
    from ..core.config import accel_devices
    return accel_devices()


def make_mesh(num_devices=None, axes=None, backend=None):
    """Build a Mesh. axes: dict axis_name -> size (row-major over devices);
    default = pure data parallelism over all devices."""
    devs = _accel_devices(backend)
    if num_devices is None:
        num_devices = int(os.environ.get('PTPU_NUM_DEVICES', len(devs)))
    devs = devs[:num_devices]
    if axes is None:
        axes = {DATA_AXIS: len(devs)}
    names = tuple(axes)
    shape = tuple(axes.values())
    assert int(np.prod(shape)) == len(devs), (
        "mesh axes %r need %d devices, have %d" %
        (axes, int(np.prod(shape)), len(devs)))
    return Mesh(np.asarray(devs).reshape(shape), names)


_trace_mesh = {'mesh': None}


@contextlib.contextmanager
def trace_mesh_scope(mesh):
    """Trace-time mesh context: set by the Executor around the step trace
    so mesh-aware lowerings (ring attention) can shard_map over the
    compile mesh without plumbing it through the op system."""
    prev = _trace_mesh['mesh']
    _trace_mesh['mesh'] = mesh
    try:
        yield
    finally:
        _trace_mesh['mesh'] = prev


def current_trace_mesh():
    return _trace_mesh['mesh']


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh, ndim, axis=DATA_AXIS):
    return NamedSharding(mesh, PartitionSpec(axis, *([None] * (ndim - 1))))
