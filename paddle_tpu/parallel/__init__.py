from .mesh import make_mesh, DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS, \
    EXPERT_AXIS  # noqa: F401
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa
from .parallel_executor import ParallelExecutor  # noqa: F401
from .api import shard_parameter, shard_embedding, MultiStepTrainer  # noqa: F401,E501
from .ring_attention import ring_attention  # noqa: F401
from .multihost import init_distributed, pod_run_id, \
    PodCheckpointManager, HostWatchdog, fs_barrier, BarrierTimeout  # noqa: F401,E501
from .reshard import ReshardError, state_shardings_for, \
    check_reshardable, reshard_to_mesh, reshard_stats, \
    reset_reshard_stats  # noqa: F401
