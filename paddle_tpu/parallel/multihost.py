"""Multi-host (pod / multi-node) wiring.

The reference's multi-node story is an id-rendezvous + NCCL communicator
per rank (gen_nccl_id_op.cc:31, platform/nccl_helper.h:130, nranks =
num_trainers x ndev, parallel_executor.cc:203) or gRPC parameter servers.
TPU-native replacement: `jax.distributed.initialize` joins every host into
ONE runtime; jax.devices() then spans the pod, a Mesh built over them spans
hosts, and the SAME SPMD program runs everywhere — GSPMD collectives ride
ICI within a slice and DCN across hosts. No id exchange, no pserver role.

Cluster env contract follows the reference's
(transpiler/distribute_transpiler.py:222 nccl2 mode / test_dist_base.py):
  PADDLE_TRAINERS            number of processes (trainer count)
  PADDLE_TRAINER_ID          this process's rank
  PADDLE_TRAINER_ENDPOINTS   comma list host:port; entry 0 is the
                             coordinator (or set PADDLE_COORDINATOR)
"""
from __future__ import annotations

import os

import numpy as np
import jax

_initialized = {'done': False}


def _effective_platform(platform):
    """The platform the backend will initialize with, best-effort: the
    explicit argument wins, then the env pins tests use."""
    if platform is not None:
        return platform
    for env in ('JAX_PLATFORMS', 'PTPU_PLATFORM'):
        v = os.environ.get(env)
        if v:
            return v.split(',')[0]
    return None


def init_distributed(coordinator_address=None, num_trainers=None,
                     trainer_id=None, platform=None):
    """Join this process into the multi-host runtime. No-op for a single
    trainer. Call before any other jax use (backends must not be
    initialized yet). Returns (num_trainers, trainer_id)."""
    if num_trainers is None:
        num_trainers = int(os.environ.get('PADDLE_TRAINERS', '1'))
    if trainer_id is None:
        trainer_id = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    if coordinator_address is None:
        coordinator_address = os.environ.get('PADDLE_COORDINATOR')
    if coordinator_address is None:
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        if eps:
            coordinator_address = eps.split(',')[0]
    if platform is not None:
        # pin the platform BEFORE backend init (e.g. 'cpu' for the
        # simulated-pod tests; on a real pod the TPU platform is
        # default). Also on the single-trainer path: an elastic pod
        # resized down to ONE host runs the same worker script, and
        # skipping the pin there would let an installed TPU plugin
        # initialize (and hang on GCP metadata) despite the explicit
        # platform argument.
        jax.config.update('jax_platforms', platform)
    if num_trainers <= 1:
        return 1, 0
    if coordinator_address is None:
        raise ValueError(
            "multi-host init needs a coordinator: set PADDLE_COORDINATOR or "
            "PADDLE_TRAINER_ENDPOINTS (first endpoint is the coordinator)")
    if _effective_platform(platform) == 'cpu':
        # XLA:CPU alone cannot execute a computation spanning processes
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"); gloo supplies the cross-process collective transport
        # for the simulated pod. Must land BEFORE backend init.
        try:
            jax.config.update('jax_cpu_collectives_implementation', 'gloo')
        except Exception:
            pass    # jaxlib without gloo: single-host-per-program only
    if not _initialized['done']:
        jax.distributed.initialize(coordinator_address,
                                   num_processes=num_trainers,
                                   process_id=trainer_id)
        _initialized['done'] = True
    return num_trainers, trainer_id


def process_count():
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


def process_index():
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def mesh_spans_processes(mesh):
    devs = np.asarray(mesh.devices).reshape(-1)
    return len({d.process_index for d in devs}) > 1


def pod_run_id():
    """One id shared by every process of THIS pod incarnation — the token
    PodCheckpointManager uses to keep a restarted pod from stitching a
    dead incarnation's stale host shards into a fresh checkpoint.
    Resolution order: PTPU_POD_RUN_ID (set by the pod supervisor /
    tools/chaos.py --pod), else rank 0 mints a uuid and shares it through
    the distributed KV store, else (single process) a local uuid."""
    rid = os.environ.get('PTPU_POD_RUN_ID')
    if rid:
        return rid
    import uuid
    if process_count() <= 1:
        return uuid.uuid4().hex
    try:
        client = jax._src.distributed.global_state.client
        if process_index() == 0:
            rid = uuid.uuid4().hex
            client.key_value_set('ptpu_pod_run_id', rid)
            return rid
        return client.blocking_key_value_get('ptpu_pod_run_id', 60_000)
    except Exception as e:
        # no KV store (older jaxlib): there is NO way to mint a token
        # that is both shared across hosts and unique per incarnation —
        # a coordinator-address fallback would repeat across restarts
        # and re-open the exact stale-shard stitching hole the run_id
        # exists to close. Make the operator supply one.
        raise RuntimeError(
            'pod_run_id: no distributed KV store available (%s: %s) — '
            'set PTPU_POD_RUN_ID to a fresh value for every pod launch'
            % (type(e).__name__, e))


# pod-scale failure-detection primitives live next to the checkpoint
# machinery (stdlib-only, standalone-loadable by tools/chaos.py); re-export
# the parallel-facing surface here
from ..core.checkpoint import (     # noqa: E402,F401
    BarrierTimeout, fs_barrier, write_heartbeat, read_heartbeats,
    stale_hosts, HostWatchdog, PodCheckpointManager, pod_latest_committed)


def place_local_shard(sharding, local_np, n_processes):
    """Assemble a GLOBAL array from this process's local batch shard
    (the TPU equivalent of each trainer feeding its own data shard,
    test_dist_base methodology). The global leading dim is
    local_rows x n_processes; sharded dims must divide accordingly."""
    local_np = np.asarray(local_np)
    global_shape = (local_np.shape[0] * n_processes,) + local_np.shape[1:]
    return jax.make_array_from_process_local_data(sharding, local_np,
                                                  global_shape=global_shape)
