"""Multi-host (pod / multi-node) wiring.

The reference's multi-node story is an id-rendezvous + NCCL communicator
per rank (gen_nccl_id_op.cc:31, platform/nccl_helper.h:130, nranks =
num_trainers x ndev, parallel_executor.cc:203) or gRPC parameter servers.
TPU-native replacement: `jax.distributed.initialize` joins every host into
ONE runtime; jax.devices() then spans the pod, a Mesh built over them spans
hosts, and the SAME SPMD program runs everywhere — GSPMD collectives ride
ICI within a slice and DCN across hosts. No id exchange, no pserver role.

Cluster env contract follows the reference's
(transpiler/distribute_transpiler.py:222 nccl2 mode / test_dist_base.py):
  PADDLE_TRAINERS            number of processes (trainer count)
  PADDLE_TRAINER_ID          this process's rank
  PADDLE_TRAINER_ENDPOINTS   comma list host:port; entry 0 is the
                             coordinator (or set PADDLE_COORDINATOR)
"""
from __future__ import annotations

import os

import numpy as np
import jax

_initialized = {'done': False}


def init_distributed(coordinator_address=None, num_trainers=None,
                     trainer_id=None, platform=None):
    """Join this process into the multi-host runtime. No-op for a single
    trainer. Call before any other jax use (backends must not be
    initialized yet). Returns (num_trainers, trainer_id)."""
    if num_trainers is None:
        num_trainers = int(os.environ.get('PADDLE_TRAINERS', '1'))
    if trainer_id is None:
        trainer_id = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    if coordinator_address is None:
        coordinator_address = os.environ.get('PADDLE_COORDINATOR')
    if coordinator_address is None:
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        if eps:
            coordinator_address = eps.split(',')[0]
    if num_trainers <= 1:
        return 1, 0
    if coordinator_address is None:
        raise ValueError(
            "multi-host init needs a coordinator: set PADDLE_COORDINATOR or "
            "PADDLE_TRAINER_ENDPOINTS (first endpoint is the coordinator)")
    if platform is not None:
        # pin the platform BEFORE backend init (e.g. 'cpu' for the
        # simulated-pod tests; on a real pod the TPU platform is default)
        jax.config.update('jax_platforms', platform)
    if not _initialized['done']:
        jax.distributed.initialize(coordinator_address,
                                   num_processes=num_trainers,
                                   process_id=trainer_id)
        _initialized['done'] = True
    return num_trainers, trainer_id


def process_count():
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


def process_index():
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def mesh_spans_processes(mesh):
    devs = np.asarray(mesh.devices).reshape(-1)
    return len({d.process_index for d in devs}) > 1


def place_local_shard(sharding, local_np, n_processes):
    """Assemble a GLOBAL array from this process's local batch shard
    (the TPU equivalent of each trainer feeding its own data shard,
    test_dist_base methodology). The global leading dim is
    local_rows x n_processes; sharded dims must divide accordingly."""
    local_np = np.asarray(local_np)
    global_shape = (local_np.shape[0] * n_processes,) + local_np.shape[1:]
    return jax.make_array_from_process_local_data(sharding, local_np,
                                                  global_shape=global_shape)
