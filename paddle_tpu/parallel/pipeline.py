"""SPMD pipeline parallelism (GPipe schedule) over the mesh 'pp' axis
(TPU-native extension; the reference's pipeline story never left the
legacy layer-placement design, SURVEY §2.4).

Shape: L IDENTICAL layers, parameters stacked on a leading [L, ...] axis
sharded over 'pp' (each of the P ranks owns L/P consecutive layers); the
batch splits into M microbatches. One lax.scan runs the classic
fill/compute/drain schedule: at every tick each rank applies its layer to
the activation arriving from the previous rank (a lax.ppermute shift
register — one ICI hop per tick), rank 0 injects fresh microbatches,
rank P-1 emits finished ones. Bubble ticks compute on don't-care data and
are masked out — the standard GPipe trade (bubble fraction
(P-1)/(M+P-1)). The scan is reverse-differentiable, so training works
out of the box.

Current scope: one layer per rank (L == P). Deeper stacks pipeline in
groups by calling gpipe_apply once per group of P layers.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import DATA_AXIS, PIPE_AXIS


def gpipe_apply(layer_fn, stacked_params, x_microbatches, mesh,
                pp_axis=PIPE_AXIS, batch_axis=DATA_AXIS):
    """Apply P stacked layers as a pipeline over `pp_axis`.

    layer_fn(params_slice, x) -> y with y.shape == x.shape
    stacked_params: pytree; every leaf has leading dim P (layer axis),
        sharded over pp_axis.
    x_microbatches: [M, mb, ...] microbatched input; the mb dim shards
        over `batch_axis` when the mesh has one (each dp group pipelines
        only its own batch shard — layers never mix rows).
    Returns [M, mb, ...]: layer P-1(...layer 0(x)).
    """
    try:
        from jax import shard_map
        rep_kw = {'check_vma': False}
    except ImportError:
        from jax.experimental.shard_map import shard_map
        rep_kw = {'check_rep': False}

    nstages = int(mesh.shape[pp_axis])
    m = x_microbatches.shape[0]
    ndp = int(mesh.shape.get(batch_axis, 1))
    # shard the microbatch rows over dp only when they divide; else
    # replicate (correct, just without the dp speedup for this op)
    b_ax = batch_axis if ndp > 1 \
        and x_microbatches.shape[1] % ndp == 0 else None
    extra = (None,) * (x_microbatches.ndim - 2)
    xs_spec = P(None, b_ax, *extra)
    param_specs = jax.tree.map(lambda _: P(pp_axis), stacked_params)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, xs_spec),
        out_specs=P(pp_axis, None, b_ax, *extra), **rep_kw)
    def pipe(params_local, xs):
        rank = jax.lax.axis_index(pp_axis)
        p_local = jax.tree.map(lambda a: a[0], params_local)  # this stage
        perm = [(i, (i + 1) % nstages) for i in range(nstages)]
        zero = jnp.zeros_like(xs[0])

        def tick(carry, t):
            shifted = carry            # output of rank-1 from last tick
            mb_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(rank == 0,
                            jax.lax.dynamic_index_in_dim(
                                xs, mb_idx, keepdims=False),
                            shifted)
            out = layer_fn(p_local, inp)
            # don't-care ticks (pipeline bubble) produce garbage that is
            # never emitted; zero it so NaNs can't propagate via ppermute
            active = (t >= rank) & (t < m + rank)
            out = jnp.where(active, out, zero)
            return jax.lax.ppermute(out, pp_axis, perm), out

        ticks = jnp.arange(m + nstages - 1)
        _, outs = jax.lax.scan(tick, zero, ticks)   # [T, mb, ...]
        # this rank's finished microbatch j sits at tick j + rank; only
        # rank P-1's slice is the pipeline output (selected by the caller
        # from the out_specs=P(pp_axis) leading axis)
        sel = jax.lax.dynamic_slice_in_dim(outs, rank, m, axis=0) \
            if nstages > 1 else outs[:m]
        return sel[None]               # [1, M, mb, ...] per rank

    stacked = pipe(stacked_params, x_microbatches)  # [P, M, mb, ...]
    return stacked[-1]                              # rank P-1's emissions
