"""ParallelExecutor (ref: python/paddle/fluid/parallel_executor.py:41,
framework/parallel_executor.cc:191).

The reference replicates the graph per GPU and schedules an SSA graph with
NCCL all-reduce handles. TPU-native: one program + one mesh; run() delegates
to the SPMD Executor path (executor.py _build with mesh). num_trainers /
trainer_id (the nccl2 multi-node knobs) are accepted: under jax.distributed
the mesh already spans hosts, so they only participate in sanity checks.
"""
from __future__ import annotations

import numpy as np

from ..executor import Executor
from ..framework import default_main_program
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy


class ParallelExecutor(object):
    def __init__(self, use_cuda, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._program = main_program or default_main_program()
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy)
        self._exe = Executor()  # backend resolved via core.config
        self._scope = scope
        self._num_trainers = num_trainers
        self._trainer_id = trainer_id

    @property
    def device_count(self):
        mesh = self._compiled._get_mesh(self._exe)
        return int(np.prod(list(mesh.shape.values()))) if mesh else 1

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        if isinstance(feed, list):
            # per-device feed list (reference semantics): concat along batch
            merged = {}
            for d in feed:
                for k, v in d.items():
                    merged.setdefault(k, []).append(np.asarray(v))
            feed = {k: np.concatenate(v) for k, v in merged.items()}
        return self._exe.run(program=self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)

    def bcast_params(self):
        pass  # params replicated by construction under SPMD
