"""CompiledProgram / BuildStrategy / ExecutionStrategy
(ref: python/paddle/fluid/compiler.py:35, framework/details/build_strategy.h:34,
execution_strategy.h).

The reference's with_data_parallel builds a replicated SSA graph with
all_reduce op handles per gradient. Here it attaches a device mesh: the SAME
single program runs under pjit with batch-sharded inputs, and GSPMD inserts
the gradient all-reduces. BuildStrategy/ExecutionStrategy knobs that steer
the reference's graph rewriting are accepted for compatibility; the ones
with TPU meaning (num_trainers → mesh size) are honored, the rest are
subsumed by XLA (fusion, memory optimize, op ordering).
"""
from __future__ import annotations


class BuildStrategy(object):
    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_relu_depthwise_conv = False
        self.fuse_broadcast_op = False
        self.memory_optimize = False
        self.enable_inplace = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.remove_unnecessary_lock = True


class ExecutionStrategy(object):
    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = False


# optimized-clone variants kept per CompiledProgram (LRU): enough for a
# train/eval/metric fetch-set rotation, bounded against fetch-set churn
_OPT_CACHE_MAX = 8


class CompiledProgram(object):
    """Wraps a Program; with_data_parallel attaches a mesh."""

    _ptpu_compiled_program = True

    def __init__(self, program):
        self._program = program
        self._mesh = None
        self._is_data_parallel = False
        self._loss_name = None
        self._build_strategy = None
        self._exec_strategy = None
        self._places = None
        # (uid, epoch, fetch sig) -> optimized program clone. LRU-capped:
        # each fetch-set variation pins a full program clone, and a metric
        # sweep cycling fetch sets would otherwise grow this without bound
        from ..core.compile_cache import LRUCache
        self._opt_cache = LRUCache(_OPT_CACHE_MAX)
        self._pass_reports = None  # reports from the latest pipeline run

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None, mesh=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._places = places
        if mesh is not None:
            self._mesh = mesh  # explicit multi-axis mesh (dp/mp/pp/...)
        return self

    def with_inference_optimize(self, config=None):
        return self

    def _optimized_program(self, fetch_names):
        """The pass-optimized clone of the wrapped program for this fetch
        set (passes/: verify, constant_fold, dead_op_elimination,
        fuse_activation), memoized per program build epoch. The fetch set
        keys the cache because dead-op elimination roots liveness in it —
        fetching a different metric later builds its own clone. Any
        pipeline failure falls back to the raw program: an optimization
        layer must never make a runnable program unrunnable."""
        src = self._program
        key = (src._uid, src._build_epoch,
               tuple(sorted(fetch_names or ())))
        hit = self._opt_cache.get(key)
        if hit is not None:
            return hit
        self._opt_cache.filter_inplace(
            lambda k: k[0] == src._uid and k[1] == src._build_epoch)
        try:
            from .. import passes
            prog, reports = passes.apply_optimization_pipeline(
                src, fetch_names=list(fetch_names or ()))
            self._pass_reports = reports
        except Exception as e:
            from ..passes.verifier import ProgramVerifyError
            if isinstance(e, ProgramVerifyError):
                raise  # strict verify: fail loudly, never fall back
            import warnings
            warnings.warn(
                "optimization pipeline failed (%s: %s); running the "
                "unoptimized program" % (type(e).__name__, e),
                RuntimeWarning)
            prog = src
        self._opt_cache.put(key, prog)
        return prog

    def _get_mesh(self, executor):
        if not self._is_data_parallel:
            return None
        if self._mesh is None:
            from .mesh import make_mesh
            n = len(self._places) if self._places else None
            self._mesh = make_mesh(num_devices=n)  # backend via core.config
        return self._mesh

    # pass-through so Executor internals see the Program surface if needed
    def __getattr__(self, item):
        return getattr(self._program, item)
