"""Ring attention: sequence/context parallelism for long sequences
(TPU-native extension; the reference has no context-parallel path —
SURVEY §2.4 lists SP as absent upstream, and the build brief makes
long-context first-class).

Design: q/k/v are sharded over the sequence axis of the mesh ('sp').
Under shard_map each device holds S/P of the sequence; the kernel loops P
steps, attending the local queries against a k/v block that rotates
around the ring via lax.ppermute (one ICI hop per step, overlapped by XLA
with the block's matmuls), accumulating with the online-softmax recurrence
(running max / denominator / output — the flash-attention math at ring
granularity). Peak memory per device is O(S·S/P) for one block of scores
instead of O(S²); ICI traffic is the k/v rotation, 2·S·D·(P-1)/P per
device — the all-to-all-free formulation of Liu et al.'s Ring Attention.

Causal masking is block-level: global q/k positions are derived from the
ring rank and rotation step, so the same kernel serves encoder and
decoder attention.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def ring_attention(q, k, v, mesh, causal=False, scale=1.0,
                   seq_axis=SEQ_AXIS, batch_axis=DATA_AXIS,
                   head_axis=MODEL_AXIS):
    """Attention over [B, H, S, D] with S sharded on `seq_axis` of `mesh`.
    B additionally shards over `batch_axis` and H over `head_axis` when
    those axes exist in the mesh. Returns [B, H, S, D], S-sharded."""
    try:
        from jax import shard_map                      # jax >= 0.8
        rep_kw = {'check_vma': False}
    except ImportError:
        from jax.experimental.shard_map import shard_map
        rep_kw = {'check_rep': False}

    nsp = int(mesh.shape[seq_axis])
    if q.shape[2] % nsp != 0:
        raise ValueError(
            "ring attention: sequence length %d must divide the %r mesh "
            "axis (size %d)" % (q.shape[2], seq_axis, nsp))
    for dim, ax in ((0, batch_axis), (1, head_axis)):
        n = int(mesh.shape.get(ax, 1))
        if n > 1 and q.shape[dim] % n != 0:
            raise ValueError(
                "ring attention: q dim %d (size %d) must divide the %r "
                "mesh axis (size %d)" % (dim, q.shape[dim], ax, n))
    b_ax = batch_axis if mesh.shape.get(batch_axis, 1) > 1 else None
    h_ax = head_axis if mesh.shape.get(head_axis, 1) > 1 else None
    spec = P(b_ax, h_ax, seq_axis, None)
    perm = [(i, (i + 1) % nsp) for i in range(nsp)]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec,
                       **rep_kw)
    def ring(ql, kl, vl):
        rank = jax.lax.axis_index(seq_axis)
        sl = ql.shape[2]
        qf = ql.astype(jnp.float32) * scale
        pos_q = rank * sl + jnp.arange(sl)

        def block(o, mx, l, kb, vb, t):
            """Fold one rotating k/v block into the online-softmax state."""
            s = jnp.einsum('bhqd,bhkd->bhqk', qf, kb.astype(jnp.float32))
            if causal:
                src = (rank - t) % nsp          # whose block we hold now
                pos_k = src * sl + jnp.arange(sl)
                s = jnp.where(pos_k[None, None, None, :]
                              <= pos_q[None, None, :, None], s, -jnp.inf)
            m_new = jnp.maximum(mx, jnp.max(s, axis=-1))
            # -inf guards: a row with no unmasked key yet has mx=-inf (no
            # prior mass -> correction 0) and possibly m_new=-inf (this
            # block all-masked too -> contribution 0)
            corr = jnp.where(jnp.isneginf(mx), 0.0, jnp.exp(mx - m_new))
            p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0,
                          jnp.exp(s - m_new[..., None]))
            l = l * corr + jnp.sum(p, axis=-1)
            o = o * corr[..., None] + jnp.einsum(
                'bhqk,bhkd->bhqd', p, vb.astype(jnp.float32))
            return o, m_new, l

        def body(carry, t):  # lax.scan: reverse-differentiable for training
            o, mx, l, kb, vb = carry
            # rotate FIRST: the local block was consumed before the scan,
            # so exactly nsp-1 ICI hops happen — no wasted final rotation
            kb = jax.lax.ppermute(kb, seq_axis, perm)
            vb = jax.lax.ppermute(vb, seq_axis, perm)
            o, mx, l = block(o, mx, l, kb, vb, t)
            return (o, mx, l, kb, vb), None

        b, h = ql.shape[0], ql.shape[1]
        o0 = jnp.zeros((b, h, sl, ql.shape[3]), jnp.float32)
        m0 = jnp.full((b, h, sl), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, sl), jnp.float32)
        o, mx, l = block(o0, m0, l0, kl, vl, 0)   # own (diagonal) block
        (o, mx, l, _, _), _ = jax.lax.scan(body, (o, mx, l, kl, vl),
                                           jnp.arange(1, nsp))
        out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
        return out.astype(ql.dtype)

    return ring(q, k, v)
