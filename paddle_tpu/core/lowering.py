"""Program → JAX tracer.

This is the heart of the framework and replaces the reference's C++
op-interpreter hot loop (framework/executor.cc:203 Executor::Run →
operator.cc:913 OperatorWithKernel::RunImpl). Instead of interpreting
OpDescs per step, we walk a Block ONCE inside a jax trace, turning each op
into XLA ops via its registered lowering; jit compiles the whole step and XLA
owns fusion/layout/memory (subsuming the reference's fusion-pass zoo,
framework/ir/, and allocator stack, memory/).

The traced function is pure: (state, feed, rng) -> (fetches, new_state).
`state` carries every persistable var (params, optimizer moments, LR
counters) — the functional equivalent of the reference's mutable Scope
(framework/scope.h:48). In-place ops (sgd writes ParamOut==Param) become env
rebinding; the executor commits new_state back to the host Scope after each
run.

Gradient ops: append_backward emits `<type>_grad` OpDescs. If no explicit
lowering is registered for a grad op, `_lower_generic_grad` re-lowers the
forward op under jax.vjp and applies the output cotangents — per-op autodiff
parity (ref GradOpDescMaker) without per-op grad code. The recomputed
forward is CSE'd by XLA against the original (same trace, same inputs) —
EXCEPT inside remat_segment sub-blocks, whose lowering wraps the trace in
jax.checkpoint (optimization-barrier-guarded), so segment interiors really
recompute in the backward instead of staying live (passes/recompute.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import registry
from .lod import LoDArray, unwrap
from ..framework import is_float_dtype


class TraceError(RuntimeError):
    pass


class OpCtx(object):
    """Per-op context handed to lowering rules."""

    __slots__ = ('tracer', 'op', 'attrs', 'block', 'abstract')

    def __init__(self, tracer, op, block):
        self.tracer = tracer
        self.op = op
        self.attrs = op.attrs
        self.block = block
        self.abstract = False

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    @property
    def is_test(self):
        return bool(self.attrs.get('is_test', False))

    def rng(self):
        # seeded ops fold the user seed into the per-step key: deterministic
        # given (program seed, step, op seed) but fresh each step — matching
        # the reference, which seeds a generator once and draws per step.
        seed = self.attrs.get('seed', 0) or self.attrs.get('_fwd_seed', 0)
        if seed:
            return jax.random.fold_in(self.tracer.step_key,
                                      int(seed) & 0x7FFFFFFF)
        uid = self.attrs.get('_fwd_op_uid', self.attrs.get('_op_uid', 0))
        return jax.random.fold_in(self.tracer.step_key, int(uid) & 0x7FFFFFFF)

    def var(self, name):
        """Compile-time Variable metadata (shape with -1s, dtype, lod_level)."""
        return self.block._find_var_recursive(name)

    def env(self, name):
        return self.tracer.env[name]

    def run_block(self, block_idx, env):
        """Run a sub-block (control flow) against an explicit env dict."""
        sub = self.tracer.program.block(block_idx)
        self.tracer.run_block(sub, env)
        return env


class _FusedActOp(object):
    """Shadow op handed to an activation lowering when it runs fused into
    its producer (fuse_act attr): carries the activation's original attrs
    plus the producer's uid for any rng bookkeeping."""

    __slots__ = ('type', 'attrs', 'inputs', 'outputs')

    def __init__(self, act_type, act_attrs, producer):
        self.type = act_type
        self.attrs = dict(act_attrs)
        self.attrs.setdefault('_op_uid', producer.attrs.get('_op_uid', 0))
        self.inputs = {}
        self.outputs = {}


class Tracer(object):
    """Walks blocks, maintaining env: var name -> traced value."""

    def __init__(self, program, step_key, scope_types=None):
        self.program = program
        self.step_key = step_key
        self.env = {}
        self.fetches = []
        self.written = set()
        # static (host) side-channels: sequence_pad records per-seq lengths
        # so sequence_unpad can rebuild a static lod; assign_value records
        # its host constant so ops needing trace-time values (e.g.
        # sequence_slice offsets) can read them even under jit
        self.static_lengths = {}
        self.host_consts = {}

    def read(self, name, op):
        if name in self.env:
            return self.env[name]
        raise TraceError(
            "Op %s reads variable %r which has no value. Feed it, initialize "
            "it via the startup program, or check op ordering." % (op, name))

    def write(self, name, value):
        self.env[name] = value
        self.written.add(name)

    def run_block(self, block, env=None):
        if env is not None:
            saved, self.env = self.env, env
        try:
            for op in block.ops:
                self.run_op(op, block)
        finally:
            if env is not None:
                self.env = saved
        return self.env

    def run_op(self, op, block):
        t = op.type
        if t == 'feed':
            return  # env pre-populated by executor
        if t == 'fetch':
            self.fetches.append(self.read(op.inputs['X'][0], op))
            return
        d = registry.get(t)
        if d is None:
            if t.endswith('_grad'):
                fwd = registry.get(t[:-5])
                if fwd is not None:
                    return self._lower_generic_grad(op, block, fwd)
            raise TraceError("No lowering registered for op type %r (%s)" %
                             (t, op))
        ctx = OpCtx(self, op, block)
        ins = self._gather_inputs(op, block)
        src_la = None
        src_rows = None
        if d.lod_mode != 'aware':
            for vals in ins.values():
                for v in vals:
                    if isinstance(v, LoDArray) and src_la is None:
                        src_la = v
                        src_rows = v.data.shape[0] if v.data.ndim else None
            if src_la is not None:
                ins = {slot: [unwrap(v) for v in vals]
                       for slot, vals in ins.items()}
        outs = d.lower(ctx, ins)
        if op.attrs.get('fuse_act'):
            outs = self._apply_fused_act(op, block, outs)
        if (d.lod_mode == 'pass' and src_la is not None and outs):
            outs = {slot: [self._maybe_wrap(v, src_la, src_rows)
                           for v in vals] if vals is not None else None
                    for slot, vals in outs.items()}
        self._scatter_outputs(op, outs)

    def _apply_fused_act(self, op, block, outs):
        """Apply a pass-fused activation (passes/fuse_act.py) to the
        producer's primary output, inside the same traced expression:
        the activation's own registered lowering runs on the slot value,
        so fused and unfused programs are bit-identical."""
        act = op.attrs['fuse_act']
        slot = op.attrs.get('fuse_act_slot', 'Out')
        d = registry.get(act)
        if d is None:
            raise TraceError(
                "op %s carries fuse_act=%r but no lowering is registered "
                "for that activation" % (op, act))
        vals = (outs or {}).get(slot)
        if not vals or vals[0] is None:
            raise TraceError(
                "op %s carries fuse_act=%r but produced no value in slot "
                "%r to activate" % (op, act, slot))
        shadow = _FusedActOp(act, op.attrs.get('fuse_act_attrs', {}), op)
        ctx = OpCtx(self, shadow, block)
        acted = d.lower(ctx, {'X': [unwrap(vals[0])]})['Out'][0]
        outs = dict(outs)
        outs[slot] = [acted] + list(vals[1:])
        return outs

    @staticmethod
    def _maybe_wrap(v, src_la, rows):
        # ShareLoD: rewrap row-aligned outputs with the source's lod,
        # preserving its static/traced mode
        if (v is not None and not isinstance(v, LoDArray)
                and hasattr(v, 'ndim') and v.ndim >= 1 and rows is not None
                and v.shape[0] == rows):
            return src_la.with_lod_of(v)
        return v

    def _gather_inputs(self, op, block):
        ins = {}
        for slot, names in op.inputs.items():
            ins[slot] = [self.read(n, op) if n else None for n in names]
        return ins

    def _scatter_outputs(self, op, outs):
        if outs is None:
            outs = {}
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            for n, v in zip(names, vals):
                if n and v is not None:
                    self.write(n, v)

    # ------------------------------------------------------------------
    # Generic VJP-derived gradient lowering.
    # Grad op convention (see backward.py):
    #   attrs['_fwd_inputs']  : {slot: [names]} of the forward op
    #   attrs['_fwd_outputs'] : {slot: [names]}
    #   attrs['_out_grad_map']: {fwd_out_name: grad_var_name or ''}
    #   attrs['_in_grad_map'] : {fwd_in_name: grad_var_name or ''}
    #   attrs['_fwd_op_uid']  : uid of the forward op (rng consistency)
    # ------------------------------------------------------------------
    def _lower_generic_grad(self, op, block, fwd_def):
        a = op.attrs
        fwd_inputs = a['_fwd_inputs']
        fwd_outputs = a['_fwd_outputs']
        out_grad_map = a['_out_grad_map']
        in_grad_map = a['_in_grad_map']

        ctx = OpCtx(self, op, block)

        # names to differentiate with respect to (deduped, order-stable)
        diff_names = []
        for slot, names in fwd_inputs.items():
            for n in names:
                if n and in_grad_map.get(n) and n not in diff_names:
                    diff_names.append(n)
        if not diff_names:
            return

        aware = fwd_def.lod_mode == 'aware'
        base_env = {}
        for slot, names in fwd_inputs.items():
            for n in names:
                if n:
                    v = self.read(n, op)
                    base_env[n] = v if aware else unwrap(v)

        # float forward outputs participate in the vjp
        float_outs = []
        for slot, names in fwd_outputs.items():
            for n in names:
                if n and n not in float_outs:
                    v = block._find_var_recursive(n)
                    if v is None or is_float_dtype(v.dtype):
                        float_outs.append(n)

        def f(diff_vals):
            env2 = dict(base_env)
            for n, v in zip(diff_names, diff_vals):
                orig = base_env.get(n)
                if isinstance(orig, LoDArray):
                    v = orig.with_lod_of(v)
                env2[n] = v
            ins = {slot: [env2.get(n) if n else None for n in names]
                   for slot, names in fwd_inputs.items()}
            outs = fwd_def.lower(ctx, ins)
            out_env = {}
            for slot, names in fwd_outputs.items():
                vals = (outs or {}).get(slot)
                if vals is None:
                    continue
                for n, v in zip(names, vals):
                    if n and v is not None:
                        out_env[n] = unwrap(v)
            return {n: out_env[n] for n in float_outs if n in out_env}

        diff_vals = [unwrap(base_env[n]) for n in diff_names]
        primals, vjp_fn = jax.vjp(f, diff_vals)

        cots = {}
        for n, p in primals.items():
            gname = out_grad_map.get(n, '')
            if gname and gname in self.env:
                g = unwrap(self.env[gname])
                if g.dtype != p.dtype:
                    g = g.astype(p.dtype)
                if g.shape != p.shape:
                    if np.prod(g.shape) == np.prod(p.shape):
                        g = g.reshape(p.shape)
                    else:
                        g = jnp.broadcast_to(g, p.shape)
                cots[n] = g
            else:
                cots[n] = jnp.zeros(p.shape, p.dtype)
        (in_grads,) = vjp_fn(cots)

        for n, g in zip(diff_names, in_grads):
            gname = in_grad_map.get(n, '')
            if gname:
                self.write(gname, g)
