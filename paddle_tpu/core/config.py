"""Backend/platform selection + FLAGS-style config registry
(ref: the reference's gflags system, platform/init.cc:81 and python
__bootstrap__ in fluid/__init__.py:97-170).

PTPU_PLATFORM env (or set_backend()) pins the jax backend for all executors
and meshes — needed because the TPU plugin registers itself as default even
when tests want the 8-device virtual CPU platform.
"""
from __future__ import annotations

import os

_backend_override = None


def set_backend(name):
    """Pin the jax backend ('cpu' | 'tpu' | None to auto)."""
    global _backend_override
    _backend_override = name


def get_backend():
    """Resolve the accelerator backend: override > PTPU_PLATFORM env >
    tpu-if-present > default."""
    if _backend_override is not None:
        return _backend_override
    env = os.environ.get('PTPU_PLATFORM')
    if env:
        return env
    import jax
    kinds = {d.platform for d in jax.devices()}
    for k in ('tpu', 'axon'):
        if k in kinds:
            return k
    return None  # jax default


def rng_impl():
    """PRNG implementation for the per-step key. On TPU the counter-based
    hardware generator ('rbg') is the default — measured +25% e2e on
    dropout-heavy transformer training vs threefry (PERF_NOTES.md);
    elsewhere (CPU tests) threefry keeps bit-stable fixtures. Override
    with FLAGS_rng_impl / set_flags({'rng_impl': ...})."""
    v = get_flag('rng_impl')
    if v:
        return v
    return 'rbg' if get_backend() in ('tpu', 'axon') else 'threefry2x32'


def accel_devices():
    import jax
    b = get_backend()
    return jax.devices(b) if b else jax.devices()


# -- FLAGS registry (reference gflags equivalents) ---------------------------
# check_nan_inf -> jax.debug_nans around every Executor step (the moral
#   equivalent of the reference's per-op output scan, operator.cc:896-905).
# deterministic -> when a program has no random_seed, the Executor still
#   derives per-step rng from a fixed root (reproducible across processes);
#   with the flag off it folds in process entropy like the reference's
#   unseeded generators. Deterministic-by-default is the TPU-first choice.
FLAGS = {
    'check_nan_inf': os.environ.get('FLAGS_check_nan_inf', '0') == '1',
    'benchmark': os.environ.get('FLAGS_benchmark', '0') == '1',
    'eager_delete_tensor_gb': float(
        os.environ.get('FLAGS_eager_delete_tensor_gb', '-1')),
    # FLAGS_deterministic is our own flag (deterministic by default); the
    # reference's FLAGS_cudnn_deterministic keeps its narrow meaning and is
    # subsumed (XLA TPU kernels are deterministic), so it is NOT overloaded
    'deterministic': os.environ.get('FLAGS_deterministic', '1') == '1',
    'tensor_array_capacity': int(
        os.environ.get('FLAGS_tensor_array_capacity', '128')),
    # per-step PRNG implementation override (rng_impl() docstring)
    'rng_impl': os.environ.get('FLAGS_rng_impl', '') or None,
    # low-bit dropout keep-decision (0 = off; 8/16 = threshold compare on
    # that many random bits — the PERF_NOTES dropout-tax ablation knob)
    'dropout_bits': int(os.environ.get('FLAGS_dropout_bits', '0')),
}


def get_flag(name, default=None):
    return FLAGS.get(name, default)


def set_flags(d):
    FLAGS.update(d)
