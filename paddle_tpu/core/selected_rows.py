"""SelectedRows: sparse row-set gradients (ref: framework/selected_rows.h:32).

The reference materializes embedding gradients as {rows, value} pairs so
pservers/optimizers touch only the looked-up rows. TPU-native re-design:
`SelectedRowsVal` is a pytree of (rows [N] int32, values [N, ...]) plus a
static `height` (the full table's row count). N is the STATIC number of
lookups in the batch (ids tensor size), so every consumer is a fixed-shape
XLA program:

  - optimizer sparse paths apply `values` at `rows` with scatter-add /
    scatter-apply (duplicate ids accumulate, exactly like the reference's
    merged SelectedRows);
  - `merge_selected_rows` sorts + segment-sums duplicates, parking merged
    slots at row == height (out-of-range rows drop in scatters);
  - densifying (`get_tensor_from_selected_rows` into a full table) is an
    explicit .to_dense(), never implicit.

Under GSPMD a sharded table + scatter from replicated SelectedRows lowers to
the same all-to-all/scatter collectives as the reference's distributed
lookup table update path (operators/distributed/parameter_prefetch.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SelectedRowsVal(object):
    """rows: [N] int32 row ids (may repeat; id == height means 'empty slot').
    values: [N, *tail] per-row data. height: static table row count."""

    __slots__ = ('rows', 'values', 'height')

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        obj = cls.__new__(cls)
        obj.rows, obj.values = children
        obj.height = height
        return obj

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self):
        """Full [height, *tail] tensor with duplicate rows accumulated."""
        dense = jnp.zeros((self.height,) + self.values.shape[1:],
                          self.values.dtype)
        return dense.at[self.rows].add(self.values, mode='drop')

    def merged(self):
        """Deduplicate rows: sort by row id, segment-sum runs of equal ids
        into the first slot, park the rest at row == height. Shapes stay
        static; scatters drop the parked slots."""
        order = jnp.argsort(self.rows)
        rows = self.rows[order]
        vals = self.values[order]
        first = jnp.concatenate(
            [jnp.ones((1,), bool), rows[1:] != rows[:-1]])
        seg = jnp.cumsum(first.astype(jnp.int32)) - 1  # [N] run index
        n = rows.shape[0]
        sum_vals = jax.ops.segment_sum(vals, seg, num_segments=n)
        # row id of each run = first row of the run
        run_rows = jnp.full((n,), self.height, rows.dtype).at[seg].set(rows)
        return SelectedRowsVal(run_rows, sum_vals, self.height)

    def scale(self, s):
        return SelectedRowsVal(self.rows, self.values * s, self.height)

    def __repr__(self):
        return "SelectedRowsVal(n=%s, height=%d, tail=%s)" % (
            self.rows.shape[0], self.height, self.values.shape[1:])


def concat_rows(srs):
    """Accumulate several SelectedRows over the same table (the `sum` op on
    sparse grads): concatenation IS addition for scatter consumers."""
    height = srs[0].height
    for s in srs:
        if s.height != height:
            raise ValueError("SelectedRows height mismatch: %d vs %d"
                             % (s.height, height))
    return SelectedRowsVal(jnp.concatenate([s.rows for s in srs]),
                           jnp.concatenate([s.values for s in srs]), height)
