"""TensorArray runtime value (ref: LoDTensorArray, framework/lod_tensor_array.h
and the array ops in operators/controlflow/tensor_array_read_write_op.cc,
lod_rank_table_op.cc).

The reference's LoDTensorArray is a host vector of LoDTensors that control
flow ops push/pop; sizes are dynamic. TPU-native re-design: a TensorArray is
a FIXED-CAPACITY device ring [capacity, *elem_shape] plus a traced length
scalar, registered as a jax pytree so it can ride the carry of
lax.while_loop/scan. Writes are lax.dynamic_update_slice at a traced index;
reads are dynamic_index. Capacity is static structure: it comes from the
static LoD (max sequence length) for lod_tensor_to_array, or from the
`capacity` attr / first outside-loop write for user arrays.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

def _default_capacity():
    """Capacity for arrays first written with no explicit capacity
    (decode-style loops). FLAGS_tensor_array_capacity overrides."""
    from .config import get_flag
    return int(get_flag('tensor_array_capacity', 128))


@jax.tree_util.register_pytree_node_class
class TensorArrayVal(object):
    """Fixed-capacity device buffer + traced length."""

    __slots__ = ('data', 'length', 'capacity')

    def __init__(self, data, length, capacity):
        self.data = data          # jnp [capacity, *elem] or None (unallocated)
        self.length = length      # traced int32 scalar
        self.capacity = capacity  # static python int (0 = not yet known)

    # -- pytree: capacity is structure ------------------------------------
    def tree_flatten(self):
        return (self.data, self.length), self.capacity

    @classmethod
    def tree_unflatten(cls, capacity, children):
        obj = cls.__new__(cls)
        obj.data, obj.length = children
        obj.capacity = capacity
        return obj

    # -- ops ---------------------------------------------------------------
    @staticmethod
    def empty(capacity=0):
        return TensorArrayVal(None, jnp.asarray(0, jnp.int32), capacity)

    def write(self, i, x):
        """Functional write at traced index i; returns a new array.

        Writes past capacity clamp onto the last slot (XLA semantics); to
        keep that LOUD instead of silently plausible, float elements written
        out of range are poisoned to NaN and `length` still counts past
        capacity so callers can assert length <= capacity on the host."""
        x = jnp.asarray(x)
        i = jnp.asarray(i, jnp.int32).reshape(())
        if self.data is None:
            cap = self.capacity or _default_capacity()
            data = jnp.zeros((cap,) + x.shape, x.dtype)
        else:
            data = self.data
            if x.shape != data.shape[1:]:
                raise ValueError(
                    "array_write element shape %r != array element shape %r"
                    % (x.shape, data.shape[1:]))
        cap = data.shape[0]
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = jnp.where(i < cap, x, jnp.full_like(x, jnp.nan))
        elif jnp.issubdtype(x.dtype, jnp.integer):
            x = jnp.where(i < cap, x, jnp.full_like(x, -1))
        data = jax.lax.dynamic_update_index_in_dim(data, x, i, 0)
        length = jnp.maximum(self.length, i + 1)
        return TensorArrayVal(data, length, cap)

    def read(self, i):
        if self.data is None:
            raise ValueError(
                "array_read from an empty TensorArray: write an element "
                "before the loop (or pass capacity+shape to create_array) so "
                "the buffer shape is known at trace time")
        i = jnp.asarray(i, jnp.int32).reshape(())
        return jax.lax.dynamic_index_in_dim(self.data, i, 0, keepdims=False)

    def stack(self, upto=None):
        """Dense [capacity or upto, *elem] view (tensor_array_to_tensor)."""
        if self.data is None:
            raise ValueError("stack of empty TensorArray")
        return self.data if upto is None else self.data[:upto]

    def __repr__(self):
        return "TensorArrayVal(cap=%s, elem=%s)" % (
            self.capacity,
            None if self.data is None else self.data.shape[1:])


class RankTable(object):
    """Static host-side rank table (ref lod_rank_table_op.cc): sequences of a
    LoD level sorted by length, descending, stable. Because our LoD offsets
    are static trace-time structure, the whole table is static too."""

    __slots__ = ('lengths', 'order', 'max_len')

    def __init__(self, offsets):
        off = np.asarray(offsets, dtype=np.int64)
        lens = off[1:] - off[:-1]
        # stable sort by descending length (reference uses stable_sort)
        self.order = tuple(int(i) for i in
                           np.argsort(-lens, kind='stable'))
        self.lengths = tuple(int(lens[i]) for i in self.order)
        self.max_len = int(lens.max()) if len(lens) else 0

    def items(self):
        return list(zip(self.order, self.lengths))

    def __repr__(self):
        return "RankTable(order=%s, lengths=%s)" % (self.order, self.lengths)
