from . import registry  # noqa: F401
from .scope import Scope, global_scope, scope_guard  # noqa: F401
from .lod import LoDArray, create_lod_array  # noqa: F401


class EOFException(Exception):
    """Raised by pipeline readers at end of epoch (ref: fluid.core.EOFException)."""
    pass


def to_dlpack(value):
    """DLPack export (ref framework/dlpack_tensor.cc) — jax arrays speak
    the protocol natively via __dlpack__ (zero-copy). The axon TPU tunnel
    does not implement external buffer references, so there we fall back
    to a host copy (numpy also speaks DLPack)."""
    import numpy as np
    from .lod import unwrap
    arr = unwrap(value)
    try:
        return arr.__dlpack__()
    except Exception:
        # host copy; np.asarray of a jax array is readonly -> copy again
        return np.array(arr, copy=True).__dlpack__()


def from_dlpack(capsule_or_array):
    """Import a DLPack capsule / any __dlpack__ provider as a device
    array (host copy when the default backend cannot import external
    buffers, e.g. the axon TPU tunnel)."""
    import numpy as np
    import jax.numpy as jnp
    if not hasattr(capsule_or_array, '__dlpack__'):
        # raw capsules are single-use: no fallback retry possible
        return jnp.from_dlpack(capsule_or_array)
    try:
        return jnp.from_dlpack(capsule_or_array)
    except Exception:
        return jnp.asarray(np.from_dlpack(capsule_or_array))
