from . import registry  # noqa: F401
from .scope import Scope, global_scope, scope_guard  # noqa: F401
from .lod import LoDArray, create_lod_array  # noqa: F401


class EOFException(Exception):
    """Raised by pipeline readers at end of epoch (ref: fluid.core.EOFException)."""
    pass
