"""Async crash-consistent checkpointing (ISSUE 6 tentpole).

The reference production stack survived failure with two mechanisms: the
Go pserver wrote CRC-checked atomic-rename checkpoints
(go/pserver/service.go:346) and the master re-leased timed-out task
chunks (go/master/service.go:89). `CheckpointManager` is the TPU-native
composition of both with the warm-start tier (core/compile_cache.py):

1. **Snapshot off the step loop** — at a step boundary the manager
   copies the scope's persistable state device->host (async D2H
   initiation first, then one blocking materialize + copy per array; the
   copy is mandatory because the NEXT dispatch DONATES the state buffers
   — a background reader racing a donated buffer reads freed memory).
   The measured snapshot time is the only stall the step loop ever sees;
   it is surfaced as checkpoint-stall %% in
   `profiler.training_report()`.
2. **Background writer** — one daemon thread serializes shards into a
   `.tmp-` staging directory (per-file fsync + sha256 manifest), makes
   the checkpoint live with ONE atomic `os.replace` of the directory,
   then appends a commit record to a flock-guarded `COMMITS.jsonl`
   journal and applies keep-last-N retention (evictions journaled too).
   A crash at ANY byte leaves either a fully-live checkpoint or an
   ignorable staging dir — never a half-readable one.
3. **Degrade, don't crash** — write-path errors (ENOSPC, EIO — the
   fault-injection harness in testing/faults.py produces them on
   demand) warn loudly and retry with exponential backoff; after
   `max_retries` the checkpoint is abandoned (counted in `stats`) and
   TRAINING CONTINUES. The writer thread never propagates into the step
   loop.
4. **Restore = newest fully-committed** — `restore()` scans candidates
   newest-first and verifies COMMIT record + manifest digest + per-file
   sha256 before loading anything; a partial or corrupt checkpoint is
   skipped with a loud warning, NEVER silently loaded. The restored meta
   carries the executor step counter (so the per-step rng stream — and
   therefore the loss curve — continues bit-exactly) and the elastic
   task-journal position (reader/elastic.py), so a killed trainer
   resumes with params + data position + compile-cache warm hit.
"""
from __future__ import annotations

import hashlib
import json
import os
import queue
import shutil
import signal as _signal
import threading
import time
import warnings
import weakref

import numpy as np

try:
    import fcntl
except ImportError:          # non-POSIX: no advisory locking available
    fcntl = None

_MANIFEST = 'MANIFEST.json'
_COMMIT = 'COMMIT.json'
_POD_COMMIT = 'POD_COMMIT.json'
_JOURNAL = 'COMMITS.jsonl'
_PREFIX = 'ckpt-'
_HOST_PREFIX = 'host-'
_TMP_PREFIX = '.tmp-'
_HB_DIR = 'heartbeats'
_BARRIER_DIR = 'barriers'
_VERSION = 1


def _program_uid(program):
    """The step-counter key for a program. A CompiledProgram resolves to a
    pass-optimized CLONE inside Executor.run (compiler._optimized_program)
    whose fresh _uid would fork the rng step stream away from the one a
    checkpoint recorded — the clone carries the RAW program's uid in
    _ptpu_counter_uid so save/restore and the executor agree on one
    counter."""
    return getattr(program, '_ptpu_counter_uid', program._uid)

# write-path indirection points: testing/faults.py wraps these to inject
# ENOSPC/EIO without touching the filesystem layer for real
_open_for_write = open
_fsync = os.fsync


def _sha256(data):
    return hashlib.sha256(data).hexdigest()


def _checkpoint_step(name):
    """Parse the step out of a 'ckpt-<step>' dir name, or None."""
    if not name.startswith(_PREFIX):
        return None
    try:
        return int(name[len(_PREFIX):])
    except ValueError:
        return None


def list_checkpoints(dirname):
    """(step, path) of every live (renamed-in) checkpoint dir, ascending
    by step. Liveness != committedness: restore() still verifies."""
    if not os.path.isdir(dirname):
        return []
    out = []
    for name in os.listdir(dirname):
        step = _checkpoint_step(name)
        if step is not None and os.path.isdir(os.path.join(dirname, name)):
            out.append((step, os.path.join(dirname, name)))
    return sorted(out)


def _check_commit(path):
    """COMMIT record present, MANIFEST present/parseable, and the COMMIT's
    digest matching the manifest bytes. Returns (manifest, commit);
    raises ValueError with a precise reason. Shard contents are NOT read
    here — per-shard digests verify on the single read that loads them."""
    commit_path = os.path.join(path, _COMMIT)
    manifest_path = os.path.join(path, _MANIFEST)
    if not os.path.exists(commit_path):
        raise ValueError('no COMMIT record (crash before commit)')
    if not os.path.exists(manifest_path):
        raise ValueError('no MANIFEST')
    with open(manifest_path, 'rb') as f:
        manifest_raw = f.read()
    try:
        manifest = json.loads(manifest_raw.decode())
    except ValueError:
        raise ValueError('MANIFEST is not valid JSON (torn write?)')
    try:
        with open(commit_path) as f:
            commit = json.load(f)
    except ValueError:
        raise ValueError('COMMIT record is not valid JSON (torn write?)')
    if commit.get('manifest_sha256') != _sha256(manifest_raw):
        raise ValueError('COMMIT/MANIFEST digest mismatch')
    return manifest, commit


def _stage_entries(tmp, entries, meta, commit_extra=None):
    """Write `entries` — (fname, value, extra manifest fields) — into the
    staging dir with per-file fsync + sha256-while-writing, then the
    MANIFEST and COMMIT records. Shared by the single-host and pod
    writers so the on-disk format cannot drift between them. Returns
    (files, manifest_raw, commit)."""
    from ..io import _serialize_tensor, _HashingFile
    files = {}
    for fname, value, extra in entries:
        with _open_for_write(os.path.join(tmp, fname), 'wb') as f:
            hf = _HashingFile(f)
            _serialize_tensor(hf, value)
            f.flush()
            _fsync(f.fileno())
        ent = {'sha256': hf.sha.hexdigest(), 'bytes': hf.nbytes}
        if extra:
            ent.update(extra)
        files[fname] = ent
    manifest_raw = json.dumps(
        {'version': _VERSION, 'step': meta['step'], 'files': files,
         'meta': meta}, indent=1, sort_keys=True).encode()
    with _open_for_write(os.path.join(tmp, _MANIFEST), 'wb') as f:
        f.write(manifest_raw)
        f.flush()
        _fsync(f.fileno())
    commit = {'step': meta['step'],
              'manifest_sha256': _sha256(manifest_raw),
              'wall_time': meta['wall_time']}
    if commit_extra:
        commit.update(commit_extra)
    with _open_for_write(os.path.join(tmp, _COMMIT), 'wb') as f:
        f.write(json.dumps(commit).encode())
        f.flush()
        _fsync(f.fileno())
    return files, manifest_raw, commit


def _read_shard(path, name, ent):
    """One shard's raw bytes, verified against its manifest entry."""
    shard = os.path.join(path, name)
    if not os.path.exists(shard):
        raise ValueError('missing shard %r' % name)
    with open(shard, 'rb') as f:
        raw = f.read()
    if len(raw) != ent['bytes']:
        raise ValueError('shard %r is %d bytes, manifest says %d '
                         '(truncated?)' % (name, len(raw), ent['bytes']))
    if _sha256(raw) != ent['sha256']:
        raise ValueError('shard %r sha256 mismatch (corrupt)' % name)
    return raw


def verify_checkpoint(path):
    """Check one checkpoint dir end to end: COMMIT record present and
    pointing at this manifest, every shard present with matching sha256
    and size. Returns (manifest dict, commit dict); raises ValueError
    with a precise reason on the first violation."""
    manifest, commit = _check_commit(path)
    for name, ent in manifest.get('files', {}).items():
        _read_shard(path, name, ent)
    return manifest, commit


def latest_committed(dirname):
    """Newest checkpoint that passes full verification, as (step, path,
    manifest, commit) — or None. Partial/corrupt candidates are skipped
    with a LOUD warning, never loaded silently. A candidate racing
    deletion (retention rmtree from another incarnation) counts as
    unloadable, not fatal — hence OSError alongside ValueError."""
    for step, path in reversed(list_checkpoints(dirname)):
        try:
            manifest, commit = verify_checkpoint(path)
            return step, path, manifest, commit
        except (ValueError, OSError) as e:
            warnings.warn(
                'checkpoint %s is not loadable: %s — skipping it and '
                'falling back to an older checkpoint' % (path, e),
                RuntimeWarning)
    return None


class CheckpointManager(object):
    """Asynchronous crash-consistent checkpoint writer + restorer.

        mgr = CheckpointManager(dirname, every_steps=100, keep_last_n=3)
        trainer = MultiStepTrainer(main, steps_per_dispatch=8,
                                   fetch_list=[loss], checkpoint=mgr)
        info = trainer.startup(startup)      # restores when a committed
        ...                                  # checkpoint exists
        mgr.flush(); mgr.close()             # end of training

    Or drive it directly: `Executor.run_steps(..., checkpoint=mgr)`
    evaluates the every-N-steps / every-T-seconds policy at each dispatch
    boundary, and `mgr.save(program, scope, step)` forces one.
    """

    def __init__(self, dirname, keep_last_n=3, every_steps=None,
                 every_seconds=None, max_retries=3, retry_backoff_s=0.25,
                 task_service=None):
        if keep_last_n is not None and int(keep_last_n) < 1:
            raise ValueError('keep_last_n must be >= 1, got %r'
                             % (keep_last_n,))
        self.dirname = dirname
        self.keep_last_n = int(keep_last_n) if keep_last_n else None
        self.every_steps = int(every_steps) if every_steps else None
        self.every_seconds = float(every_seconds) if every_seconds else None
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.task_service = task_service
        self._last_step = None
        self._last_time = time.monotonic()
        self._stats_lock = threading.Lock()
        self.stats = {'snapshots': 0, 'commits': 0, 'failed': 0,
                      'skipped_busy': 0, 'retries': 0, 'evicted': 0,
                      'stall_s': 0.0, 'write_s': 0.0, 'bytes_written': 0,
                      'last_error': None}
        # depth-1 queue: at most one checkpoint in flight; a boundary that
        # fires while the writer is busy is SKIPPED (counted), because
        # queueing snapshots would grow host memory without bound when the
        # disk is slower than the policy
        self._jobs = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._closed = False
        self._warned_busy = False
        self._clean_stale_tmp()
        self._writer = threading.Thread(target=self._write_loop,
                                        name='ptpu-ckpt-writer', daemon=True)
        self._writer.start()

    def _clean_stale_tmp(self):
        """Remove staging dirs left by a writer that was SIGKILLed
        mid-write — but only when their owning pid is dead (a concurrent
        writer's live staging must survive)."""
        if not os.path.isdir(self.dirname):
            return
        for name in os.listdir(self.dirname):
            if not name.startswith(_TMP_PREFIX):
                continue
            try:
                pid = int(name.rsplit('.', 1)[-1])
                os.kill(pid, 0)
                alive = True
            except (ValueError, ProcessLookupError):
                alive = False
            except OSError:
                alive = True     # EPERM: someone else's live process
            if not alive:
                shutil.rmtree(os.path.join(self.dirname, name),
                              ignore_errors=True)

    # -- policy --------------------------------------------------------
    def step_boundary(self, executor, program, scope, step):
        """Called by Executor.run_steps after each dispatch. Evaluates the
        checkpoint_every(steps|seconds) policy and snapshots when due.
        Returns the stall seconds this boundary cost (0.0 when idle)."""
        due = False
        if self.every_steps is not None:
            # baseline 0 (or the restore point, set by restore()): the
            # FIRST checkpoint lands after every_steps trained steps, not
            # at the first boundary seen
            base = self._last_step if self._last_step is not None else 0
            due = step - base >= self.every_steps
        if not due and self.every_seconds is not None:
            due = time.monotonic() - self._last_time >= self.every_seconds
        if not due:
            return 0.0
        return self.save(program, scope, step, executor=executor)

    # -- snapshot (the only step-loop work) ----------------------------
    def _snapshot_state(self, program, scope):
        """Persistable scope state as host numpy (+ static lod), copied:
        jax buffers are donated by the next dispatch, so the writer thread
        must never hold device references."""
        from .lod import unwrap, lod_of
        names = [v.name for v in program.list_vars() if v.persistable]
        vals = [(n, scope.get(n)) for n in sorted(set(names))]
        vals = [(n, v) for n, v in vals if v is not None]
        for _n, v in vals:          # start every D2H transfer first
            data = unwrap(v)
            start = getattr(data, 'copy_to_host_async', None)
            if start is not None:
                try:
                    start()
                except Exception:
                    pass            # best-effort prefetch only
        out = {}
        for n, v in vals:
            arr = np.array(unwrap(v), copy=True)    # blocks; owns memory
            lod = [np.asarray(l).tolist() for l in lod_of(v)]
            out[n] = (arr, lod)
        return out

    def save(self, program, scope, step, executor=None, meta=None,
             blocking=False):
        """Snapshot now and enqueue the write. Returns the snapshot stall
        in seconds. When the writer is still busy with the previous
        checkpoint the snapshot is skipped (latest-wins would hoard host
        memory); `blocking=True` waits for the writer instead (and for
        the write to finish — the final checkpoint of a run)."""
        if self._closed:
            raise RuntimeError('CheckpointManager is closed')
        if blocking:
            self.flush()
        elif not self._idle.is_set() or not self._jobs.empty():
            with self._stats_lock:
                self.stats['skipped_busy'] += 1
            if not self._warned_busy:
                self._warned_busy = True
                warnings.warn(
                    'checkpoint writer still busy at a due boundary — '
                    'skipping this snapshot (repeats are counted in '
                    "stats['skipped_busy']); lower the checkpoint "
                    'frequency or speed up the target filesystem',
                    RuntimeWarning)
            return 0.0
        t0 = time.perf_counter()
        state = self._snapshot_state(program, scope)
        job_meta = {
            'version': _VERSION,
            'step': int(step),
            'executor_step': int(
                executor._step_counters.get(_program_uid(program), step))
            if executor is not None else int(step),
            'wall_time': time.time(),
            'random_seed': getattr(program, 'random_seed', 0),
        }
        if self.task_service is not None:
            job_meta['task_journal'] = {
                'path': getattr(self.task_service, '_journal_path', None),
                'position': self.task_service.journal_position(),
                'epoch': self.task_service.epoch,
            }
        if meta:
            job_meta['user'] = meta
        stall = time.perf_counter() - t0
        with self._stats_lock:
            self.stats['snapshots'] += 1
            self.stats['stall_s'] += stall
        self._idle.clear()
        self._jobs.put((state, job_meta))
        self._last_step = int(step)
        self._last_time = time.monotonic()
        if blocking:
            self.flush()
        return stall

    def flush(self, timeout=None):
        """Block until the writer has drained (committed or given up)."""
        self._idle.wait(timeout)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._jobs.put(None)
        self._writer.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- background writer ---------------------------------------------
    def _write_loop(self):
        while True:
            job = self._jobs.get()
            if job is None:
                self._idle.set()
                return
            state, meta = job
            t0 = time.perf_counter()
            for attempt in range(self.max_retries + 1):
                try:
                    nbytes = self._write_checkpoint(state, meta)
                    with self._stats_lock:
                        self.stats['commits'] += 1
                        self.stats['bytes_written'] += nbytes
                    break
                except Exception as e:      # degrade, never crash the loop
                    with self._stats_lock:
                        self.stats['last_error'] = '%s: %s' % (
                            type(e).__name__, e)
                    if attempt < self.max_retries \
                            and not getattr(e, 'no_retry', False):
                        with self._stats_lock:
                            self.stats['retries'] += 1
                        backoff = self.retry_backoff_s * (2 ** attempt)
                        warnings.warn(
                            'checkpoint step %d write failed (%s: %s) — '
                            'retrying in %.2fs (%d/%d); training continues'
                            % (meta['step'], type(e).__name__, e, backoff,
                               attempt + 1, self.max_retries),
                            RuntimeWarning)
                        time.sleep(backoff)
                    else:
                        with self._stats_lock:
                            self.stats['failed'] += 1
                        warnings.warn(
                            'checkpoint step %d ABANDONED after %d retries '
                            '(%s: %s); training continues on the previous '
                            'checkpoint' % (meta['step'], attempt,
                                            type(e).__name__, e),
                            RuntimeWarning)
                        break
            with self._stats_lock:
                self.stats['write_s'] += time.perf_counter() - t0
            self._idle.set()

    def _write_checkpoint(self, state, meta):
        """One atomic checkpoint: stage dir -> shards (fsync each, sha256
        while writing) -> MANIFEST -> COMMIT -> one os.replace makes it
        live -> flock-journaled commit record -> retention."""
        from .lod import LoDArray
        step = meta['step']
        final = os.path.join(self.dirname, '%s%d' % (_PREFIX, step))
        tmp = os.path.join(self.dirname, '%sckpt-%d.%d' % (
            _TMP_PREFIX, step, os.getpid()))
        os.makedirs(self.dirname, exist_ok=True)
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            entries = [(name,
                        LoDArray(arr, [np.asarray(l, np.int32)
                                       for l in lod]) if lod else arr,
                        None)
                       for name, (arr, lod) in sorted(state.items())]
            files, _manifest_raw, commit = _stage_entries(tmp, entries,
                                                          meta)
            if os.path.isdir(final):        # re-checkpoint of a resumed step
                shutil.rmtree(final)
            os.replace(tmp, final)          # THE commit point
            self._fsync_dir(self.dirname)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        nbytes = sum(e['bytes'] for e in files.values())
        # journal + retention are post-commit bookkeeping: a failure here
        # must not fail (or re-run) the already-live checkpoint
        try:
            self._journal_and_retain(step, commit)
        except Exception as e:
            warnings.warn('checkpoint step %d committed but journal/'
                          'retention failed: %s' % (step, e), RuntimeWarning)
        return nbytes

    def _retention_victims(self, live):
        """Which (step, path) entries retention evicts: everything beyond
        the newest keep_last_n. The pod manager overrides this — only
        POD-COMMITTED checkpoints may count toward the keep budget."""
        return live[:-self.keep_last_n]

    @staticmethod
    def _fsync_dir(path):
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _journal_and_retain(self, step, commit):
        journal = os.path.join(self.dirname, _JOURNAL)
        with open(journal, 'a') as jf:
            if fcntl is not None:
                try:
                    fcntl.flock(jf, fcntl.LOCK_EX)
                except OSError:
                    pass        # lockless FS: journaling still append-only
            jf.write(json.dumps({'event': 'commit', 'step': step,
                                 'manifest_sha256': commit['manifest_sha256'],
                                 'wall_time': commit['wall_time']}) + '\n')
            evicted = []
            if self.keep_last_n is not None:
                live = list_checkpoints(self.dirname)
                for old_step, old_path in self._retention_victims(live):
                    shutil.rmtree(old_path, ignore_errors=True)
                    evicted.append(old_step)
                    jf.write(json.dumps({'event': 'evict',
                                         'step': old_step}) + '\n')
            jf.flush()
            _fsync(jf.fileno())
            # flock released on close
        if evicted:
            with self._stats_lock:
                self.stats['evicted'] += len(evicted)

    # -- restore --------------------------------------------------------
    def restore(self, executor=None, program=None, scope=None):
        """Load the newest fully-committed checkpoint into `scope` (the
        global scope by default). Returns an info dict {'step', 'path',
        'meta', 'task_journal'} or None when no committed checkpoint
        exists. Candidates are tried newest-first, each shard verified on
        the SAME read that loads it (one disk pass per shard — the
        seconds-scale-resume path never reads a checkpoint twice);
        partial/corrupt candidates are skipped with a loud warning and
        nothing of them reaches the scope. When `executor` and `program`
        are given, the executor's per-program step counter is restored so
        the per-step rng stream — and therefore every subsequent loss —
        continues bit-exactly."""
        for step, path in reversed(list_checkpoints(self.dirname)):
            try:
                manifest, _commit = _check_commit(path)
                info = self.load_into_scope(path, manifest,
                                            program=program, scope=scope)
            except (ValueError, OSError) as e:
                warnings.warn(
                    'checkpoint %s is not loadable: %s — skipping it and '
                    'falling back to an older checkpoint' % (path, e),
                    RuntimeWarning)
                continue
            meta = manifest.get('meta', {})
            if executor is not None and program is not None:
                executor._step_counters[_program_uid(program)] = int(
                    meta.get('executor_step', step))
            self._last_step = step
            self._last_time = time.monotonic()
            info.update(step=step, path=path, meta=meta,
                        task_journal=meta.get('task_journal'))
            return info
        return None

    @staticmethod
    def load_into_scope(path, manifest=None, program=None, scope=None):
        """Deserialize every shard of a checkpoint dir into the scope,
        verifying each against its manifest entry on the same read. The
        scope is only touched after EVERY shard decoded — a corrupt late
        shard must not leave half a checkpoint behind. Returns {'loaded':
        [names], 'missing': [persistable names the checkpoint does not
        carry]} — `missing` is warned about, not silently left stale."""
        import io as _pyio
        from ..io import _deserialize_tensor
        from .scope import global_scope
        scope = scope if scope is not None else global_scope()
        if manifest is None:
            manifest, _ = _check_commit(path)
        files = manifest.get('files', {})
        decoded = {name: _deserialize_tensor(
            _pyio.BytesIO(_read_shard(path, name, files[name])))
            for name in sorted(files)}
        loaded = []
        for name, value in decoded.items():
            scope.set(name, value)
            loaded.append(name)
        missing = []
        if program is not None:
            missing = sorted({v.name for v in program.list_vars()
                              if v.persistable
                              and scope.get(v.name) is not None}
                             - set(loaded))
            if missing:
                warnings.warn(
                    'checkpoint %s does not carry persistable vars %r — '
                    'they keep their startup values (program changed '
                    'since the checkpoint was written?)' % (path, missing),
                    RuntimeWarning)
        return {'loaded': loaded, 'missing': missing}


# ===========================================================================
# Graceful preemption (ISSUE 10 satellite)
# ===========================================================================
# A preemption notice (SIGTERM from the cluster scheduler) must not become
# a SIGKILL-style crash: the trainer drains ONE final checkpoint at the
# next step boundary — params, step counter, and the elastic data-journal
# position all describing the same history — and exits 0 so the
# supervisor restarts it into a clean resume with nothing to replay.
_preempt = threading.Event()


def request_preemption(signum=None, frame=None):
    """Mark the process as preempted. Signal-handler-safe (only sets an
    Event); the drain happens at the next step boundary on the training
    thread, never inside the handler."""
    _preempt.set()


def preemption_requested():
    return _preempt.is_set()


def clear_preemption():
    _preempt.clear()


def install_preemption_handler(signum=None):
    """Route SIGTERM (or another signal) to request_preemption. Returns
    the previous handler. Main-thread only (signal module contract)."""
    signum = _signal.SIGTERM if signum is None else signum
    return _signal.signal(signum, request_preemption)


def maybe_drain_preemption(manager, executor, program, scope, step):
    """Called by Executor.run_steps/run at a step boundary after the
    checkpoint policy ran. When a preemption was requested: write one
    final BLOCKING checkpoint (unless this boundary just snapshotted this
    exact step — then only wait the in-flight write out), close the
    manager, and exit 0. No-op (returns False) otherwise."""
    if manager is None or not _preempt.is_set():
        return False
    warnings.warn(
        'preemption requested — draining a final checkpoint at step %d '
        'and exiting 0 (resume continues bit-exactly from here)' % step,
        RuntimeWarning)
    if manager._last_step == step:
        # this boundary already snapshotted step N; let the writer land it
        manager.flush()
        if manager.stats['failed'] == 0 and manager.stats['commits'] > 0:
            manager.close()
            raise SystemExit(0)
        # the in-flight write was abandoned: fall through and force one
    commits_before = manager.stats['commits']
    manager.save(program, scope, step, executor=executor, blocking=True)
    drained = manager.stats['commits'] > commits_before
    manager.close()
    if not drained:
        # the forced final write was itself abandoned (persistent
        # ENOSPC/EIO): exiting 0 would tell the supervisor the drain
        # succeeded and silently lose every step since the last commit
        warnings.warn(
            'preemption drain FAILED: the final checkpoint at step %d '
            'was abandoned (%s) — exiting 1 so the supervisor knows the '
            'resume point is older than this boundary'
            % (step, manager.stats['last_error']), RuntimeWarning)
        raise SystemExit(1)
    raise SystemExit(0)


# ===========================================================================
# Pod-scale fault tolerance (ISSUE 10 tentpole)
# ===========================================================================
# Multihost composed-mesh training adds three failure problems the
# single-host manager above cannot see:
#   * state is GLOBAL (one jax.Array spans every host) — no single process
#     can snapshot it, so each host writes only its mesh-local shards and
#     a checkpoint is the UNION of per-host shard sets;
#   * a checkpoint is only usable when EVERY host's shards landed — the
#     commit point must be pod-level, not per-host (two-phase: host
#     manifests first, then ONE coordinator POD_COMMIT naming each
#     manifest sha);
#   * a dead host leaves survivors blocked inside a cross-host collective
#     that no Python exception can interrupt — failure detection is
#     filesystem heartbeats plus a watchdog whose only safe remedy is a
#     bounded-time process exit (the pod supervisor restarts the whole
#     pod, which resumes in seconds off the warm compile cache).
class BarrierTimeout(RuntimeError):
    """A cross-host barrier did not complete within its deadline; the
    message names the missing ranks."""


class PodCommitTimeout(RuntimeError):
    """Phase 2 of a pod checkpoint did not complete: a host manifest
    (coordinator side) or the POD_COMMIT record (every other rank) never
    appeared — peer dead, writer busy (SKIP marker), or coordinator
    abandon. The writer loop abandons immediately (no_retry): retrying
    would hold the writer busy for multiples of commit_timeout_s, which
    is exactly what desynchronizes the pod's checkpoint schedule."""
    no_retry = True


_BARRIER_GC_TTL_S = 600.0


def _gc_barriers(bdir, ttl_s=_BARRIER_GC_TTL_S):
    """Best-effort unlink of marker files older than ttl_s. Any barrier
    is deadline-bounded (timeout_s), so a marker this old belongs to a
    completed or abandoned synchronization point — without GC the dir
    grows one inode per host per barrier forever, and a dead
    incarnation's stale markers could instantly satisfy a reused name."""
    now = time.time()
    try:
        names = os.listdir(bdir)
    except OSError:
        return
    for fname in names:
        path = os.path.join(bdir, fname)
        try:
            if now - os.path.getmtime(path) > ttl_s:
                os.unlink(path)
        except OSError:
            pass


def fs_barrier(dirname, name, rank, num_hosts, timeout_s=60.0,
               poll_s=0.02):
    """Filesystem barrier with a bounded wait: each rank touches a marker
    file and waits for all num_hosts markers. Returns the seconds spent
    waiting; raises BarrierTimeout naming the ranks that never arrived
    (the survivors' bounded-time alternative to hanging forever where an
    in-graph collective would block uninterruptibly). `name` must be
    unique per synchronization point (include the step / run id —
    PodCheckpointManager.barrier salts with the run_id for you); markers
    older than _BARRIER_GC_TTL_S (10 min) are garbage-collected on
    entry."""
    bdir = os.path.join(dirname, _BARRIER_DIR)
    os.makedirs(bdir, exist_ok=True)
    # TTL scales with the deadline: markers of a barrier whose timeout
    # exceeds the default TTL must not be GC'd out from under a rank
    # that is still legitimately waiting
    _gc_barriers(bdir, ttl_s=max(_BARRIER_GC_TTL_S, 2 * float(timeout_s)))
    mark = os.path.join(bdir, '%s.%s%d' % (name, _HOST_PREFIX, rank))
    with open(mark, 'w') as f:
        f.write(str(os.getpid()))
    t0 = time.monotonic()
    deadline = t0 + float(timeout_s)
    while True:
        present = [r for r in range(num_hosts) if os.path.exists(
            os.path.join(bdir, '%s.%s%d' % (name, _HOST_PREFIX, r)))]
        if len(present) == num_hosts:
            return time.monotonic() - t0
        if time.monotonic() > deadline:
            missing = sorted(set(range(num_hosts)) - set(present))
            raise BarrierTimeout(
                'barrier %r timed out after %.1fs: hosts %r never arrived '
                '(dead or wedged — restart the pod)'
                % (name, float(timeout_s), missing))
        time.sleep(poll_s)


def heartbeat_path(dirname, rank):
    return os.path.join(dirname, _HB_DIR, '%s%d.json' % (_HOST_PREFIX,
                                                         rank))


def write_heartbeat(dirname, rank, payload=None):
    """Refresh this host's heartbeat file (atomic replace; the mtime is
    the liveness signal, the JSON payload carries pod-health stats for
    profiler.training_report's pod table). flock-free by design: a hung
    NFS lock must never be able to stall the writer thread."""
    hb_dir = os.path.join(dirname, _HB_DIR)
    os.makedirs(hb_dir, exist_ok=True)
    path = heartbeat_path(dirname, rank)
    tmp = '%s.%d.tmp' % (path, os.getpid())
    rec = dict(payload or {})
    rec.setdefault('rank', int(rank))
    rec.setdefault('pid', os.getpid())
    rec['time'] = time.time()
    with open(tmp, 'w') as f:
        f.write(json.dumps(rec))
    os.replace(tmp, path)
    return path


def read_heartbeats(dirname, num_hosts=None):
    """{rank: heartbeat payload + 'age_s'} for every heartbeat file (or
    the first num_hosts ranks). Unparseable files (torn write race) come
    back as {'age_s': age} only."""
    hb_dir = os.path.join(dirname, _HB_DIR)
    out = {}
    if not os.path.isdir(hb_dir):
        return out
    now = time.time()
    for fname in os.listdir(hb_dir):
        if not (fname.startswith(_HOST_PREFIX) and fname.endswith('.json')):
            continue
        try:
            rank = int(fname[len(_HOST_PREFIX):-len('.json')])
        except ValueError:
            continue
        if num_hosts is not None and rank >= num_hosts:
            continue
        path = os.path.join(hb_dir, fname)
        try:
            age = now - os.path.getmtime(path)
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            try:
                rec, age = {}, now - os.path.getmtime(path)
            except OSError:
                continue
        rec['age_s'] = age
        out[rank] = rec
    return out


def stale_hosts(dirname, num_hosts, timeout_s, run_id=None):
    """Ranks considered dead: heartbeat file missing entirely, stale by
    mtime, or (when run_id is given) still carrying a PREVIOUS
    incarnation's run id — a restarted pod must not trust a corpse's
    last heartbeat."""
    beats = read_heartbeats(dirname, num_hosts)
    dead = []
    for r in range(int(num_hosts)):
        rec = beats.get(r)
        if rec is None or rec.get('age_s', 1e18) > float(timeout_s) \
                or (run_id is not None
                    and rec.get('run_id') not in (None, run_id)):
            dead.append(r)
    return dead


class HostWatchdog(object):
    """Bounded-time failure detection for pod members. A survivor whose
    peer died mid-step is blocked inside a cross-host collective that no
    Python exception can interrupt, so the default remedy is a hard
    process exit (action='exit', os._exit) — the pod supervisor then
    restarts the WHOLE pod, which resumes from the newest pod-committed
    checkpoint in seconds via the warm compile cache.

        wd = HostWatchdog(ckpt_dir, rank=r, num_hosts=n, timeout_s=10,
                          run_id=run_id).start()

    action: 'exit' (default) | 'warn' | a callable(dead_ranks). A peer is
    only judged once it has heartbeat at least once under THIS run_id (or
    after grace_s, covering a host that died before its first beat).
    """

    def __init__(self, dirname, rank, num_hosts, timeout_s=10.0,
                 poll_s=0.25, grace_s=60.0, action='exit', exit_code=3,
                 run_id=None):
        self.dirname = dirname
        self.rank = int(rank)
        self.num_hosts = int(num_hosts)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self.grace_s = float(grace_s)
        self.action = action
        self.exit_code = int(exit_code)
        self.run_id = run_id
        self.dead = set()
        self._seen = set()
        self._departed = {}    # rank -> when its done tombstone was seen
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name='ptpu-pod-watchdog',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self):
        t0 = time.monotonic()
        while not self._stop.wait(self.poll_s):
            beats = read_heartbeats(self.dirname, self.num_hosts)
            dead = []
            for r in range(self.num_hosts):
                if r == self.rank:
                    continue
                rec = beats.get(r)
                fresh = rec is not None and (
                    self.run_id is None
                    or rec.get('run_id') in (None, self.run_id))
                if fresh and rec.get('done'):
                    # clean-shutdown tombstone (manager.close()): the
                    # peer FINISHED — never a death, but a pod missing a
                    # member cannot complete another collective, so a
                    # host still running timeout_s after a peer departed
                    # is wedged (e.g. staggered preemption: the departed
                    # host drained at a boundary this one never reached)
                    # and exits through the same bounded path
                    first = self._departed.setdefault(r, time.monotonic())
                    if time.monotonic() - first > self.timeout_s:
                        dead.append(r)
                    continue
                if fresh:
                    self._seen.add(r)
                    if rec.get('age_s', 0.0) > self.timeout_s:
                        dead.append(r)
                elif r in self._seen or (time.monotonic() - t0
                                         > self.grace_s + self.timeout_s):
                    dead.append(r)   # beat once then vanished, or never
                    # produced a fresh beat within the whole grace window
            new = [r for r in dead if r not in self.dead]
            if not new:
                continue
            self.dead.update(new)
            msg = ('pod host(s) %r stopped heartbeating (> %.1fs stale) — '
                   'detected by host %d' % (sorted(self.dead),
                                            self.timeout_s, self.rank))
            if callable(self.action):
                warnings.warn(msg, RuntimeWarning)
                self.action(set(self.dead))
            elif self.action == 'exit':
                # stderr directly: os._exit skips atexit AND io flushing,
                # and this line is the post-mortem breadcrumb
                import sys
                sys.stderr.write('FATAL: %s; exiting %d so the pod can '
                                 'restart\n' % (msg, self.exit_code))
                sys.stderr.flush()
                os._exit(self.exit_code)
            else:
                warnings.warn(msg, RuntimeWarning)


def _norm_index(idx, shape):
    """A shard's index (tuple of slices, possibly open-ended) normalized
    to a hashable ((start, stop), ...) per dim."""
    out = []
    for s, dim in zip(idx, shape):
        start = 0 if s.start is None else int(s.start)
        stop = int(dim) if s.stop is None else int(s.stop)
        out.append((start, stop))
    return tuple(out)


def pod_verify(path, num_hosts=None):
    """Verify one pod checkpoint dir's two-phase commit: POD_COMMIT
    present and parseable, the pod shape matching `num_hosts`, and every
    named host manifest present with its COMMIT digest matching the sha
    the POD_COMMIT recorded. Shard bytes are NOT read here — they verify
    on the read that loads them. Returns (pod_commit, {rank: manifest});
    raises ValueError with a precise reason."""
    pc_path = os.path.join(path, _POD_COMMIT)
    if not os.path.exists(pc_path):
        raise ValueError('no POD_COMMIT record (partial pod checkpoint: a '
                         'host died before the coordinator could commit)')
    try:
        with open(pc_path) as f:
            pod = json.load(f)
    except ValueError:
        raise ValueError('POD_COMMIT is not valid JSON (torn write?)')
    hosts = pod.get('hosts', {})
    if num_hosts is not None and int(pod.get('num_hosts', -1)) \
            != int(num_hosts):
        raise ValueError('pod shape changed: checkpoint was written by %s '
                         'hosts, this pod has %d (strict shape check; '
                         'PodCheckpointManager.restore() performs '
                         'topology-change resume)' % (pod.get('num_hosts'),
                                                      int(num_hosts)))
    if sorted(int(r) for r in hosts) != list(range(int(
            pod.get('num_hosts', len(hosts))))):
        raise ValueError('POD_COMMIT names hosts %r but records '
                         'num_hosts=%s — inconsistent commit record'
                         % (sorted(hosts), pod.get('num_hosts')))
    manifests = {}
    for r_str, sha in sorted(hosts.items()):
        host_dir = os.path.join(path, '%s%s' % (_HOST_PREFIX, r_str))
        manifest, commit = _check_commit(host_dir)
        if commit.get('manifest_sha256') != sha:
            raise ValueError('host %s manifest does not match the '
                             'POD_COMMIT record (mixed-incarnation '
                             'checkpoint?)' % r_str)
        manifests[int(r_str)] = manifest
    return pod, manifests


def _warn_skip(path, why):
    warnings.warn(
        'pod checkpoint %s is not restorable: %s — skipping it and '
        'falling back to an older checkpoint' % (path, why),
        RuntimeWarning)


def _pod_candidates(dirname, num_hosts=None):
    """Newest-first (step, path, pod_commit, {rank: manifest}) over every
    pod checkpoint passing two-phase-commit verification. Partial pods
    (missing POD_COMMIT, missing/mismatched host manifests) are skipped
    with a LOUD warning, exactly like single-host corrupt entries."""
    for step, path in reversed(list_checkpoints(dirname)):
        try:
            pod, manifests = pod_verify(path, num_hosts)
        except (ValueError, OSError) as e:
            _warn_skip(path, e)
            continue
        yield step, path, pod, manifests


def pod_latest_committed(dirname, num_hosts=None):
    """Newest pod checkpoint passing two-phase-commit verification, as
    (step, path, pod_commit, {rank: manifest}) — or None."""
    return next(_pod_candidates(dirname, num_hosts), None)


class PodCheckpointManager(CheckpointManager):
    """Sharded crash-consistent checkpointing for a multi-process pod.

        mgr = PodCheckpointManager(dirname, rank=jax.process_index(),
                                   num_hosts=jax.process_count(),
                                   every_steps=100, run_id=run_id)
        info = mgr.restore(executor=exe, program=prog)   # all ranks
        ...
        exe.run(prog, feed=feed, fetch_list=[loss], checkpoint=mgr)

    Two-phase commit over the shared filesystem:

    phase 1 (every host): snapshot only the mesh-local param/state shards
    this process OWNS (for each distinct shard index of a global array,
    the owner is the lowest process_index holding it — replicated state
    is written once, by the coordinator), stage them with per-shard
    sha256 manifests exactly like the single-host writer, and make the
    host's shard set live with ONE atomic rename into
    ckpt-<step>/host-<rank>/.

    phase 2 (coordinator, rank 0): wait (bounded by commit_timeout_s) for
    every host manifest of THIS run_id to land and verify, then write one
    POD_COMMIT record naming each host manifest's sha — the pod-level
    commit point. restore() only ever loads checkpoints whose POD_COMMIT
    covers all hosts with matching digests; partial pods (a host died
    mid-write, a stale dir from a previous incarnation) are skipped with
    a loud warning, never loaded.

    run_id distinguishes incarnations: after a kill-and-restart at the
    same step, a stale host dir from the dead run must never be stitched
    together with fresh shards into a Frankenstein checkpoint — the
    coordinator only counts manifests carrying its own run_id.

    The writer thread doubles as the liveness signal: a heartbeat file
    per host (mtime-refreshed every heartbeat_interval_s, flock-free,
    payload carrying ckpt-stall/barrier/commit stats for the profiler's
    pod table). Pair with HostWatchdog for bounded-time failure
    detection on the training side.

    Policy note: only the step-deterministic every_steps policy is
    supported (wall-clock policies desynchronize the snapshot step
    across hosts). One host skipping a due boundary because its writer
    is still busy (stats['skipped_busy']) costs the pod THAT checkpoint
    — the coordinator abandons it loudly after commit_timeout_s and the
    next boundary tries again; older committed pods stay restorable.
    """

    def __init__(self, dirname, rank, num_hosts, keep_last_n=3,
                 every_steps=None, every_seconds=None, max_retries=3,
                 retry_backoff_s=0.25, task_service=None,
                 commit_timeout_s=60.0, heartbeat_interval_s=0.5,
                 run_id=None, topology=None):
        self.rank = int(rank)
        self.num_hosts = int(num_hosts)
        # pod topology (hosts x mesh axes) for the operator surface: a
        # dict of mesh axis -> size (or a pre-rendered string) carried
        # in every heartbeat payload and POD_COMMIT, so a resize is
        # visible in profiler.pod_report() — stale-shape heartbeat
        # files from the previous incarnation are already ignored by
        # run_id, exactly like stale shard dirs
        self.topology = topology
        if isinstance(topology, dict):
            self._topology_str = '%dh x %s' % (
                int(num_hosts), ','.join('%s=%d' % (a, int(s))
                                         for a, s in topology.items()))
        elif topology is not None:
            self._topology_str = str(topology)
        else:
            self._topology_str = '%dh' % int(num_hosts)
        if not (0 <= self.rank < self.num_hosts):
            raise ValueError('rank %d outside pod of %d hosts'
                             % (self.rank, self.num_hosts))
        if every_seconds is not None:
            # wall-clock policies fire at different steps on different
            # hosts, and the two-phase commit needs every host at the
            # SAME step — the coordinator would wait commit_timeout_s for
            # a manifest that never comes and abandon every checkpoint
            raise ValueError(
                'PodCheckpointManager does not support every_seconds: '
                'per-host clocks desynchronize the snapshot step across '
                'the pod; use every_steps (deterministic on every host)')
        self.commit_timeout_s = float(commit_timeout_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.run_id = run_id if run_id is not None \
            else os.environ.get('PTPU_POD_RUN_ID')
        if self.run_id is None:
            # without an incarnation token the phase-2 stale filter has
            # nothing to compare — a restarted pod could stitch a dead
            # incarnation's host dir into POD_COMMIT (or commit a sha the
            # live host is about to overwrite)
            raise ValueError(
                'PodCheckpointManager needs a run_id shared by every '
                'host of THIS incarnation: pass run_id='
                'paddle_tpu.parallel.pod_run_id() or set PTPU_POD_RUN_ID')
        self._executor_ref = None
        super(PodCheckpointManager, self).__init__(
            dirname, keep_last_n=keep_last_n, every_steps=every_steps,
            every_seconds=every_seconds, max_retries=max_retries,
            retry_backoff_s=retry_backoff_s, task_service=task_service)
        self.stats.update({'pod_commits': 0, 'pod_abandoned': 0,
                           'barrier_wait_s': 0.0})
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(target=self._hb_loop,
                                           name='ptpu-pod-heartbeat',
                                           daemon=True)
        self._hb_thread.start()
        self._register_pod_source()

    # -- heartbeat / pod-health surface --------------------------------
    def _hb_payload(self):
        p = {'rank': self.rank, 'run_id': self.run_id,
             'topology': self._topology_str,
             'step': self._last_step if self._last_step is not None else 0}
        with self._stats_lock:
            p.update(commits=self.stats['commits'],
                     failed=self.stats['failed'],
                     pod_abandoned=self.stats.get('pod_abandoned', 0),
                     ckpt_stall_ms=self.stats['stall_s'] * 1e3,
                     barrier_ms=self.stats.get('barrier_wait_s', 0.0) * 1e3)
        ex = self._executor_ref() if self._executor_ref is not None else None
        if ex is not None:
            st = ex._dispatch_stats
            if st.get('run_s'):
                p['ckpt_stall_pct'] = (100.0 * st['ckpt_stall_s']
                                       / st['run_s'])
                p['host_stall_pct'] = (100.0 * st['host_stall_s']
                                       / st['run_s'])
        return p

    def _hb_loop(self):
        while True:
            try:
                write_heartbeat(self.dirname, self.rank, self._hb_payload())
            except OSError:
                pass      # a full/unreachable fs must not kill liveness
            if self._hb_stop.wait(self.heartbeat_interval_s):
                return

    def _register_pod_source(self):
        try:
            from .. import profiler as _profiler
        except ImportError:
            return            # standalone module load (tools/chaos.py)
        ref = weakref.ref(self)
        name = 'pod@%s' % os.path.basename(os.path.abspath(self.dirname))

        def snap():
            mgr = ref()
            if mgr is None:
                _profiler.unregister_pod_source(name)
                raise ReferenceError('pod manager collected')
            return {'num_hosts': mgr.num_hosts, 'rank': mgr.rank,
                    'hosts': read_heartbeats(mgr.dirname, mgr.num_hosts)}
        _profiler.register_pod_source(name, snap)
        self._pod_source_name = name

    def barrier(self, name, timeout_s=None):
        """fs_barrier over this pod's checkpoint dir, salted with the
        run_id (markers left by a dead incarnation can never satisfy a
        restarted pod's barrier), accounted into stats['barrier_wait_s']
        (the profiler pod table's barrier column)."""
        waited = fs_barrier(self.dirname, '%s.%s' % (self.run_id, name),
                            self.rank, self.num_hosts,
                            timeout_s=timeout_s if timeout_s is not None
                            else self.commit_timeout_s)
        with self._stats_lock:
            self.stats['barrier_wait_s'] += waited
        return waited

    def step_boundary(self, executor, program, scope, step):
        """Pod boundaries are a PURE FUNCTION of the step (step %%
        every_steps == 0), never of this host's last-snapshot drift: the
        base class's `step - _last_step >= every` rule lets one busy
        host slide onto a different schedule than its peers, after which
        every checkpoint has a missing manifest and times out. A host
        that IS busy at a due boundary declines loudly — a SKIP marker
        in the pod dir — so the coordinator abandons that checkpoint
        immediately instead of waiting commit_timeout_s for a manifest
        that will never come."""
        self._executor_ref = weakref.ref(executor)
        if self.every_steps is None:
            return 0.0
        if step % self.every_steps != 0 or step == self._last_step:
            return 0.0
        if not self._idle.is_set() or not self._jobs.empty():
            self._mark_skip(step)
        return self.save(program, scope, step, executor=executor)

    def _mark_skip(self, step):
        """Tell the pod this host declines the checkpoint at `step`
        (writer still busy): peers abandon it in bounded-short time."""
        pod_dir = os.path.join(self.dirname, '%s%d' % (_PREFIX, step))
        try:
            os.makedirs(pod_dir, exist_ok=True)
            with open(os.path.join(
                    pod_dir, 'SKIP.%s%d' % (_HOST_PREFIX, self.rank)),
                    'w') as f:
                f.write(json.dumps({'rank': self.rank,
                                    'run_id': self.run_id}))
        except OSError:
            pass     # peers fall back to the commit timeout

    def _skip_marker(self, step, rank):
        """True when `rank` declined the checkpoint at `step` under THIS
        run_id (stale markers from a dead incarnation are ignored)."""
        path = os.path.join(self.dirname, '%s%d' % (_PREFIX, step),
                            'SKIP.%s%d' % (_HOST_PREFIX, rank))
        try:
            with open(path) as f:
                return json.load(f).get('run_id') == self.run_id
        except (OSError, ValueError):
            return False

    def close(self):
        if self._closed:
            return
        # drain the writer FIRST, heartbeat still beating: the final
        # blocking write (plus the phase-2 wait, up to commit_timeout_s)
        # can outlast any watchdog timeout — going silent before it
        # finishes would get still-training peers hard-exited
        super(PodCheckpointManager, self).close()
        self._hb_stop.set()
        self._hb_thread.join(timeout=5)
        try:
            # clean-shutdown tombstone: peers' watchdogs must be able to
            # tell a host that FINISHED from one that died — without it,
            # the first host to close would stop heartbeating and get
            # every survivor hard-exited mid final write
            write_heartbeat(self.dirname, self.rank,
                            dict(self._hb_payload(), done=True))
        except OSError:
            pass
        if getattr(self, '_pod_source_name', None):
            try:
                from .. import profiler as _profiler
                _profiler.unregister_pod_source(self._pod_source_name)
            except ImportError:
                pass

    # -- sharded snapshot ----------------------------------------------
    def _owned_shards(self, arr):
        """{normalized index: device shard} for every distinct shard of a
        global array that THIS process owns. Ownership: the lowest
        process_index among the devices holding that exact index — so
        each distinct piece of the array is written exactly once across
        the pod, and fully-replicated state is written only by rank 0."""
        shape = arr.shape
        owner = {}
        for d, idx in arr.sharding.devices_indices_map(shape).items():
            key = _norm_index(idx, shape)
            p = int(d.process_index)
            if key not in owner or p < owner[key]:
                owner[key] = p
        mine = {}
        for sh in arr.addressable_shards:
            key = _norm_index(sh.index, shape)
            if owner.get(key) == self.rank and key not in mine:
                mine[key] = sh.data
        return mine

    def _snapshot_state(self, program, scope):
        """Mesh-local snapshot: global arrays contribute only the shards
        this process owns; host-local values (startup numpy, LoD state —
        identical on every host by SPMD construction) are written by the
        coordinator alone. Same stall discipline as the base class: D2H
        started async for every owned shard first, then one blocking copy
        each — the copy is mandatory, the next dispatch donates."""
        from .lod import unwrap, lod_of
        names = [v.name for v in program.list_vars() if v.persistable]
        vals = [(n, scope.get(n)) for n in sorted(set(names))]
        vals = [(n, v) for n, v in vals if v is not None]
        plan = []
        for n, v in vals:
            data = unwrap(v)
            if getattr(data, 'is_fully_addressable', True):
                if self.rank == 0:
                    plan.append((n, 'full', v, data))
            else:
                shards = self._owned_shards(data)
                if shards:
                    plan.append((n, 'shards', v, shards))
        for _n, kind, _v, payload in plan:   # start every D2H first
            targets = [payload] if kind == 'full' else payload.values()
            for t in targets:
                start = getattr(t, 'copy_to_host_async', None)
                if start is not None:
                    try:
                        start()
                    except Exception:
                        pass            # best-effort prefetch only
        out = {}
        for n, kind, v, payload in plan:
            if kind == 'full':
                arr = np.array(unwrap(v), copy=True)
                lod = [np.asarray(l).tolist() for l in lod_of(v)]
                out[n] = ('full', arr, lod)
            else:
                shards = {key: np.array(data, copy=True)
                          for key, data in sorted(payload.items())}
                gshape = tuple(int(d) for d in unwrap(v).shape)
                out[n] = ('shards', shards, gshape)
        return out

    # -- two-phase write ------------------------------------------------
    def _write_checkpoint(self, state, meta):
        """Phase 1 for this host (stage shards -> MANIFEST -> COMMIT ->
        one atomic rename into ckpt-<step>/host-<rank>), then phase 2 on
        the coordinator (wait for every host manifest of this run_id,
        write POD_COMMIT, journal + retention)."""
        from ..io import _serialize_tensor, _HashingFile
        from .lod import LoDArray
        step = meta['step']
        meta = dict(meta, rank=self.rank, num_hosts=self.num_hosts,
                    run_id=self.run_id, pod=True,
                    topology=self._topology_str)
        pod_dir = os.path.join(self.dirname, '%s%d' % (_PREFIX, step))
        if os.path.exists(os.path.join(pod_dir, _POD_COMMIT)):
            try:
                # shape-agnostic (num_hosts=None): after an elastic
                # resize, a committed checkpoint at this step from the
                # OLD topology describes the same training history and
                # is restorable by the elastic restore() — rewriting
                # its host dirs in place would be the exact
                # mixed-incarnation destruction this guard forbids
                pod_verify(pod_dir, None)
                committed = True
            except (ValueError, OSError):
                committed = False
            if committed:
                # a FULLY pod-committed checkpoint at this step already
                # exists (idempotent re-save after a no-train resume, or
                # a restarted incarnation reaching the same boundary) —
                # unlike the single-host writer's whole-dir replace,
                # rewriting host dirs in place is NOT atomic across the
                # pod: a peer mid-restore would see mixed incarnations,
                # and an abandoned rewrite would destroy the newest good
                # checkpoint. Keep the committed one; it describes the
                # same training history.
                return 0
        host_dir = os.path.join(pod_dir, '%s%d' % (_HOST_PREFIX, self.rank))
        tmp = os.path.join(self.dirname, '%spod-%d.h%d.%d' % (
            _TMP_PREFIX, step, self.rank, os.getpid()))
        os.makedirs(self.dirname, exist_ok=True)
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            entries = []
            for name, entry in sorted(state.items()):
                if entry[0] == 'full':
                    _, arr, lod = entry
                    value = LoDArray(arr, [np.asarray(l, np.int32)
                                           for l in lod]) if lod else arr
                    entries.append((name, value, {'var': name}))
                else:
                    _, shards, gshape = entry
                    for i, (key, arr) in enumerate(sorted(shards.items())):
                        entries.append(('%s@%d' % (name, i), arr,
                                        {'var': name,
                                         'index': [[b, e] for b, e in key],
                                         'global_shape': list(gshape)}))
            files, manifest_raw, _commit = _stage_entries(
                tmp, entries, meta,
                commit_extra={'rank': self.rank, 'run_id': self.run_id})
            os.makedirs(pod_dir, exist_ok=True)
            if os.path.isdir(host_dir):   # re-checkpoint of a resumed step
                shutil.rmtree(host_dir)
            os.replace(tmp, host_dir)     # phase-1 commit for THIS host
            try:
                # a re-save of a step this host previously DECLINED must
                # retract the decline, or the coordinator would abandon
                # the fresh attempt off the stale marker
                os.unlink(os.path.join(
                    pod_dir, 'SKIP.%s%d' % (_HOST_PREFIX, self.rank)))
            except OSError:
                pass
            self._fsync_dir(pod_dir)
            self._fsync_dir(self.dirname)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        nbytes = sum(e['bytes'] for e in files.values())
        # a checkpoint only COUNTS once it is pod-committed: both phases
        # raise PodCommitTimeout (no_retry — holding the writer busy for
        # more timeout rounds is what desynchronizes the pod schedule),
        # the writer loop books the abandon in stats['failed'], and a
        # preemption drain then exits 1 instead of reporting a drain
        # that is not restorable
        if self.rank == 0:
            self._pod_commit(step, meta)
            try:
                self._journal_and_retain(step, {
                    'manifest_sha256': _sha256(manifest_raw),
                    'wall_time': meta['wall_time']})
            except Exception as e:
                warnings.warn('pod checkpoint step %d committed but '
                              'journal/retention failed: %s' % (step, e),
                              RuntimeWarning)
        else:
            self._await_pod_commit(step)
        return nbytes

    def _retention_victims(self, live):
        """Pod-aware retention: only POD-COMMITTED checkpoints count
        toward keep_last_n — abandoned partial dirs must never crowd a
        restorable checkpoint out of the keep budget. Partials OLDER
        than the newest committed checkpoint are dead weight and
        evicted; newer ones are left alone (a peer may be mid-phase-1
        in them right now)."""
        committed = []
        for step, path in live:
            try:
                # shape-agnostic: a committed OLD-topology checkpoint
                # (pre-resize history) is restorable by the elastic
                # restore() and counts toward — and is protected by —
                # the keep budget; verifying against THIS pod's shape
                # would misclassify it as a dead partial and evict the
                # entire pre-resize history on the first new commit
                pod_verify(path, None)
                committed.append((step, path))
            except (ValueError, OSError):
                pass
        keep = {path for _s, path in committed[-self.keep_last_n:]}
        if not keep:
            return []     # never evict while nothing verified survives
        newest = committed[-1][0]
        return [(s, p) for s, p in live if p not in keep and s <= newest]

    def _abandon_pod(self, step):
        """Publish the coordinator's abandon decision (and count it):
        other ranks' _await_pod_commit exits immediately instead of
        waiting out its own timeout."""
        with self._stats_lock:
            self.stats['pod_abandoned'] += 1
        pod_dir = os.path.join(self.dirname, '%s%d' % (_PREFIX, step))
        try:
            with open(os.path.join(pod_dir, 'POD_ABANDONED'), 'w') as f:
                f.write(json.dumps({'run_id': self.run_id}))
        except OSError:
            pass     # peers fall back to their commit timeout

    def _abandoned_marker(self, step):
        path = os.path.join(self.dirname, '%s%d' % (_PREFIX, step),
                            'POD_ABANDONED')
        try:
            with open(path) as f:
                return json.load(f).get('run_id') == self.run_id
        except (OSError, ValueError):
            return False

    def _await_pod_commit(self, step):
        """Non-coordinator half of phase 2: block (bounded) until the
        coordinator's POD_COMMIT for this step and run_id appears, so
        every host's commit accounting means the same thing — a
        restorable pod checkpoint. Exits early when the coordinator
        abandoned the step or declined it with a SKIP marker. The
        deadline is anchored to the COORDINATOR's phase-1 end (its host
        dir appearing), capped at 2x commit_timeout_s: rank 0 writes the
        most data (every replicated host-local var), and a fast rank
        timing out on its own clock would book a failure for a
        checkpoint that actually commits."""
        pod_dir = os.path.join(self.dirname, '%s%d' % (_PREFIX, step))
        pod_path = os.path.join(pod_dir, _POD_COMMIT)
        host0 = os.path.join(pod_dir, '%s0' % _HOST_PREFIX)
        deadline = time.monotonic() + 2 * self.commit_timeout_s
        host0_seen = False
        while time.monotonic() <= deadline:
            if not host0_seen and os.path.isdir(host0):
                host0_seen = True
                deadline = min(deadline,
                               time.monotonic() + self.commit_timeout_s)
            try:
                with open(pod_path) as f:
                    pod = json.load(f)
                if int(pod.get('step', -1)) == int(step) \
                        and pod.get('run_id') == self.run_id:
                    return
            except (OSError, ValueError):
                pass
            if self._abandoned_marker(step) or self._skip_marker(step, 0):
                with self._stats_lock:
                    self.stats['pod_abandoned'] += 1
                raise PodCommitTimeout(
                    'pod checkpoint step %d: the coordinator abandoned '
                    'or declined this boundary — not restorable' % step)
            time.sleep(0.05)
        with self._stats_lock:
            self.stats['pod_abandoned'] += 1
        raise PodCommitTimeout(
            'pod checkpoint step %d: the coordinator never wrote '
            'POD_COMMIT within %.1fs (dead or slow host 0) — this '
            'checkpoint is not restorable' % (step, self.commit_timeout_s))

    def _pod_commit(self, step, meta):
        """Phase 2 (coordinator only): wait for every host's phase-1
        manifest of THIS run_id, then write the single pod-level commit
        record. A host that never lands within commit_timeout_s raises
        PodCommitTimeout — the writer loop retries, then abandons LOUDLY;
        the partial dir is skipped by restore() and aged out by
        retention; training continues."""
        pod_dir = os.path.join(self.dirname, '%s%d' % (_PREFIX, step))
        try:
            # a fresh commit attempt retracts any previous abandon of
            # this step (re-save after an earlier decline)
            os.unlink(os.path.join(pod_dir, 'POD_ABANDONED'))
        except OSError:
            pass
        deadline = time.monotonic() + self.commit_timeout_s
        shas, pending = {}, set(range(self.num_hosts))
        while True:
            for r in sorted(pending):
                host_dir = os.path.join(pod_dir,
                                        '%s%d' % (_HOST_PREFIX, r))
                try:
                    manifest, commit = _check_commit(host_dir)
                except (ValueError, OSError):
                    continue
                if manifest.get('meta', {}).get('run_id') != self.run_id:
                    # stale dir from a dead incarnation (including one
                    # launched WITHOUT a run id): wait for this host's
                    # fresh rewrite, never stitch — counting a corpse's
                    # manifest would commit a sha the live host is about
                    # to overwrite, rotting the newest checkpoint slot
                    continue
                if int(manifest.get('step', -1)) != int(step):
                    continue
                shas[str(r)] = commit['manifest_sha256']
                pending.discard(r)
            if not pending:
                break
            declined = [r for r in sorted(pending)
                        if self._skip_marker(step, r)]
            if declined:
                self._abandon_pod(step)
                raise PodCommitTimeout(
                    'pod checkpoint step %d: host(s) %r declined (writer '
                    'still busy at the boundary) — abandoning without '
                    'waiting out the timeout' % (step, declined))
            if time.monotonic() > deadline:
                self._abandon_pod(step)
                raise PodCommitTimeout(
                    'pod checkpoint step %d: host(s) %r never landed '
                    'their shard manifests within %.1fs (dead or slow '
                    'host) — the partial pod dir will be skipped by '
                    'restore()' % (step, sorted(pending),
                                   self.commit_timeout_s))
            time.sleep(0.05)
        pod = {'version': _VERSION, 'step': step,
               'num_hosts': self.num_hosts, 'hosts': shas,
               'topology': self._topology_str,
               'run_id': self.run_id, 'wall_time': meta['wall_time']}
        tmpf = os.path.join(pod_dir, '%s%s.%d' % (_TMP_PREFIX, _POD_COMMIT,
                                                  os.getpid()))
        with _open_for_write(tmpf, 'wb') as f:
            f.write(json.dumps(pod).encode())
            f.flush()
            _fsync(f.fileno())
        os.replace(tmpf, os.path.join(pod_dir, _POD_COMMIT))
        self._fsync_dir(pod_dir)
        with self._stats_lock:
            self.stats['pod_commits'] += 1

    # -- restore --------------------------------------------------------
    def _load_pod(self, path, manifests):
        """Decode every var of a verified pod checkpoint: single
        full-coverage entries load as-is (lod preserved); sharded vars
        assemble into one global numpy array, each shard verified against
        its manifest entry on the same read. Raises ValueError on any
        missing/corrupt shard or coverage hole."""
        import io as _pyio
        from ..io import _deserialize_tensor
        groups = {}
        for r, manifest in sorted(manifests.items()):
            host_dir = os.path.join(path, '%s%d' % (_HOST_PREFIX, r))
            for fname, ent in manifest.get('files', {}).items():
                var = ent.get('var', fname)
                groups.setdefault(var, []).append((host_dir, fname, ent))
        out = {}
        for var, entries in sorted(groups.items()):
            if len(entries) == 1 and 'index' not in entries[0][2]:
                host_dir, fname, ent = entries[0]
                out[var] = _deserialize_tensor(
                    _pyio.BytesIO(_read_shard(host_dir, fname, ent)))
                continue
            gshape = tuple(entries[0][2]['global_shape'])
            buf, covered = None, 0
            for host_dir, fname, ent in entries:
                arr = np.asarray(_deserialize_tensor(
                    _pyio.BytesIO(_read_shard(host_dir, fname, ent))))
                if buf is None:
                    buf = np.empty(gshape, arr.dtype)
                idx = tuple(slice(b, e) for b, e in ent['index'])
                buf[idx] = arr
                covered += arr.size
            if covered != int(np.prod(gshape, dtype=np.int64)):
                # owner-deduped shards never overlap, so a size mismatch
                # is a coverage hole (lost host manifest entry)
                raise ValueError(
                    'var %r shards cover %d of %d elements — coverage '
                    'hole' % (var, covered,
                              int(np.prod(gshape, dtype=np.int64))))
            out[var] = buf
        return out

    def restore(self, executor=None, program=None, scope=None, mesh=None):
        """Load the newest FULLY pod-committed checkpoint: POD_COMMIT
        present, every host manifest matching its recorded sha, every
        shard verifying on the read that loads it. Every rank assembles
        the same global host values; partial pods — a host died between
        phase 1 and phase 2 — are skipped with a loud warning, exactly
        like single-host corrupt entries.

        Topology-change resume (ISSUE 14): the checkpoint does NOT have
        to match this pod's host count. Same shape keeps today's
        bit-exact fast path — assembled numpy straight into the scope,
        ZERO resharding work (pinned by tests/test_elastic_pod.py).
        When the checkpoint was written by N != num_hosts hosts, the
        assembled global state is resharded onto the NEW mesh (the one
        `program`/`mesh` describes) through parallel/reshard.py: the
        same annotation + optimizer-slot-inheritance rule the executor
        dispatches with, validated for divisibility FIRST — an
        impossible reshard raises ReshardError naming the param, the
        old/new shardings, and the nearest valid host counts. The info
        dict then carries every OLD host's task-journal position
        (`task_journals`) so the data plane can re-stride its
        exactly-once journal (reader/sharded.restride_journal).

        Restores this rank's executor step counter from its own host
        manifest (rank 0's when this rank did not exist in the old pod
        — the counters are identical across hosts by SPMD construction),
        keeping the per-step rng stream exact across the resize."""
        from .scope import global_scope
        for step, path, pod, manifests in _pod_candidates(self.dirname,
                                                          None):
            ckpt_hosts = int(pod.get('num_hosts', len(manifests)))
            t0 = time.perf_counter()
            try:
                values = self._load_pod(path, manifests)
            except (ValueError, OSError) as e:
                _warn_skip(path, e)
                continue
            stitch_s = time.perf_counter() - t0
            resharded = False
            reshard = None
            # a topology change is a different host count OR — when both
            # incarnations recorded their mesh axes (topology=) — the
            # same host count over different axes (dp=4,mp=2 ->
            # dp=2,mp=4): the latter reshards just the same, and taking
            # the fast path would skip the divisibility gate. When
            # either side did not record axes (' x ' absent: the bare
            # '%dh' default, or a pre-topology checkpoint) host count is
            # all there is to compare, exactly as before.
            ckpt_topo = pod.get('topology')
            if ckpt_hosts != self.num_hosts or (
                    ckpt_topo and ' x ' in ckpt_topo
                    and ' x ' in self._topology_str
                    and ckpt_topo != self._topology_str):
                warnings.warn(
                    'topology-change restore: checkpoint %s was written '
                    'by %d host(s) (%s), restoring onto %d host(s) (%s) '
                    '— global state reshards to the new mesh; same-step '
                    'losses stay within float-accumulation tolerance, '
                    'rng stream and sample accounting stay exact'
                    % (path, ckpt_hosts, ckpt_topo or '?',
                       self.num_hosts, self._topology_str),
                    RuntimeWarning)
                # ReshardError (axis not divisible by the new mesh)
                # propagates: it is an operator error about the NEW
                # topology, not a corrupt candidate to skip
                values, reshard = self._reshard_restored(
                    values, program, executor, mesh, ckpt_hosts)
                resharded = True
            sc = scope if scope is not None else global_scope()
            for name, value in values.items():
                sc.set(name, value)
            my_meta = manifests.get(self.rank,
                                    manifests.get(0, {})).get('meta', {})
            if executor is not None and program is not None:
                executor._step_counters[_program_uid(program)] = int(
                    my_meta.get('executor_step', step))
            self._last_step = step
            self._last_time = time.monotonic()
            return {'step': step, 'path': path, 'meta': my_meta,
                    'task_journal': my_meta.get('task_journal'),
                    'task_journals': {
                        r: m.get('meta', {}).get('task_journal')
                        for r, m in sorted(manifests.items())},
                    'pod_num_hosts': ckpt_hosts,
                    'pod_topology': pod.get('topology'),
                    'resharded': resharded, 'reshard': reshard,
                    'stitch_s': stitch_s,
                    'loaded': sorted(values), 'missing': []}
        return None

    def _reshard_restored(self, values, program, executor, mesh,
                          ckpt_hosts):
        """Shape-change half of restore(): validate divisibility against
        the new mesh and scatter the assembled global values onto it.
        Without a program/mesh (duck-typed units, standalone loads, a
        caller that reshards at first dispatch) the assembled numpy is
        returned as-is — the executor's `_mesh_put` replaces the
        explicit resharding program, at the cost of meeting any
        divisibility error only at dispatch."""
        try:
            from ..parallel.reshard import (state_shardings_for,
                                            check_reshardable,
                                            reshard_to_mesh,
                                            reshard_stats)
        except ImportError:
            return values, None     # standalone module load (tools/)
        if mesh is None and program is not None \
                and hasattr(program, '_get_mesh'):
            mesh = program._get_mesh(executor)
        if mesh is None or program is None:
            if program is not None:
                # without a mesh the divisibility pre-check cannot run;
                # say so instead of silently deferring the failure mode
                # to a bare XLA shape error at first dispatch
                warnings.warn(
                    'topology-change restore has no mesh to reshard '
                    'onto (pass mesh= or a CompiledProgram with a '
                    'mesh): restoring host-side numpy — resharding and '
                    'any divisibility error happen at first dispatch',
                    RuntimeWarning)
            return values, None
        names = sorted(values)
        shardings, specs = state_shardings_for(program, mesh, names)
        shapes = {n: tuple(np.shape(v)) for n, v in values.items()
                  if isinstance(v, np.ndarray)}
        check_reshardable(shapes, specs, mesh,
                          old_num_hosts=ckpt_hosts,
                          new_num_hosts=self.num_hosts)
        before = dict(reshard_stats)
        out = reshard_to_mesh(values, shardings, mesh)
        return out, {k: reshard_stats[k] - before[k] if isinstance(
            reshard_stats[k], (int, float)) else reshard_stats[k]
            for k in reshard_stats}
