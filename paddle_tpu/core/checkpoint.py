"""Async crash-consistent checkpointing (ISSUE 6 tentpole).

The reference production stack survived failure with two mechanisms: the
Go pserver wrote CRC-checked atomic-rename checkpoints
(go/pserver/service.go:346) and the master re-leased timed-out task
chunks (go/master/service.go:89). `CheckpointManager` is the TPU-native
composition of both with the warm-start tier (core/compile_cache.py):

1. **Snapshot off the step loop** — at a step boundary the manager
   copies the scope's persistable state device->host (async D2H
   initiation first, then one blocking materialize + copy per array; the
   copy is mandatory because the NEXT dispatch DONATES the state buffers
   — a background reader racing a donated buffer reads freed memory).
   The measured snapshot time is the only stall the step loop ever sees;
   it is surfaced as checkpoint-stall %% in
   `profiler.training_report()`.
2. **Background writer** — one daemon thread serializes shards into a
   `.tmp-` staging directory (per-file fsync + sha256 manifest), makes
   the checkpoint live with ONE atomic `os.replace` of the directory,
   then appends a commit record to a flock-guarded `COMMITS.jsonl`
   journal and applies keep-last-N retention (evictions journaled too).
   A crash at ANY byte leaves either a fully-live checkpoint or an
   ignorable staging dir — never a half-readable one.
3. **Degrade, don't crash** — write-path errors (ENOSPC, EIO — the
   fault-injection harness in testing/faults.py produces them on
   demand) warn loudly and retry with exponential backoff; after
   `max_retries` the checkpoint is abandoned (counted in `stats`) and
   TRAINING CONTINUES. The writer thread never propagates into the step
   loop.
4. **Restore = newest fully-committed** — `restore()` scans candidates
   newest-first and verifies COMMIT record + manifest digest + per-file
   sha256 before loading anything; a partial or corrupt checkpoint is
   skipped with a loud warning, NEVER silently loaded. The restored meta
   carries the executor step counter (so the per-step rng stream — and
   therefore the loss curve — continues bit-exactly) and the elastic
   task-journal position (reader/elastic.py), so a killed trainer
   resumes with params + data position + compile-cache warm hit.
"""
from __future__ import annotations

import hashlib
import json
import os
import queue
import shutil
import threading
import time
import warnings

import numpy as np

try:
    import fcntl
except ImportError:          # non-POSIX: no advisory locking available
    fcntl = None

_MANIFEST = 'MANIFEST.json'
_COMMIT = 'COMMIT.json'
_JOURNAL = 'COMMITS.jsonl'
_PREFIX = 'ckpt-'
_TMP_PREFIX = '.tmp-'
_VERSION = 1

# write-path indirection points: testing/faults.py wraps these to inject
# ENOSPC/EIO without touching the filesystem layer for real
_open_for_write = open
_fsync = os.fsync


def _sha256(data):
    return hashlib.sha256(data).hexdigest()


def _checkpoint_step(name):
    """Parse the step out of a 'ckpt-<step>' dir name, or None."""
    if not name.startswith(_PREFIX):
        return None
    try:
        return int(name[len(_PREFIX):])
    except ValueError:
        return None


def list_checkpoints(dirname):
    """(step, path) of every live (renamed-in) checkpoint dir, ascending
    by step. Liveness != committedness: restore() still verifies."""
    if not os.path.isdir(dirname):
        return []
    out = []
    for name in os.listdir(dirname):
        step = _checkpoint_step(name)
        if step is not None and os.path.isdir(os.path.join(dirname, name)):
            out.append((step, os.path.join(dirname, name)))
    return sorted(out)


def _check_commit(path):
    """COMMIT record present, MANIFEST present/parseable, and the COMMIT's
    digest matching the manifest bytes. Returns (manifest, commit);
    raises ValueError with a precise reason. Shard contents are NOT read
    here — per-shard digests verify on the single read that loads them."""
    commit_path = os.path.join(path, _COMMIT)
    manifest_path = os.path.join(path, _MANIFEST)
    if not os.path.exists(commit_path):
        raise ValueError('no COMMIT record (crash before commit)')
    if not os.path.exists(manifest_path):
        raise ValueError('no MANIFEST')
    with open(manifest_path, 'rb') as f:
        manifest_raw = f.read()
    try:
        manifest = json.loads(manifest_raw.decode())
    except ValueError:
        raise ValueError('MANIFEST is not valid JSON (torn write?)')
    try:
        with open(commit_path) as f:
            commit = json.load(f)
    except ValueError:
        raise ValueError('COMMIT record is not valid JSON (torn write?)')
    if commit.get('manifest_sha256') != _sha256(manifest_raw):
        raise ValueError('COMMIT/MANIFEST digest mismatch')
    return manifest, commit


def _read_shard(path, name, ent):
    """One shard's raw bytes, verified against its manifest entry."""
    shard = os.path.join(path, name)
    if not os.path.exists(shard):
        raise ValueError('missing shard %r' % name)
    with open(shard, 'rb') as f:
        raw = f.read()
    if len(raw) != ent['bytes']:
        raise ValueError('shard %r is %d bytes, manifest says %d '
                         '(truncated?)' % (name, len(raw), ent['bytes']))
    if _sha256(raw) != ent['sha256']:
        raise ValueError('shard %r sha256 mismatch (corrupt)' % name)
    return raw


def verify_checkpoint(path):
    """Check one checkpoint dir end to end: COMMIT record present and
    pointing at this manifest, every shard present with matching sha256
    and size. Returns (manifest dict, commit dict); raises ValueError
    with a precise reason on the first violation."""
    manifest, commit = _check_commit(path)
    for name, ent in manifest.get('files', {}).items():
        _read_shard(path, name, ent)
    return manifest, commit


def latest_committed(dirname):
    """Newest checkpoint that passes full verification, as (step, path,
    manifest, commit) — or None. Partial/corrupt candidates are skipped
    with a LOUD warning, never loaded silently. A candidate racing
    deletion (retention rmtree from another incarnation) counts as
    unloadable, not fatal — hence OSError alongside ValueError."""
    for step, path in reversed(list_checkpoints(dirname)):
        try:
            manifest, commit = verify_checkpoint(path)
            return step, path, manifest, commit
        except (ValueError, OSError) as e:
            warnings.warn(
                'checkpoint %s is not loadable: %s — skipping it and '
                'falling back to an older checkpoint' % (path, e),
                RuntimeWarning)
    return None


class CheckpointManager(object):
    """Asynchronous crash-consistent checkpoint writer + restorer.

        mgr = CheckpointManager(dirname, every_steps=100, keep_last_n=3)
        trainer = MultiStepTrainer(main, steps_per_dispatch=8,
                                   fetch_list=[loss], checkpoint=mgr)
        info = trainer.startup(startup)      # restores when a committed
        ...                                  # checkpoint exists
        mgr.flush(); mgr.close()             # end of training

    Or drive it directly: `Executor.run_steps(..., checkpoint=mgr)`
    evaluates the every-N-steps / every-T-seconds policy at each dispatch
    boundary, and `mgr.save(program, scope, step)` forces one.
    """

    def __init__(self, dirname, keep_last_n=3, every_steps=None,
                 every_seconds=None, max_retries=3, retry_backoff_s=0.25,
                 task_service=None):
        if keep_last_n is not None and int(keep_last_n) < 1:
            raise ValueError('keep_last_n must be >= 1, got %r'
                             % (keep_last_n,))
        self.dirname = dirname
        self.keep_last_n = int(keep_last_n) if keep_last_n else None
        self.every_steps = int(every_steps) if every_steps else None
        self.every_seconds = float(every_seconds) if every_seconds else None
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.task_service = task_service
        self._last_step = None
        self._last_time = time.monotonic()
        self._stats_lock = threading.Lock()
        self.stats = {'snapshots': 0, 'commits': 0, 'failed': 0,
                      'skipped_busy': 0, 'retries': 0, 'evicted': 0,
                      'stall_s': 0.0, 'write_s': 0.0, 'bytes_written': 0,
                      'last_error': None}
        # depth-1 queue: at most one checkpoint in flight; a boundary that
        # fires while the writer is busy is SKIPPED (counted), because
        # queueing snapshots would grow host memory without bound when the
        # disk is slower than the policy
        self._jobs = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._closed = False
        self._warned_busy = False
        self._clean_stale_tmp()
        self._writer = threading.Thread(target=self._write_loop,
                                        name='ptpu-ckpt-writer', daemon=True)
        self._writer.start()

    def _clean_stale_tmp(self):
        """Remove staging dirs left by a writer that was SIGKILLed
        mid-write — but only when their owning pid is dead (a concurrent
        writer's live staging must survive)."""
        if not os.path.isdir(self.dirname):
            return
        for name in os.listdir(self.dirname):
            if not name.startswith(_TMP_PREFIX):
                continue
            try:
                pid = int(name.rsplit('.', 1)[-1])
                os.kill(pid, 0)
                alive = True
            except (ValueError, ProcessLookupError):
                alive = False
            except OSError:
                alive = True     # EPERM: someone else's live process
            if not alive:
                shutil.rmtree(os.path.join(self.dirname, name),
                              ignore_errors=True)

    # -- policy --------------------------------------------------------
    def step_boundary(self, executor, program, scope, step):
        """Called by Executor.run_steps after each dispatch. Evaluates the
        checkpoint_every(steps|seconds) policy and snapshots when due.
        Returns the stall seconds this boundary cost (0.0 when idle)."""
        due = False
        if self.every_steps is not None:
            # baseline 0 (or the restore point, set by restore()): the
            # FIRST checkpoint lands after every_steps trained steps, not
            # at the first boundary seen
            base = self._last_step if self._last_step is not None else 0
            due = step - base >= self.every_steps
        if not due and self.every_seconds is not None:
            due = time.monotonic() - self._last_time >= self.every_seconds
        if not due:
            return 0.0
        return self.save(program, scope, step, executor=executor)

    # -- snapshot (the only step-loop work) ----------------------------
    def _snapshot_state(self, program, scope):
        """Persistable scope state as host numpy (+ static lod), copied:
        jax buffers are donated by the next dispatch, so the writer thread
        must never hold device references."""
        from .lod import unwrap, lod_of
        names = [v.name for v in program.list_vars() if v.persistable]
        vals = [(n, scope.get(n)) for n in sorted(set(names))]
        vals = [(n, v) for n, v in vals if v is not None]
        for _n, v in vals:          # start every D2H transfer first
            data = unwrap(v)
            start = getattr(data, 'copy_to_host_async', None)
            if start is not None:
                try:
                    start()
                except Exception:
                    pass            # best-effort prefetch only
        out = {}
        for n, v in vals:
            arr = np.array(unwrap(v), copy=True)    # blocks; owns memory
            lod = [np.asarray(l).tolist() for l in lod_of(v)]
            out[n] = (arr, lod)
        return out

    def save(self, program, scope, step, executor=None, meta=None,
             blocking=False):
        """Snapshot now and enqueue the write. Returns the snapshot stall
        in seconds. When the writer is still busy with the previous
        checkpoint the snapshot is skipped (latest-wins would hoard host
        memory); `blocking=True` waits for the writer instead (and for
        the write to finish — the final checkpoint of a run)."""
        if self._closed:
            raise RuntimeError('CheckpointManager is closed')
        if blocking:
            self.flush()
        elif not self._idle.is_set() or not self._jobs.empty():
            with self._stats_lock:
                self.stats['skipped_busy'] += 1
            if not self._warned_busy:
                self._warned_busy = True
                warnings.warn(
                    'checkpoint writer still busy at a due boundary — '
                    'skipping this snapshot (repeats are counted in '
                    "stats['skipped_busy']); lower the checkpoint "
                    'frequency or speed up the target filesystem',
                    RuntimeWarning)
            return 0.0
        t0 = time.perf_counter()
        state = self._snapshot_state(program, scope)
        job_meta = {
            'version': _VERSION,
            'step': int(step),
            'executor_step': int(
                executor._step_counters.get(program._uid, step))
            if executor is not None else int(step),
            'wall_time': time.time(),
            'random_seed': getattr(program, 'random_seed', 0),
        }
        if self.task_service is not None:
            job_meta['task_journal'] = {
                'path': getattr(self.task_service, '_journal_path', None),
                'position': self.task_service.journal_position(),
                'epoch': self.task_service.epoch,
            }
        if meta:
            job_meta['user'] = meta
        stall = time.perf_counter() - t0
        with self._stats_lock:
            self.stats['snapshots'] += 1
            self.stats['stall_s'] += stall
        self._idle.clear()
        self._jobs.put((state, job_meta))
        self._last_step = int(step)
        self._last_time = time.monotonic()
        if blocking:
            self.flush()
        return stall

    def flush(self, timeout=None):
        """Block until the writer has drained (committed or given up)."""
        self._idle.wait(timeout)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._jobs.put(None)
        self._writer.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- background writer ---------------------------------------------
    def _write_loop(self):
        while True:
            job = self._jobs.get()
            if job is None:
                self._idle.set()
                return
            state, meta = job
            t0 = time.perf_counter()
            for attempt in range(self.max_retries + 1):
                try:
                    nbytes = self._write_checkpoint(state, meta)
                    with self._stats_lock:
                        self.stats['commits'] += 1
                        self.stats['bytes_written'] += nbytes
                    break
                except Exception as e:      # degrade, never crash the loop
                    with self._stats_lock:
                        self.stats['last_error'] = '%s: %s' % (
                            type(e).__name__, e)
                    if attempt < self.max_retries:
                        with self._stats_lock:
                            self.stats['retries'] += 1
                        backoff = self.retry_backoff_s * (2 ** attempt)
                        warnings.warn(
                            'checkpoint step %d write failed (%s: %s) — '
                            'retrying in %.2fs (%d/%d); training continues'
                            % (meta['step'], type(e).__name__, e, backoff,
                               attempt + 1, self.max_retries),
                            RuntimeWarning)
                        time.sleep(backoff)
                    else:
                        with self._stats_lock:
                            self.stats['failed'] += 1
                        warnings.warn(
                            'checkpoint step %d ABANDONED after %d retries '
                            '(%s: %s); training continues on the previous '
                            'checkpoint' % (meta['step'], self.max_retries,
                                            type(e).__name__, e),
                            RuntimeWarning)
            with self._stats_lock:
                self.stats['write_s'] += time.perf_counter() - t0
            self._idle.set()

    def _write_checkpoint(self, state, meta):
        """One atomic checkpoint: stage dir -> shards (fsync each, sha256
        while writing) -> MANIFEST -> COMMIT -> one os.replace makes it
        live -> flock-journaled commit record -> retention."""
        from ..io import _serialize_tensor, _HashingFile
        from .lod import LoDArray
        step = meta['step']
        final = os.path.join(self.dirname, '%s%d' % (_PREFIX, step))
        tmp = os.path.join(self.dirname, '%sckpt-%d.%d' % (
            _TMP_PREFIX, step, os.getpid()))
        os.makedirs(self.dirname, exist_ok=True)
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            files = {}
            for name, (arr, lod) in sorted(state.items()):
                value = LoDArray(arr, [np.asarray(l, np.int32)
                                       for l in lod]) if lod else arr
                with _open_for_write(os.path.join(tmp, name), 'wb') as f:
                    hf = _HashingFile(f)
                    _serialize_tensor(hf, value)
                    f.flush()
                    _fsync(f.fileno())
                files[name] = {'sha256': hf.sha.hexdigest(),
                               'bytes': hf.nbytes}
            manifest_raw = json.dumps(
                {'version': _VERSION, 'step': step, 'files': files,
                 'meta': meta}, indent=1, sort_keys=True).encode()
            with _open_for_write(os.path.join(tmp, _MANIFEST), 'wb') as f:
                f.write(manifest_raw)
                f.flush()
                _fsync(f.fileno())
            commit = {'step': step, 'manifest_sha256': _sha256(manifest_raw),
                      'wall_time': meta['wall_time']}
            with _open_for_write(os.path.join(tmp, _COMMIT), 'wb') as f:
                f.write(json.dumps(commit).encode())
                f.flush()
                _fsync(f.fileno())
            if os.path.isdir(final):        # re-checkpoint of a resumed step
                shutil.rmtree(final)
            os.replace(tmp, final)          # THE commit point
            self._fsync_dir(self.dirname)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        nbytes = sum(e['bytes'] for e in files.values())
        # journal + retention are post-commit bookkeeping: a failure here
        # must not fail (or re-run) the already-live checkpoint
        try:
            self._journal_and_retain(step, commit)
        except Exception as e:
            warnings.warn('checkpoint step %d committed but journal/'
                          'retention failed: %s' % (step, e), RuntimeWarning)
        return nbytes

    @staticmethod
    def _fsync_dir(path):
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _journal_and_retain(self, step, commit):
        journal = os.path.join(self.dirname, _JOURNAL)
        with open(journal, 'a') as jf:
            if fcntl is not None:
                try:
                    fcntl.flock(jf, fcntl.LOCK_EX)
                except OSError:
                    pass        # lockless FS: journaling still append-only
            jf.write(json.dumps({'event': 'commit', 'step': step,
                                 'manifest_sha256': commit['manifest_sha256'],
                                 'wall_time': commit['wall_time']}) + '\n')
            evicted = []
            if self.keep_last_n is not None:
                live = list_checkpoints(self.dirname)
                for old_step, old_path in live[:-self.keep_last_n]:
                    shutil.rmtree(old_path, ignore_errors=True)
                    evicted.append(old_step)
                    jf.write(json.dumps({'event': 'evict',
                                         'step': old_step}) + '\n')
            jf.flush()
            _fsync(jf.fileno())
            # flock released on close
        if evicted:
            with self._stats_lock:
                self.stats['evicted'] += len(evicted)

    # -- restore --------------------------------------------------------
    def restore(self, executor=None, program=None, scope=None):
        """Load the newest fully-committed checkpoint into `scope` (the
        global scope by default). Returns an info dict {'step', 'path',
        'meta', 'task_journal'} or None when no committed checkpoint
        exists. Candidates are tried newest-first, each shard verified on
        the SAME read that loads it (one disk pass per shard — the
        seconds-scale-resume path never reads a checkpoint twice);
        partial/corrupt candidates are skipped with a loud warning and
        nothing of them reaches the scope. When `executor` and `program`
        are given, the executor's per-program step counter is restored so
        the per-step rng stream — and therefore every subsequent loss —
        continues bit-exactly."""
        for step, path in reversed(list_checkpoints(self.dirname)):
            try:
                manifest, _commit = _check_commit(path)
                info = self.load_into_scope(path, manifest,
                                            program=program, scope=scope)
            except (ValueError, OSError) as e:
                warnings.warn(
                    'checkpoint %s is not loadable: %s — skipping it and '
                    'falling back to an older checkpoint' % (path, e),
                    RuntimeWarning)
                continue
            meta = manifest.get('meta', {})
            if executor is not None and program is not None:
                executor._step_counters[program._uid] = int(
                    meta.get('executor_step', step))
            self._last_step = step
            self._last_time = time.monotonic()
            info.update(step=step, path=path, meta=meta,
                        task_journal=meta.get('task_journal'))
            return info
        return None

    @staticmethod
    def load_into_scope(path, manifest=None, program=None, scope=None):
        """Deserialize every shard of a checkpoint dir into the scope,
        verifying each against its manifest entry on the same read. The
        scope is only touched after EVERY shard decoded — a corrupt late
        shard must not leave half a checkpoint behind. Returns {'loaded':
        [names], 'missing': [persistable names the checkpoint does not
        carry]} — `missing` is warned about, not silently left stale."""
        import io as _pyio
        from ..io import _deserialize_tensor
        from .scope import global_scope
        scope = scope if scope is not None else global_scope()
        if manifest is None:
            manifest, _ = _check_commit(path)
        files = manifest.get('files', {})
        decoded = {name: _deserialize_tensor(
            _pyio.BytesIO(_read_shard(path, name, files[name])))
            for name in sorted(files)}
        loaded = []
        for name, value in decoded.items():
            scope.set(name, value)
            loaded.append(name)
        missing = []
        if program is not None:
            missing = sorted({v.name for v in program.list_vars()
                              if v.persistable
                              and scope.get(v.name) is not None}
                             - set(loaded))
            if missing:
                warnings.warn(
                    'checkpoint %s does not carry persistable vars %r — '
                    'they keep their startup values (program changed '
                    'since the checkpoint was written?)' % (path, missing),
                    RuntimeWarning)
        return {'loaded': loaded, 'missing': missing}
