"""bf16 mixed-precision compute (TPU-native AMP).

The reference era had a float16 type (platform/float16.h) but no AMP
training surface; on TPU bf16 is the MXU-native input format and shares
float32's exponent range, so mixed precision needs NO loss scaling: params,
reductions and elementwise math stay float32, while matmul/conv operands
are cast to bf16 and accumulate to float32. The backward pass mirrors this
via a custom vjp: cotangents are cast to bf16 so the gradient matmuls/convs
also hit the MXU at full rate.

Activated per-program (`program._amp_bf16 = True`, set by
contrib.mixed_precision.decorate) and scoped around the trace by the
Executor, so the same lowering code serves both precisions.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

_state = {'bf16': False}


def enabled():
    return _state['bf16']


@contextlib.contextmanager
def scope(on):
    prev = _state['bf16']
    _state['bf16'] = bool(on)
    try:
        yield
    finally:
        _state['bf16'] = prev


def _is_f32(x):
    return getattr(x, 'dtype', None) == jnp.float32


def matmul(x, y, preferred_element_type=None):
    """jnp.matmul that computes in bf16 (fwd AND bwd) under the amp scope."""
    if not (enabled() and _is_f32(x) and _is_f32(y)):
        if preferred_element_type is not None:
            return jnp.matmul(x, y,
                              preferred_element_type=preferred_element_type)
        return jnp.matmul(x, y)

    @jax.custom_vjp
    def f(a, b):
        return jnp.matmul(a.astype(jnp.bfloat16),
                          b.astype(jnp.bfloat16)).astype(jnp.float32)

    def f_fwd(a, b):
        ab, bb = a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)
        return jnp.matmul(ab, bb).astype(jnp.float32), (ab, bb)

    def f_bwd(res, g):
        ab, bb = res
        _, vjp = jax.vjp(jnp.matmul, ab, bb)
        da, db = vjp(g.astype(jnp.bfloat16))
        return da.astype(jnp.float32), db.astype(jnp.float32)

    f.defvjp(f_fwd, f_bwd)
    return f(x, y)


def conv_general_dilated(x, w, **params):
    """lax.conv_general_dilated in bf16 (fwd and bwd) under the amp scope."""
    if not (enabled() and _is_f32(x) and _is_f32(w)):
        return jax.lax.conv_general_dilated(x, w, **params)

    def conv(a, b):
        return jax.lax.conv_general_dilated(a, b, **params)

    @jax.custom_vjp
    def f(a, b):
        return conv(a.astype(jnp.bfloat16),
                    b.astype(jnp.bfloat16)).astype(jnp.float32)

    def f_fwd(a, b):
        ab, bb = a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)
        return conv(ab, bb).astype(jnp.float32), (ab, bb)

    def f_bwd(res, g):
        ab, bb = res
        _, vjp = jax.vjp(conv, ab, bb)
        da, db = vjp(g.astype(jnp.bfloat16))
        return da.astype(jnp.float32), db.astype(jnp.float32)

    f.defvjp(f_fwd, f_bwd)
    return f(x, w)
