"""bf16 mixed-precision compute (TPU-native AMP).

The reference era had a float16 type (platform/float16.h) but no AMP
training surface; on TPU bf16 is the MXU-native input format and shares
float32's exponent range, so mixed precision needs NO loss scaling.

Design ("value-mode" bf16, the jmp/flax policy): under the amp scope,
matmul/conv lowerings cast operands to bf16 and KEEP the result bf16, so
activations flow through the network at half the HBM traffic — this, not the
MXU rate, is what bounds BN-heavy models like ResNet on TPU. Params stay
float32 in the state dict; they are cast to bf16 at each use inside the
traced step, and the transpose of that cast makes every parameter gradient
arrive float32 for the optimizer with no explicit plumbing. Numerically
sensitive ops opt out via `promote_f32`: norm statistics, softmax, and
losses compute in float32 (nn_ops/math_ops call it regardless of amp —
bf16 inputs are upcast wherever stats/log-exp live).

Activated per-program (`program._amp_bf16 = True`, set by
contrib.mixed_precision.decorate / enable_bf16) and scoped around the trace
by the Executor, so the same lowering code serves both precisions.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

_state = {'bf16': False}


def enabled():
    return _state['bf16']


@contextlib.contextmanager
def scope(on):
    prev = _state['bf16']
    _state['bf16'] = bool(on)
    try:
        yield
    finally:
        _state['bf16'] = prev


def _is_amp_float(x):
    return getattr(x, 'dtype', None) in (jnp.float32, jnp.bfloat16)


def promote_f32(x):
    """Upcast bf16 to f32 for numerically sensitive math (norm stats,
    softmax, log/exp losses). Identity for every other dtype."""
    if getattr(x, 'dtype', None) == jnp.bfloat16:
        return x.astype(jnp.float32)
    return x


def restore(y, like):
    """Cast y back to `like`'s compute dtype (bf16 stays bf16)."""
    dt = getattr(like, 'dtype', None)
    if dt == jnp.bfloat16 and y.dtype == jnp.float32:
        return y.astype(jnp.bfloat16)
    return y


def unify(x, y):
    """Under the amp scope, resolve a bf16/f32 operand mix to bf16 — a
    value-mode program otherwise silently re-promotes to f32 at every
    param + activation elementwise (e.g. the fc bias add), defeating the
    halved-HBM-traffic design. Identity outside the scope or for any other
    dtype pairing."""
    if (enabled()
            and getattr(x, 'dtype', None) in (jnp.float32, jnp.bfloat16)
            and getattr(y, 'dtype', None) in (jnp.float32, jnp.bfloat16)
            and x.dtype != y.dtype):
        return x.astype(jnp.bfloat16), y.astype(jnp.bfloat16)
    return x, y


def matmul(x, y, preferred_element_type=None):
    """jnp.matmul that runs operands and result in bf16 under the amp scope.

    The result stays bf16 (MXU accumulates f32 internally); the backward
    matmuls are bf16 automatically since jax.vjp of a bf16 matmul is bf16.
    """
    if enabled() and _is_amp_float(x) and _is_amp_float(y):
        return jnp.matmul(x.astype(jnp.bfloat16), y.astype(jnp.bfloat16))
    if preferred_element_type is not None:
        return jnp.matmul(x, y, preferred_element_type=preferred_element_type)
    return jnp.matmul(x, y)


def conv_general_dilated(x, w, **params):
    """lax.conv_general_dilated in bf16 (result stays bf16) under amp."""
    if enabled() and _is_amp_float(x) and _is_amp_float(w):
        return jax.lax.conv_general_dilated(
            x.astype(jnp.bfloat16), w.astype(jnp.bfloat16), **params)
    return jax.lax.conv_general_dilated(x, w, **params)
