"""Op registry: every op = shape inference + a JAX lowering rule (+ optional
custom grad maker).

This replaces the reference's C++ operator system (OperatorBase /
OperatorWithKernel / REGISTER_OPERATOR / GradOpDescMaker — ref:
paddle/fluid/framework/operator.h:109,458, op_registry.h:197,
grad_op_desc_maker.h). Key inversion: instead of per-device kernels selected
at run time by OpKernelType, each op registers ONE lowering rule that emits
jax/XLA ops; XLA owns kernel selection, fusion and layout. Gradients need no
per-op GradOpDescMaker: append_backward emits a generic `<type>_grad` op and
the tracer derives its lowering with jax.vjp of the forward lowering (XLA
CSEs the recomputed forward). Ops may still register a custom grad maker
(e.g. ops whose lowering is non-differentiable or that have a cheaper grad).
"""
from __future__ import annotations

import numpy as np

# probe value substituted for -1 dims during eval_shape-based inference;
# any output dim that equals a deterministic function of it maps back to -1.
_PROBE = 12289


class OpDef(object):
    __slots__ = ('type', 'lower', 'infer_shape', 'grad_maker', 'no_grad',
                 'diff_inputs', 'infer_lod', 'lod_mode')

    def __init__(self, type, lower, infer_shape=None, grad_maker=None,
                 no_grad=False, diff_inputs=None, infer_lod=None, lod='pass'):
        self.type = type
        self.lower = lower
        self.infer_shape = infer_shape
        self.grad_maker = grad_maker
        self.no_grad = no_grad
        # slots eligible for gradients; None = every float-dtype input slot
        self.diff_inputs = diff_inputs
        self.infer_lod = infer_lod
        # 'pass': inputs auto-unwrapped from LoDArray, outputs with matching
        #         leading dim re-wrapped with the input LoD (the reference's
        #         default ShareLoD behavior); 'none': unwrap, never re-wrap;
        #         'aware': lowering sees/produces LoDArray itself.
        self.lod_mode = lod


_REGISTRY = {}


def register(type, lower=None, infer_shape=None, grad_maker=None,
             no_grad=False, diff_inputs=None, infer_lod=None, lod='pass'):
    """Register an op. Usable as decorator on the lowering fn:

        @register('relu')
        def _relu(ctx, ins):
            return {'Out': [jax.nn.relu(ins['X'][0])]}
    """
    def deco(fn):
        _REGISTRY[type] = OpDef(type, fn, infer_shape, grad_maker, no_grad,
                                diff_inputs, infer_lod, lod)
        return fn
    if lower is not None:
        return deco(lower)
    return deco


def get(type):
    return _REGISTRY.get(type)


def registered_ops():
    return sorted(_REGISTRY)


def is_registered(type):
    return type in _REGISTRY or (
        type.endswith('_grad') and type[:-5] in _REGISTRY)


# ---------------------------------------------------------------------------
# Shape inference. Default path: abstract-evaluate the lowering rule with
# jax.eval_shape over ShapeDtypeStructs, substituting _PROBE for -1 dims and
# mapping probe-derived output dims back to -1. Mirrors the reference's
# compile-time InferShape (framework/shape_inference.h) without per-op code.
# ---------------------------------------------------------------------------

class ShapeCtx(object):
    """Minimal ctx passed to lowerings during abstract evaluation."""

    def __init__(self, op, block):
        self.op = op
        self.block = block
        self.attrs = op.attrs
        self.is_test = bool(op.attrs.get('is_test', False))
        self.abstract = True

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def rng(self):
        import jax
        return jax.random.key(0)

    def var(self, name):
        return self.block._find_var_recursive(name)


def _probe_shape(shape):
    return tuple(_PROBE if d in (-1, None) else int(d) for d in shape)


def _unprobe_dim(d, had_probe):
    if not had_probe:
        return int(d)
    if d % _PROBE == 0 and d != 0:
        # any multiple of the probe derives from the dynamic dim (probe*k
        # from tiling/expanding it k times) — a coincidental static
        # multiple of the large prime probe is vanishingly unlikely, and
        # keeping it static poisons downstream inference (a 49156-row
        # "static" expand output broke reshape/concat/fc chains)
        return -1
    return int(d)


def infer_shape(op, block):
    """Infer and assign output var shapes/dtypes for a freshly appended op."""
    d = get(op.type)
    if d is None:
        if op.type.endswith('_grad'):
            return _infer_grad_shape(op, block)
        return  # unknown op: leave declared shapes alone (feed/fetch etc.)
    if d.infer_shape is not None:
        d.infer_shape(op, block)
        return
    _generic_infer_shape(op, block, d)


def _generic_infer_shape(op, block, d):
    import jax
    import jax.numpy as jnp

    had_probe = False
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if not n:
                vals.append(None)
                continue
            v = block._find_var_recursive(n)
            if v is None or v.shape is None:
                return  # can't infer
            if any(s in (-1, None) for s in v.shape):
                had_probe = True
            vals.append(jax.ShapeDtypeStruct(_probe_shape(v.shape),
                                             jnp.dtype(v.dtype)))
        ins[slot] = vals

    ctx = ShapeCtx(op, block)

    def f(ins):
        return d.lower(ctx, ins)

    try:
        outs = jax.eval_shape(f, ins)
    except Exception:
        return  # lowering needs concrete values; rely on declared shapes

    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        for n, sds in zip(names, vals):
            if not n or sds is None:
                continue
            v = block._find_var_recursive(n)
            if v is None:
                continue
            shape = tuple(_unprobe_dim(s, had_probe) for s in sds.shape)
            v.shape = shape
            from ..framework import convert_dtype
            v.dtype = convert_dtype(sds.dtype)
    if d.infer_lod is not None:
        d.infer_lod(op, block)


def _infer_grad_shape(op, block):
    """Grad var shape == forward var shape (generic grad convention)."""
    from ..framework import GRAD_SUFFIX
    for slot, names in op.outputs.items():
        for n in names:
            if not n:
                continue
            gv = block._find_var_recursive(n)
            if gv is None:
                continue
            base = n
            if GRAD_SUFFIX in n:
                base = n[:n.index(GRAD_SUFFIX)]
            fv = block._find_var_recursive(base)
            if fv is not None and gv.shape is None:
                gv.shape = fv.shape
                gv.dtype = fv.dtype
