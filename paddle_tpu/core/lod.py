"""LoD (level-of-detail) runtime representation.

The reference's LoDTensor (paddle/fluid/framework/lod_tensor.h:58,110) packs
variable-length sequences into one dense tensor plus offset tables. TPU-native
re-design: the dense data is a jax.Array; the offsets ride along as device
int32 arrays inside a registered pytree (`LoDArray`) so they can flow through
jit/pjit. Shapes stay static per (batch-size, total-token) signature; callers
that need shape stability should bucket/pad on the host (see
layers/io + lod_tensor helpers).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class LoDArray(object):
    """Dense data + per-level row-split offsets (device arrays)."""

    __slots__ = ('data', 'lod')

    def __init__(self, data, lod=()):
        self.data = data
        self.lod = tuple(jnp.asarray(l, dtype=jnp.int32) for l in lod)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data,) + self.lod, len(self.lod)

    @classmethod
    def tree_unflatten(cls, nlod, children):
        obj = cls.__new__(cls)
        obj.data = children[0]
        obj.lod = tuple(children[1:1 + nlod])
        return obj

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def recursive_sequence_lengths(self):
        out = []
        for level in self.lod:
            l = np.asarray(level)
            out.append((l[1:] - l[:-1]).tolist())
        return out

    def __repr__(self):
        return "LoDArray(shape=%s, lod_levels=%d)" % (
            tuple(self.data.shape), len(self.lod))


def unwrap(x):
    return x.data if isinstance(x, LoDArray) else x


def lod_of(x):
    return x.lod if isinstance(x, LoDArray) else ()


def lengths_to_offsets(lengths):
    lengths = np.asarray(lengths, dtype=np.int64)
    return np.concatenate([[0], np.cumsum(lengths)]).astype(np.int32)


def create_lod_array(data, recursive_seq_lens=None, lod=None):
    """Build a LoDArray from dense data + python nested lengths or offsets."""
    if lod is None:
        lod = []
        if recursive_seq_lens:
            for lens in recursive_seq_lens:
                lod.append(lengths_to_offsets(lens))
    return LoDArray(jnp.asarray(data), lod)


def segment_ids_from_offsets(offsets, total):
    """offsets: i32[nseq+1] device array; total: static int row count.
    Returns i32[total] mapping row -> sequence index. The workhorse for
    lowering sequence_* ops onto XLA segment primitives."""
    rows = jnp.arange(total, dtype=jnp.int32)
    # searchsorted(side='right') - 1 gives the segment of each row
    return jnp.searchsorted(offsets, rows, side='right').astype(jnp.int32) - 1
