"""LoD (level-of-detail) runtime representation.

The reference's LoDTensor (paddle/fluid/framework/lod_tensor.h:58,110) packs
variable-length sequences into one dense tensor plus offset tables, and its
kernels read the offsets on the HOST (mixed_vector.h keeps a CPU home for
the LoD).

TPU-native re-design, two modes per LoDArray:

- STATIC (default): offsets are host tuples carried in the pytree STRUCTURE
  (aux data). Sequence ops constant-fold them into static-shape XLA
  programs; the jit cache keys on the lod pattern. Right for fixed corpora
  and for ops whose OUTPUT SHAPE depends on lod content (sequence_expand,
  sequence_erase, lod_tensor_to_array) — dynamic output shapes cannot be
  compiled, so those recompile per pattern by design.

- TRACED: offsets are device int32 arrays carried as pytree CHILDREN. The
  compiled program's shape depends only on the BUCKET shape (total rows,
  nseq, padded length), not the lod values, so any same-bucket batch hits
  the same executable — this kills the per-batch recompile the reference
  avoided with lod-generic kernels (operators/math/sequence2batch.h).
  Lowerings use `off_t()` + searchsorted/segment math, which serves BOTH
  modes (static offsets become XLA constants and fold away).

Host-side bucketing (reader decorators bucket_by_length) pairs with traced
mode: pad each batch to its bucket's (rows, nseq) and every bucket compiles
exactly once.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _freeze(lod):
    return tuple(tuple(int(v) for v in np.asarray(l).reshape(-1))
                 for l in lod)


@jax.tree_util.register_pytree_node_class
class LoDArray(object):
    """Dense device data + per-level row-split offsets (static or traced)."""

    __slots__ = ('data', '_lod', '_lod_t')

    def __init__(self, data, lod=()):
        self.data = data
        self._lod = _freeze(lod)
        self._lod_t = None

    @classmethod
    def traced(cls, data, offsets):
        """Build a traced-offset LoDArray. offsets: list of int32 device
        arrays [n_i + 1] (one per lod level)."""
        obj = cls.__new__(cls)
        obj.data = data
        obj._lod = None
        obj._lod_t = tuple(jnp.asarray(o, jnp.int32) for o in offsets)
        return obj

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        if self._lod_t is not None:
            return (self.data,) + self._lod_t, ('traced', len(self._lod_t))
        return (self.data,), ('static', self._lod)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = cls.__new__(cls)
        kind, info = aux
        if kind == 'traced':
            obj.data = children[0]
            obj._lod = None
            obj._lod_t = tuple(children[1:1 + info])
        else:
            obj.data = children[0]
            obj._lod = info
            obj._lod_t = None
        return obj

    # -- mode --------------------------------------------------------------
    @property
    def is_traced(self):
        return self._lod_t is not None

    @property
    def lod(self):
        """Host offsets (tuple of tuples). In traced mode this works only
        OUTSIDE a trace (concrete device offsets pull to host — fetch/save
        time); under jit the offsets are tracers and ops that genuinely
        need host values (content-dependent output shapes) cannot run on
        traced-lod inputs."""
        if self._lod_t is not None:
            if any(isinstance(o, jax.core.Tracer) for o in self._lod_t):
                raise TracedLoDError(
                    "this op needs HOST lod values (its output shape "
                    "depends on them), but the input carries traced "
                    "(device) lod. Feed a static-lod batch for this op, or "
                    "restructure to the padded equivalent "
                    "(sequence_pad/sequence_mask).")
            return _freeze([np.asarray(o) for o in self._lod_t])
        return self._lod

    @property
    def nlevels(self):
        return len(self._lod_t) if self._lod_t is not None else len(self._lod)

    def off_t(self, level=-1):
        """Offsets of `level` as an int32 device value — traced arrays in
        traced mode, XLA constants in static mode. The uniform currency for
        lowerings (one implementation serves both modes)."""
        if self._lod_t is not None:
            return self._lod_t[level]
        return jnp.asarray(np.asarray(self._lod[level]), jnp.int32)

    def nseq_of(self, level=-1):
        """STATIC sequence count (offset array length - 1) — shape-level in
        both modes."""
        if self._lod_t is not None:
            return int(self._lod_t[level].shape[0]) - 1
        return len(self._lod[level]) - 1

    def with_lod_of(self, data, level_slice=None):
        """New LoDArray around `data` sharing this one's lod (same mode)."""
        if self._lod_t is not None:
            lt = self._lod_t if level_slice is None else \
                self._lod_t[level_slice]
            return LoDArray.traced(data, lt)
        l = self._lod if level_slice is None else self._lod[level_slice]
        return LoDArray(data, l)

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nseq(self):
        if self._lod_t is not None:
            return int(self._lod_t[0].shape[0]) - 1
        return len(self._lod[0]) - 1 if self._lod else None

    def offsets(self, level=0):
        return np.asarray(self.lod[level], dtype=np.int64)

    def lengths(self, level=0):
        off = self.offsets(level)
        return off[1:] - off[:-1]

    def recursive_sequence_lengths(self):
        return [list(self.lengths(i)) for i in range(len(self.lod))]

    def last_level_offsets(self):
        return self.offsets(len(self.lod) - 1)

    def __repr__(self):
        if self._lod_t is not None:
            return "LoDArray(shape=%s, traced lod x%d)" % (
                tuple(self.data.shape), len(self._lod_t))
        return "LoDArray(shape=%s, lod=%s)" % (
            tuple(self.data.shape),
            [list(l)[:8] for l in self._lod])


class TracedLoDError(TypeError):
    pass


def unwrap(x):
    return x.data if isinstance(x, LoDArray) else x


def lod_of(x):
    return x.lod if isinstance(x, LoDArray) else ()


def lengths_to_offsets(lengths):
    lengths = np.asarray(lengths, dtype=np.int64)
    return np.concatenate([[0], np.cumsum(lengths)]).astype(np.int64)


def create_lod_array(data, recursive_seq_lens=None, lod=None, traced=False,
                     bucket_rows=None):
    """Build a LoDArray from dense data + nested lengths or offsets.

    traced=True: offsets become device data (see module docstring) so the
    compiled program is lod-generic. bucket_rows pads `data`'s leading dim
    up to the bucket capacity so every same-bucket batch shares one shape.
    """
    if lod is None:
        lod = []
        if recursive_seq_lens:
            for lens in recursive_seq_lens:
                lod.append(lengths_to_offsets(lens))
    data = jnp.asarray(data)
    if bucket_rows is not None and data.shape[0] < bucket_rows:
        pad = [(0, bucket_rows - data.shape[0])] + [(0, 0)] * (data.ndim - 1)
        data = jnp.pad(data, pad)
    if traced:
        return LoDArray.traced(data, [jnp.asarray(np.asarray(l), jnp.int32)
                                      for l in lod])
    return LoDArray(data, lod)


def seg_ids_t(off_t, total):
    """Traced/constant row -> sequence-index map: searchsorted over the
    offsets. Padding rows past off[-1] map to nseq (out of range), which
    jax segment_* ops drop and gathers must mask."""
    return (jnp.searchsorted(off_t.astype(jnp.int32),
                             jnp.arange(total, dtype=jnp.int32),
                             side='right') - 1).astype(jnp.int32)


def valid_rows_t(off_t, total):
    """Bool [total]: row belongs to a real sequence (not bucket padding)."""
    return jnp.arange(total, dtype=jnp.int32) < off_t[-1].astype(jnp.int32)


def segment_ids_from_offsets(offsets, total):
    """offsets: static int sequence [nseq+1]; total rows. Returns a host
    int32[total] mapping row -> sequence index (becomes an XLA constant)."""
    off = np.asarray(offsets, dtype=np.int64)
    ids = np.zeros(total, dtype=np.int32)
    for i in range(len(off) - 1):
        ids[off[i]:off[i + 1]] = i
    return jnp.asarray(ids)
