"""LoD (level-of-detail) runtime representation.

The reference's LoDTensor (paddle/fluid/framework/lod_tensor.h:58,110) packs
variable-length sequences into one dense tensor plus offset tables, and its
kernels read the offsets on the HOST (mixed_vector.h keeps a CPU home for
the LoD). TPU-native re-design keeps that split: the dense data is a
jax.Array; the offsets are STATIC host-side tuples carried in the pytree
structure (aux data). Sequence ops therefore lower to fully static-shape XLA
programs — the fastest form XLA can compile — and the jit cache keys on the
lod pattern. Variable-length batches should be bucketed/padded on the host
(reader decorators provide bucketing) to bound recompiles, exactly as
TPU input pipelines do.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _freeze(lod):
    return tuple(tuple(int(v) for v in np.asarray(l).reshape(-1))
                 for l in lod)


@jax.tree_util.register_pytree_node_class
class LoDArray(object):
    """Dense device data + static per-level row-split offsets."""

    __slots__ = ('data', 'lod')

    def __init__(self, data, lod=()):
        self.data = data
        self.lod = _freeze(lod)

    # -- pytree protocol: lod is STRUCTURE, not a leaf --------------------
    def tree_flatten(self):
        return (self.data,), self.lod

    @classmethod
    def tree_unflatten(cls, lod, children):
        obj = cls.__new__(cls)
        obj.data = children[0]
        obj.lod = lod
        return obj

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nseq(self):
        return len(self.lod[0]) - 1 if self.lod else None

    def offsets(self, level=0):
        return np.asarray(self.lod[level], dtype=np.int64)

    def lengths(self, level=0):
        off = self.offsets(level)
        return off[1:] - off[:-1]

    def recursive_sequence_lengths(self):
        return [list(self.lengths(i)) for i in range(len(self.lod))]

    def last_level_offsets(self):
        return self.offsets(len(self.lod) - 1)

    def __repr__(self):
        return "LoDArray(shape=%s, lod=%s)" % (
            tuple(self.data.shape),
            [list(l)[:8] for l in self.lod])


def unwrap(x):
    return x.data if isinstance(x, LoDArray) else x


def lod_of(x):
    return x.lod if isinstance(x, LoDArray) else ()


def lengths_to_offsets(lengths):
    lengths = np.asarray(lengths, dtype=np.int64)
    return np.concatenate([[0], np.cumsum(lengths)]).astype(np.int64)


def create_lod_array(data, recursive_seq_lens=None, lod=None):
    """Build a LoDArray from dense data + python nested lengths or offsets."""
    if lod is None:
        lod = []
        if recursive_seq_lens:
            for lens in recursive_seq_lens:
                lod.append(lengths_to_offsets(lens))
    return LoDArray(jnp.asarray(data), lod)


def segment_ids_from_offsets(offsets, total):
    """offsets: static int sequence [nseq+1]; total rows. Returns a host
    int32[total] mapping row -> sequence index (becomes an XLA constant)."""
    off = np.asarray(offsets, dtype=np.int64)
    ids = np.zeros(total, dtype=np.int32)
    for i in range(len(off) - 1):
        ids[off[i]:off[i + 1]] = i
    return jnp.asarray(ids)
