"""Host-side Scope: name -> device array map.

The reference Scope (framework/scope.h:48) is a hierarchical C++ map of
type-erased Variables mutated in place by every op. TPU-native re-design:
ops never mutate — the Executor traces a pure step function whose carry is
the persistable subset of this dict, and commits the returned new state back
here. The Scope is thus just the host-side home of parameters/optimizer
state between runs (and the save/load surface).
"""
from __future__ import annotations

import contextlib

import numpy as np


class Scope(object):
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self._kids = []

    def var(self, name):
        """Create-or-get (reference Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return _VarHandle(self, name)

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return _VarHandle(s, name)
            s = s.parent
        return None

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    # -- direct value access (the common path) -----------------------------
    def get(self, name, default=None):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return default

    def has(self, name):
        return self.find_var(name) is not None

    def set(self, name, value):
        self._vars[name] = value

    def delete(self, name):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars)

    def __contains__(self, name):
        return self.has(name)


class _VarHandle(object):
    """Mimics the reference Variable handle enough for user code:
    var.get_tensor().set(np_array, place) / np.array(tensor)."""

    __slots__ = ('scope', 'name')

    def __init__(self, scope, name):
        self.scope = scope
        self.name = name

    def get_tensor(self):
        return _TensorHandle(self.scope, self.name)

    def get_value(self):
        return self.scope.get(self.name)

    def set_value(self, v):
        self.scope.set(self.name, v)


class _TensorHandle(object):
    __slots__ = ('scope', 'name')

    def __init__(self, scope, name):
        self.scope = scope
        self.name = name

    def set(self, array, place=None):
        import jax.numpy as jnp
        self.scope.set(self.name, jnp.asarray(array))

    def shape(self):
        v = self.scope.get(self.name)
        return list(v.shape) if v is not None else []

    def __array__(self, dtype=None):
        v = self.scope.get(self.name)
        from .lod import unwrap
        arr = np.asarray(unwrap(v))
        return arr.astype(dtype) if dtype is not None else arr

    def set_lod(self, lod):
        from .lod import LoDArray, unwrap
        v = self.scope.get(self.name)
        self.scope.set(self.name, LoDArray(unwrap(v), lod))

    def lod(self):
        from .lod import lod_of
        return [np.asarray(l).tolist() for l in lod_of(self.scope.get(self.name))]


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev
