"""Per-backend capability probes.

Some PJRT plugins (notably the axon TPU tunnel) don't implement host
send/recv callbacks, which `py_func` (ops/tensor_ops.py, lowered via
jax.pure_callback — ref: operators/py_func_op.cc) depends on. Probing once
per platform and failing at BUILD time turns an opaque runtime
UNIMPLEMENTED into an actionable error before any compile work happens.
"""
from __future__ import annotations

_cache = {}


def _platform_key(device):
    client = getattr(device, 'client', None)
    if client is not None and getattr(client, 'platform', None):
        return client.platform
    return device.platform


def host_callbacks_supported(device=None):
    """True if jax.pure_callback works on `device` (default: first default
    device). Probed once per platform, cached."""
    import jax
    import jax.numpy as jnp
    if device is None:
        device = jax.devices()[0]
    key = _platform_key(device)
    if key not in _cache:
        def probe(x):
            return jax.pure_callback(
                lambda a: a, jax.ShapeDtypeStruct((), jnp.float32), x)
        try:
            with jax.default_device(device):
                jax.jit(probe)(jnp.float32(0.0)).block_until_ready()
            _cache[key] = True
        except Exception:
            _cache[key] = False
    return _cache[key]
