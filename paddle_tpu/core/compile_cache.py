"""Persistent compile cache + AOT warm-start (ISSUE 5).

Every cache in the repo used to be in-memory and per-process — a fresh
process paid full trace + XLA compile for every (program, bucket, mesh)
even when the identical executable was built seconds earlier in the
previous run. This module is the on-disk, cross-process tier the ROADMAP's
serving story needs (autoscaled replicas, elastic-restarted trainers):
the same problem JAX's persistent compilation cache and TF's tfcompile/AOT
path solve upstream, specialized to the Program/Executor contract.

Three tiers, tried in order:

  1. **Executable tier** (`<key>.exec`): the XLA executable serialized via
     `jax.experimental.serialize_executable` — a warm hit skips BOTH the
     Python trace and the XLA compile (zero compiles, the AOT warm start).
  2. **StableHLO tier** (`<key>.hlo`): the `jax.export` serialization of
     the traced function — a warm hit skips the (often dominant) Python
     re-trace and still XLA-compiles. This tier also survives jaxlib
     upgrades that invalidate tier 1 (export has its own compatibility
     window).
  3. **JAX persistent compilation cache** underneath (`<dir>/xla`):
     enabled for the whole process when this cache is enabled, so even
     compiles that bypass this module (utility jits, the bulk-infer scan)
     warm-start at the XLA level.

Content-addressed keys: sha256 over (serialized program desc, feed/fetch
signatures, arg avals + shardings, amp/mesh/K, rng impl + dropout bits,
jax + jaxlib versions, backend/topology, XLA_FLAGS). Anything that changes
the compiled numerics changes the key — a miss is always safe, a false hit
never happens.

Knobs: ``PTPU_COMPILE_CACHE=1`` enables (also implied by setting
``PTPU_COMPILE_CACHE_DIR``), ``PTPU_COMPILE_CACHE_DIR`` places it
(default ``~/.cache/paddle_tpu/compile``), ``PTPU_COMPILE_CACHE_MAX_MB``
bounds it (LRU by last-use mtime, default 512). Programmatic:
``enable(dir)`` / ``disable()``.

Discipline: flock-guarded writes/eviction (the elastic-journal pattern,
reader/elastic.py), atomic tmp+rename entry files, and LOUD fallback —
a corrupt or stale entry warns, is deleted, and recompiles; it never
fails the run and never silently serves garbage.

Numerics contract: within the cached world, cold and warm runs are
bit-identical — the cold path executes the very executable it persists,
and a StableHLO-tier recompile of the same module on the same
backend/version reproduces the same binary. (The cold *cached* path
compiles through ``jax.export``, which may differ in the last bit from
the uncached `jax.jit` path on some backends — the cache is opt-in
per process, never mixed mid-stream.)
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import threading
import time
import warnings

import numpy as np

try:
    import fcntl
except ImportError:          # non-POSIX: no advisory locking available
    fcntl = None

_SCHEMA = 1                  # bump to invalidate every entry wholesale

_override_enabled = None     # enable()/disable() beat the env
_override_dir = None
_override_max_mb = None

_stats = {
    'exec_hits': 0,          # tier-1 hits (zero trace, zero compile)
    'hlo_hits': 0,           # tier-2 hits (zero trace, one compile)
    'misses': 0,
    'compiles': 0,           # XLA compiles performed BY this cache layer
    'compile_s': 0.0,        # seconds spent tracing+compiling on miss
    'hit_load_s': 0.0,       # seconds spent deserializing on hit
    'bytes_read': 0,
    'bytes_written': 0,
    'corrupt': 0,            # entries dropped by the loud-fallback path
    'evicted': 0,
    # raw jax-wide counters (monitoring listener): every backend compile
    # in the process, and how many were served by the persistent XLA
    # cache (tier 3) — net real compiles = xla_compiles - xla_pcache_hits
    'xla_compiles': 0,
    'xla_compile_s': 0.0,
    'xla_pcache_hits': 0,
}
_stats_lock = threading.Lock()
_listener_on = False
_prof_registered = False
_dir_ready = set()


# -- knobs -------------------------------------------------------------------

def enabled():
    """Cache on? enable()/disable() override > PTPU_COMPILE_CACHE env >
    implied-on when PTPU_COMPILE_CACHE_DIR is set."""
    if _override_enabled is not None:
        return _override_enabled
    v = os.environ.get('PTPU_COMPILE_CACHE')
    if v is not None:
        return v not in ('0', 'false', 'off', '')
    return bool(os.environ.get('PTPU_COMPILE_CACHE_DIR'))


def cache_dir():
    if _override_dir is not None:
        return _override_dir
    return os.environ.get('PTPU_COMPILE_CACHE_DIR') or os.path.join(
        os.path.expanduser('~'), '.cache', 'paddle_tpu', 'compile')


def max_mb():
    if _override_max_mb is not None:
        return _override_max_mb
    try:
        return float(os.environ.get('PTPU_COMPILE_CACHE_MAX_MB', '512'))
    except ValueError:
        return 512.0


def enable(dir=None, max_mb=None):
    """Turn the cache on for this process (beats the env knobs)."""
    global _override_enabled, _override_dir, _override_max_mb
    _override_enabled = True
    if dir is not None:
        _override_dir = dir
    if max_mb is not None:
        _override_max_mb = float(max_mb)
    _ensure_ready()


def disable():
    global _override_enabled
    _override_enabled = False


def _entries_dir():
    return os.path.join(cache_dir(), 'entries')


def _ensure_ready():
    """Create the cache dir, hook the jax persistent cache underneath
    (tier 3), and start the compile-event listener + profiler source."""
    d = cache_dir()
    if d not in _dir_ready:
        os.makedirs(_entries_dir(), exist_ok=True)
        _enable_jax_pcache(os.path.join(d, 'xla'))
        _dir_ready.add(d)
    _ensure_listener()
    _register_profiler_source()


_pcache_dir_set = None   # the xla dir THIS module configured (if any)


def _enable_jax_pcache(xla_dir):
    """Tier 3: JAX's own persistent compilation cache. Set it when unset;
    RE-point it when a later enable(dir=...) moves the cache and the
    current value is one this module set (a user-configured dir is never
    touched) — otherwise tier-3 traffic would silently keep landing in
    the old dir, invisible to stats/prune on the new one."""
    global _pcache_dir_set
    import jax
    try:
        cur = jax.config.jax_compilation_cache_dir
        if cur is None or (cur == _pcache_dir_set and cur != xla_dir):
            jax.config.update('jax_compilation_cache_dir', xla_dir)
            # cache everything: tiny executor steps matter here, and the
            # default min-entry/min-time thresholds would skip them
            jax.config.update('jax_persistent_cache_min_entry_size_bytes',
                              -1)
            jax.config.update('jax_persistent_cache_min_compile_time_secs',
                              0)
            _pcache_dir_set = xla_dir
    except Exception as e:          # older jaxlib without the knobs
        warnings.warn('compile cache: could not enable the jax persistent '
                      'compilation cache (%s: %s); tiers 1/2 still work'
                      % (type(e).__name__, e), RuntimeWarning)


# -- compile-event counter (profiler register_compile_source feed) -----------

def _ensure_listener():
    """Count every XLA backend compile in the process via jax.monitoring.
    `/jax/core/compile/backend_compile_duration` fires even when the
    persistent XLA cache serves the compile, so the net real-compile
    count is xla_compiles - xla_pcache_hits."""
    global _listener_on
    if _listener_on:
        return
    _listener_on = True
    try:
        from jax._src import monitoring
    except ImportError:
        return

    def _dur(event, secs, **kw):
        if event == '/jax/core/compile/backend_compile_duration':
            with _stats_lock:
                _stats['xla_compiles'] += 1
                _stats['xla_compile_s'] += secs

    def _ev(event, **kw):
        if event == '/jax/compilation_cache/cache_hits':
            with _stats_lock:
                _stats['xla_pcache_hits'] += 1

    monitoring.register_event_duration_secs_listener(_dur)
    monitoring.register_event_listener(_ev)


def _register_profiler_source():
    global _prof_registered
    if _prof_registered:
        return
    _prof_registered = True
    try:
        from .. import profiler
        profiler.register_compile_source('compile_cache', stats)
    except Exception:
        pass


def stats():
    """Snapshot of the cache counters (profiler compile_report contract).
    `xla_compiles_net` is the number of REAL backend compiles the process
    performed — zero on a fully warm run."""
    with _stats_lock:
        s = dict(_stats)
    s['xla_compiles_net'] = s['xla_compiles'] - s['xla_pcache_hits']
    return s


def reset_stats():
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0 if not isinstance(_stats[k], float) else 0.0


# -- fingerprints ------------------------------------------------------------

def _canon(obj):
    """Canonical byte form for key hashing: dict/set order-stable, numpy
    content-hashed (repr truncates big arrays — a collision source)."""
    if isinstance(obj, dict):
        return '{%s}' % ','.join(
            '%s:%s' % (_canon(k), _canon(v)) for k, v in sorted(obj.items()))
    if isinstance(obj, (set, frozenset)):
        return '{%s}' % ','.join(sorted(_canon(x) for x in obj))
    if isinstance(obj, (list, tuple)):
        return '(%s)' % ','.join(_canon(x) for x in obj)
    if isinstance(obj, np.ndarray):
        return 'nd[%s;%s;%s]' % (obj.shape, obj.dtype,
                                 hashlib.sha256(
                                     np.ascontiguousarray(obj).tobytes()
                                 ).hexdigest())
    if isinstance(obj, (np.generic,)):
        return 'ns[%s;%r]' % (obj.dtype, obj.item())
    return repr(obj)


def quant_tag(tag, program):
    """Entry tag for `program`: '<tag>-int8' when it carries quantized
    ops (passes/quantize.py output), else `tag` unchanged. The int8 ops
    already distinguish the FINGERPRINT (they are part of the serialized
    desc); the tag split makes the quantized tier VISIBLE in the
    `cache_ctl.py stats` per-tag breakdown so a replica owner can audit
    that warm int8 programs are cached alongside the bf16 ones."""
    try:
        for b in program.blocks:
            for op in b.ops:
                if op.type.endswith('_int8'):
                    return tag + '-int8'
    except Exception:
        pass
    return tag


def program_fingerprint(program):
    """Stable content hash of the serialized program desc: blocks, ops
    (type, slots, attrs — including the per-op uid that seeds op-local
    rng streams), and var metadata. Cross-process stable, unlike the
    executor's (uid, build_epoch) in-memory key; memoized per build
    epoch on the program."""
    cached = program.__dict__.get('_ptpu_fingerprint')
    if cached is not None and cached[0] == program._build_epoch:
        return cached[1]
    h = hashlib.sha256()
    for b in program.blocks:
        h.update(('B%d<%d' % (b.idx, b.parent_idx)).encode())
        for name in sorted(b.vars):
            v = b.vars[name]
            h.update(_canon((
                'V', name, tuple(getattr(v, 'shape', ()) or ()),
                str(getattr(v, 'dtype', '')),
                bool(getattr(v, 'persistable', False)),
                int(getattr(v, 'lod_level', 0) or 0),
                bool(getattr(v, 'stop_gradient', False)),
                getattr(v, 'sharding_spec', None))).encode())
        for op in b.ops:
            h.update(_canon((
                'O', op.type, sorted(op.inputs.items()),
                sorted(op.outputs.items()),
                sorted(op.attrs.items()))).encode())
    fp = h.hexdigest()
    program.__dict__['_ptpu_fingerprint'] = (program._build_epoch, fp)
    return fp


def _versions():
    import jax
    import jaxlib
    return (jax.__version__, jaxlib.__version__)


def env_fingerprint(device=None, mesh=None):
    """Everything about the process that can change the compiled binary:
    jax/jaxlib versions, backend platform + device kind, topology
    (device/process counts; the mesh axes when compiling for one), and
    XLA_FLAGS (it carries codegen knobs and the virtual device count)."""
    import jax
    parts = [('schema', _SCHEMA), ('ver', _versions()),
             ('xla_flags', os.environ.get('XLA_FLAGS', ''))]
    if mesh is not None:
        devs = np.asarray(mesh.devices).reshape(-1)
        parts.append(('mesh', tuple(mesh.shape.items()),
                      tuple(sorted({d.device_kind for d in devs})),
                      len(devs),
                      len({d.process_index for d in devs})))
    else:
        d = device
        if d is None:
            d = jax.devices()[0]
        parts.append(('dev', d.platform, d.device_kind))
    try:
        parts.append(('nproc', jax.process_count()))
    except RuntimeError:
        parts.append(('nproc', 1))
    return tuple(parts)


def args_signature(args):
    """Aval + sharding signature of a concrete arg pytree — the same
    information jit keys its own C++ cache on."""
    import jax
    leaves, treedef = jax.tree.flatten(args)
    sig = []
    for x in leaves:
        srd = getattr(x, 'sharding', None)
        sig.append((tuple(getattr(x, 'shape', ()) or ()),
                    str(getattr(x, 'dtype', type(x).__name__)),
                    str(srd) if srd is not None else ''))
    return (str(treedef), tuple(sig))


def entry_key(parts):
    """Content-addressed entry name: sha256 over the canonical parts."""
    return hashlib.sha256(_canon(parts).encode()).hexdigest()


# -- on-disk entries ---------------------------------------------------------

def _paths(key):
    base = os.path.join(_entries_dir(), key)
    return base + '.exec', base + '.hlo', base + '.json'


class _flocked(object):
    """Exclusive flock on <dir>/.lock around writes/eviction — the
    elastic-journal discipline (reader/elastic.py): concurrent replicas
    warming one shared cache dir must not interleave eviction with a
    half-written entry. Filesystems without flock degrade to unlocked
    (atomic tmp+rename still keeps readers safe)."""

    def __init__(self):
        self._f = None

    def __enter__(self):
        if fcntl is None:
            return self
        try:
            self._f = open(os.path.join(cache_dir(), '.lock'), 'a+')
            fcntl.flock(self._f, fcntl.LOCK_EX)
        except OSError:
            if self._f is not None:
                self._f.close()
            self._f = None
        return self

    def __exit__(self, *exc):
        if self._f is not None:
            try:
                fcntl.flock(self._f, fcntl.LOCK_UN)
            except OSError:
                pass
            self._f.close()


def _atomic_write(path, data):
    tmp = '%s.tmp-%d' % (path, os.getpid())
    with open(tmp, 'wb') as f:
        f.write(data)
    os.replace(tmp, path)
    return len(data)


def _drop_entry(key, reason=None):
    """Delete an entry's files; with `reason`, this is the loud-fallback
    path (corrupt/stale entry — warn, drop, recompile)."""
    if reason is not None:
        warnings.warn('compile cache entry %s...: %s — dropping it and '
                      'recompiling' % (key[:12], reason), RuntimeWarning)
        with _stats_lock:
            _stats['corrupt'] += 1
    for p in _paths(key):
        try:
            os.remove(p)
        except OSError:
            pass


def _touch(key):
    now = time.time()
    for p in _paths(key):
        try:
            os.utime(p, (now, now))
        except OSError:
            pass


def load(key, donate_argnums=()):
    """Load an entry: tier-1 executable (zero compile), else tier-2
    StableHLO (compiles, skips re-trace). None on miss. Corrupt entries
    drop loudly and return None. `donate_argnums`: the caller's
    certified donation plan — the tier-2 recompile applies it (a fresh
    bookkept jit, so it is safe where a reloaded tier-1 alias is not),
    keeping the warm-path copy recovery alive across jaxlib bumps."""
    exec_p, hlo_p, _meta_p = _paths(key)
    t0 = time.perf_counter()
    if os.path.exists(exec_p):
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load)
            with open(exec_p, 'rb') as f:
                blob = f.read()
            payload, in_tree, out_tree = pickle.loads(blob)
            fn = deserialize_and_load(payload, in_tree, out_tree)
            with _stats_lock:
                _stats['exec_hits'] += 1
                _stats['bytes_read'] += len(blob)
                _stats['hit_load_s'] += time.perf_counter() - t0
            _touch(key)
            return fn
        except Exception as e:
            # e.g. a jaxlib bump: the executable format is not stable
            # across versions even though the key matched a hash race —
            # drop tier 1, fall through to tier 2
            _drop_entry_file(exec_p)
            warnings.warn('compile cache entry %s...: executable tier '
                          'unusable (%s: %s) — falling back to the '
                          'StableHLO tier' % (key[:12], type(e).__name__,
                                              e), RuntimeWarning)
            with _stats_lock:
                _stats['corrupt'] += 1
    if os.path.exists(hlo_p):
        try:
            import jax
            from jax import export as jexport
            with open(hlo_p, 'rb') as f:
                blob = f.read()
            exp = jexport.deserialize(blob)
            fn = jax.jit(exp.call,
                         donate_argnums=tuple(donate_argnums or ()))
            with _stats_lock:
                _stats['hlo_hits'] += 1
                _stats['bytes_read'] += len(blob)
                _stats['hit_load_s'] += time.perf_counter() - t0
            _touch(key)
            return fn
        except Exception as e:
            _drop_entry(key, 'StableHLO tier unusable (%s: %s)'
                        % (type(e).__name__, e))
    return None


def _drop_entry_file(path):
    try:
        os.remove(path)
    except OSError:
        pass


def store(key, compiled=None, exported_bytes=None, tag='program',
          donated=False):
    """Persist an entry (either tier may be absent) and LRU-evict over
    budget. Write failures warn and are non-fatal — the cache never
    breaks the run."""
    wrote = 0
    exec_p, hlo_p, meta_p = _paths(key)
    try:
        with _flocked():
            if compiled is not None:
                try:
                    from jax.experimental.serialize_executable import (
                        serialize)
                    payload, in_tree, out_tree = serialize(compiled)
                    wrote += _atomic_write(
                        exec_p, pickle.dumps((payload, in_tree, out_tree)))
                except Exception as e:
                    # backend without executable serialization: tier-2 only
                    warnings.warn('compile cache: executable tier '
                                  'unavailable (%s: %s); storing StableHLO '
                                  'only' % (type(e).__name__, e),
                                  RuntimeWarning)
            if exported_bytes is not None:
                wrote += _atomic_write(hlo_p, exported_bytes)
            if wrote:
                meta = {'tag': tag, 'created': time.time(),
                        'ver': list(_versions()), 'schema': _SCHEMA,
                        'donated': bool(donated)}
                wrote += _atomic_write(
                    meta_p, json.dumps(meta).encode())
                with _stats_lock:
                    _stats['bytes_written'] += wrote
                _evict_over_budget(keep=key)
    except Exception as e:
        warnings.warn('compile cache: store failed (%s: %s)'
                      % (type(e).__name__, e), RuntimeWarning)
    return wrote


def _entry_index():
    """{key: (bytes, last_use_mtime)} over the entries dir."""
    idx = {}
    d = _entries_dir()
    try:
        names = os.listdir(d)
    except OSError:
        return idx
    for n in names:
        stem, dot, ext = n.rpartition('.')
        if ext not in ('exec', 'hlo', 'json') or not stem:
            continue
        try:
            st = os.stat(os.path.join(d, n))
        except OSError:
            continue
        b, m = idx.get(stem, (0, 0.0))
        idx[stem] = (b + st.st_size, max(m, st.st_mtime))
    return idx


def _xla_dir():
    return os.path.join(cache_dir(), 'xla')


def _xla_index():
    """{path: (bytes, mtime)} over the tier-3 jax persistent-cache dir —
    those bytes count against the SAME budget (the module's MAX_MB claim
    must hold for the whole cache dir, not just entries/)."""
    idx = {}
    d = _xla_dir()
    try:
        names = os.listdir(d)
    except OSError:
        return idx
    for n in names:
        p = os.path.join(d, n)
        try:
            st = os.stat(p)
        except OSError:
            continue
        if os.path.isfile(p):
            idx[p] = (st.st_size, st.st_mtime)
    return idx


def _sweep_stale_tmp(max_age_s=3600.0):
    """Remove *.tmp-<pid> orphans a killed process left behind (the
    elastic-restart scenario): invisible to the entry index, so without
    this sweep they would accumulate unbounded. Age-gated so an in-flight
    write in another process is never torn."""
    n = 0
    cutoff = time.time() - max_age_s
    for d in (_entries_dir(), _xla_dir()):
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            if '.tmp-' not in name:
                continue
            p = os.path.join(d, name)
            try:
                if os.path.isfile(p) and os.stat(p).st_mtime < cutoff:
                    os.remove(p)
                    n += 1
            except OSError:
                pass
    return n


def _evict_over_budget(keep=None, budget_mb=None):
    """LRU eviction by last-use mtime (reads _touch their entry) down to
    the byte budget, across entries/ AND the tier-3 xla dir. Caller holds
    the flock."""
    budget = (max_mb() if budget_mb is None else float(budget_mb)) * 2**20
    _sweep_stale_tmp()
    idx = _entry_index()
    xla = _xla_index()
    total = sum(b for b, _ in idx.values()) + sum(b for b, _ in xla.values())
    if total <= budget:
        return 0
    items = [(m, 'entry', k, b) for k, (b, m) in idx.items()] \
        + [(m, 'xla', p, b) for p, (b, m) in xla.items()]
    n = 0
    for m, kind, ident, b in sorted(items):
        if total <= budget:
            break
        if kind == 'entry':
            if ident == keep:
                continue
            _drop_entry(ident)
        else:
            try:
                os.remove(ident)
            except OSError:
                continue
        total -= b
        n += 1
    with _stats_lock:
        _stats['evicted'] += n
    return n


def prune(budget_mb=None, clear=False):
    """CLI/maintenance eviction: down to `budget_mb` (default: the
    configured budget), or everything — entries, tier-3 xla files, and
    stale tmp orphans — with clear=True. Returns items removed."""
    _ensure_ready()
    with _flocked():
        if clear:
            n = _sweep_stale_tmp(max_age_s=0.0)
            idx = _entry_index()
            for key in idx:
                _drop_entry(key)
            for p in _xla_index():
                try:
                    os.remove(p)
                    n += 1
                except OSError:
                    pass
            with _stats_lock:
                _stats['evicted'] += len(idx) + n
            return len(idx) + n
        return _evict_over_budget(budget_mb=budget_mb)


def disk_stats():
    """On-disk view (tools/cache_ctl.py stats): entry count, bytes (split
    entries vs tier-3 xla), per-tag breakdown, oldest/newest last use."""
    _ensure_ready()
    idx = _entry_index()
    tags = {}
    for key in idx:
        meta_p = _paths(key)[2]
        tag = '?'
        try:
            with open(meta_p) as f:
                tag = json.load(f).get('tag', '?')
        except (OSError, ValueError):
            pass
        tags[tag] = tags.get(tag, 0) + 1
    mts = [m for _, m in idx.values()]
    ebytes = sum(b for b, _ in idx.values())
    xbytes = sum(b for b, _ in _xla_index().values())
    return {'dir': cache_dir(), 'entries': len(idx),
            'bytes': ebytes, 'xla_bytes': xbytes,
            'total_bytes': ebytes + xbytes,
            'max_mb': max_mb(), 'tags': tags,
            'oldest_use': min(mts) if mts else None,
            'newest_use': max(mts) if mts else None}


# -- the main entry: AOT-or-jit ----------------------------------------------

def aot_or_jit(jitted, args, key_parts, tag='program', fun=None,
               device=None, mesh=None, use_export=None,
               donate_argnums=None):
    """Warm-start for the avals of `args`, or compile-and-persist.

    Returns a callable with jitted's calling convention:
      * cache disabled -> `jitted` unchanged (the zero-risk path);
      * tier-1 hit     -> the deserialized executable (NO trace, NO
                          compile);
      * tier-2 hit     -> jit of the deserialized StableHLO (no re-trace,
                          one compile — which tier 3 may itself absorb);
      * miss           -> traces ONCE through jax.export, compiles, stores
                          both tiers, and returns the compiled executable
                          (so the cold run executes the exact binary the
                          warm run will load — bit-identity by
                          construction).

    `key_parts` must carry every trace-time input that is not visible in
    the arg avals (program fingerprint, fetch names, amp/K/rng flags);
    avals/shardings and the env fingerprint are appended here.

    DONATION: by default cached executables compile WITHOUT input
    donation, from `fun` (the raw step callable) when given. A
    serialized-then-reloaded executable keeps its XLA input/output
    aliasing but jax's buffer bookkeeping no longer knows the args were
    donated — the computation then scribbles over buffers the caller
    still holds (measured: nondeterministic fetches / NaN on the
    composed mesh programs). Correctness beats the one extra state copy
    — UNLESS the caller proves safety: pass `donate_argnums` only with
    a dataflow donation certificate (passes/dataflow.certify_donation)
    showing no caller-visible buffer aliases the donated args. Donated
    and undonated entries never collide (the donation plan is part of
    the entry key), the meta records `donated` for doctor/cache_ctl
    visibility, and a donated compile that fails falls back to the
    undonated path loudly.

    `use_export`: whether the miss path serializes through jax.export
    (both tiers) or direct-compiles (tier 1 only). Default: export for
    single-device programs, direct for mesh programs — jax.export does
    not faithfully round-trip every manual-collective pattern the
    composed mesh programs use (shard_map pipelines), and a wrong-answer
    cache would be worse than no cache.
    """
    if not enabled():
        return jitted
    _ensure_ready()
    import jax
    if use_export is None:
        use_export = mesh is None
    donate = tuple(donate_argnums or ())
    if donate and mesh is not None:
        donate = ()  # round-8 NaN cliff: mesh programs never donate
    key = entry_key((tag, key_parts, args_signature(args),
                     env_fingerprint(device=device, mesh=mesh),
                     ('donate', donate)))
    fn = load(key, donate_argnums=donate)
    if fn is not None:
        return fn
    with _stats_lock:
        _stats['misses'] += 1
    t0 = time.perf_counter()
    # the undonated jit the cached tier-2 module exports from (docstring);
    # tier 1 compiles WITH certified donation so the serialized
    # executable carries the state aliasing (warm runs skip the copy)
    cache_jit = jax.jit(fun) if fun is not None else jitted
    exported_bytes = None
    compiled = None
    donated = False
    # fresh_compile: the executable below goes to store()'s tier 1 via
    # serialize_executable — a tier-3-satisfied compile would serialize
    # into a blob no other process can load
    if use_export:
        try:
            from jax import export as jexport
            exp = jexport.export(cache_jit)(*args)
            exported_bytes = exp.serialize()
            with fresh_compile():
                compiled, donated = _compile_maybe_donated(
                    jax, exp.call, donate, args)
        except Exception:
            exported_bytes = None
            compiled = None
    if compiled is None:
        # programs jax.export cannot carry (host callbacks, exotic
        # shardings): direct AOT compile — tier 1 only
        try:
            with fresh_compile():
                if donate and fun is not None:
                    compiled, donated = _compile_maybe_donated(
                        jax, fun, donate, args)
                else:
                    compiled = cache_jit.lower(*args).compile()
        except TypeError:
            # a backend/jit wrapper without .lower: give up on caching
            return jitted
    with _stats_lock:
        _stats['compiles'] += 1
        _stats['compile_s'] += time.perf_counter() - t0
    # `donated` is the OUTCOME, not the request: a donated compile that
    # fell back stores donated=False so doctor/cache_ctl/smoke guards
    # never report a recovery that did not happen
    store(key, compiled=compiled, exported_bytes=exported_bytes, tag=tag,
          donated=donated)
    return compiled


@contextlib.contextmanager
def fresh_compile():
    """Compile with jax's persistent compilation cache (tier 3)
    DISABLED. An executable that tier 3 satisfied re-serializes into a
    blob other processes CANNOT deserialize ('Symbols not found: ...'
    at load — measured on XLA:CPU, ISSUE 12 round): anything destined
    for serialize_executable (tier-1 entries, AOT warm-start sidecars)
    must come from a genuinely fresh XLA compile. Scoped and
    exception-safe; a no-op on jax versions without the flag.

    jax latches cache-enablement ONCE per process
    (compilation_cache.is_cache_used caches its verdict), so toggling
    the flag alone is ignored after the first compile — the latch is
    reset around the scope (and re-reset after, so the surrounding
    run's tier-3 behavior is unchanged)."""
    import jax

    def _unlatch():
        try:
            from jax._src import compilation_cache as _jcc
            _jcc.reset_cache()
        except Exception:
            pass
    try:
        old = bool(jax.config.jax_enable_compilation_cache)
    except AttributeError:
        yield
        return
    try:
        jax.config.update('jax_enable_compilation_cache', False)
        _unlatch()
        yield
    finally:
        jax.config.update('jax_enable_compilation_cache', old)
        _unlatch()


def _compile_maybe_donated(jax, fn, donate, args):
    """AOT-compile `fn`, donating `donate` argnums when certified;
    returns (compiled, donated_outcome). A donated compile that fails
    warns and falls back to undonated (the copy tax returns,
    correctness never leaves)."""
    if donate:
        try:
            return (jax.jit(fn, donate_argnums=donate).lower(
                *args).compile(), True)
        except Exception as e:
            warnings.warn(
                'compile cache: donated compile failed (%s: %s) — '
                'falling back to the undonated executable (one extra '
                'state copy per step)' % (type(e).__name__, e),
                RuntimeWarning)
    return jax.jit(fn).lower(*args).compile(), False


# -- shared in-memory LRU helper ---------------------------------------------

class LRUCache(object):
    """Tiny insertion/access-ordered LRU (dict preserves order; move-to-end
    on hit) — the in-memory sibling of the on-disk eviction above, shared
    with CompiledProgram._opt_cache (parallel/compiler.py)."""

    def __init__(self, maxsize):
        self.maxsize = int(maxsize)
        self._d = {}

    def get(self, key, default=None):
        if key not in self._d:
            return default
        val = self._d.pop(key)
        self._d[key] = val
        return val

    def put(self, key, val):
        self._d.pop(key, None)
        self._d[key] = val
        while len(self._d) > self.maxsize:
            self._d.pop(next(iter(self._d)))

    def filter_inplace(self, keep):
        """Drop entries whose key fails `keep(key)` (epoch turnover)."""
        for k in [k for k in self._d if not keep(k)]:
            del self._d[k]

    def __len__(self):
        return len(self._d)

    def __contains__(self, key):
        return key in self._d
